"""Stall doctor: classify the pipeline's current bottleneck.

tf.data-style per-stage bottleneck attribution over the metrics the
pipeline already emits. The streaming stack has five distinct failure
modes, previously told apart by hand-reading counter dumps in
``BENCH_r0*.json``; the doctor encodes that reading as a deterministic
decision procedure over one :meth:`Metrics.report` snapshot:

==============  ============================================================
verdict         evidence
==============  ============================================================
compile-bound   one-time jit/AOT compile wall time (``train.compile_ms``)
                dominates the window: a cold start, not a slow step —
                checked first so cold-start runs never misread as
                step-bound; the advice points at the persistent
                compilation cache (docs/performance.md "Instant start")
step-bound      ingest outruns the consumer: ``ingest.queue_full_waits``
                climbing while the consumer barely waits on the queue, or
                the driver's dispatch ring blocking (``driver.ring_wait`` /
                ``train.host_blocks``)
feed-bound      host→device transfer is the wall: ``feed.throttle_blocks``
                with a significant ``feed.throttle_wait``/``feed.place``
                share
decode-bound    the standalone decode jit dominates (``decode.dispatch``)
wire-bound      the consumer starves (``ingest.queue_wait`` high) AND
                frames arrive already old (per-producer e2e staleness p95
                above ``stale_wire_s``): the socket/codec path is slow,
                not the producers
producer-bound  the consumer starves but frames arrive FRESH: producers
                simply don't render fast enough
echo-saturated  a data-echoing pipeline's draw loop blocked on its echo
                budget (``echo.saturated_waits`` / ``echo.wait_fresh``):
                echoing already absorbs all it may — raise producers,
                reservoir capacity, or ``max_echo_factor``
retrace-storm   compiles recurring past warm-up: the device ledger's
                retrace audit counted ``device.retraces`` dispatches
                whose batch signature missed every compiled shape —
                each one re-traces and re-compiles mid-run
memory-bound    HBM headroom collapsing (``device.hbm_headroom_frac``
                below the floor), with the ledger's static accounting
                (``device.temp_bytes`` vs ``device.hbm_peak_bytes``)
                naming whether temporaries or resident state dominate
==============  ============================================================

plus ``balanced`` (no single stage dominates — the healthy verdict) and
``idle`` (no span data yet). The discriminator between wire- and
producer-bound is frame lineage (:mod:`blendjax.obs.lineage`): identical
queue-wait symptoms, opposite staleness signatures. A starving consumer
whose ``echo.*`` counters show an active, unsaturated reservoir is
reported producer-bound with an "echo-mitigated" reason — the step rate
is being sustained by echoing, and the advice shifts from "the run is
starving" to "fresh-data diversity is the limit".

All inputs are plain dicts so synthetic fixtures exercise every verdict
without sockets or devices (``tests/test_obs.py``).
"""

from __future__ import annotations

import dataclasses

# Verdict kinds, in the order the decision procedure tests them.
VERDICTS = (
    "compile-bound",
    "retrace-storm",
    "memory-bound",
    "step-bound",
    "feed-bound",
    "decode-bound",
    "wire-bound",
    "producer-bound",
    "echo-saturated",
    "balanced",
    "idle",
)

# Staleness p95 above which a starving consumer reads wire-bound rather
# than producer-bound: a healthy local pipe delivers frames in tens of
# milliseconds; a quarter second of age on arrival means the frames
# existed long before we got them.
DEFAULT_STALE_WIRE_S = 0.25

# device.retraces at or above which recurring mid-run recompiles read as
# a storm: one or two can be a legitimately novel shape; three means
# shapes keep missing the compiled ladder.
DEFAULT_RETRACE_STORM = 3

# device.hbm_headroom_frac below which the run reads memory-bound: under
# ~8% free, allocator fragmentation alone can OOM a step whose peak fits
# on paper.
DEFAULT_HBM_HEADROOM_FLOOR = 0.08


@dataclasses.dataclass(frozen=True)
class Verdict:
    """One classification: ``kind`` (a :data:`VERDICTS` member), a
    human ``reason`` with the deciding numbers inlined, ``advice`` (the
    lever to pull), and the span ``shares`` it was computed from."""

    kind: str
    reason: str
    advice: str
    shares: dict

    def render(self) -> str:
        return f"doctor: {self.kind} — {self.reason} ({self.advice})"

    def __str__(self) -> str:  # str(verdict) in f-strings/logs
        return self.render()


def _total(spans: dict, name: str) -> float:
    v = spans.get(name)
    if not v:
        return 0.0
    return float(v.get("total_s", 0.0))


def diagnose(
    report: dict,
    driver: dict | None = None,
    lineage: dict | None = None,
    staleness_p95_s: float | None = None,
    stale_wire_s: float = DEFAULT_STALE_WIRE_S,
    prefetch: int | None = None,
    retrace_storm: int = DEFAULT_RETRACE_STORM,
    hbm_headroom_floor: float = DEFAULT_HBM_HEADROOM_FLOOR,
) -> Verdict:
    """Classify one :meth:`blendjax.utils.metrics.Metrics.report`
    snapshot. ``driver`` is an optional ``TrainDriver.stats`` dict;
    ``lineage`` an optional :meth:`FrameLineage.report` snapshot (used
    for the staleness discriminator when ``staleness_p95_s`` isn't
    given directly); ``prefetch`` — when the caller knows the ingest
    queue bound — lets the ``ingest.queue_depth_hwm`` gauge act as
    backpressure evidence (queue pinned at its bound == producers
    outran the consumer) alongside ``ingest.queue_full_waits``."""
    spans = report.get("spans", {})
    counters = report.get("counters", {})
    gauges = report.get("gauges", {})

    recv = sum(
        float(v.get("total_s", 0.0))
        for k, v in spans.items()
        if k.startswith("ingest.recv")
    )
    qwait = _total(spans, "ingest.queue_wait")
    place = _total(spans, "feed.place")
    throttle = _total(spans, "feed.throttle_wait")
    decode = _total(spans, "decode.dispatch")
    train = _total(spans, "train.dispatch")
    ring = _total(spans, "driver.ring_wait")
    # Echoing pipelines starve in their own span: the draw loop blocked
    # waiting for fresh frames (the inner consumer's queue_wait accrues
    # concurrently in the drain thread).
    ewait = _total(spans, "echo.wait_fresh")
    # One-time jit/AOT compile wall time (blendjax.train.aot). Included
    # in the evidence so a cold-start-dominated run reads compile-bound
    # — not step-bound — and the advice points at the persistent cache.
    compile_s = _total(spans, "train.compile_ms")

    busy = (
        recv + qwait + place + throttle + decode + train + ring + ewait
        + compile_s
    )
    shares = {
        "ingest.recv": recv,
        "ingest.queue_wait": qwait,
        "feed.place": place,
        "feed.throttle_wait": throttle,
        "decode.dispatch": decode,
        "train.dispatch": train,
        "driver.ring_wait": ring,
        "echo.wait_fresh": ewait,
        "train.compile_ms": compile_s,
    }
    if busy <= 0.0:
        return Verdict(
            "idle", "no span data recorded yet",
            "run the pipeline before asking for a diagnosis", shares,
        )
    shares = {k: round(v / busy, 4) for k, v in shares.items()}

    full_waits = int(counters.get("ingest.queue_full_waits", 0))
    throttle_blocks = int(counters.get("feed.throttle_blocks", 0))
    host_blocks = int(counters.get("train.host_blocks", 0))
    if driver:
        host_blocks = max(host_blocks, int(driver.get("host_blocks", 0)))

    if staleness_p95_s is None and lineage:
        vals = [
            p.get("e2e_staleness_ms", {}).get("p95")
            for p in lineage.values()
            if p.get("e2e_staleness_ms", {}).get("count")
        ]
        vals = [v for v in vals if v is not None]
        if vals:
            staleness_p95_s = max(vals) / 1e3

    # 0. compile-bound: one-time trace+compile wall time dominates the
    #    window — a cold start, not a slow step. Checked FIRST: compile
    #    stalls the consumer loop, so every downstream signature (full
    #    ingest queue, ring waits) fires too and would misread as
    #    step-bound.
    if shares["train.compile_ms"] > 0.5:
        return Verdict(
            "compile-bound",
            f"train.compile_ms share={shares['train.compile_ms']:.0%} "
            f"(aot_cache_hits={int(counters.get('train.aot_cache_hits', 0))}, "
            f"aot_cache_misses="
            f"{int(counters.get('train.aot_cache_misses', 0))}): this "
            "window is cold-start compilation, not steady-state work",
            "AOT-compile before step 0 behind the persistent cache "
            "(TrainDriver.build(aot=True, aot_cache_dir=...)); warm "
            "restarts then pay milliseconds — see docs/performance.md "
            "'Instant start'",
            shares,
        )

    # 0b. retrace-storm: the device ledger's audit counted dispatches
    #     whose batch signature missed every compiled shape — each one
    #     re-traces and re-compiles MID-RUN (unlike arm 0's one-time
    #     cold start). Checked before step-bound: a storm's compile
    #     stalls produce ring waits and full queues too, and the lever
    #     is shape hygiene, not a faster step.
    retraces = int(counters.get("device.retraces", 0))
    if retraces >= max(1, int(retrace_storm)):
        return Verdict(
            "retrace-storm",
            f"device.retraces={retraces} (threshold {retrace_storm}): "
            "batch shapes keep missing the compiled ladder and "
            "re-compile mid-run — the ledger's retrace events name the "
            "offending signatures",
            "bucket the ragged tails (pad_to_bucket / driver "
            "pad_partial=True), widen buckets= to cover the observed "
            "shapes, or AOT-compile the full ladder "
            "(TrainDriver.build(aot=True))",
            shares,
        )

    # 0c. memory-bound: live HBM headroom collapsing (the reporter-tick
    #     device.memory_stats() poll). Before step-bound for the same
    #     reason: an allocator running at the wall thrashes and stalls
    #     dispatches, and the fix is memory, not compute.
    headroom = gauges.get("device.hbm_headroom_frac")
    if headroom is not None and float(headroom) < hbm_headroom_floor:
        temp = float(gauges.get("device.temp_bytes", 0) or 0)
        peak = float(gauges.get("device.hbm_peak_bytes", 0) or 0)
        temp_dominant = peak > 0 and temp / peak > 0.5
        culprit = (
            "step temporaries dominate the compiled peak "
            f"(temp {temp / peak:.0%} of it)" if temp_dominant
            else "resident state (params/optimizer/batches), not step "
            "temporaries, holds the memory"
        )
        return Verdict(
            "memory-bound",
            f"device.hbm_headroom_frac={float(headroom):.1%} < floor "
            f"{hbm_headroom_floor:.0%}: {culprit}",
            "shrink batch/chunk or remat the step if temporaries "
            "dominate; shard state over the mesh (fsdp) or drop "
            "optimizer precision if resident state does — see "
            "docs/performance.md 'Reading the device ledger'",
            shares,
        )

    # 1. step-bound (specific evidence): the dispatch ring genuinely
    #    filling — these signals implicate the STEP itself, so they
    #    outrank the generic backpressure arm below (which any
    #    downstream-of-queue bottleneck also produces).
    depth_hwm = int(gauges.get("ingest.queue_depth_hwm", 0))
    backpressured = full_waits > 0 or (
        prefetch is not None and prefetch > 0 and depth_hwm >= prefetch
    )

    def step_verdict():
        return Verdict(
            "step-bound",
            f"ingest.queue_full_waits={full_waits}, "
            f"queue_depth_hwm={depth_hwm}, "
            f"ring_wait share={shares['driver.ring_wait']:.0%}, "
            f"host_blocks={host_blocks}: the train step can't keep up "
            "with ingest",
            "raise chunk/inflight, shrink the model, or add chips",
            shares,
        )

    if shares["driver.ring_wait"] > 0.35 or (
        host_blocks > 0 and shares["train.dispatch"] > 0.35
    ):
        return step_verdict()

    # 2. feed-bound: host→device transfer throttling the loop. Checked
    #    BEFORE the backpressure step-bound arm: a slow feed fills the
    #    ingest queue too, and its own counters are the more specific
    #    evidence.
    if throttle_blocks > 0 and (
        shares["feed.throttle_wait"] + shares["feed.place"] > 0.25
    ):
        return Verdict(
            "feed-bound",
            f"feed.throttle_blocks={throttle_blocks}, "
            f"throttle_wait+place share="
            f"{shares['feed.throttle_wait'] + shares['feed.place']:.0%}: "
            "host->device transfer is the wall",
            "shrink wire bytes (tile/pal encoding), raise chunk, or "
            "check link weather",
            shares,
        )

    # 3. decode-bound: the standalone decode jit dominates.
    others = max(
        shares["ingest.recv"], shares["ingest.queue_wait"],
        shares["feed.place"], shares["feed.throttle_wait"],
        shares["train.dispatch"], shares["driver.ring_wait"],
        shares["echo.wait_fresh"], shares["train.compile_ms"],
    )
    if shares["decode.dispatch"] > 0.30 and shares["decode.dispatch"] >= others:
        return Verdict(
            "decode-bound",
            f"decode.dispatch share={shares['decode.dispatch']:.0%} "
            "dominates the loop",
            "fuse the decode into the step (emit_packed + "
            "make_fused_tile_step — run-length 'ndr' wire frames then "
            "expand in-jit too) or revisit tile geometry",
            shares,
        )

    # 3b. step-bound (generic backpressure): ingest blocked on a full
    #     queue — or the depth high-water mark pinned at the known
    #     bound — while the consumer barely waits on it. Reached only
    #     once feed and decode have been ruled out, because ANY
    #     downstream-of-queue bottleneck produces this signature.
    if backpressured and shares["ingest.queue_wait"] < 0.15:
        return step_verdict()

    # 4/5. consumer starving: gate on ingest.queue_wait (the consumer-
    #      observed wait) or echo.wait_fresh (the echoing draw loop's
    #      own starvation span) — NOT ingest.recv, which accrues
    #      concurrently in N worker threads (N shards blocked in recv
    #      can bank ~N x wall of span time) and would misclassify a
    #      healthy sharded run as starving; it only corroborates via
    #      the reason string.
    starving = (
        shares["ingest.queue_wait"] > 0.30
        or shares["echo.wait_fresh"] > 0.30
    )
    echo_fresh = int(counters.get("echo.fresh", 0))
    echo_echoed = int(counters.get("echo.echoed", 0))
    echo_active = echo_fresh + echo_echoed > 0
    if starving:
        if staleness_p95_s is not None and staleness_p95_s >= stale_wire_s:
            return Verdict(
                "wire-bound",
                f"consumer starving (queue_wait share="
                f"{shares['ingest.queue_wait']:.0%}) and frames arrive "
                f"{staleness_p95_s * 1e3:.0f} ms old (p95): the "
                "socket/codec path is slow, not the producers",
                "enable wire compression (compress_level zlib, or "
                "compress_rle for run-heavy frames — near-free "
                "inflate, in-jit on the fused path), raise "
                "ingest_workers (whose shared inflate pool pipelines "
                "decode-ahead; wire.inflate_ms shows the host decode "
                "cost), or fix the link",
                shares,
            )
        fresh = (
            f"{staleness_p95_s * 1e3:.0f} ms old (p95)"
            if staleness_p95_s is not None else "unstamped"
        )
        if echo_active:
            # The echo arm: same producer-shaped starvation, but a data-
            # echoing reservoir sits between it and the step. Saturated
            # (the draw loop blocked on its budget) means echoing already
            # gives all it may; unsaturated means the step rate is being
            # sustained and fresh-data diversity is the real limit.
            sat = int(counters.get("echo.saturated_waits", 0))
            factor = round(
                (echo_fresh + echo_echoed) / max(echo_fresh, 1), 2
            )
            if sat > 0 or shares["echo.wait_fresh"] > 0.30:
                return Verdict(
                    "echo-saturated",
                    f"echo budget exhausted {sat} times "
                    f"(wait_fresh share={shares['echo.wait_fresh']:.0%}, "
                    f"echo factor {factor}): the reservoir can't echo "
                    "any further under its budget",
                    "raise producer instances (blendjax.fleet autoscales "
                    "on this verdict), reservoir capacity, or "
                    "max_echo_factor",
                    shares,
                )
            return Verdict(
                "producer-bound",
                f"producer-bound, echo-mitigated: frames arrive fresh "
                f"({fresh}) at a fraction of the step rate, and the "
                f"reservoir echoes each {factor}x to keep the step fed "
                f"(unique fraction "
                f"{echo_fresh / (echo_fresh + echo_echoed):.0%})",
                "launch more producer instances for fresh-data "
                "diversity; the step rate itself is already sustained",
                shares,
            )
        return Verdict(
            "producer-bound",
            f"consumer starving (queue_wait share="
            f"{shares['ingest.queue_wait']:.0%}) while frames arrive "
            f"fresh ({fresh}): producers don't render fast enough",
            "launch more producer instances — by hand or via "
            "blendjax.fleet.FleetController, which autoscales on this "
            "verdict — cheapen the scene/render, or absorb the gap "
            "with data echoing (blendjax.data.EchoingPipeline)",
            shares,
        )

    return Verdict(
        "balanced",
        "no single stage dominates",
        "nothing to fix; scale the workload to find the next wall",
        shares,
    )


def diagnose_current(driver: dict | None = None,
                     stale_wire_s: float = DEFAULT_STALE_WIRE_S,
                     prefetch: int | None = None) -> Verdict:
    """Diagnose the live process-wide registries (the convenience the
    :class:`blendjax.obs.reporter.StatsReporter` thread and
    ``StreamDataPipeline.doctor()`` call)."""
    from blendjax.obs.lineage import lineage
    from blendjax.utils.metrics import metrics

    return diagnose(
        metrics.report(),
        driver=driver,
        staleness_p95_s=lineage.staleness_p95_s(),
        stale_wire_s=stale_wire_s,
        prefetch=prefetch,
    )
