"""Exporters: Prometheus text endpoint, JSONL snapshots, Chrome trace.

Everything here is stdlib-only (``http.server``, ``json``, ``re``) so a
producer process — Blender's Python — can export its own metrics
without jax, zmq, or numpy, and CI can smoke it on the CPU wheel.

Three sinks, one source (:meth:`blendjax.utils.metrics.Metrics.report`
plus the optional :meth:`blendjax.obs.lineage.FrameLineage.report`):

- :func:`prometheus_text` / :func:`start_http_exporter` — the pull
  model: a ``GET /metrics`` endpoint in Prometheus text exposition
  format (counters as ``_total``, gauges as-is, histograms as native
  cumulative ``_bucket``/``_sum``/``_count`` series, per-producer
  lineage as labeled series with bounded label cardinality).
- :class:`JsonlExporter` — the archive model: append one
  timestamped JSON line per snapshot (the shape ``BENCH_r0*.json``
  consumers already parse, now available continuously).
- :func:`chrome_trace` / :func:`write_chrome_trace` — the deep-dive
  model: span events as Chrome/Perfetto "complete" (``ph: "X"``)
  events, loadable in ``chrome://tracing`` / ui.perfetto.dev next to a
  ``jax.profiler`` trace of the same run (enable event recording first:
  ``metrics.enable_span_events()``).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from blendjax.utils.metrics import Metrics, metrics

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _prom_name(name: str, prefix: str = "blendjax_") -> str:
    """Sanitize a dotted metric name into the Prometheus grammar
    (``wire.raw_bytes`` -> ``blendjax_wire_raw_bytes``)."""
    out = prefix + _NAME_RE.sub("_", name)
    if not re.match(r"[a-zA-Z_:]", out[0]):
        out = "_" + out
    return out


def _num(v) -> str:
    """Prometheus sample value rendering (floats stay floats; bools and
    non-numbers degrade to 1/0 rather than invalidating the page)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, (int, float)):
        return repr(float(v)) if isinstance(v, float) else str(v)
    return "0"


def prometheus_text(report: dict | None = None,
                    lineage_report: dict | None = None,
                    registry: Metrics = metrics) -> str:
    """Render one snapshot as Prometheus text exposition format.

    ``report`` defaults to a fresh ``registry.report()``;
    ``lineage_report`` defaults to the process-wide lineage tracker's
    snapshot. Histograms (which include every span's duration
    distribution) are emitted as native cumulative-bucket histograms in
    their source unit (seconds for spans).
    """
    if report is None:
        # include_buckets: the native-histogram buckets come from the
        # SAME locked snapshot as the counters/gauges/spans, so a page
        # can never pair one snapshot's counters with another's
        # histogram series.
        report = registry.report(include_buckets=True)
    if lineage_report is None:
        from blendjax.obs.lineage import lineage

        lineage_report = lineage.report()
    lines: list = []

    for name in sorted(report.get("counters", {})):
        pn = _prom_name(name) + "_total"
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_num(report['counters'][name])}")

    for name in sorted(report.get("gauges", {})):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_num(report['gauges'][name])}")

    # Native histograms need the raw buckets: prefer the ones carried
    # in the report snapshot itself (same lock acquisition as the
    # counters above); a caller-provided report without them falls
    # back to a fresh locked snapshot from ``registry`` — consistent
    # only if that is the registry the report came from.
    hists = report.get("histogram_buckets")
    if hists is None:
        hists = registry.histogram_buckets()
    for name in sorted(hists):
        buckets, count, total = hists[name]
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        for le, cum in buckets:
            lines.append(f'{pn}_bucket{{le="{le!r}"}} {cum}')
        lines.append(f'{pn}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{pn}_sum {_num(total)}")
        lines.append(f"{pn}_count {count}")

    if lineage_report:
        # Metric-major emission: the exposition format requires every
        # line of one metric name to form a single contiguous group —
        # interleaving btids across names (btid-major) is rejected by
        # strict parsers (promtool/OpenMetrics) exactly in the
        # multi-producer case this export exists for.
        btids = sorted(lineage_report)
        sn = "blendjax_producer_e2e_staleness_ms"
        lines.append(f"# TYPE {sn} summary")
        for btid in btids:
            stale = lineage_report[btid].get("e2e_staleness_ms", {})
            for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                if key in stale:
                    lines.append(
                        f'{sn}{{btid="{btid}",quantile="{q}"}} '
                        f"{_num(stale[key])}"
                    )
        for key, metric in (
            ("received", "blendjax_producer_frames_total"),
            ("seq_gaps", "blendjax_producer_seq_gaps_total"),
            ("seq_reorders", "blendjax_producer_seq_reorders_total"),
            ("restarts", "blendjax_producer_restarts_total"),
        ):
            lines.append(f"# TYPE {metric} counter")
            for btid in btids:
                lines.append(
                    f'{metric}{{btid="{btid}"}} '
                    f"{_num(lineage_report[btid].get(key, 0))}"
                )
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    server: "MetricsHTTPServer"

    def do_GET(self):  # noqa: N802 (http.server API)
        if self.path.split("?", 1)[0] == "/healthz":
            self._serve_health()
            return
        try:
            body = prometheus_text(registry=self.server.registry).encode()
        except Exception as e:  # never take the scrape target down
            self.send_response(500)
            self.end_headers()
            self.wfile.write(repr(e).encode())
            return
        self.send_response(200)
        self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _serve_health(self) -> None:
        """``/healthz``: 200 when the configured health source says
        healthy (or when none is configured — an exporter without SLOs
        is a metrics endpoint, not a judge), 503 on an active SLO
        breach. The body is the health source's full state as JSON, so
        a fleet controller gets the breaching rules, not just a bit."""
        health = self.server.health
        try:
            state = health() if callable(health) else None
        except Exception as e:
            self.send_response(500)
            self.end_headers()
            self.wfile.write(repr(e).encode())
            return
        if state is None:
            state = {"healthy": True, "slo": "unconfigured"}
        elif not isinstance(state, dict):
            state = {"healthy": bool(state)}
        body = json.dumps(state, default=str).encode()
        self.send_response(200 if state.get("healthy", True) else 503)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-scrape stderr spam
        del args


class MetricsHTTPServer(ThreadingHTTPServer):
    """Prometheus scrape target on a daemon thread. ``port=0`` picks a
    free port; read it back from :attr:`port`. Close with
    :meth:`close`. ``health`` is an optional zero-arg callable (e.g.
    ``StatsReporter.health``) returning a dict with a ``healthy`` key:
    it backs the ``/healthz`` endpoint (200/503) beside ``/metrics``."""

    daemon_threads = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry: Metrics = metrics, health=None):
        super().__init__((host, port), _Handler)
        self.registry = registry
        self.health = health
        self.port = self.server_address[1]
        self._thread = threading.Thread(
            target=self.serve_forever, name="blendjax-metrics-http",
            daemon=True,
        )

    def start(self) -> "MetricsHTTPServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self.shutdown()
        self.server_close()


def start_http_exporter(port: int = 0, host: str = "127.0.0.1",
                        registry: Metrics = metrics,
                        health=None) -> MetricsHTTPServer:
    """``curl http://host:port/metrics`` (and ``/healthz``, when a
    ``health`` source is given) while the pipeline runs."""
    return MetricsHTTPServer(
        host=host, port=port, registry=registry, health=health
    ).start()


class JsonlExporter:
    """Append timestamped report snapshots to a JSONL file (one JSON
    object per line; safe to tail while the run is live).

    ``rotate_bytes`` bounds the archive: once the file reaches that
    size it is rotated to ``<path>.1`` (older generations shift to
    ``.2`` … ``.<keep>``, the oldest deleted), so a long run's
    ``run_stats.jsonl`` can no longer grow without limit. ``None``
    (the default here; :class:`blendjax.obs.reporter.StatsReporter`
    turns rotation on) keeps the historical append-forever behavior."""

    def __init__(self, path: str, rotate_bytes: int | None = None,
                 keep: int = 3):
        self.path = path
        self.rotate_bytes = int(rotate_bytes) if rotate_bytes else None
        self.keep = max(1, int(keep))
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()

    def write(self, report: dict | None = None,
              extra: dict | None = None,
              registry: Metrics = metrics) -> None:
        if report is None:
            report = registry.report()
        rec = {"t": time.time(), "report": report}
        if extra:
            rec.update(extra)
        line = json.dumps(rec, default=str)
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line + "\n")
                size = f.tell()
            if self.rotate_bytes and size >= self.rotate_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        # shift .1 -> .2 ... .<keep-1> -> .<keep> (overwriting the
        # oldest), then the live file becomes .1 — a fresh append
        # starts the next generation.
        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")


def chrome_trace(events: list | None = None,
                 registry: Metrics = metrics,
                 frame_traces=None) -> dict:
    """Span events → a Chrome trace object (``traceEvents`` with
    ``ph: "X"`` complete events, microsecond timestamps on the
    ``perf_counter`` clock). Load in ui.perfetto.dev beside a
    ``jax.profiler`` trace of the same window to line host-side ingest
    stages up with device activity.

    Completed distributed frame traces (:mod:`blendjax.obs.trace`) are
    merged in as cross-process lanes with producer→consumer flow
    arrows: pass a :class:`~blendjax.obs.trace.FrameTraceCollector` as
    ``frame_traces``, or leave the default — exporting the process-wide
    registry pulls the process-wide ``tracer`` in automatically
    (``frame_traces=False`` opts out)."""
    if events is None:
        events = registry.span_events()
    pid = os.getpid()
    trace_events = [
        {
            "name": name,
            "cat": name.split(".", 1)[0],
            "ph": "X",
            "ts": round(t0 * 1e6, 3),
            "dur": round(dur * 1e6, 3),
            "pid": pid,
            "tid": tid,
        }
        for name, t0, dur, tid in events
    ]
    if frame_traces is None and registry is metrics:
        from blendjax.obs.trace import tracer as frame_traces
    if frame_traces:
        trace_events.extend(frame_traces.chrome_events())
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, events: list | None = None,
                       registry: Metrics = metrics,
                       frame_traces=None) -> int:
    """Write the Chrome trace JSON; returns the event count. Requires
    event recording to have been on (``metrics.enable_span_events()``)
    or completed frame traces in the collector — without either the
    trace is valid but empty."""
    obj = chrome_trace(events, registry=registry, frame_traces=frame_traces)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f)
    return len(obj["traceEvents"])
