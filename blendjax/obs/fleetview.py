"""Fleet-wide observability for mesh runs: one report across processes.

On a multi-host mesh every process runs its own ingest shard, so the
doctor, lineage, and trace registries each hold ONE process's view.
This module assembles them into a single fleet report:

- :func:`process_snapshot` — the local process's metrics/lineage/trace
  snapshot plus its doctor verdict, tagged with the jax process index;
- :func:`gather_fleet_snapshots` — every process's snapshot on every
  process (single-process runs short-circuit to the local one;
  multihost runs exchange JSON over two ``process_allgather`` rounds —
  length, then padded bytes — so uneven snapshot sizes agree);
- :func:`fleet_report` — the aggregate: per-process verdicts, lineage
  merged under ``p{index}/{btid}`` keys, fleet-summed seq gaps and
  trace completions, and a dominant verdict for dashboards.

Producer-side ref divergence is NOT smoothed over here: the pipeline's
multihost digest check (``TileStreamDecoder._assert_fleet_digest``)
raises before any report exists — aggregation only ever sees fleets
whose reference content already agreed.

Module import stays jax-free (the :mod:`blendjax.obs` contract);
process queries and the allgather are deferred into the calls.
"""

from __future__ import annotations

import json


def _process_info() -> tuple:
    """(index, count) of this jax process; (0, 1) when jax is absent or
    uninitialized (producer processes, unit tests without a backend)."""
    try:
        import jax

        return int(jax.process_index()), int(jax.process_count())
    except Exception:
        return 0, 1


def process_snapshot(driver: dict | None = None,
                     prefetch: int | None = None) -> dict:
    """The local process's observability snapshot, process-tagged.

    ``driver`` may be a ``TrainDriver.stats`` dict so ring-full blocks
    feed the verdict; ``prefetch`` is the ingest queue bound (see
    ``diagnose``)."""
    from blendjax.obs.doctor import diagnose_current
    from blendjax.obs.lineage import lineage
    from blendjax.obs.trace import tracer
    from blendjax.utils.metrics import metrics

    index, count = _process_info()
    return {
        "process": index,
        "processes": count,
        "metrics": metrics.report(),
        "lineage": lineage.report(),
        "seq_gaps": lineage.total_gaps(),
        "trace": tracer.report(),
        "verdict": diagnose_current(
            driver=driver, prefetch=prefetch
        ).render(),
        "driver": dict(driver) if driver else None,
    }


def gather_fleet_snapshots(snapshot: dict | None = None,
                           driver: dict | None = None,
                           prefetch: int | None = None) -> list:
    """Every process's snapshot, in process-index order, available on
    every process. Pass a pre-built ``snapshot`` to gather something
    custom; by default each process contributes its own
    :func:`process_snapshot`."""
    local = snapshot if snapshot is not None else process_snapshot(
        driver=driver, prefetch=prefetch
    )
    _, count = _process_info()
    if count <= 1:
        return [local]
    import numpy as np
    from jax.experimental import multihost_utils

    # Variable-size JSON over fixed-size collectives: agree on the max
    # length first, then allgather the zero-padded byte vectors. Two
    # rounds, no coordinator, no second socket.
    data = np.frombuffer(
        json.dumps(local, default=str).encode("utf-8"), dtype=np.uint8
    )
    lens = np.asarray(
        multihost_utils.process_allgather(
            np.asarray([data.size], np.int32)
        )
    ).reshape(-1)
    padded = np.zeros(int(lens.max()), np.uint8)
    padded[: data.size] = data
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    return [
        json.loads(bytes(gathered[i][: int(lens[i])]).decode("utf-8"))
        for i in range(len(lens))
    ]


def fleet_report(snapshots: list) -> dict:
    """Aggregate per-process snapshots into one fleet view.

    Lineage entries are re-keyed ``p{process}/{btid}`` (two processes
    legitimately track different producers — or the same producer via
    different ingest shards — so entries are namespaced, never merged
    by btid); gap/trace counters sum exactly; verdicts stay visible
    per process with a ``dominant`` pick for one-line summaries (the
    most common actionable kind, falling back to the most common
    overall)."""
    lineage: dict = {}
    verdicts: dict = {}
    seq_gaps = 0
    trace_completed = 0
    trace_unordered = 0
    for snap in snapshots:
        p = int(snap.get("process", 0))
        for btid, entry in (snap.get("lineage") or {}).items():
            lineage[f"p{p}/{btid}"] = entry
        seq_gaps += int(snap.get("seq_gaps") or 0)
        tr = snap.get("trace") or {}
        trace_completed += int(tr.get("completed") or 0)
        trace_unordered += int(tr.get("unordered") or 0)
        verdicts[f"p{p}"] = snap.get("verdict")
    kinds: dict = {}
    for v in verdicts.values():
        if not v:
            continue
        kind = v.split("—")[0].removeprefix("doctor:").strip()
        kinds[kind] = kinds.get(kind, 0) + 1
    actionable = {
        k: n for k, n in kinds.items() if k not in ("balanced", "idle")
    }
    pool = actionable or kinds
    dominant = max(pool, key=pool.get) if pool else None
    return {
        "processes": len(snapshots),
        "verdicts": verdicts,
        "dominant_verdict": dominant,
        "lineage": lineage,
        "seq_gaps": seq_gaps,
        "trace_completed": trace_completed,
        "trace_unordered": trace_unordered,
    }


__all__ = ["process_snapshot", "gather_fleet_snapshots", "fleet_report"]
