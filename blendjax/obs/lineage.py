"""Frame lineage: publish stamps → per-producer staleness and gap counts.

Dapper-style cross-process latency attribution for the data stream:
``DataPublisherSocket`` stamps every message with a wall + monotonic
publish time and a per-producer monotonic sequence number (and
periodically piggybacks a telemetry snapshot of the producer's own
metrics registry — see :mod:`blendjax.transport.channels`); the
consumer-side receive loop hands each decoded message to
:meth:`FrameLineage.ingest`, which turns the stamps into:

- a per-producer **end-to-end staleness histogram** (consumer receive
  wall time minus producer publish wall time — how old a frame already
  is when it reaches ingest; the wire/producer discriminator the stall
  doctor uses),
- **seq-gap / reorder counters** (``wire.seq_gaps`` counts *dropped*
  messages exactly: the PUSH/PULL data plane is at-most-once by design,
  so a nonzero gap count on a clean local run is a bug, which is why
  the bench-smoke CI job asserts it stays 0),
- a **fleet telemetry view**: the latest piggybacked producer snapshot
  per producer, aggregated without a second socket.

Sequence tracking is PER PRODUCER (keyed by ``btid``), so the sharded
ingest pool's round-robin partitioning — which interleaves producers
across shards arbitrarily — never manufactures false gaps: each
producer's stream lands whole on exactly one shard socket, and a gap is
only counted when that producer's own numbering skips.

Cardinality note: per-producer state lives in this tracker's own dict
(bounded by the real fleet size), NOT as dynamic metric-registry names —
the shape bjx-lint BJX107 exists to enforce.
"""

from __future__ import annotations

# bjx: hot-path (ingest() runs once per received message: BJX102 flags
# any blocking device sync added to this module)

import threading
import time

# The sampled frame-trace context is a publish stamp too: strip_stamps
# removes it on replay — recorded wall stamps would read as hours of
# wire latency in the trace histograms. Imported from its defining
# module so a rename can never desynchronize the strip list.
from blendjax.obs.trace import TRACE_KEY
from blendjax.utils.metrics import Histogram, metrics

# Wire keys (stamped by DataPublisherSocket, popped here). Underscored
# like the other wire-control keys (`_batched`, `_prebatched`) so they
# can never collide with a user field.
SEQ_KEY = "_seq"
PUB_WALL_KEY = "_pub_wall"
PUB_MONO_KEY = "_pub_mono"
TELEMETRY_KEY = "_telemetry"

# Deliberately NOT a stamp: "_scenario" (blendjax.scenario). Lineage
# stamps describe the TRANSPORT of a frame (when/in what order it was
# published) and go stale on replay; the scenario stamp describes the
# CONTENT (which distribution rendered it) and must survive replay so
# recorded streams re-account per scenario deterministically.
_STAMP_KEYS = (SEQ_KEY, PUB_WALL_KEY, PUB_MONO_KEY, TELEMETRY_KEY,
               TRACE_KEY)


def strip_stamps(msg: dict) -> dict:
    """Remove lineage/telemetry stamps without accounting them — the
    replay path (recorded wall times would read as hours of staleness)
    and any consumer that wants the pre-PR-4 message shape back."""
    for k in _STAMP_KEYS:
        msg.pop(k, None)
    return msg


class _Producer:
    """Per-producer lineage state (guarded by the tracker's lock)."""

    __slots__ = (
        "received", "last_seq", "gaps", "reorders", "restarts",
        "staleness", "telemetry", "telemetry_at", "last_pub_wall",
        "last_pub_mono",
    )

    def __init__(self) -> None:
        self.received = 0
        self.last_seq: int | None = None
        self.gaps = 0
        self.reorders = 0
        self.restarts = 0
        self.staleness = Histogram()  # seconds
        self.telemetry: dict | None = None
        self.telemetry_at: float | None = None
        self.last_pub_wall: float | None = None
        self.last_pub_mono: float | None = None


class FrameLineage:
    """Consumer-side lineage aggregator (one per process, like the
    metrics registry; thread-safe for the sharded ingest pool)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._producers: dict = {}

    def ingest(self, msg: dict, track_gaps: bool = True) -> None:
        """Pop the publish stamps off one decoded message and account
        them. Messages without stamps (pre-PR-4 producers, reference
        pickle producers) pass through untouched — lineage is additive,
        not a wire-compat break.

        ``track_gaps=False`` skips the sequence bookkeeping (gaps,
        reorders, restarts) while keeping staleness and telemetry: the
        mode for consumers that share a producer fan-in with peers
        (each sees a strided subsequence — see
        :class:`blendjax.data.stream.RemoteStream`)."""
        seq = msg.pop(SEQ_KEY, None)
        wall = msg.pop(PUB_WALL_KEY, None)
        mono = msg.pop(PUB_MONO_KEY, None)
        tele = msg.pop(TELEMETRY_KEY, None)
        if seq is None and wall is None and tele is None:
            return
        now = time.time()
        btid = msg.get("btid")
        stale = None
        gap = 0
        reordered = restarted = False
        with self._lock:
            # get-then-insert, not setdefault: setdefault would allocate
            # a throwaway _Producer (+ Histogram) on EVERY message for a
            # dict hit that succeeds ~always — churn on the per-frame
            # hot path.
            p = self._producers.get(btid)
            if p is None:
                p = self._producers[btid] = _Producer()
            p.received += 1
            if wall is not None:
                stale = now - float(wall)
                p.staleness.observe(stale)
                p.last_pub_wall = float(wall)
            if mono is not None:
                p.last_pub_mono = float(mono)
            if seq is not None and track_gaps:
                seq = int(seq)
                if p.last_seq is None:
                    p.last_seq = seq
                else:
                    expected = p.last_seq + 1
                    if seq > expected:
                        gap = seq - expected
                        p.gaps += gap
                        p.last_seq = seq
                    elif seq == expected:
                        p.last_seq = seq
                    elif seq == 0:
                        # A fresh publisher numbers from 0: this is a
                        # producer RESTART (launcher respawn reuses the
                        # btid), not a reorder. Without the reset, every
                        # post-respawn message would read as a reorder
                        # until seq caught the dead instance's maximum —
                        # and real drops in that window would be
                        # invisible.
                        restarted = True
                        p.restarts += 1
                        p.last_seq = 0
                    else:
                        # late delivery of an older number: a reorder,
                        # not a drop (and not a negative gap). last_seq
                        # keeps the high-water mark.
                        reordered = True
                        p.reorders += 1
            if tele is not None:
                p.telemetry = tele
                p.telemetry_at = now
        # Registry mirrors OUTSIDE the lineage lock (constant names —
        # the fleet-wide aggregates beside the per-producer detail).
        if stale is not None:
            metrics.observe("wire.e2e_staleness_s", stale)
        if gap:
            metrics.count("wire.seq_gaps", gap)
        if reordered:
            metrics.count("wire.seq_reorders")
        if restarted:
            metrics.count("wire.producer_restarts")

    # -- snapshots ------------------------------------------------------------

    def report(self) -> dict:
        """Per-producer lineage snapshot, keyed by ``str(btid)``:
        staleness summary (ms percentiles), exact gap/reorder counts,
        and the latest piggybacked telemetry."""
        with self._lock:
            out = {}
            for btid, p in self._producers.items():
                s = p.staleness.summary()
                entry = {
                    "received": p.received,
                    "last_seq": p.last_seq,
                    "seq_gaps": p.gaps,
                    "seq_reorders": p.reorders,
                    "restarts": p.restarts,
                    "e2e_staleness_ms": {
                        "count": s["count"],
                        "p50": round(s["p50"] * 1e3, 3),
                        "p95": round(s["p95"] * 1e3, 3),
                        "p99": round(s["p99"] * 1e3, 3),
                        "max": round(s["max"] * 1e3, 3) if s["count"] else 0.0,
                    },
                }
                if p.telemetry is not None:
                    entry["telemetry"] = p.telemetry
                    entry["telemetry_age_s"] = round(
                        time.time() - (p.telemetry_at or 0.0), 3
                    )
                out[str(btid)] = entry
            return out

    def staleness_p95_s(self) -> float | None:
        """Worst per-producer staleness p95 in seconds (None when no
        stamped frames were seen) — the doctor's wire/producer
        discriminator."""
        with self._lock:
            vals = [
                p.staleness.quantile(0.95)
                for p in self._producers.values()
                if p.staleness.count
            ]
        return max(vals) if vals else None

    def total_gaps(self) -> int:
        with self._lock:
            return sum(p.gaps for p in self._producers.values())

    # -- elastic membership ---------------------------------------------------

    def register(self, btid) -> None:
        """Pre-register a producer (fleet admission): its entry exists
        before the first frame, so the fleet view shows a joining
        member immediately. ``ingest`` would create it lazily anyway —
        a brand-new btid starts tracking at its first observed seq, so
        joining mid-run can never read as a drop storm."""
        with self._lock:
            if btid not in self._producers:
                self._producers[btid] = _Producer()

    def retire(self, btid) -> bool:
        """Drop a producer's lineage state on clean retirement (fleet
        scale-down). Without this a retired slot's stale seq state
        would (a) keep a dead member in every ``report()`` forever and
        (b) — if the btid is ever reused by a NEW producer numbering
        from its own 0 — count the rejoin as a restart plus reorder
        noise instead of fresh tracking. Returns True when state
        existed. NOT for crashes: a respawned producer reuses its slot
        and the seq==0 restart detection is the correct accounting
        there."""
        with self._lock:
            return self._producers.pop(btid, None) is not None

    def reset(self) -> None:
        with self._lock:
            self._producers.clear()

    # -- session snapshot (blendjax.checkpoint) -------------------------------

    def state_dict(self) -> dict:
        """Per-producer seq positions + exact counters for the session
        store. Staleness histograms are deliberately dropped: they
        describe the dead process's transport window, and stale
        percentiles would poison the resumed doctor's wire/producer
        discrimination. Keys keep their native type (btids are ints on
        the wire; msgpack carries them)."""
        with self._lock:
            return {
                btid: {
                    "received": p.received,
                    "last_seq": p.last_seq,
                    "gaps": p.gaps,
                    "reorders": p.reorders,
                    "restarts": p.restarts,
                }
                for btid, p in self._producers.items()
            }

    def load_state_dict(self, d: dict) -> None:
        """Restore seq positions so cross-restart accounting stays
        exact: a producer that kept publishing while the consumer was
        down resumes gap tracking from its last counted seq, and a
        producer that restarted alongside the consumer (fresh
        numbering from 0) is detected as a RESTART by the existing
        seq==0 arm — never as a gap storm."""
        with self._lock:
            for btid, e in d.items():
                p = self._producers.get(btid)
                if p is None:
                    p = self._producers[btid] = _Producer()
                p.received = int(e.get("received", 0))
                seq = e.get("last_seq")
                p.last_seq = int(seq) if seq is not None else None
                p.gaps = int(e.get("gaps", 0))
                p.reorders = int(e.get("reorders", 0))
                p.restarts = int(e.get("restarts", 0))


# Default process-wide tracker (mirrors ``blendjax.utils.metrics.metrics``).
lineage = FrameLineage()
