"""StatsReporter: a background thread that keeps the operator informed.

Every ``interval_s`` it takes one consistent metrics snapshot, runs the
stall doctor over it, logs the one-line verdict, and (optionally)
appends the full snapshot to a JSONL archive — the always-on version of
what ``bench.py`` stamps into its stage breakdowns, for long training
runs that never go through the bench harness.

Since the SLO watchdog landed the reporter is also the evaluation
cadence for declarative health rules: pass ``slos=[...]`` (specs or
:class:`~blendjax.obs.watchdog.Slo` objects) and each tick checks them
against the fresh snapshot; a sustained breach triggers the
:class:`~blendjax.obs.watchdog.FlightRecorder` (``flight_dir=...``)
with the reporter's last-K history ring as evidence, and
:meth:`health` backs the HTTP exporter's ``/healthz`` (200/503).
"""

from __future__ import annotations

import collections
import time

import threading

from blendjax.obs.doctor import diagnose
from blendjax.obs.exporters import JsonlExporter
from blendjax.obs.lineage import FrameLineage
from blendjax.obs.lineage import lineage as default_lineage
from blendjax.utils.metrics import Metrics, metrics
from blendjax.utils.logging import get_logger

logger = get_logger("obs")

# Default JSONL archive bound: ~64 MiB per generation, 3 generations
# kept. A 10s-tick run writes a few KB per line, so this is weeks of
# history — while an unbounded archive on a long-lived trainer is a
# disk-full incident waiting (the pre-rotation behavior).
DEFAULT_ROTATE_BYTES = 64 * 1024 * 1024


class StatsReporter:
    """Periodic doctor verdict + optional JSONL snapshot archive,
    SLO evaluation, and breach-triggered flight recording.

    >>> rep = StatsReporter(
    ...     interval_s=10, jsonl_path="run_stats.jsonl",
    ...     slos=["rate(wire.seq_gaps) == 0",
    ...           "p95(wire.e2e_staleness_s) <= 0.5 @ 30"],
    ...     flight_dir="flight-records",
    ... )
    >>> rep.start()
    ... # train ...  (serve rep.health via start_http_exporter(health=...))
    >>> rep.stop()

    ``driver_stats`` may be a zero-arg callable returning a
    ``TrainDriver.stats`` dict so ring-full blocks feed the diagnosis.
    ``history`` bounds the ring of recent (snapshot, verdict) pairs the
    flight recorder dumps on a breach.
    """

    def __init__(
        self,
        interval_s: float = 10.0,
        registry: Metrics = metrics,
        lineage: FrameLineage = default_lineage,
        jsonl_path: str | None = None,
        driver_stats=None,
        log=logger,
        slos=None,
        flight_dir: str | None = None,
        flight_profile_s: float = 0.0,
        history: int = 32,
        jsonl_rotate_bytes: int | None = DEFAULT_ROTATE_BYTES,
        jsonl_keep: int = 3,
        fleet=None,
        checkpoint_on_breach=None,
    ):
        self.interval_s = float(interval_s)
        self.registry = registry
        self.lineage = lineage
        self.driver_stats = driver_stats
        # Optional FleetController (or anything with .state() -> dict):
        # its instance count / streaks / scale-event log are archived
        # beside the verdict each tick, so a JSONL trail answers "what
        # did the fleet do when the verdict flipped" without correlating
        # two logs.
        self.fleet = fleet
        self.log = log
        self._jsonl = (
            JsonlExporter(
                jsonl_path, rotate_bytes=jsonl_rotate_bytes,
                keep=jsonl_keep,
            )
            if jsonl_path else None
        )
        # Last-K (snapshot, verdict) ring — always on (cheap: K dict
        # refs), so a flight record has history even when the breach
        # lands on the first watchdog tick after a long healthy run.
        self.history: collections.deque = collections.deque(
            maxlen=max(1, int(history))
        )
        self.watchdog = None
        if slos:
            from blendjax.obs.watchdog import SloWatchdog

            self.watchdog = SloWatchdog(slos)
        self.flight = None
        if flight_dir:
            from blendjax.obs.watchdog import FlightRecorder

            # checkpoint_on_breach: zero-arg callable fired inside the
            # breach bundle dump — wire ``driver.request_checkpoint``
            # so a breached run snapshots at its next step boundary
            # (docs/checkpointing.md "Checkpoint on breach").
            self.flight = FlightRecorder(
                flight_dir, profile_s=flight_profile_s,
                checkpoint=checkpoint_on_breach,
            )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_verdict = None
        # The reporter tick is the device ledger's runtime cadence: the
        # HBM poll runs before each snapshot, and the ledger's retrace
        # storm can trip this reporter's flight recorder.
        from blendjax.obs.devledger import ledger as _ledger

        self.ledger = _ledger
        if self.flight is not None:
            self.ledger.attach_flight(self.flight)

    def tick(self):
        """One report cycle (public so tests — and callers that want a
        verdict NOW — can run it synchronously)."""
        try:
            # device.hbm_* gauges land in the snapshot below; a no-stats
            # backend (CPU) returns None without publishing
            self.ledger.poll_memory(self.registry)
        except Exception:
            self.log.exception("device memory poll failed")
        report = self.registry.report()
        driver = self.driver_stats() if callable(self.driver_stats) else None
        verdict = diagnose(
            report, driver=driver,
            staleness_p95_s=self.lineage.staleness_p95_s(),
        )
        # Lock-free observability publish: one atomic reference
        # swap per tick; /healthz reads whole verdict objects.
        # bjx: ignore[BJX117] — atomic reference publish
        self.last_verdict = verdict
        self.log.info("%s", verdict.render())
        self.history.append({
            "t": time.time(),
            "doctor": {
                "kind": verdict.kind,
                "reason": verdict.reason,
                "shares": verdict.shares,
            },
            "report": report,
        })
        if self.watchdog is not None:
            self._evaluate_slos(report, verdict)
        if self._jsonl is not None:
            extra = {
                "doctor": {
                    "kind": verdict.kind,
                    "reason": verdict.reason,
                    "shares": verdict.shares,
                },
                "lineage": self.lineage.report(),
            }
            if self.watchdog is not None:
                extra["slo"] = self.watchdog.state()
            if self.fleet is not None:
                try:
                    extra["fleet"] = self.fleet.state()
                except Exception:
                    self.log.exception("fleet state snapshot failed")
            # Echoing runs get their accounting surfaced beside the
            # verdict (fresh/echoed counters sum exactly to drawn
            # samples; the echo-mitigated/saturated arms read these).
            echo = {
                k: v
                for src in (report.get("counters", {}),
                            report.get("gauges", {}))
                for k, v in src.items()
                if k.startswith("echo.")
            }
            if echo:
                extra["echo"] = echo
            # Device ledger family beside the verdict: the static
            # compile-time accounting gauges plus the live HBM poll and
            # retrace counter, so a JSONL trail answers "what did the
            # device look like when the verdict flipped".
            device = {
                k: v
                for src in (report.get("counters", {}),
                            report.get("gauges", {}))
                for k, v in src.items()
                if k.startswith("device.")
            }
            if device:
                extra["device"] = device
            self._jsonl.write(report, extra=extra)
        return verdict

    def _evaluate_slos(self, report: dict, verdict) -> None:
        result = self.watchdog.evaluate(report, verdict=verdict)
        # Registry mirrors: the gauge is the scrapeable health bit, the
        # counter the lifetime breach count — both constant names.
        self.registry.gauge("slo.breached", 0 if result["healthy"] else 1)
        if result["newly_breached"]:
            self.registry.count(
                "slo.breach_events", len(result["newly_breached"])
            )
            names = [s["slo"] for s in result["newly_breached"]]
            self.log.warning(
                "SLO breach: %s (values %s)",
                names,
                {s["slo"]: s["value"] for s in result["newly_breached"]},
            )
            if self.flight is not None:
                try:
                    self.flight.dump(
                        reason=f"slo-breach: {'; '.join(names)}",
                        history=list(self.history),
                        lineage_report=self.lineage.report(),
                        slo_states=result["states"],
                        registry=self.registry,
                    )
                except Exception:
                    # evidence capture must never take the reporter down
                    self.log.exception("flight-record dump failed")
        for spec in result["newly_recovered"]:
            self.log.info("SLO recovered: %s", spec)

    # -- health (the /healthz source) -----------------------------------------

    @property
    def healthy(self) -> bool:
        return self.watchdog is None or self.watchdog.healthy

    def health(self) -> dict:
        """State dict for the HTTP exporter's ``/healthz`` endpoint:
        ``start_http_exporter(health=reporter.health)``."""
        out = {
            "healthy": self.healthy,
            "verdict": getattr(self.last_verdict, "kind", None),
        }
        if self.watchdog is None:
            out["slo"] = "unconfigured"
        else:
            out["slo"] = self.watchdog.state()
        return out

    def _run(self) -> None:
        # wait-first loop: a reporter started beside an empty pipeline
        # shouldn't open with a meaningless "idle" line
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # a reporting flake must not kill the run
                self.log.exception("stats reporter tick failed")

    def start(self) -> "StatsReporter":
        assert self._thread is None, "already started"
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="blendjax-stats-reporter", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, final_tick: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_tick:
            try:
                self.tick()  # closing snapshot: the run's last word
            except Exception:
                self.log.exception("final stats tick failed")

    def __enter__(self) -> "StatsReporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
