"""StatsReporter: a background thread that keeps the operator informed.

Every ``interval_s`` it takes one consistent metrics snapshot, runs the
stall doctor over it, logs the one-line verdict, and (optionally)
appends the full snapshot to a JSONL archive — the always-on version of
what ``bench.py`` stamps into its stage breakdowns, for long training
runs that never go through the bench harness.
"""

from __future__ import annotations

import threading

from blendjax.obs.doctor import diagnose
from blendjax.obs.exporters import JsonlExporter
from blendjax.obs.lineage import FrameLineage
from blendjax.obs.lineage import lineage as default_lineage
from blendjax.utils.metrics import Metrics, metrics
from blendjax.utils.logging import get_logger

logger = get_logger("obs")


class StatsReporter:
    """Periodic doctor verdict + optional JSONL snapshot archive.

    >>> rep = StatsReporter(interval_s=10, jsonl_path="run_stats.jsonl")
    >>> rep.start()
    ... # train ...
    >>> rep.stop()

    ``driver_stats`` may be a zero-arg callable returning a
    ``TrainDriver.stats`` dict so ring-full blocks feed the diagnosis.
    """

    def __init__(
        self,
        interval_s: float = 10.0,
        registry: Metrics = metrics,
        lineage: FrameLineage = default_lineage,
        jsonl_path: str | None = None,
        driver_stats=None,
        log=logger,
    ):
        self.interval_s = float(interval_s)
        self.registry = registry
        self.lineage = lineage
        self.driver_stats = driver_stats
        self.log = log
        self._jsonl = JsonlExporter(jsonl_path) if jsonl_path else None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_verdict = None

    def tick(self):
        """One report cycle (public so tests — and callers that want a
        verdict NOW — can run it synchronously)."""
        report = self.registry.report()
        driver = self.driver_stats() if callable(self.driver_stats) else None
        verdict = diagnose(
            report, driver=driver,
            staleness_p95_s=self.lineage.staleness_p95_s(),
        )
        self.last_verdict = verdict
        self.log.info("%s", verdict.render())
        if self._jsonl is not None:
            extra = {
                "doctor": {
                    "kind": verdict.kind,
                    "reason": verdict.reason,
                    "shares": verdict.shares,
                },
                "lineage": self.lineage.report(),
            }
            # Echoing runs get their accounting surfaced beside the
            # verdict (fresh/echoed counters sum exactly to drawn
            # samples; the echo-mitigated/saturated arms read these).
            echo = {
                k: v
                for src in (report.get("counters", {}),
                            report.get("gauges", {}))
                for k, v in src.items()
                if k.startswith("echo.")
            }
            if echo:
                extra["echo"] = echo
            self._jsonl.write(report, extra=extra)
        return verdict

    def _run(self) -> None:
        # wait-first loop: a reporter started beside an empty pipeline
        # shouldn't open with a meaningless "idle" line
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # a reporting flake must not kill the run
                self.log.exception("stats reporter tick failed")

    def start(self) -> "StatsReporter":
        assert self._thread is None, "already started"
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="blendjax-stats-reporter", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, final_tick: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_tick:
            try:
                self.tick()  # closing snapshot: the run's last word
            except Exception:
                self.log.exception("final stats tick failed")

    def __enter__(self) -> "StatsReporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
