"""Frame-level distributed tracing: sampled end-to-end frame timelines.

PR 4's lineage answers "how old are frames on arrival, per producer";
this module answers the question lineage can't: *where does one frame's
latency go* across the whole pipeline. Following the Dapper pattern
(sampled end-to-end traces beside always-on aggregates),
``DataPublisherSocket`` stamps every ``trace_every``-th message (default
64) with a ``_trace`` context — a tiny dict riding beside the existing
``_seq``/``_pub_*`` lineage stamps — and each downstream stage appends
``[stage, t_mono, t_wall]`` in place as the frame passes through:

==================  =========================================================
stage               where it is stamped
==================  =========================================================
``publish``         ``DataPublisherSocket._stamp`` (producer process)
``recv``            ``RemoteStream.__iter__`` (after lineage accounting)
``batch``           ``HostIngest``/``ShardedHostIngest`` handing the message
                    to batch assembly (or passing a prebatched one through)
``place``           ``DeviceFeeder`` after the host->device transfer dispatch
``decode``          ``TileStreamDecoder.device_stage`` after the decode jit
                    (absent on the fused ``emit_packed`` path, where the
                    decode lives inside the train dispatch)
``reservoir_insert``  ``EchoingPipeline`` writing the sample into the ring
``reservoir_sample``  the frame's FIRST draw back out of the reservoir
``step_dispatch``   ``TrainDriver.submit``
``step_retire``     ``TrainDriver`` retiring the ring entry (terminal stage:
                    the driver hands the completed record to the collector)
==================  =========================================================

Clocks: every stamp carries BOTH ``time.monotonic()`` (duration-safe —
and comparable across processes on one host, where CLOCK_MONOTONIC is
system-wide) and ``time.time()`` (the only clock comparable across
hosts). Same-process transitions are measured on the monotonic clock;
the cross-process ``publish -> recv`` hop uses wall time, exactly like
lineage staleness.

Off the sampled path the cost is one dict lookup per message — no
allocations beyond the existing lineage stamps; ``trace_every=0``
disables stamping entirely.

:class:`FrameTraceCollector` (module-global ``tracer``, mirroring the
``metrics``/``lineage`` registries) receives completed records, feeds
the per-transition histograms (``trace.wire_ms``, ``trace.queue_ms``,
``trace.decode_ms``, ``trace.reservoir_dwell_ms``, ``trace.step_ms``),
and renders cross-process Chrome-trace output with flow arrows binding
the producer's pid lane to the consumer lanes
(:meth:`FrameTraceCollector.chrome_events`, merged into
:func:`blendjax.obs.exporters.chrome_trace`).

Import-cheap and stdlib-only, like the rest of ``blendjax.obs``.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from collections import deque

from blendjax.utils.metrics import metrics

# Wire key for the sampled trace context (underscored like the lineage
# stamps so it can never collide with a user field). Stripped on replay
# (``blendjax.obs.lineage.strip_stamps``) — recorded wall stamps would
# read as hours of wire latency.
TRACE_KEY = "_trace"

# Batch-level carrier: once a traced message is folded into a batch its
# trace context rides the batch dict (and survives the tile host stage
# inside the per-batch ``rest``/``_meta`` sidecars) under this key.
TRACES_KEY = "_traces"

TERMINAL_STAGE = "step_retire"

# Named per-transition histograms (milliseconds). ``from`` may list
# fallbacks: the first stage present in the record wins — e.g. the
# fused emit_packed path has no ``decode`` stamp, and a non-echo
# pipeline has no reservoir stages; transitions whose endpoints are
# absent are simply not observed.
_TRANSITIONS = (
    ("trace.wire_ms", ("publish",), "recv", "wall"),
    ("trace.queue_ms", ("recv",), "batch", "mono"),
    ("trace.decode_ms", ("place", "batch"), "decode", "mono"),
    ("trace.reservoir_dwell_ms", ("reservoir_insert",),
     "reservoir_sample", "mono"),
    ("trace.step_ms", ("step_dispatch",), "step_retire", "mono"),
)


def make_trace(trace_id: str, btid=None, pid: int | None = None) -> dict:
    """A fresh trace context with its ``publish`` stamp. Producers
    (Blender's Python) inline this shape rather than importing the
    module; it exists for tests and non-socket sources."""
    return {
        "id": trace_id,
        "btid": btid,
        "pid": os.getpid() if pid is None else pid,
        "stages": [["publish", time.monotonic(), time.time()]],
    }


def stage(tr: dict, name: str) -> None:
    """Append one ``[stage, t_mono, t_wall]`` stamp in place."""
    tr["stages"].append([name, time.monotonic(), time.time()])


def iter_traces(batch: dict):
    """Yield every trace context reachable from a batch dict: the
    batch-level ``_traces`` list, plus any carried inside ``_meta``
    when it is a list of sidecar dicts (the tile chunk-group form,
    where per-batch ``rest`` dicts ride as ``_meta`` entries)."""
    trs = batch.get(TRACES_KEY)
    if trs:
        yield from trs
    meta = batch.get("_meta")
    if isinstance(meta, list):
        for m in meta:
            if isinstance(m, dict):
                inner = m.get(TRACES_KEY)
                if inner:
                    yield from inner


def stamp_batch(batch: dict, name: str) -> None:
    """Stamp ``name`` onto every trace riding a batch (fast no-op for
    the untraced common case)."""
    trs = batch.get(TRACES_KEY)
    if trs:
        for tr in trs:
            stage(tr, name)
    meta = batch.get("_meta")
    if isinstance(meta, list):
        for m in meta:
            if isinstance(m, dict):
                inner = m.get(TRACES_KEY)
                if inner:
                    for tr in inner:
                        stage(tr, name)


def pop_traces(batch: dict) -> list:
    """Remove and return every trace riding a batch (batch-level key
    and ``_meta``-carried alike); ``[]`` when untraced."""
    out = list(batch.pop(TRACES_KEY, None) or ())
    meta = batch.get("_meta")
    if isinstance(meta, list):
        for m in meta:
            if isinstance(m, dict) and TRACES_KEY in m:
                out.extend(m.pop(TRACES_KEY) or ())
    return out


def _first_stamps(tr: dict) -> tuple:
    """``(first-occurrence {stage: (mono, wall)}, mono-ordered?)``."""
    stamps: dict = {}
    ordered = True
    prev = None
    for entry in tr.get("stages", ()):
        name, mono, wall = entry[0], float(entry[1]), float(entry[2])
        if name not in stamps:
            stamps[name] = (mono, wall)
        if prev is not None and mono < prev:
            ordered = False
        prev = mono
    return stamps, ordered


class FrameTraceCollector:
    """Process-wide sink for completed frame traces (one per process,
    like the metrics registry; thread-safe — the driver's retire path
    and tests hand records in concurrently).

    ``complete(tr)`` files one finished record: per-transition durations
    are observed into the shared metrics registry (so ``trace.*``
    histograms appear in every ``Metrics.report()``/Prometheus page),
    and the record itself is kept in a bounded ring (``keep``, oldest
    dropped) for Chrome-trace export and flight-record bundles.
    """

    def __init__(self, keep: int = 256, registry=metrics):
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=int(keep))
        self.registry = registry
        self.n_completed = 0
        self.n_unordered = 0

    def complete(self, tr: dict) -> None:
        stamps, ordered = _first_stamps(tr)
        durs = []
        for metric, froms, to, clock in _TRANSITIONS:
            end = stamps.get(to)
            if end is None:
                continue
            start = next(
                (stamps[f] for f in froms if f in stamps), None
            )
            if start is None:
                continue
            i = 0 if clock == "mono" else 1
            durs.append((metric, (end[i] - start[i]) * 1e3))
        with self._lock:
            self._records.append(tr)
            self.n_completed += 1
            if not ordered:
                self.n_unordered += 1
        # Registry observes OUTSIDE the collector lock (the registry has
        # its own; nesting the two invites ordering deadlocks).
        for metric, ms in durs:
            self.registry.observe(metric, ms)
        self.registry.count("trace.completed")
        if not ordered:
            self.registry.count("trace.unordered")

    # -- snapshots ------------------------------------------------------------

    def records(self) -> list:
        with self._lock:
            return list(self._records)

    def report(self) -> dict:
        """Summary over the kept records: counts, end-to-end stage
        completeness (every record spans publish -> step_retire), mono
        ordering, and per-transition percentiles in ms."""
        recs = self.records()
        with self._lock:
            completed, unordered = self.n_completed, self.n_unordered
        transitions: dict = {}
        end_to_end = bool(recs)
        for tr in recs:
            stamps, _ = _first_stamps(tr)
            if "publish" not in stamps or TERMINAL_STAGE not in stamps:
                end_to_end = False
            for metric, froms, to, clock in _TRANSITIONS:
                end = stamps.get(to)
                start = next(
                    (stamps[f] for f in froms if f in stamps), None
                )
                if end is None or start is None:
                    continue
                i = 0 if clock == "mono" else 1
                transitions.setdefault(metric, []).append(
                    (end[i] - start[i]) * 1e3
                )

        def summary(vals: list) -> dict:
            vals = sorted(vals)
            pick = lambda q: vals[min(int(q * len(vals)), len(vals) - 1)]  # noqa: E731
            return {
                "count": len(vals),
                "p50_ms": round(pick(0.50), 3),
                "p95_ms": round(pick(0.95), 3),
                "max_ms": round(vals[-1], 3),
            }

        return {
            "completed": completed,
            "unordered": unordered,
            "kept": len(recs),
            "end_to_end": end_to_end,
            "transitions": {k: summary(v) for k, v in transitions.items()},
        }

    # -- Chrome-trace rendering ----------------------------------------------

    def chrome_events(self) -> list:
        """Completed records as Chrome/Perfetto events: one ``ph: "X"``
        slice per stage transition — producer-side slices in the
        producer's pid lane, consumer-side slices in this process's —
        plus ``s``/``f`` flow events binding the publish slice to the
        recv slice across lanes (the producer -> consumer arrow), and
        process_name metadata so the lanes are labeled.

        Timestamps are wall-clock micros shifted onto the consumer's
        ``perf_counter`` timebase, so frame-trace lanes line up with
        the span-event lanes :func:`blendjax.obs.exporters.chrome_trace`
        already emits from the same process."""
        recs = self.records()
        if not recs:
            return []
        off = time.perf_counter() - time.time()
        cpid = os.getpid()
        events: list = []
        lanes: dict = {cpid: "blendjax consumer"}
        for tr in recs:
            sts = tr.get("stages") or []
            if len(sts) < 2:
                continue
            ppid = int(tr.get("pid") or 0)
            lanes.setdefault(ppid, f"blendjax producer btid={tr.get('btid')}")
            tid = int(tr.get("btid") or 0)
            flow_id = zlib.crc32(str(tr.get("id")).encode()) & 0x7FFFFFFF
            for (n0, _m0, w0), (n1, _m1, w1) in zip(sts, sts[1:]):
                events.append({
                    "name": f"{n0}→{n1}",
                    "cat": "frame_trace",
                    "ph": "X",
                    "ts": round((w0 + off) * 1e6, 3),
                    "dur": round(max(w1 - w0, 0.0) * 1e6, 3),
                    "pid": ppid if n0 == "publish" else cpid,
                    "tid": tid,
                    "args": {"trace": tr.get("id")},
                })
            events.append({
                "name": "frame", "cat": "frame_trace", "ph": "s",
                "id": flow_id, "pid": ppid, "tid": tid,
                "ts": round((sts[0][2] + off) * 1e6, 3),
            })
            events.append({
                "name": "frame", "cat": "frame_trace", "ph": "f",
                "bp": "e", "id": flow_id, "pid": cpid, "tid": tid,
                "ts": round((sts[1][2] + off) * 1e6, 3),
            })
        for pid, label in lanes.items():
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": label},
            })
        return events

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self.n_completed = 0
            self.n_unordered = 0


# Default process-wide collector (mirrors ``metrics``/``lineage``).
tracer = FrameTraceCollector()
