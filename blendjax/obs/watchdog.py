"""SLO watchdog + flight recorder: breach detection with evidence capture.

PR 4's doctor only speaks when polled; the ROADMAP's fleet controller
and multi-host driver both need a *continuous, machine-readable* health
signal plus automatic evidence when it goes bad. Two pieces:

- :class:`Slo` / :class:`SloWatchdog` — declarative floor/ceiling rules
  over any counter **rate**, gauge, histogram **quantile**, or doctor
  verdict, evaluated against plain ``Metrics.report()`` snapshots (one
  per :class:`~blendjax.obs.reporter.StatsReporter` tick) with
  sustained-breach windows, so a one-tick blip doesn't page anyone.
- :class:`FlightRecorder` — on a breach transition, dump a bounded
  diagnostic bundle to disk: the last-K metrics snapshots + doctor
  verdicts (the reporter's history ring), the span-event ring and
  completed frame traces as one Chrome trace, the raw frame-trace
  records, the lineage report, the breaching rule states, and an
  optional *guarded* ``jax.profiler`` capture of the next few seconds
  (a no-op with a warning if a user trace is already open — see the
  reentrancy-safe :func:`blendjax.utils.metrics.trace`).

The HTTP exporter serves the watchdog state at ``/healthz`` (200/503)
beside ``/metrics`` — the admission/scaling signal a fleet controller
consumes. Wire all of it through
``StatsReporter(slos=..., flight_dir=...)``; see docs/observability.md
"SLOs and the flight recorder".

Rule spec grammar (``Slo.parse``)::

    rate(echo.fresh) >= 80          # counter rate, per second between ticks
    rate(wire.seq_gaps) == 0        # exact-zero floor on a drop counter
    p95(wire.e2e_staleness_s) <= 0.5   # histogram quantile (source unit)
    gauge(train.mfu) >= 0.01        # gauge floor
    doctor != wire-bound            # verdict rule (string compare)
    rate(echo.saturated_waits) == 0 @ 30   # sustain: breach must hold 30s

Everything stdlib-only and import-cheap (no jax until a profiler
capture actually starts), like the rest of ``blendjax.obs``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import shutil
import threading
import time

from blendjax.utils.logging import get_logger
from blendjax.utils.metrics import metrics

logger = get_logger("obs")

_OPS = {
    "<=": lambda v, t: v <= t,
    ">=": lambda v, t: v >= t,
    "==": lambda v, t: v == t,
    "!=": lambda v, t: v != t,
    "<": lambda v, t: v < t,
    ">": lambda v, t: v > t,
}

_SPEC_RE = re.compile(
    r"^\s*(?P<target>[^<>=!]+?)\s*(?P<op><=|>=|==|!=|<|>)\s*"
    r"(?P<value>[^@]+?)\s*(?:@\s*(?P<sustain>[0-9.]+)\s*s?\s*)?$"
)
_FUNC_RE = re.compile(
    r"^(?P<fn>rate|gauge|counter|p50|p95|p99)\s*\(\s*(?P<metric>[^)]+?)\s*\)$"
)


@dataclasses.dataclass(frozen=True)
class Slo:
    """One declarative rule: ``kind`` is how the value is read from a
    report snapshot (``rate``/``gauge``/``counter``/``quantile``/
    ``doctor``), ``op``+``threshold`` the bound, ``sustain_s`` how long
    the violation must hold continuously before it counts as a breach.
    ``spec`` keeps the original text for logs and bundle files."""

    spec: str
    kind: str
    metric: str
    op: str
    threshold: float | str
    quantile: str = "p95"
    sustain_s: float = 0.0

    @classmethod
    def parse(cls, spec: str) -> "Slo":
        m = _SPEC_RE.match(spec)
        if not m:
            raise ValueError(
                f"unparseable SLO spec {spec!r} (expected e.g. "
                "'rate(wire.seq_gaps) == 0', 'p95(wire.e2e_staleness_s) "
                "<= 0.5 @ 30', 'doctor != wire-bound')"
            )
        target = m.group("target").strip()
        op = m.group("op")
        raw_value = m.group("value").strip()
        sustain = float(m.group("sustain") or 0.0)
        if target == "doctor":
            if op not in ("==", "!="):
                raise ValueError(
                    f"doctor SLOs compare verdict kinds with == / != "
                    f"(got {op!r} in {spec!r})"
                )
            return cls(spec=spec, kind="doctor", metric="doctor", op=op,
                       threshold=raw_value, sustain_s=sustain)
        fm = _FUNC_RE.match(target)
        if fm:
            fn, metric = fm.group("fn"), fm.group("metric")
        else:
            # bare name: a gauge (the most common always-on signal)
            fn, metric = "gauge", target
        try:
            value = float(raw_value)
        except ValueError:
            raise ValueError(
                f"SLO threshold {raw_value!r} is not a number ({spec!r})"
            ) from None
        if fn in ("p50", "p95", "p99"):
            return cls(spec=spec, kind="quantile", metric=metric, op=op,
                       threshold=value, quantile=fn, sustain_s=sustain)
        return cls(spec=spec, kind=fn, metric=metric, op=op,
                   threshold=value, sustain_s=sustain)


class SloWatchdog:
    """Evaluate a rule set against successive report snapshots.

    Pure over plain dicts (no registry coupling, no side effects beyond
    its own breach state) so tests — and the flight-record bundle —
    exercise every arm synthetically. Counter rates are computed
    between consecutive ``evaluate`` calls; the first call therefore
    reports rates as "no evidence yet" (healthy)."""

    def __init__(self, slos):
        self.slos = [
            Slo.parse(s) if isinstance(s, str) else s for s in slos
        ]
        # One RLock over all breach state: evaluate() runs on the
        # reporter thread while /healthz serves state() from the HTTP
        # exporter's thread — an unlocked sorted(self._breached) there
        # can throw "set changed size during iteration" mid-breach
        # (BJX117; reentrant because evaluate reads `healthy` itself).
        self._lock = threading.RLock()
        self._prev: tuple | None = None  # (t_mono, counters snapshot)
        self._breach_start: dict = {}
        self._breached: set = set()
        self.breach_events = 0
        self.last_states: list = []

    @property
    def healthy(self) -> bool:
        with self._lock:
            return not self._breached

    def _value(self, slo: Slo, report: dict, verdict, now: float):
        if slo.kind == "doctor":
            if verdict is None:
                return None
            return getattr(verdict, "kind", verdict)
        if slo.kind == "gauge":
            return report.get("gauges", {}).get(slo.metric)
        if slo.kind == "counter":
            # absent counter = no evidence yet (rules bind once the
            # metric exists), NOT an implicit zero
            return report.get("counters", {}).get(slo.metric)
        if slo.kind == "quantile":
            h = report.get("histograms", {}).get(slo.metric)
            if not h or not h.get("count"):
                return None
            return h.get(slo.quantile)
        # rate: delta over the previous evaluate call
        if self._prev is None:
            return None
        t0, prev = self._prev
        dt = now - t0
        if dt <= 0:
            return None
        counters = report.get("counters", {})
        if slo.metric not in counters and slo.metric not in prev:
            # the counter has never existed: no evidence, not rate 0 —
            # a floor rule must not breach before the pipeline has even
            # started producing the metric (slow producer spin-up)
            return None
        return (
            counters.get(slo.metric, 0) - prev.get(slo.metric, 0)
        ) / dt

    def evaluate(self, report: dict, verdict=None,
                 now: float | None = None) -> dict:
        """One evaluation pass. Returns ``{"healthy", "states",
        "newly_breached", "newly_recovered"}``; ``states`` carries one
        entry per rule with the observed value and its breach state."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return self._evaluate_locked(report, verdict, now)

    def _evaluate_locked(self, report: dict, verdict, now: float) -> dict:
        was_breached = set(self._breached)
        states: list = []
        newly_recovered: list = []
        for slo in self.slos:
            value = self._value(slo, report, verdict, now)
            ok = True if value is None else _OPS[slo.op](
                value, slo.threshold
            )
            if ok:
                self._breach_start.pop(slo.spec, None)
                if slo.spec in self._breached:
                    self._breached.discard(slo.spec)
                    newly_recovered.append(slo.spec)
            else:
                t0 = self._breach_start.setdefault(slo.spec, now)
                if now - t0 >= slo.sustain_s:
                    self._breached.add(slo.spec)
            states.append({
                "slo": slo.spec,
                "value": value,
                "ok": ok,
                "breached": slo.spec in self._breached,
                "violating_for_s": (
                    round(now - self._breach_start[slo.spec], 3)
                    if slo.spec in self._breach_start else 0.0
                ),
            })
        self._prev = (now, dict(report.get("counters", {})))
        self.last_states = states
        newly_breached = [
            s for s in states
            if s["breached"] and s["slo"] not in was_breached
        ]
        if newly_breached:
            # one event per newly-breached RULE, matching the
            # reporter's slo.breach_events registry counter — the two
            # published totals must agree whichever surface is read
            self.breach_events += len(newly_breached)
        return {
            "healthy": self.healthy,
            "states": states,
            "newly_breached": newly_breached,
            "newly_recovered": newly_recovered,
        }

    def state(self) -> dict:
        with self._lock:
            return {
                "healthy": self.healthy,
                "breached": sorted(self._breached),
                "breach_events": self.breach_events,
                "states": self.last_states,
            }


class FlightRecorder:
    """Dump bounded diagnostic bundles on SLO breaches.

    Each ``dump()`` writes one ``flight-<n>/`` directory under
    ``directory`` containing:

    - ``breach.json`` — reason, timestamp, the full SLO rule states
    - ``snapshots.jsonl`` — the reporter's last-K history entries
      (metrics report + doctor verdict per tick)
    - ``lineage.json`` — the per-producer lineage report
    - ``trace.json`` — span-event ring + completed frame traces as one
      Chrome/Perfetto trace (load in ui.perfetto.dev)
    - ``frame_traces.json`` — the raw completed frame-trace records
    - ``profile/`` — optional ``jax.profiler`` capture of the next
      ``profile_s`` seconds (guarded: degrades to a no-op when a user
      trace is already open, never raises into the reporter thread)

    At most ``max_bundles`` bundles are kept (oldest deleted), so a
    flapping SLO cannot fill the disk.
    """

    def __init__(self, directory: str, max_bundles: int = 4,
                 profile_s: float = 0.0, keep_traces: int = 64,
                 checkpoint=None):
        self.directory = directory
        self.max_bundles = max(1, int(max_bundles))
        self.profile_s = float(profile_s)
        self.keep_traces = int(keep_traces)
        # Checkpoint-on-breach arm (docs/checkpointing.md): a zero-arg
        # callable — typically ``driver.request_checkpoint``, which
        # flags the TRAIN thread to snapshot at its next step boundary
        # (the recorder must never serialize device state from the
        # reporter thread itself). Its invocation + return value are
        # recorded in the bundle's checkpoint.json.
        self.checkpoint = checkpoint
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        # Resume numbering after existing bundles: a restarted run must
        # not reuse flight-0001 (mixing two incidents' artifacts in one
        # directory, and sorting itself to the front of the prune line).
        self._seq = max(
            (
                int(d.rsplit("-", 1)[1])
                for d in os.listdir(directory)
                if d.startswith("flight-")
                and d.rsplit("-", 1)[1].isdigit()
            ),
            default=0,
        )

    def dump(self, reason: str = "slo-breach", history=(),
             lineage_report: dict | None = None,
             slo_states=None, registry=metrics,
             frame_tracer=None) -> str:
        """Write one bundle; returns its path. Never raises into the
        caller for partial-evidence failures — each artifact is written
        independently and a broken one is logged and skipped."""
        from blendjax.obs.exporters import write_chrome_trace

        if frame_tracer is None:
            from blendjax.obs.trace import tracer as frame_tracer
        with self._lock:
            self._seq += 1
            bundle = os.path.join(
                self.directory, f"flight-{self._seq:04d}"
            )
            os.makedirs(bundle, exist_ok=True)
            self._prune_locked()
        def _write(name, fn):
            try:
                fn(os.path.join(bundle, name))
            except Exception:
                logger.exception("flight recorder: %s failed", name)

        def _json(obj, indent=None):
            def writer(p):
                with open(p, "w", encoding="utf-8") as f:
                    json.dump(obj, f, default=str, indent=indent)
            return writer

        def _snapshots(p):
            with open(p, "w", encoding="utf-8") as f:
                for entry in history:
                    f.write(json.dumps(entry, default=str) + "\n")

        _write("breach.json", _json(
            {"t": time.time(), "reason": reason, "slo": slo_states},
            indent=2,
        ))
        _write("snapshots.jsonl", _snapshots)
        if lineage_report is not None:
            _write("lineage.json", _json(lineage_report, indent=2))
        _write("trace.json", lambda p: write_chrome_trace(
            p, registry=registry, frame_traces=frame_tracer,
        ))
        _write("frame_traces.json", _json({
            "report": frame_tracer.report(),
            "records": frame_tracer.records()[-self.keep_traces:],
        }))

        def _device_ledger(p):
            from blendjax.obs.devledger import ledger

            with open(p, "w", encoding="utf-8") as f:
                json.dump(ledger.report(), f, default=str, indent=2)

        # per-signature cost/memory/collective accounting + retrace
        # events + last HBM sample — what the device was doing when the
        # breach (or retrace storm) fired
        _write("device_ledger.json", _device_ledger)
        if self.checkpoint is not None:
            def _ckpt_arm(p):
                result = self.checkpoint()
                with open(p, "w", encoding="utf-8") as f:
                    json.dump(
                        {
                            "t": time.time(),
                            "requested": True,
                            "result": result,
                        },
                        f, default=str, indent=2,
                    )
            _write("checkpoint.json", _ckpt_arm)
        if self.profile_s > 0:
            t = threading.Thread(
                target=self._profile,
                args=(os.path.join(bundle, "profile"),),
                name="blendjax-flight-profile", daemon=True,
            )
            t.start()
        logger.warning("flight record written: %s (%s)", bundle, reason)
        return bundle

    def _prune_locked(self) -> None:
        bundles = sorted(
            d for d in os.listdir(self.directory)
            if d.startswith("flight-")
            and os.path.isdir(os.path.join(self.directory, d))
        )
        while len(bundles) > self.max_bundles:
            victim = bundles.pop(0)
            shutil.rmtree(
                os.path.join(self.directory, victim), ignore_errors=True
            )

    def _profile(self, logdir: str) -> None:
        """Guarded post-breach profiler capture: the reentrancy-safe
        :func:`blendjax.utils.metrics.trace` degrades to a warning
        no-op when a user trace is already open, and any backend error
        (no jax, no device) is logged, never raised."""
        try:
            from blendjax.utils.metrics import trace

            with trace(logdir):
                time.sleep(self.profile_s)
        except Exception:
            logger.exception("flight recorder: profiler capture failed")
