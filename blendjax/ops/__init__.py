"""On-device image ops (XLA + Pallas).

The reference burns producer CPU on these (gamma correction at
``pkg_blender/blendtorch/btb/offscreen.py:105-112`` and in consumer
transforms, ``examples/datagen/generate.py:10-14``); blendjax moves them
onto the TPU where they fuse into the input cast of the train step.
"""

from blendjax.ops.image import (
    gamma_correct,
    maybe_normalize_uint8,
    normalize_uint8,
    random_flip,
    uint8_gamma_normalize,
)

__all__ = [
    "gamma_correct",
    "normalize_uint8",
    "maybe_normalize_uint8",
    "uint8_gamma_normalize",
    "random_flip",
]
