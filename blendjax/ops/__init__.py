"""On-device image ops (XLA + Pallas) and the tile-delta stream codec.

The reference burns producer CPU on these (gamma correction at
``pkg_blender/blendtorch/btb/offscreen.py:105-112`` and in consumer
transforms, ``examples/datagen/generate.py:10-14``); blendjax moves them
onto the TPU where they fuse into the input cast of the train step.

Attribute access is lazy (PEP 562): producer processes import
``blendjax.ops.tiles`` (numpy-only) without pulling in jax via
``blendjax.ops.image``.
"""

_IMAGE = {
    "gamma_correct",
    "normalize_uint8",
    "maybe_normalize_uint8",
    "uint8_gamma_normalize",
    "random_flip",
}
_TILES = {
    "TileDeltaEncoder",
    "decode_tile_delta",
    "pack_batch",
    "tile_ref",
    "tile_hw",
    "geom_tile",
    "tileshape_wire",
}
_AUGMENT = {
    "make_augment",
    "random_crop",
    "color_jitter",
    "random_cutout",
    "random_flip_with_points",
    "random_crop_with_points",
}

__all__ = sorted(_IMAGE | _TILES | _AUGMENT)


def __getattr__(name):
    if name in _IMAGE:
        from blendjax.ops import image

        return getattr(image, name)
    if name in _TILES:
        from blendjax.ops import tiles

        return getattr(tiles, name)
    if name in _AUGMENT:
        from blendjax.ops import augment

        return getattr(augment, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
