"""Local (single-device) attention backends.

Net-new vs the reference (blendtorch has no sequence models, SURVEY.md
§2.4). Two exact backends behind one call:

- ``xla``: :func:`blendjax.parallel.ring.reference_attention` — plain
  einsum attention with bf16 MXU matmuls, f32 score accumulation, and
  f32 softmax. Materializes the (B, H, T, T) score tensor in HBM.
- ``flash``: the Pallas TPU flash-attention kernel
  (``jax.experimental.pallas.ops.tpu.flash_attention``) — streaming
  softmax in VMEM, never materializing the score tensor. fwd+bwd via
  the kernel's own custom VJP.

``auto`` picks by measured crossover on the v5e: the materialized path
wins slightly at short sequences (T=768: 0.57 vs 0.68 ms fwd+bwd —
kernel launch overhead beats one small score tensor) while flash wins
past ~1k tokens and scales: at T=3072 flash measures 2.43 vs 3.33 ms
fwd+bwd (1.37x) and saves the O(T^2) f32 residuals (~600 MB at that
size) that backprop would otherwise hold in HBM.

The sequence-parallel kernels (:mod:`blendjax.parallel.ring`,
:mod:`blendjax.parallel.ulysses`) shard T across devices *before* any
local attention runs; this module is the per-device math below them.
"""

from __future__ import annotations

from blendjax.parallel.ring import reference_attention

# Measured v5e crossover (docstring): flash wins from ~1k tokens.
FLASH_MIN_TOKENS = 1024
# The kernel's default block sizes divide 128; eligibility keyed on it.
FLASH_BLOCK = 128


def flash_supported(q, k=None) -> bool:
    """Whether the Pallas TPU flash kernel can take these (B, T, H, D)
    inputs: TPU backend and sequence lengths the kernel's 128-wide
    blocks tile exactly — the KV length too, for cross-attention (the
    kernel pads head_dim internally)."""
    import jax

    if jax.default_backend() != "tpu":
        return False
    if not (q.ndim == 4 and q.shape[1] % FLASH_BLOCK == 0):
        return False
    d = q.shape[-1]
    if d > 128 and d % 128:
        # the kernel pads head_dim UP to 128 but requires multiples of
        # 128 above it (its own NotImplementedError otherwise)
        return False
    return k is None or (
        k.ndim == 4 and k.shape[1] % FLASH_BLOCK == 0
    )


def local_attention(q, k, v, causal: bool = False, scale=None,
                    backend: str = "auto"):
    """Exact multi-head attention over (B, T, H, D) tensors.

    ``backend``: ``"xla"`` | ``"flash"`` | ``"auto"`` (flash on TPU for
    T >= ``FLASH_MIN_TOKENS`` when eligible, else xla). ``"flash"``
    raises on an ineligible input instead of silently measuring xla —
    same explicitness contract as the tile decode's ``use_pallas``.
    """
    if backend not in ("auto", "flash", "xla"):
        # ValueError, not assert: a typo'd backend under `python -O`
        # must not silently measure the xla path
        raise ValueError(f"unknown attention backend {backend!r}")
    if backend == "flash" and not flash_supported(q, k):
        raise ValueError(
            "flash attention backend requested but unsupported here: "
            f"backend must be TPU and T (q {q.shape[1]}, kv "
            f"{k.shape[1]}) must be multiples of {FLASH_BLOCK}"
        )
    use_flash = backend == "flash" or (
        backend == "auto"
        and q.shape[1] >= FLASH_MIN_TOKENS
        and flash_supported(q, k)
    )
    if not use_flash:
        return reference_attention(q, k, v, causal=causal, scale=scale)

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention,
    )

    d = q.shape[-1]
    scale = scale if scale is not None else d**-0.5
    # kernel layout is (B, H, T, D)
    o = flash_attention(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal,
        sm_scale=scale,
    )
    return o.transpose(0, 2, 1, 3)
