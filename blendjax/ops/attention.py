"""Local (single-device) attention backends.

Net-new vs the reference (blendtorch has no sequence models, SURVEY.md
§2.4). Two exact backends behind one call:

- ``xla``: :func:`blendjax.parallel.ring.reference_attention` — plain
  einsum attention with bf16 MXU matmuls, f32 score accumulation, and
  f32 softmax. Materializes the (B, H, T, T) score tensor in HBM.
- ``flash``: the Pallas TPU flash-attention kernel
  (``jax.experimental.pallas.ops.tpu.flash_attention``) — streaming
  softmax in VMEM, never materializing the score tensor. fwd+bwd via
  the kernel's own custom VJP.

``auto`` policy (v5e measurements, full train steps — StreamFormer
dim 512 depth 8 heads 4):

- ISOLATED attention fwd+bwd favors flash past ~1k tokens (T=3072:
  2.43 vs 3.33 ms, 1.37x), but IN-MODEL the materialized path keeps
  winning well beyond that — T=3072: 39.4 vs 31.3 img/s; T=6144
  (1.2 GB/layer transient scores): 9.7 vs 7.8 img/s — the kernel's
  separate bwd passes cost more than XLA's fused attention backward
  while HBM still absorbs the score tensors.
- What the materialized path cannot do is run when the saved-for-
  backward score tensors stop fitting (e.g. T=16k at B=1, H=4: ~4.3
  GB/layer of f32 probs — a couple of layers exhaust a 16 GB chip).

So ``auto`` defers to ``xla`` until a single call's score residual
would exceed :data:`FLASH_RESIDUAL_BYTES`, and takes ``flash`` beyond
— flash is the long-context enabler, not a mid-length speedup, on
this hardware. Explicit ``backend="flash"`` always takes the kernel.

The sequence-parallel kernels (:mod:`blendjax.parallel.ring`,
:mod:`blendjax.parallel.ulysses`) shard T across devices *before* any
local attention runs; this module is the per-device math below them.
"""

from __future__ import annotations

from blendjax.parallel.ring import reference_attention

# Per-call score-residual budget (bytes of f32 probs saved for the
# backward pass) above which `auto` switches to the flash kernel: at
# 2 GiB/call even a handful of layers threatens a 16 GB chip, and the
# measured in-model xla advantage (see module docstring) no longer
# applies because xla can no longer run at all. (T=16k at B=1, H=4 is
# ~4.3 GB/call — comfortably over.)
FLASH_RESIDUAL_BYTES = 2 << 30
# OUR pinned block edge, not the kernel's default: every flash call
# passes an explicit ``BlockSizes`` built from this constant (see
# ``flash_block_sizes``), so ``flash_supported``'s tiling check and the
# kernel's real grid can never drift apart across jax upgrades — a new
# release changing the kernel's *default* block sizes changes nothing
# here. Sequence lengths must tile these blocks; head_dim is padded up
# to 128 but must be a multiple of 128 above it.
FLASH_BLOCK = 128


def flash_block_sizes(t_q: int, t_kv: int) -> "object":
    """Explicit kernel grid for a (t_q, t_kv) call: every forward and
    backward block edge pinned to :data:`FLASH_BLOCK` (clamped to the
    sequence lengths for short inputs). ``flash_supported`` admits a
    shape if and only if it tiles THESE blocks — one source of truth
    for eligibility and launch."""
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    bq = min(FLASH_BLOCK, int(t_q))
    bk = min(FLASH_BLOCK, int(t_kv))
    return BlockSizes(
        block_q=bq,
        block_k_major=bk,
        block_k=bk,
        block_b=1,
        block_q_major_dkv=bq,
        block_k_major_dkv=bk,
        block_k_dkv=bk,
        block_q_dkv=bq,
        block_k_major_dq=bk,
        block_k_dq=bk,
        block_q_dq=bq,
    )


def scores_residual_bytes(q, k=None) -> int:
    """Bytes of attention probabilities one call saves for its backward
    pass — the term that makes materialized attention infeasible at
    long context. f32: ``reference_attention`` computes and normalizes
    the probs in f32 and only casts at the output matmul, so the
    saved-for-backward tensor is f32 (confirmed by the measured ~600 MB
    at B=4, H=4, T=3072 — exactly 4*4*3072^2*4 bytes)."""
    b, tq, h, _ = q.shape
    tk = q.shape[1] if k is None else k.shape[1]
    return b * h * tq * tk * 4


def flash_supported(q, k=None) -> bool:
    """Whether the Pallas TPU flash kernel can take these (B, T, H, D)
    inputs: TPU backend and sequence lengths the kernel's 128-wide
    blocks tile exactly — the KV length too, for cross-attention (the
    kernel pads head_dim up to 128; above that it requires multiples
    of 128, its own constraint)."""
    import jax

    if jax.default_backend() != "tpu":
        return False
    if not (q.ndim == 4 and q.shape[1] % FLASH_BLOCK == 0):
        return False
    d = q.shape[-1]
    if d > 128 and d % 128:
        return False
    return k is None or (
        k.ndim == 4 and k.shape[1] % FLASH_BLOCK == 0
    )


def auto_picks_flash(q, k=None) -> bool:
    """The ``auto`` policy, exposed so callers (the bench's longseq
    row) can report which backend a shape resolves to."""
    return (
        flash_supported(q, k)
        and scores_residual_bytes(q, k) > FLASH_RESIDUAL_BYTES
    )


def local_attention(q, k, v, causal: bool = False, scale=None,
                    backend: str = "auto"):
    """Exact multi-head attention over (B, T, H, D) tensors.

    ``backend``: ``"xla"`` | ``"flash"`` | ``"auto"`` (the
    memory-driven policy above). ``"flash"`` raises on an ineligible
    input instead of silently measuring xla — same explicitness
    contract as the tile decode's ``use_pallas``.
    """
    if backend not in ("auto", "flash", "xla"):
        # ValueError, not assert: a typo'd backend under `python -O`
        # must not silently measure the xla path
        raise ValueError(f"unknown attention backend {backend!r}")
    if backend == "flash" and not flash_supported(q, k):
        raise ValueError(
            "flash attention backend requested but unsupported here: "
            f"backend must be TPU and T (q {q.shape[1]}, kv "
            f"{k.shape[1]}) must be multiples of {FLASH_BLOCK}"
        )
    use_flash = backend == "flash" or (
        backend == "auto" and auto_picks_flash(q, k)
    )
    if not use_flash:
        return reference_attention(q, k, v, causal=causal, scale=scale)

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention,
    )

    d = q.shape[-1]
    scale = scale if scale is not None else d**-0.5
    # kernel layout is (B, H, T, D); blocks pinned explicitly so the
    # launch grid is the one flash_supported admitted, on every jax
    o = flash_attention(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal,
        sm_scale=scale,
        block_sizes=flash_block_sizes(q.shape[1], k.shape[1]),
    )
    return o.transpose(0, 2, 1, 3)
