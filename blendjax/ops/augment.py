"""On-device batched data augmentation.

SURVEY.md §7 build step 8: the reference does all image post-processing
on CPU numpy (gamma in ``generate.py:10-14``; no augmentation at all) —
blendjax runs augmentation ON the accelerator, inside the jitted train
step, where it fuses with the uint8 normalization and the first conv
and shards along the batch axis like any other op (per-sample
randomness via ``vmap``'d key splits; no host round trip, no Python RNG
in the hot loop).

Every op has signature ``op(rng, images) -> images`` over uint8 or
float NHWC batches and is jit/vmap/shard-safe (static shapes; per-
sample decisions ride ``jnp.where``/``dynamic_slice``). Compose with
:func:`make_augment`, or hand the composition to
``blendjax.train.make_supervised_step(augment=...)`` which folds a
per-step key from the training step counter (deterministic resume).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# One source of truth: the flip op predates this module (image.py);
# _flip_bits is the shared per-sample decision draw that keeps the
# paired variant below key-compatible with it.
from blendjax.ops.image import _flip_bits, random_flip


def _crop_offsets(key, pad: int):
    """Per-sample (oy, ox) crop offsets — the ONE key-fold scheme shared
    by the paired and unpaired crop variants (they must stay key-
    compatible: recorded augmentation sequences depend on it)."""
    oy = jax.random.randint(key, (), 0, 2 * pad + 1)
    ox = jax.random.randint(jax.random.fold_in(key, 1), (), 0, 2 * pad + 1)
    return oy, ox


def random_crop(rng, images, pad: int = 4):
    """Pad-and-crop (the CIFAR recipe): edge-pad ``pad`` pixels then
    take a per-sample random HxW crop back to the original size —
    static output shapes, so jit compiles once."""
    b, h, w, c = images.shape
    padded = jnp.pad(
        images, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="edge"
    )
    keys = jax.random.split(rng, b)

    def crop_one(key, img):
        oy, ox = _crop_offsets(key, pad)
        return jax.lax.dynamic_slice(img, (oy, ox, 0), (h, w, c))

    return jax.vmap(crop_one)(keys, padded)


def color_jitter(rng, images, brightness: float = 0.2,
                 contrast: float = 0.2):
    """Per-sample brightness/contrast jitter, uint8-in/uint8-out; float
    input must already be normalized to [0, 1] (the package-wide float
    contract, see ``maybe_normalize_uint8``) and stays float. One fused
    elementwise expression — XLA folds it into whatever consumes the
    batch. Internal arithmetic is f32 regardless of the train step's
    compute dtype: the per-image mean is a reduction, and reductions
    stay in the policy's accum dtype (:mod:`blendjax.precision`)."""
    b = images.shape[0]
    is_int = jnp.issubdtype(images.dtype, jnp.integer)
    x = images.astype(jnp.float32)
    if is_int:
        x = x / 255.0
    kb, kc = jax.random.split(rng)
    shape = (b,) + (1,) * (images.ndim - 1)
    bright = jax.random.uniform(
        kb, shape, minval=-brightness, maxval=brightness
    )
    contr = 1.0 + jax.random.uniform(
        kc, shape, minval=-contrast, maxval=contrast
    )
    mean = x.mean(axis=(1, 2), keepdims=True)
    x = jnp.clip((x - mean) * contr + mean + bright, 0.0, 1.0)
    if is_int:
        return jnp.round(x * 255.0).astype(images.dtype)
    return x.astype(images.dtype)


def random_cutout(rng, images, size: int = 16, fill: int = 0):
    """Per-sample square cutout (random erasing) at a random location.
    Static shapes: the mask is built from coordinate comparisons."""
    b, h, w, _ = images.shape
    keys = jax.random.split(rng, b)
    ys = jnp.arange(h)[:, None]
    xs = jnp.arange(w)[None, :]

    def one(key, img):
        cy = jax.random.randint(key, (), 0, h)
        cx = jax.random.randint(jax.random.fold_in(key, 1), (), 0, w)
        mask = (
            (ys >= cy - size // 2) & (ys < cy + size // 2)
            & (xs >= cx - size // 2) & (xs < cx + size // 2)
        )
        return jnp.where(
            mask[..., None], jnp.asarray(fill, img.dtype), img
        )

    return jax.vmap(one)(keys, images)


def random_flip_with_points(rng, images, points, axis: int = 2):
    """Per-sample flip of ``images`` WITH the matching mirror of pixel-
    space ``points`` (B, P, 2) in (x, y) order — the paired form for
    tasks supervising spatial labels (flipping only the image would
    train on corrupted supervision). ``axis=2`` flips width (mirrors
    x); ``axis=1`` flips height (mirrors y). Returns
    ``(images, points)``."""
    points = jnp.asarray(points)  # eager numpy callers: .at needs jnp
    b = images.shape[0]
    size = images.shape[axis]
    coord = 0 if axis == 2 else 1
    bits = _flip_bits(rng, b)  # shared draw: key-compatible with random_flip
    flipped = jnp.flip(images, axis=axis)
    ishape = (b,) + (1,) * (images.ndim - 1)
    out_imgs = jnp.where(bits.reshape(ishape), flipped, images)
    mirrored = points.at[..., coord].set(
        (size - 1) - points[..., coord]
    )
    out_pts = jnp.where(bits.reshape((b, 1, 1)), mirrored, points)
    return out_imgs, out_pts


def random_crop_with_points(rng, images, points, pad: int = 4):
    """Paired pad-and-crop: shifts ``points`` (B, P, 2) in (x, y) pixel
    coords by the same per-sample offset the crop applies. Points can
    land outside [0, W)x[0, H) when the crop pushes them off-frame —
    callers that care should mask on the returned coordinates. Returns
    ``(images, points)``."""
    b, h, w, c = images.shape
    padded = jnp.pad(
        images, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="edge"
    )
    keys = jax.random.split(rng, b)

    def one(key, img, pts):
        oy, ox = _crop_offsets(key, pad)
        img = jax.lax.dynamic_slice(img, (oy, ox, 0), (h, w, c))
        pts = pts + jnp.stack(
            [pad - ox, pad - oy]
        ).astype(pts.dtype)
        return img, pts

    return jax.vmap(one)(keys, padded, jnp.asarray(points))


def make_batch_augment(*ops, image_key: str = "image",
                       points_key: str | None = None):
    """Lift image augmentation ops to whole batch DICTS, keeping
    spatial labels consistent with the images — the form the data-
    echoing reservoir applies per draw (``blendjax.data.echo``).

    Each op draws from an independent fold of the key, like
    :func:`make_augment`. Ops come in two shapes, told apart by their
    required-parameter count:

    - ``op(rng, images)`` — photometric/unpaired (2 required params):
      applied to ``batch[image_key]`` alone.
    - ``op(rng, images, points)`` — paired (3 required params, e.g.
      :func:`random_flip_with_points`): applied to the image AND the
      ``batch[points_key]`` labels together, so geometric ops can't
      desynchronize supervision. Requires ``points_key``.

    Fields other than ``image_key``/``points_key`` pass through
    untouched; a batch missing ``image_key`` is returned unchanged.

    >>> aug = make_batch_augment(random_flip_with_points, color_jitter,
    ...                          points_key="xy")
    >>> batch_out = jax.jit(aug)(key, {"image": imgs, "xy": pts})
    """
    import inspect

    def n_required(op):
        empty = inspect.Parameter.empty
        positional = (
            inspect.Parameter.POSITIONAL_ONLY,
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
        )
        return sum(
            1 for p in inspect.signature(op).parameters.values()
            if p.default is empty and p.kind in positional
        )

    paired = tuple(n_required(op) >= 3 for op in ops)
    if any(paired) and points_key is None:
        raise ValueError(
            "paired ops (rng, images, points) need points_key= to name "
            "the label field they co-transform"
        )

    def augment(rng, batch):
        if image_key not in batch:
            return batch
        images = batch[image_key]
        points = batch.get(points_key) if points_key is not None else None
        if points is None and any(paired):
            # Fail at the misconfiguration, not as an opaque TypeError
            # deep inside a paired op's jit trace (e.g. the reservoir
            # dropped the label field as a lead-mismatched sidecar).
            raise KeyError(
                f"paired augmentation needs batch[{points_key!r}], which "
                f"is missing (batch fields: {sorted(batch)})"
            )
        for i, (op, pair) in enumerate(zip(ops, paired)):
            key = jax.random.fold_in(rng, i)
            if pair:
                images, points = op(key, images, points)
            else:
                images = op(key, images)
        out = dict(batch)
        out[image_key] = images
        if points is not None:
            out[points_key] = points
        return out

    return augment


def make_augment(*ops):
    """Compose augmentation ops into one ``fn(rng, images)``; each op
    draws from an independent fold of the key.

    >>> import functools
    >>> aug = make_augment(random_flip,
    ...                    functools.partial(random_crop, pad=4))
    >>> batch_out = jax.jit(aug)(key, batch)
    """

    def augment(rng, images):
        for i, op in enumerate(ops):
            images = op(jax.random.fold_in(rng, i), images)
        return images

    return augment


__all__ = [
    "random_flip",
    "random_crop",
    "color_jitter",
    "random_cutout",
    "random_flip_with_points",
    "random_crop_with_points",
    "make_augment",
    "make_batch_augment",
]
