"""Image preprocessing ops.

``uint8_gamma_normalize`` is the hot-path op — every streamed frame goes
uint8 -> normalized compute dtype (+ optional gamma). It has two
implementations:

- a plain jnp version XLA fuses into the consuming op, and
- a Pallas TPU kernel (``_pallas_gamma_normalize``) demonstrating the
  kernel path for ops XLA can't fuse: processes the image as 2D tiles in
  VMEM, one grid row per image row-block (guide:
  /opt/skills/guides/pallas_guide.md "Minimal Kernel"/"Grid and Block
  Specifications"). On non-TPU backends it runs in interpreter mode so
  tests stay hermetic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def gamma_correct(x, gamma: float = 2.2):
    """float image in [0,1] -> gamma-corrected (reference does this on CPU
    numpy, ``offscreen.py:105-112``)."""
    return jnp.power(jnp.clip(x, 0.0, 1.0), 1.0 / gamma)


def normalize_uint8(x, dtype=jnp.bfloat16):
    """uint8 -> [0,1] in compute dtype (fuses into the next matmul/conv)."""
    return x.astype(dtype) / jnp.asarray(255.0, dtype)


def maybe_normalize_uint8(x, dtype=jnp.bfloat16):
    """Model-input canonicalization: uint8 is scaled to [0,1]; float input
    is assumed already normalized and only cast. The single shared guard
    all blendjax models use, so the semantics can't drift per-model."""
    if x.dtype == jnp.uint8:
        return normalize_uint8(x, dtype)
    return x.astype(dtype)


def _flip_bits(rng, b: int):
    """Per-sample flip decisions — the ONE bit-draw scheme shared by the
    paired (`augment.random_flip_with_points`) and unpaired flips; they
    must stay key-compatible (recorded augmentation sequences depend on
    flipping the same samples for the same key)."""
    return jax.random.bernoulli(rng, 0.5, (b,))


def random_flip(rng, x, axis: int = 2):
    """Batched random horizontal flip (augmentation; per-sample bit)."""
    b = x.shape[0]
    bits = _flip_bits(rng, b)
    flipped = jnp.flip(x, axis=axis)
    shape = (b,) + (1,) * (x.ndim - 1)
    return jnp.where(bits.reshape(shape), flipped, x)


# -- pallas kernel ----------------------------------------------------------


def _gamma_kernel(x_ref, o_ref, *, inv_gamma: float, scale: float):
    x = x_ref[:].astype(jnp.float32) * scale  # uint8 -> [0,1]
    y = jnp.power(x, inv_gamma)
    o_ref[:] = y.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("gamma", "dtype", "interpret"))
def _pallas_gamma_normalize(x, gamma: float = 2.2, dtype=jnp.float32,
                            interpret: bool = False):
    from jax.experimental import pallas as pl

    b, h, w, c = x.shape
    x2 = x.reshape(b * h, w * c)  # 2D layout for (sublane, lane) tiling
    # Largest divisor of the row count <= 256: keeps blocks within VMEM for
    # any resolution (worst case degrades to single-row blocks).
    rows = b * h
    block_rows = max(d for d in range(1, min(256, rows) + 1) if rows % d == 0)
    grid = (rows // block_rows,)
    out = pl.pallas_call(
        functools.partial(
            _gamma_kernel, inv_gamma=1.0 / gamma, scale=1.0 / 255.0
        ),
        out_shape=jax.ShapeDtypeStruct(x2.shape, dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, w * c), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, w * c), lambda i: (i, 0)),
        interpret=interpret,
    )(x2)
    return out.reshape(b, h, w, c)


def uint8_gamma_normalize(x, gamma: float = 2.2, dtype=jnp.float32,
                          use_pallas: bool | None = None):
    """uint8 NHWC -> gamma-corrected [0,1] image in ``dtype``.

    ``use_pallas=None`` auto-selects: the Pallas kernel on TPU, fused jnp
    elsewhere (Pallas interpret mode stays available for testing).
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        return _pallas_gamma_normalize(x, gamma=gamma, dtype=dtype)
    return gamma_correct(normalize_uint8(x, jnp.float32)).astype(dtype)
