"""Lossless tile-delta encoding for image streams.

Synthetic-render streams are sparse: between frames (or against a static
background) only the pixels the scene geometry touches change. The
reference ships every frame as a full pickled RGBA buffer
(``publisher.py:43`` -> ``dataset.py:105``); on a TPU host the equivalent
raw stream is bounded by host->HBM transfer bandwidth long before the chip
is busy. This module moves the bottleneck: producers send only the tiles
that differ from a *reference image* (typically the scene background), and
the consumer reconstructs exact full frames **on device** with a jitted
batched scatter — so the bytes that cross the host->device boundary scale
with scene activity, not resolution.

Encoding (host side, producer):
    ``TileDeltaEncoder(ref).encode(img)`` -> ``(idx, tiles)`` where ``idx``
    holds flattened tile indices (row-major over the tile grid) and
    ``tiles`` the changed ``t x t x C`` blocks; ``pack_batch`` pads frames
    to a shared capacity with the sentinel index ``num_tiles`` which the
    device scatter drops.

Decoding (device side, consumer):
    ``ref_tiles = tile_ref(ref)`` once per stream, then
    ``decode_tile_delta(ref_tiles, idx, tiles, shape=...)`` per batch:
    a ``vmap``-ed ``.at[idx].set(tiles, mode='drop')`` scatter plus a
    reshape back to NHWC. Exact reconstruction — ``decode(encode(x)) == x``
    bit-for-bit (asserted by ``tests/test_tiles.py``).

Wire convention (understood by ``blendjax.data.StreamDataPipeline`` and
the torch adapter; full table in ``docs/wire-protocol.md``): for an image
field ``name`` a tile-encoded batch message carries ``name__tileidx``
(B, K) int32, ``name__tileshape`` — the 5-element rectangular form
[H, W, C, th, tw] (tiles are th x tw x C blocks, row-major over the
ceil(H/th) x ceil(W/tw) grid; see ``geom_tile``; consumers also accept
the legacy square v1 form [H, W, C, t] = th == tw == t) — and the tile
payload: ``name__tiles`` (B, K, th, tw, C) uint8 raw, or the
palette-compressed ``name__tilepal2``/``4``/``8`` + ``name__palette``
when the batch's colors fit 2/4/8-bit indices. The reference image
travels as ``name__tileref`` (H, W, C) in the producer's first message —
and, when ``TileBatchPublisher(ref_interval=N)`` is set (default off),
every Nth batch as a keyframe so late-joining consumers can sync.

The changed-tile scan runs in C++ when the native helper builds
(``blendjax/_native/tiledelta.cpp``); the numpy fallback is identical.
"""

from __future__ import annotations

import numpy as np

TILE = 32  # default tile side; must divide both image dims

TILEIDX_SUFFIX = "__tileidx"
TILES_SUFFIX = "__tiles"
TILESHAPE_SUFFIX = "__tileshape"
TILEREF_SUFFIX = "__tileref"
# palette-compressed tile payloads (PNG-8 style; lossless):
TILEPAL2_SUFFIX = "__tilepal2"   # four 2-bit palette indices per byte
TILEPAL4_SUFFIX = "__tilepal4"   # two 4-bit palette indices per byte
TILEPAL8_SUFFIX = "__tilepal8"   # one byte per pixel
PALETTE_SUFFIX = "__palette"     # (cap, C) or per-row (B, cap, C)
#                                  uint8, zero-padded past used entries
# palette-compressed FULL frames (the non-sparse codec: no reference
# frame, no temporal assumption — see palettize_frames):
FRAMEPAL2_SUFFIX = "__framepal2"  # (B, H*W/4) 2-bit indices
FRAMEPAL4_SUFFIX = "__framepal4"  # (B, H*W/2) nibble indices
FRAMEPAL8_SUFFIX = "__framepal8"  # (B, H*W) byte indices
FRAMESHAPE_SUFFIX = "__frameshape"  # [H, W, C, bits]

FRAMEPAL_SUFFIXES = {
    2: FRAMEPAL2_SUFFIX, 4: FRAMEPAL4_SUFFIX, 8: FRAMEPAL8_SUFFIX,
}
TILEPAL_SUFFIXES = {
    2: TILEPAL2_SUFFIX, 4: TILEPAL4_SUFFIX, 8: TILEPAL8_SUFFIX,
}


def pack_palette_indices(idx, bits: int):
    """Pack uint8 palette indices along the LAST axis: 4 per byte for
    ``bits=2``, 2 per byte for ``bits=4``, pass-through for ``bits=8``.
    The single definition of the bit order (first index in the high
    bits) — every producer packs and every consumer unpacks through
    this pair, so the wire variants stay in one place."""
    if bits == 2:
        return (
            (idx[..., 0::4] << 6) | (idx[..., 1::4] << 4)
            | (idx[..., 2::4] << 2) | idx[..., 3::4]
        )
    if bits == 4:
        return (idx[..., 0::2] << 4) | idx[..., 1::2]
    return idx


def unpack_palette_indices(packed, bits: int, xp=np):
    """Inverse of :func:`pack_palette_indices` (``xp``: ``numpy`` or
    ``jax.numpy`` — the expression is jit-safe)."""
    lead = packed.shape[:-1]
    m = packed.shape[-1]
    if bits == 2:
        return xp.stack(
            [packed >> 6, (packed >> 4) & 3, (packed >> 2) & 3,
             packed & 3],
            axis=-1,
        ).reshape(*lead, m * 4)
    if bits == 4:
        return xp.stack(
            [packed >> 4, packed & 0xF], axis=-1
        ).reshape(*lead, m * 2)
    return packed


def tile_hw(tile):
    """Normalize a tile spec — an int side or a ``(rows, cols)`` pair —
    to ``(th, tw)`` pixel dims.

    Rectangular tiles exist for the decoder's benefit: a (16, 32) tile
    at C=4 spans exactly 128 output lanes (the TPU's native lane
    width), which unlocks the direct-spatial Pallas decode
    (:func:`_pallas_decode_spatial`: no slot buffer, no reference-
    broadcast init pass, no tile->frame transpose pass).
    """
    if isinstance(tile, (tuple, list, np.ndarray)):
        if len(tile) != 2:
            raise ValueError(
                f"tile spec must be an int or (th, tw), got {tile!r}"
            )
        return int(tile[0]), int(tile[1])
    return int(tile), int(tile)


def geom_tile(geom):
    """Wire-geometry tuple -> ``(th, tw)`` tile pixel dims: the square
    v1 form is ``[h, w, c, t]``, the rectangular form ``[h, w, c, th,
    tw]`` (see :func:`tileshape_wire`)."""
    if len(geom) >= 5:
        return int(geom[3]), int(geom[4])
    return int(geom[3]), int(geom[3])


def tileshape_wire(h, w, c, tile):
    """Geometry -> the wire ``__tileshape`` list. Square tiles keep the
    4-element v1 form so consumers of either vintage decode square
    streams; rectangular tiles use the 5-element form."""
    th, tw = tile_hw(tile)
    base = [int(h), int(w), int(c), th]
    return base if th == tw else base + [tw]


def tile_grid(shape, tile=TILE):
    """(H, W, C) image shape -> (GH, GW) tile-grid shape.

    ``tile`` is an int side or a ``(th, tw)`` pair. Raises if the tile
    size does not divide the image dims (callers should fall back to
    raw frames for such shapes).
    """
    th, tw = tile_hw(tile)
    h, w = int(shape[0]), int(shape[1])
    if h % th or w % tw:
        raise ValueError(f"tile {th}x{tw} does not divide image {h}x{w}")
    return h // th, w // tw


class TileDeltaEncoder:
    """Per-stream host-side encoder: images -> (idx, tiles) deltas.

    Holds the reference image and preallocated staging buffers so the
    per-frame cost is one changed-tile scan plus copies of only the
    changed tiles. Use one encoder per stream/scene.
    """

    def __init__(self, ref: np.ndarray, tile=TILE):
        ref = np.ascontiguousarray(ref)
        if ref.dtype != np.uint8 or ref.ndim != 3:
            raise ValueError(f"ref must be (H, W, C) uint8, got {ref.shape} {ref.dtype}")
        self.ref = ref
        self.th, self.tw = tile_hw(tile)
        self.tile = tile  # original spec (int or pair), for repr/pickle
        self.grid = tile_grid(ref.shape, (self.th, self.tw))
        self.num_tiles = self.grid[0] * self.grid[1]
        h, w, c = ref.shape
        self._idx = np.empty((self.num_tiles,), np.int32)
        self._tiles = np.empty((self.num_tiles, self.th, self.tw, c), np.uint8)
        from blendjax._native import load_tile_delta

        self._native = load_tile_delta()
        self._native_palidx = None  # resolved on first encode_palidx
        self._pal_state = None

    def _check_frame(self, img: np.ndarray) -> None:
        if img.shape != self.ref.shape or img.dtype != np.uint8:
            raise ValueError(
                f"frame shape {img.shape}/{img.dtype} != ref "
                f"{self.ref.shape}/uint8"
            )

    def __getstate__(self):
        """Copy/pickle safety: drop the native handles (ctypes functions
        don't pickle) and the palette state — its cached raw buffer
        addresses would alias the ORIGINAL encoder's buffers in a
        deepcopy, or point at garbage in a spawned process. Both rebuild
        lazily."""
        state = dict(self.__dict__)
        state["_native"] = None
        state["_native_palidx"] = None
        state["_pal_state"] = None
        # staging buffers are uninitialized scratch (MBs for large
        # streams) — drop them too; shapes re-derive from ref/tile
        state.pop("_palidx_stage", None)
        state.pop("_idx", None)
        state.pop("_tiles", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        from blendjax._native import load_tile_delta

        self._native = load_tile_delta()
        c = self.ref.shape[2]
        self._idx = np.empty((self.num_tiles,), np.int32)
        self._tiles = np.empty(
            (self.num_tiles, self.th, self.tw, c), np.uint8
        )

    def tile_bounds(self, hint):
        """Pixel-rect ``hint`` -> tile-grid scan bounds
        ``(ty0, ty1, tx0, tx1)`` (full grid for ``hint=None``)."""
        th, tw = self.th, self.tw
        gh, gw = self.grid
        if hint is None:
            return 0, gh, 0, gw
        y0, y1, x0, x1 = hint
        return (
            max(y0 // th, 0), min(-(-y1 // th), gh),
            max(x0 // tw, 0), min(-(-x1 // tw), gw),
        )

    def encode(self, img: np.ndarray, hint=None):
        """One frame -> ``(idx int32[K], tiles uint8[K, t, t, C])`` views
        into internal staging (valid until the next ``encode`` call).

        ``hint`` is an optional pixel rect ``(y0, y1, x0, x1)`` promising
        that pixels outside it equal the reference (e.g. the rasterizer's
        ``last_drawn`` dirty rect) — the scan then touches only the tiles
        the rect overlaps. ``hint=None`` scans the full frame.
        """
        th, tw = self.th, self.tw
        h, w, c = self.ref.shape
        gh, gw = self.grid
        self._check_frame(img)
        ty0, ty1, tx0, tx1 = self.tile_bounds(hint)
        if ty0 >= ty1 or tx0 >= tx1:
            return self._idx[:0], self._tiles[:0]
        if self._native is not None and img.flags.c_contiguous:
            import ctypes

            u8 = ctypes.POINTER(ctypes.c_uint8)
            count = self._native(
                img.ctypes.data_as(u8),
                self.ref.ctypes.data_as(u8),
                h, w, c, th, tw, ty0, ty1, tx0, tx1,
                self._idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                self._tiles.ctypes.data_as(u8),
            )
            return self._idx[:count], self._tiles[:count]
        v = img.reshape(gh, th, gw, tw, c)
        r = self.ref.reshape(gh, th, gw, tw, c)
        sub = (v[ty0:ty1, :, tx0:tx1] != r[ty0:ty1, :, tx0:tx1]).any(
            axis=(1, 3, 4)
        )  # (ty1-ty0, tx1-tx0)
        sy, sx = np.nonzero(sub)
        idx = ((sy + ty0) * gw + (sx + tx0)).astype(np.int32)
        k = len(idx)
        self._idx[:k] = idx
        # Advanced indexing (rows, :, cols) puts the K axis first -> (K,th,tw,C).
        self._tiles[:k] = v[idx // gw, :, idx % gw]
        return self._idx[:k], self._tiles[:k]

    # -- fused scan + palettize (native only) -------------------------------

    def palidx_available(self) -> bool:
        """True when the fused scan+palettize (``encode_palidx``) can run
        (native helpers built, <= 4 channels)."""
        if self.ref.shape[2] > 4:
            return False
        if self._native_palidx is None:
            from blendjax._native import load_tile_delta_palidx

            self._native_palidx = load_tile_delta_palidx()
        return self._native_palidx is not None

    def reset_palette(self) -> None:
        """Clear the palette table (call at each batch boundary so
        color-drifting scenes never exhaust the 256 entries)."""
        if self._pal_state is not None:
            self._pal_state["vals"].fill(-1)
            self._pal_state["count"][0] = 0

    @property
    def palette(self) -> np.ndarray:
        """(256, C) uint8 palette filled up to ``palette_count``."""
        return self._pal_state["table"]

    @property
    def palette_count(self) -> int:
        return int(self._pal_state["count"][0]) if self._pal_state else 0

    def encode_palidx(self, img: np.ndarray, hint=None):
        """One frame -> ``(idx int32[K], palidx uint8[K, t*t])`` views
        into internal staging — the fused form of :meth:`encode` that
        emits palette indices against the encoder's palette table
        instead of raw tiles (one pass; no tile materialization).

        Returns ``None`` when a pixel would push the table past 256
        colors — the caller falls back to :meth:`encode` (the table
        state stays valid). The caller owns the reset policy via
        :meth:`reset_palette` (TileBatchPublisher resets per frame and
        ships per-row palette snapshots).
        """
        if not self.palidx_available():
            return None
        self._check_frame(img)
        img = np.ascontiguousarray(img)
        h, w, c = self.ref.shape
        if self._pal_state is None:
            s = {
                "keys": np.zeros(1024, np.uint32),
                "vals": np.full(1024, -1, np.int16),
                "table": np.zeros((256, c), np.uint8),
                "count": np.zeros(1, np.int64),
            }
            self._palidx_stage = np.empty(
                (self.num_tiles, self.th * self.tw), np.uint8
            )
            # Pointers to the persistent buffers are cached as plain
            # ints (the native argtypes are void*): re-marshalling 8
            # ctypes pointer objects per frame costs ~0.05ms — real
            # money in a ~1ms/frame producer loop.
            s["ptrs"] = (
                self.ref.ctypes.data,
                self._idx.ctypes.data,
                self._palidx_stage.ctypes.data,
                s["keys"].ctypes.data,
                s["vals"].ctypes.data,
                s["table"].ctypes.data,
                s["count"].ctypes.data,
            )
            self._pal_state = s
        ty0, ty1, tx0, tx1 = self.tile_bounds(hint)
        (p_ref, p_idx, p_stage, p_keys, p_vals, p_table, p_count
         ) = self._pal_state["ptrs"]
        k = self._native_palidx(
            img.ctypes.data, p_ref,
            h, w, c, self.th, self.tw, ty0, ty1, tx0, tx1,
            p_idx, p_stage, p_keys, p_vals, p_table, p_count,
            256,
        )
        if k < 0:
            return None
        return self._idx[:k], self._palidx_stage[:k]


def pack_batch(deltas, num_tiles: int, bucket: int = 16, capacity=None):
    """Pack per-frame ``(idx, tiles)`` deltas into fixed-capacity batch
    arrays.

    Every distinct capacity is a distinct ``(B, K, ...)`` shape, and each
    shape costs one jit compilation of the consumer's decode — so stable
    capacities matter more than tight ones. Pass ``capacity`` (a sticky
    per-stream value the producer grows only on overflow) to pin the
    shape; without it, capacity is the batch's largest per-frame tile
    count rounded up to a multiple of ``bucket``. Padding slots carry the
    sentinel index ``num_tiles`` which the device scatter drops.

    Returns ``(idx (B, K) int32, tiles (B, K, t, t, C) uint8)``.
    """
    b = len(deltas)
    kmax = max((len(i) for i, _ in deltas), default=0)
    bucket = max(int(bucket), 1)
    if capacity is not None and int(capacity) >= kmax:
        cap = int(capacity)
    else:
        cap = max(-(-kmax // bucket) * bucket, bucket)
    cap = min(cap, num_tiles)
    th, tw, c = deltas[0][1].shape[1], deltas[0][1].shape[2], deltas[0][1].shape[3]
    idx = np.full((b, cap), num_tiles, np.int32)
    tiles = np.empty((b, cap, th, tw, c), np.uint8)
    for i, (fi, ft) in enumerate(deltas):
        k = len(fi)
        idx[i, :k] = fi
        tiles[i, :k] = ft
        tiles[i, k:] = 0  # don't ship uninitialized heap bytes in padding
    return idx, tiles


def pop_stream_refs(msg: dict, refs: dict, btid) -> None:
    """Pop every ``<name>__tileref`` entry of a message into ``refs``
    keyed ``(name, btid)`` — the shared wire-convention bookkeeping for
    all tile-stream consumers (device pipeline and torch adapter)."""
    for key in [k for k in msg if k.endswith(TILEREF_SUFFIX)]:
        refs[(key[: -len(TILEREF_SUFFIX)], btid)] = msg.pop(key)


def pop_tile_batches(msg: dict):
    """Pop tile-delta geometry entries from a message.

    Returns ``[(name, geom), ...]`` — empty for non-tile messages —
    where ``geom`` is the wire tuple ``(h, w, c, t)`` for square tiles
    or ``(h, w, c, th, tw)`` for rectangular ones (decode the tile dims
    with :func:`geom_tile`, never by indexing position 3). The payload fields (``__tileidx`` plus ``__tiles`` or the
    palette-compressed ``__tilepal4/8`` + ``__palette``) stay in the
    message for the caller to transfer/decode. Callers look refs up
    under ``(name, btid)`` and should SKIP (not fail) messages whose ref
    hasn't arrived yet: with fair fan-in across multiple consumers, the
    one-time (or keyframe-interval) reference lands on one consumer's
    socket at a time.
    """
    out = []
    for key in [k for k in msg if k.endswith(TILESHAPE_SUFFIX)]:
        name = key[: -len(TILESHAPE_SUFFIX)]
        out.append((name, tuple(int(v) for v in msg.pop(key))))
    return out


def pop_tile_payload(fields: dict, name: str, geom, expand):
    """Pop ``name``'s tile payload from ``fields`` and return the
    expanded (K-leading) tile array, where ``expand`` is
    :func:`expand_palette_tiles` (device) or
    :func:`expand_palette_tiles_np` (host). Shared by every consumer so
    the raw-vs-palette wire variants stay in one place."""
    t = geom_tile(geom)
    for bits, suffix in TILEPAL_SUFFIXES.items():
        if name + suffix in fields:
            packed = fields.pop(name + suffix)
            pal = fields.pop(name + PALETTE_SUFFIX)
            return expand(packed, pal, bits, t, pal.shape[-1])
    return fields.pop(name + TILES_SUFFIX)


def decode_tile_delta_np(ref: np.ndarray, idx: np.ndarray,
                         tiles: np.ndarray, tile=None) -> np.ndarray:
    """Host-side (numpy) reconstruction — for consumers that never touch
    a device, e.g. the torch-compat dataset adapter. Same semantics as
    :func:`decode_tile_delta`: sentinel indices are dropped, channel-
    sliced tiles restore their remaining channels from the reference.

    ``idx``: (B, K) int32; ``tiles``: (B, K, th, tw, Ct) — the tile
    pixel dims come from the tiles array itself (``tile`` is accepted
    for back-compat and ignored). Returns (B, H, W, C) uint8, bit-exact.
    """
    del tile
    h, w, c = ref.shape
    th, tw = tiles.shape[2], tiles.shape[3]
    gh, gw = tile_grid(ref.shape, (th, tw))
    n = gh * gw
    b = idx.shape[0]
    ct = tiles.shape[-1]
    out = np.broadcast_to(ref, (b, h, w, c)).copy()
    ov = out.reshape(b, gh, th, gw, tw, c)
    for bi in range(b):
        # Positional like the device decoder: mask BOTH idx and tiles so
        # sentinels anywhere (not just a suffix) pair correctly.
        m = idx[bi] < n
        real = idx[bi][m]
        # (K,) flat ids -> rows/cols; advanced indexing puts K first
        ov[bi, real // gw, :, real % gw, :, :ct] = tiles[bi][m]
    return out


# -- palette compression (host encode / device expand) ----------------------
#
# Flat-shaded synthetic frames carry very few distinct colors, so the
# changed tiles compress losslessly to palette indices: <=16 colors ->
# two 4-bit indices per byte (8x fewer bytes than RGBA), <=256 -> one
# byte per pixel (4x). The device side is a trivial fused gather.


def _palettize_flat(flat: np.ndarray, max_colors: int):
    """Core palette pass over (N, C) uint8 pixels: returns
    ``(idx (N,) uint8, palette (max_colors, C), count)`` or ``None``
    when the pixels hold more than ``max_colors`` distinct colors.
    One native C pass when available; numpy fallback."""
    from blendjax._native import load_palettize

    n, c = flat.shape
    native = load_palettize()
    if native is not None:
        import ctypes

        pal = np.zeros((max_colors, c), np.uint8)
        idx = np.empty((n,), np.uint8)
        u8 = ctypes.POINTER(ctypes.c_uint8)
        count = native(
            flat.ctypes.data_as(u8), n, c, max_colors,
            pal.ctypes.data_as(u8), idx.ctypes.data_as(u8),
        )
        if count < 0:
            return None
        return idx, pal, count
    key = np.zeros(n, np.uint32)
    for j in range(c):
        key |= flat[:, j].astype(np.uint32) << (8 * j)
    uniq, idx32 = np.unique(key, return_inverse=True)
    count = len(uniq)
    if count > max_colors:
        return None
    idx = idx32.astype(np.uint8)
    pal = np.zeros((max_colors, c), np.uint8)
    for j in range(c):
        pal[:count, j] = (uniq >> (8 * j)).astype(np.uint8)
    return idx, pal, count


def palettize_tiles(tiles: np.ndarray, max_colors: int = 256):
    """Try to palette-compress a packed tile array (B, K, t, t, C).

    Returns ``(packed, palette, bits)`` — ``packed`` is
    (B, K, t*t/4 | t*t/2 | t*t) uint8 for ``bits`` 2/4/8 (chosen by the
    batch's distinct-color count: <=4 / <=16 / <=256), ``palette`` is
    (4|16|256, C) zero-padded — or ``None`` when the tiles hold more
    than ``max_colors`` distinct colors (ship raw instead). Runs as one
    native C pass when available; numpy fallback.
    """
    max_colors = min(int(max_colors), 256)  # uint8 indices; native tables
    b, k, th, tw, c = tiles.shape
    tt = th * tw
    flat = np.ascontiguousarray(tiles).reshape(-1, c)
    out = _palettize_flat(flat, max_colors)
    if out is None:
        return None
    idx, pal, count = out
    if count <= 4 and tt % 4 == 0:
        pal4c = np.zeros((4, c), np.uint8)
        pal4c[: min(len(pal), 4)] = pal[:4]
        packed = pack_palette_indices(idx, 2).reshape(b, k, tt // 4)
        return packed, pal4c, 2
    if count <= 16 and tt % 2 == 0:
        pal16 = np.zeros((16, c), np.uint8)
        pal16[: min(len(pal), 16)] = pal[:16]
        packed = pack_palette_indices(idx, 4).reshape(b, k, tt // 2)
        return packed, pal16, 4
    return idx.reshape(b, k, tt), pal, 8


def palettize_frames(frames: np.ndarray, max_colors: int = 256):
    """Try to palette-compress FULL frames (B, H, W, C) — the lossless
    wire+transfer codec for the non-sparse path (no reference frame, no
    temporal assumption; only "synthetic frames carry few colors").

    PER-FRAME palettes: each frame indexes its own color table, so one
    frame's count — not the batch's — picks the index width (a
    flat-shaded frame is typically <=4 colors even when the batch
    drifts past 16). Returns ``(packed, palette, bits)`` — ``packed``
    (B, H*W/4 | H*W/2 | H*W) uint8 for ``bits`` 2/4/8 (16x/8x/4x fewer
    bytes than RGBA across BOTH the socket and the host->device link;
    the device side is one fused gather through ``palette`` (B, cap,
    C)) — or ``None`` when any single frame holds more than
    ``max_colors`` distinct colors (ship raw instead).
    """
    max_colors = min(int(max_colors), 256)
    b, h, w, c = frames.shape
    hw = h * w
    rows = []
    counts = []
    frames = np.ascontiguousarray(frames)
    for i in range(b):
        out = _palettize_flat(frames[i].reshape(-1, c), max_colors)
        if out is None:
            return None
        idx, pal, count = out
        rows.append((idx, pal))
        counts.append(count)
    cmax = max(counts) if counts else 0
    if cmax <= 4 and hw % 4 == 0:
        bits, cap = 2, 4
    elif cmax <= 16 and hw % 2 == 0:
        bits, cap = 4, 16
    else:
        bits, cap = 8, 256
    palette = np.zeros((b, cap, c), np.uint8)
    packed = np.empty((b, hw * bits // 8), np.uint8)
    for i, (idx, pal) in enumerate(rows):
        palette[i, : counts[i]] = pal[: counts[i]]
        packed[i] = pack_palette_indices(idx, bits)
    return packed, palette, bits


def _lut_expand(packed, palette, bits: int):
    """Device-side byte-LUT palette expand: ONE gather per packed byte
    through a 256-entry LUT (byte value -> ``8/bits`` pixels x C bytes,
    built on device from the palette) instead of bit-unpack + per-pixel
    gather. Bit-exact by construction; measured 1.2x faster than the
    unpack+gather chain on a v5e (scripts/exp_lut_expand.py).

    ``packed``: (..., M) uint8; ``palette``: (cap, C). Returns
    (..., M, (8/bits)*C) uint8 — the caller reshapes (packed bytes hold
    consecutive pixels of the flattened pixel axis, so flattening the
    last two dims restores flat pixel-major x channel order).
    """
    import jax.numpy as jnp

    px = 8 // bits
    nib = unpack_palette_indices(
        jnp.arange(256, dtype=jnp.uint8)[:, None], bits, jnp
    )  # (256, px) index table, built once per jit trace
    c = palette.shape[-1]
    lut = palette[nib].reshape(256, px * c)
    return lut[packed]


def expand_palette_frames(packed, palette, bits: int, h: int, w: int,
                          c: int):
    """Device-side inverse of :func:`palettize_frames` (jit-safe
    gather). ``packed``: (..., H*W/4|H*W/2|H*W) uint8; ``palette``:
    (cap, C) batch-level, or (..., cap, C) per-row with leading axes
    matching ``packed``'s (each row gathers through its own table).
    Returns (..., H, W, C) uint8."""
    import jax.numpy as jnp

    if palette.ndim >= 3:
        import jax

        return jax.vmap(
            lambda p, q: expand_palette_frames(p, q, bits, h, w, c)
        )(packed, palette)
    lead = packed.shape[:-1]
    if bits < 8:
        return _lut_expand(packed, palette, bits).reshape(*lead, h, w, c)
    idx = unpack_palette_indices(packed, bits, jnp)
    return palette[idx].reshape(*lead, h, w, c)


def expand_palette_frames_np(packed, palette, bits: int, h: int, w: int,
                             c: int):
    """Host (numpy) twin of :func:`expand_palette_frames`."""
    if palette.ndim >= 3:
        return np.stack([
            expand_palette_frames_np(p, q, bits, h, w, c)
            for p, q in zip(packed, palette)
        ])
    lead = packed.shape[:-1]
    idx = unpack_palette_indices(packed, bits, np)
    return palette[idx].reshape(*lead, h, w, c)


def pop_frame_palette_payload(fields: dict, name: str, bits: int, h: int,
                              w: int, c: int, expand):
    """Pop ``name``'s full-frame palette payload from ``fields`` and
    return the expanded frames, where ``expand`` is
    :func:`expand_palette_frames` (device) or
    :func:`expand_palette_frames_np` (host). Shared by every consumer
    (pipeline fast paths, host fallbacks, torch adapter) so the 2/4/8-
    bit wire variants stay in one place."""
    packed = fields.pop(name + FRAMEPAL_SUFFIXES[bits])
    pal = fields.pop(name + PALETTE_SUFFIX)
    return expand(packed, pal, bits, h, w, c)


def pop_frame_palette_batches(hb: dict):
    """Detect+pop full-frame palette batches from a host batch: returns
    ``[(name, (h, w, c, bits))]`` and removes each ``name__frameshape``
    sidecar (the payload/palette fields stay for the decode stage)."""
    out = []
    for key in [k for k in hb if k.endswith(FRAMESHAPE_SUFFIX)]:
        name = key[: -len(FRAMESHAPE_SUFFIX)]
        h, w, c, bits = (int(v) for v in hb.pop(key))
        out.append((name, (h, w, c, bits)))
    return out


def expand_palette_tiles(packed, palette, bits: int, t, c: int):
    """Device-side inverse of :func:`palettize_tiles` (jit-safe gather).

    ``packed``: (..., K, t*t/2|t*t) uint8; ``palette``: (cap, C), or
    (..., cap, C) with leading axes matching ``packed``'s leading dims
    (per-frame palettes, and the chunked-decode case stacks another
    level) — each row then gathers through its own palette. ``t`` is an
    int side or ``(th, tw)`` pair. Returns (..., K, th, tw, C) uint8.
    """
    import jax.numpy as jnp

    th, tw = tile_hw(t)
    if palette.ndim >= 3:
        import jax

        return jax.vmap(
            lambda p, q: expand_palette_tiles(p, q, bits, t, c)
        )(packed, palette)
    lead = packed.shape[:-1]
    if bits < 8:
        return _lut_expand(packed, palette, bits).reshape(
            *lead, th, tw, c
        )
    idx = unpack_palette_indices(packed, bits, jnp)
    return palette[idx].reshape(*lead, th, tw, c)


def expand_palette_tiles_np(packed, palette, bits: int, t, c: int):
    """Host (numpy) twin of :func:`expand_palette_tiles`."""
    th, tw = tile_hw(t)
    if palette.ndim >= 3:
        return np.stack([
            expand_palette_tiles_np(p, q, bits, t, c)
            for p, q in zip(packed, palette)
        ])
    lead = packed.shape[:-1]
    idx = unpack_palette_indices(packed, bits, np)
    return palette[idx].reshape(*lead, th, tw, c)


# -- run-length "ndr" tile-group codec (host encode / device expand) --------
#
# Palette indices (and flat-shaded uint8 frames generally) are run-heavy:
# a background-dominated row is a handful of (value, run) pairs. The
# "ndr" wire kind (blendjax.transport.wire) ships those pairs instead of
# zlib streams, so the consumer either inflates with one vectorized
# np.repeat (still ~10x cheaper than a zlib inflate) or — the fused
# path — defers the expansion to a jitted gather INSIDE the train
# dispatch (:func:`rle_expand_packed`), where it costs zero host time.
#
# Packed per-row layout (one uint8 buffer of shape (rows, cap*(isz+2))):
#   [values: cap x isz bytes][run lo-bytes: cap][run hi-bytes: cap]
# ``isz`` is the run item width in bytes (4 for RGBA pixel runs, 1 for
# palette indices); runs are uint16 split into explicit lo/hi planes so
# host and device decode share one endian-free definition. Unused tail
# entries carry run == 0 and expand to nothing. ``cap`` is the per-row
# pair capacity — sticky per publisher key and bucket-rounded, so the
# packed shape (and with it the consumer's jit cache) stays stable
# across frames, exactly like ``pack_batch``'s tile capacity.

NDR_SUFFIX = "__ndr"          # deferred packed run buffer (rows, stride)
NDRSPEC_SUFFIX = "__ndrspec"  # sidecar [shape, isz, cap] riding the batch

RLE_MAX_RUN = 0xFFFF  # uint16 run length; longer runs split at encode
RLE_BUCKET = 64       # cap rounding granularity (jit-cache stability)


def rle_item_size(shape) -> int:
    """Run item width in bytes for a uint8 array ``shape``: the trailing
    channel dim when it looks like pixels ((..., C) with C <= 4), else
    single bytes. One definition shared by encoder and decoder."""
    if len(shape) >= 2 and 2 <= int(shape[-1]) <= 4:
        return int(shape[-1])
    return 1


def rle_packed_stride(cap: int, isz: int) -> int:
    return int(cap) * (int(isz) + 2)


def _rle_geometry(shape, isz: int):
    """shape -> (rows, items-per-row). Rows are the leading axis (the
    batch of a batched field, scan lines of a single frame)."""
    shape = tuple(int(s) for s in shape)
    total = 1
    for s in shape:
        total *= s
    rows = shape[0] if len(shape) >= 2 else 1
    if rows <= 0 or total <= 0:
        raise ValueError(f"ndr geometry needs a non-empty shape, got {shape}")
    row_bytes, rem = divmod(total, rows)
    if rem or row_bytes % isz:
        raise ValueError(
            f"ndr geometry {shape} does not split into rows of whole "
            f"{isz}-byte items"
        )
    return rows, row_bytes // isz


def rle_encode_rows(arr: np.ndarray, cap: int | None = None,
                    bucket: int = RLE_BUCKET):
    """Run-length encode a uint8 array row-wise into the packed wire
    layout. Returns ``(buf (rows, cap*(isz+2)) uint8, cap, isz)`` or
    ``None`` when the array is ineligible (non-uint8, empty) or does
    not fit: a pinned ``cap`` too small for this frame's run count
    (caller falls back to raw — the per-key skip memo in
    ``blendjax.transport.wire`` keeps that cheap)."""
    if not isinstance(arr, np.ndarray) or arr.dtype != np.uint8 or arr.size == 0:
        return None
    isz = rle_item_size(arr.shape)
    try:
        rows, t = _rle_geometry(arr.shape, isz)
    except ValueError:
        isz = 1
        rows, t = _rle_geometry(arr.shape, isz)
    flat = np.ascontiguousarray(arr).reshape(rows, t, isz)
    per = []
    kmax = 1
    for r in range(rows):
        row = flat[r]
        change = np.empty(t, np.bool_)
        change[0] = True
        if t > 1:
            np.any(row[1:] != row[:-1], axis=1, out=change[1:])
        starts = np.flatnonzero(change)
        runs = np.diff(np.append(starts, t)).astype(np.int64)
        if len(runs) and runs.max() > RLE_MAX_RUN:
            reps = (runs + RLE_MAX_RUN - 1) // RLE_MAX_RUN
            vals = np.repeat(row[starts], reps, axis=0)
            split = np.full(int(reps.sum()), RLE_MAX_RUN, np.int64)
            split[np.cumsum(reps) - 1] = runs - (reps - 1) * RLE_MAX_RUN
            runs = split
        else:
            vals = row[starts]
        kmax = max(kmax, len(runs))
        per.append((vals, runs))
    if cap is not None:
        if kmax > int(cap):
            return None
        cap = int(cap)
    else:
        bucket = max(int(bucket), 1)
        cap = max(-(-kmax // bucket) * bucket, bucket)
    buf = np.zeros((rows, rle_packed_stride(cap, isz)), np.uint8)
    vals_plane = buf[:, : cap * isz].reshape(rows, cap, isz)
    lo_plane = buf[:, cap * isz: cap * (isz + 1)]
    hi_plane = buf[:, cap * (isz + 1):]
    for r, (vals, runs) in enumerate(per):
        k = len(runs)
        vals_plane[r, :k] = vals
        lo_plane[r, :k] = (runs & 0xFF).astype(np.uint8)
        hi_plane[r, :k] = (runs >> 8).astype(np.uint8)
    return buf, cap, isz


def _rle_runs_np(buf: np.ndarray, cap: int, isz: int):
    vals = buf[:, : cap * isz].reshape(buf.shape[0], cap, isz)
    lo = buf[:, cap * isz: cap * (isz + 1)].astype(np.uint32)
    hi = buf[:, cap * (isz + 1):].astype(np.uint32)
    return vals, lo | (hi << 8)


def rle_validate_packed(buf, shape, isz: int, cap: int) -> None:
    """Hostile-stream guards for a packed run buffer — the ndz decode
    bounds carried over to the DEFERRED device plan: allocation is
    bounded by the declared shape, the buffer must carry exactly the
    declared capacity, and each row's runs must sum to the declared
    item count (truncated or padded streams fail loudly here instead of
    expanding to garbage inside the train jit). Cheap: reads only the
    2*cap run bytes per row, never the values."""
    isz, cap = int(isz), int(cap)
    if isz < 1 or isz > 16 or cap < 1:
        raise ValueError(f"ndr spec out of bounds (isz={isz}, cap={cap})")
    rows, t = _rle_geometry(shape, isz)  # raises on zero-byte shapes
    buf = np.asarray(buf)
    if buf.dtype != np.uint8 or buf.shape != (rows, rle_packed_stride(cap, isz)):
        raise ValueError(
            f"ndr buffer shape {buf.shape}/{buf.dtype} does not match "
            f"declared rows={rows} cap={cap} isz={isz}"
        )
    _, runs = _rle_runs_np(buf, cap, isz)
    sums = runs.sum(axis=1)
    if not (sums == t).all():
        raise ValueError(
            f"ndr rows do not expand to the declared {t} items "
            f"(row sums {sums.min()}..{sums.max()})"
        )


def rle_expand_packed_np(buf: np.ndarray, shape, isz: int, cap: int):
    """Host (numpy) inverse of :func:`rle_encode_rows` — what the wire
    decode uses when the consumer does not defer to device. Validates
    first (same guards as the deferred plan)."""
    rle_validate_packed(buf, shape, isz, cap)
    shape = tuple(int(s) for s in shape)
    rows, _t = _rle_geometry(shape, int(isz))
    vals, runs = _rle_runs_np(np.asarray(buf), int(cap), int(isz))
    out = np.concatenate(
        [np.repeat(vals[r], runs[r], axis=0) for r in range(rows)]
    )
    return out.reshape(shape)


def rle_expand_packed(buf, shape, isz: int, cap: int):
    """Device-side (jit-safe) inverse of :func:`rle_encode_rows`: one
    ``cumsum`` over the run planes plus one ``searchsorted`` gather per
    row — the scan/gather that lets ``make_fused_tile_step`` decompress
    the wire INSIDE the train dispatch with zero host inflate cost.
    Static shapes come from the decode plan; a hostile buffer that
    slipped past host validation can only produce wrong pixels, never
    out-of-bounds memory (indices clamp to ``cap``)."""
    import jax
    import jax.numpy as jnp

    shape = tuple(int(s) for s in shape)
    isz, cap = int(isz), int(cap)
    rows, t = _rle_geometry(shape, isz)
    buf = buf.reshape(rows, rle_packed_stride(cap, isz))
    vals = buf[:, : cap * isz].reshape(rows, cap, isz)
    lo = buf[:, cap * isz: cap * (isz + 1)].astype(jnp.uint32)
    hi = buf[:, cap * (isz + 1):].astype(jnp.uint32)
    ends = jnp.cumsum(lo | (hi << 8), axis=1)
    pos = jnp.arange(t, dtype=jnp.uint32)
    idx = jax.vmap(
        lambda e: jnp.searchsorted(e, pos, side="right")
    )(ends)
    out = jax.vmap(lambda v, i: v[jnp.minimum(i, cap - 1)])(vals, idx)
    return out.reshape(shape)


def pop_rle_batches(fields: dict):
    """Detect+pop deferred run-length sidecars from a host batch:
    returns the static plan ``((base, (shape, isz, cap)), ...)`` and
    removes each ``<base>__ndrspec`` entry (the ``<base>__ndr`` buffer
    stays for packing/transfer). The shared bookkeeping for every
    consumer of deferred "ndr" wire frames."""
    out = []
    for key in [k for k in fields if k.endswith(NDRSPEC_SUFFIX)]:
        base = key[: -len(NDRSPEC_SUFFIX)]
        shape, isz, cap = fields.pop(key)
        out.append((base, (tuple(int(s) for s in shape), int(isz), int(cap))))
    return tuple(out)


def expand_rle_fields(fields: dict, rle_groups) -> dict:
    """Expand every deferred run buffer of an (unpacked, on-device)
    field dict in place — jit-safe; runs FIRST in the decode entry
    points below so palette/tile expansion sees the restored fields."""
    for base, (shape, isz, cap) in rle_groups:
        fields[base] = rle_expand_packed(
            fields.pop(base + NDR_SUFFIX), shape, isz, cap
        )
    return fields


# -- packed single-transfer form --------------------------------------------
#
# On remote/tunneled device hosts every host->device op pays a round trip,
# so a batch spread over five arrays (idx, tiles, labels, ids, ...) costs
# 5x the latency of one. pack_fields/unpack_fields collapse a batch dict
# into ONE uint8 buffer + a static spec; the unpack runs under jit on
# device (slice + bitcast), so the whole batch rides a single device_put.


# 64-bit payloads are value-cast to 32 bits on the host before packing —
# the same width jax's dtype canonicalization would give them on
# device_put (and, for floats, a correct numeric conversion where a raw
# bitcast would silently produce garbage). Skipped entirely when
# jax_enable_x64 is set (device_put would keep 64 bits then, and the
# packed path must match the raw-frame path bit for bit). Integer
# narrowing is range-checked: a value that doesn't fit 32 bits (e.g. a
# time_ns timestamp) raises instead of silently wrapping.
_PACK_NARROW = {
    np.dtype(np.float64): np.float32,
    np.dtype(np.int64): np.int32,
    np.dtype(np.uint64): np.uint32,
}


def _narrow_for_pack(name: str, arr: np.ndarray) -> np.ndarray:
    import jax

    if jax.config.jax_enable_x64:
        return arr  # device keeps 64 bits; pack must too
    target = _PACK_NARROW[arr.dtype]
    if arr.dtype.kind in "iu" and arr.size:
        info = np.iinfo(target)
        lo, hi = int(arr.min()), int(arr.max())
        if lo < info.min or hi > info.max:
            raise ValueError(
                f"pack_fields: field {name!r} ({arr.dtype}) holds values "
                f"[{lo}, {hi}] that do not fit {np.dtype(target)} — "
                "pre-cast the field on the producer (e.g. ms instead of "
                "time_ns) or enable jax_enable_x64"
            )
    return arr.astype(target)


def pack_fields(fields: dict):
    """Concatenate ndarray fields into one uint8 buffer.

    Returns ``(buf uint8[total], spec)`` where ``spec`` is a hashable
    tuple of ``(name, dtype_str, shape, offset, nbytes)`` suitable as a
    static jit argument for :func:`unpack_fields`. 64-bit fields are
    narrowed to 32 bits first (see ``_PACK_NARROW``) and bools travel as
    bytes, so every packed dtype reconstructs exactly on device.
    """
    spec = []
    offset = 0
    parts = []
    for name, arr in fields.items():
        arr = np.ascontiguousarray(arr)
        if arr.dtype in _PACK_NARROW:
            arr = _narrow_for_pack(name, arr)
        raw = arr.view(np.uint8).reshape(-1)
        spec.append((name, arr.dtype.str, arr.shape, offset, raw.nbytes))
        parts.append(raw)
        offset += raw.nbytes
    return np.concatenate(parts), tuple(
        (n, d, tuple(int(x) for x in s), o, b) for n, d, s, o, b in spec
    )


def unpack_fields(buf, spec):
    """Device-side inverse of :func:`pack_fields` (jit-safe: slices +
    ``lax.bitcast_convert_type``). ``buf`` is the transferred uint8
    buffer; returns ``{name: array}``."""
    from jax import lax

    out = {}
    for name, dtype_str, shape, offset, nbytes in spec:
        dt = np.dtype(dtype_str)
        raw = lax.dynamic_slice_in_dim(buf, offset, nbytes)
        if dt == np.uint8:
            arr = raw
        elif dt == np.bool_:
            arr = raw.astype(np.bool_)  # packed as 0/1 bytes
        elif dt.itemsize == 1:
            arr = lax.bitcast_convert_type(raw, dt)
        else:
            arr = lax.bitcast_convert_type(raw.reshape(-1, dt.itemsize), dt)
        out[name] = arr.reshape(shape)
    return out


def decode_packed_superbatch(packed, refs, spec, names, geoms,
                             mesh=None, data_axis: str = "data",
                             rle_groups=()):
    """Decode a stacked packed chunk group to full fields — jit-safe.

    ``packed``: (K, total) uint8, K packed batches of identical layout
    ``spec``. Each image field in ``names`` is reconstructed against its
    device reference ``refs[name]`` with the per-name geometry in
    ``geoms``; every name's tiles decode flattened over (K*B) in ONE
    scatter call. Returns ``{field: (K, B, ...)}`` — all sidecar fields
    keep their packed (K, ...) shapes.

    Shared by :class:`blendjax.data.TileStreamDecoder` (decode-then-step)
    and :func:`blendjax.train.make_fused_tile_step` (decode fused into
    the train jit: one device call per K batches instead of two, which
    matters on high-latency device links).
    """
    import jax

    fields = jax.vmap(
        lambda p: expand_rle_fields(unpack_fields(p, spec), rle_groups)
    )(packed)
    for name, geom in zip(names, geoms):
        idx = fields.pop(name + TILEIDX_SUFFIX)
        tiles = pop_tile_payload(fields, name, geom, expand_palette_tiles)
        k, b = idx.shape[:2]
        img = decode_tile_delta(
            refs[name],
            idx.reshape(k * b, *idx.shape[2:]),
            tiles.reshape(k * b, *tiles.shape[2:]),
            geom[:3],
            mesh=mesh, data_axis=data_axis,
        )
        fields[name] = img.reshape(k, b, *img.shape[1:])
    return fields


def decode_packed_pal_batch(packed, spec, pal_groups, rle_groups=()):
    """Decode ONE packed full-frame-palette batch to full fields —
    jit-safe (slice/bitcast unpack + the byte-LUT palette gather).

    ``packed``: (total,) uint8 buffer of :func:`pack_fields` layout
    ``spec``; ``pal_groups``: ``((name, (h, w, c, bits)), ...)`` as
    produced by :func:`pop_frame_palette_batches`; ``rle_groups``: the
    deferred run-length plan from :func:`pop_rle_batches`, expanded
    first (a palette index plane may itself ride the wire run-packed,
    and a raw uint8 frame may ride with ``pal_groups`` empty). Shared
    by :class:`blendjax.data.TileStreamDecoder` (decode-then-step) and
    :func:`blendjax.train.make_fused_tile_step` (decode fused into the
    train jit), so the two paths cannot drift."""
    fields = expand_rle_fields(unpack_fields(packed, spec), rle_groups)
    for name, (h, w, c, bits) in pal_groups:
        fields[name] = pop_frame_palette_payload(
            fields, name, bits, h, w, c, expand_palette_frames
        )
    return fields


def decode_packed_pal_superbatch(packed, spec, pal_groups, rle_groups=()):
    """(K', total) stacked packed pal buffers -> (K', B, ...) superbatch
    fields — each group member gathers through its OWN palette (vmap
    over the chunk axis). The full-frame-palette twin of
    :func:`decode_packed_superbatch`, consumed by the same two callers.
    """
    import jax

    return jax.vmap(
        lambda p: decode_packed_pal_batch(p, spec, pal_groups, rle_groups)
    )(packed)


# -- device side ------------------------------------------------------------


def tile_ref(ref, tile=TILE):
    """Reference image (H, W, C) -> device-resident tiled view
    (num_tiles, th, tw, C); compute once per stream, reuse per batch."""
    import jax.numpy as jnp

    ref = jnp.asarray(ref)
    h, w, c = ref.shape
    th, tw = tile_hw(tile)
    gh, gw = tile_grid(ref.shape, (th, tw))
    return ref.reshape(gh, th, gw, tw, c).transpose(0, 2, 1, 3, 4).reshape(
        gh * gw, th, tw, c
    )


def tile_ref_np(ref: np.ndarray, tile=TILE) -> np.ndarray:
    """Host (numpy) twin of :func:`tile_ref` — for consumers that must
    assemble the tiled reference into a multi-process global array
    (``jax.make_array_from_process_local_data`` takes host data)."""
    h, w, c = ref.shape
    th, tw = tile_hw(tile)
    gh, gw = tile_grid(ref.shape, (th, tw))
    return np.ascontiguousarray(
        ref.reshape(gh, th, gw, tw, c)
        .transpose(0, 2, 1, 3, 4)
        .reshape(gh * gw, th, tw, c)
    )


def _pallas_decode_scatter(ref_tiles, idx, tiles, interpret: bool = False):
    """Pallas TPU kernel for the tile scatter: ``(B, N, t*t*C)`` output
    where each grid step (b, k) DMAs one changed tile into the slot
    ``idx[b, k]`` of a reference-initialized buffer.

    The TPU-idiomatic form of a sparse update (pallas_guide.md
    "PrefetchScalarGridSpec"): ``idx`` rides as a scalar-prefetch operand
    so the *output* BlockSpec's index_map is data-dependent — the kernel
    body is a single VMEM block copy, and sentinel indices land in a
    padded slot ``N`` that the caller slices off. The reference-broadcast
    base is donated via ``input_output_aliases`` so unwritten slots keep
    their contents.

    Returns (B, N, t*t*C) uint8 (flattened tiles; caller reshapes).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, k = idx.shape
    n = ref_tiles.shape[0]
    th, tw, c = tiles.shape[-3], tiles.shape[-2], tiles.shape[-1]
    ttc = th * tw * c
    # Each tile is viewed as an (8, ttc/8) block: Mosaic's lowering check
    # requires the trailing two block dims be divisible by (8, 128), and
    # every RGBA tile size is a multiple of 1024 bytes (16*16*4), so
    # ttc/8 is a multiple of 128. (uint8's native tile is (32, 128) —
    # the compiler pads the sublane dim; measured ~25x faster than the
    # XLA scatter on a v5e chip regardless, since the op is one DMA per
    # tile. Covered on real hardware by the tpu-marked test.)
    lanes = ttc // 8
    base = jnp.broadcast_to(
        ref_tiles.reshape(1, n, 8, lanes), (b, n, 8, lanes)
    )
    # One sentinel slot at N absorbs padding writes.
    basep = jnp.concatenate(
        [base, jnp.zeros((b, 1, 8, lanes), jnp.uint8)], axis=1
    )
    flat_tiles = tiles.reshape(b, k, 8, lanes)

    def kernel(idx_ref, base_ref, tiles_blk, out_blk):
        del idx_ref, base_ref  # consumed by the out index_map / aliasing
        out_blk[...] = tiles_blk[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, k),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # base: alias target
            pl.BlockSpec(
                (1, 1, 8, lanes), lambda bi, ki, idxp: (bi, ki, 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, 8, lanes),
            lambda bi, ki, idxp: (bi, idxp[bi, ki], 0, 0),
        ),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n + 1, 8, lanes), jnp.uint8),
        input_output_aliases={1: 0},  # basep (after the prefetch arg)
        interpret=interpret,
    )(idx, basep, flat_tiles)
    return out[:, :n].reshape(b, n, ttc)


def _pallas_decode_spatial(ref_tiles, idx, tiles, shape,
                           interpret: bool = False):
    """Direct-spatial Pallas decode: ONE kernel pass writes the full
    frames in frame layout. Each grid step owns one tile footprint of
    the output and gathers either the changed tile that landed there or
    the reference block — so the slot buffer, its reference-broadcast
    init pass, and the tile->frame transpose pass of
    :func:`_pallas_decode_scatter` all disappear (measured as the two
    largest HBM terms of the decode chain; scripts/diagnose_decode.py).

    The tile->slot map inverts on device first (one tiny scatter over
    (B, GH*GW) int32): ``inv[b, p]`` is the row of ``tiles`` covering
    slot ``p``, or K for "unchanged". The kernel's tile-input index_map
    then reads ``inv`` as a scalar-prefetch operand (gather form — the
    data-dependent BlockSpec pattern of pallas_guide.md), and the body
    selects tile vs reference on ``inv < K``.

    Needs ``tw*C % 128 == 0`` (a tile row spans whole 128-lane vregs —
    why rectangular (16, 32) tiles exist for C=4) and ``th % 8 == 0``;
    callers gate on that. ``idx``: (B, K) int32 with sentinel N (those
    rows land in a dropped pad slot of ``inv``). Returns (B, H, W, C)
    uint8, bit-exact.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, k = idx.shape
    th, tw, c = tiles.shape[-3], tiles.shape[-2], tiles.shape[-1]
    h, w, _ = (int(s) for s in shape)
    gh, gw = h // th, w // tw
    n = gh * gw
    twc = tw * c
    ref_img = ref_tiles.reshape(gh, gw, th, tw, c).transpose(
        0, 2, 1, 3, 4
    ).reshape(h, w * c)  # ~1 MB un-tiling; noise next to the frame write
    if k == 0:  # nothing changed anywhere: every block is the reference
        return jnp.broadcast_to(
            ref_img.reshape(1, h, w, c), (b, h, w, c)
        )
    inv = jnp.full((b, n + 1), k, jnp.int32)
    inv = inv.at[
        jnp.arange(b, dtype=jnp.int32)[:, None], idx
    ].set(
        jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32)[None, :], (b, k)),
        mode="drop",
    )[:, :n]
    tiles3 = tiles.reshape(b, k, th, twc)

    def kernel(inv_ref, ref_blk, tile_blk, out_blk):
        bi = pl.program_id(0)
        gy = pl.program_id(1)
        gx = pl.program_id(2)
        j = inv_ref[bi, gy * gw + gx]
        out_blk[0] = jnp.where(j < k, tile_blk[0, 0], ref_blk[...])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, gh, gw),
        in_specs=[
            pl.BlockSpec((th, twc), lambda bi, gy, gx, invp: (gy, gx)),
            # Unchanged blocks clamp to a real (ignored) tile row so the
            # index stays in bounds without a padded tile copy.
            pl.BlockSpec(
                (1, 1, th, twc),
                lambda bi, gy, gx, invp: (
                    bi,
                    jnp.minimum(invp[bi, gy * gw + gx], k - 1),
                    0, 0,
                ),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, th, twc), lambda bi, gy, gx, invp: (bi, gy, gx)
        ),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, w * c), jnp.uint8),
        interpret=interpret,
    )(inv, ref_img, tiles3)
    return out.reshape(b, h, w, c)


def decode_tile_delta(ref_tiles, idx, tiles, shape, use_pallas=None,
                      mesh=None, data_axis: str = "data"):
    """Reconstruct exact full frames on device.

    ``ref_tiles``: (N, t, t, C) from :func:`tile_ref` (any backend array).
    ``idx``: (B, K) int32 flattened tile indices, sentinel ``N`` = no-op.
    ``tiles``: (B, K, t, t, Ct) changed tile contents. ``Ct < C`` means the
    producer shipped only the leading channels (e.g. RGB of an RGBA stream
    whose alpha matched the reference everywhere — it verified that before
    slicing); the remaining channels reconstruct from the reference. Still
    bit-exact.
    ``shape``: static (H, W, C) of the full image.

    Returns (B, H, W, C). Jit-safe (static shapes; the sentinel rides on
    scatter ``mode='drop'``), batch-parallel (``vmap`` over B, so a batch
    sharded along ``data`` decodes shard-locally with a replicated ref).

    ``use_pallas=None`` auto-selects a Pallas kernel on TPU: the
    direct-spatial gather (:func:`_pallas_decode_spatial` — one pass,
    no slot buffer, no transpose) when the tile geometry is
    lane-aligned (``tw*C % 128 == 0``, ``th % 8 == 0``; the (16, 32)
    tiles the flagship scene streams), else the slot scatter
    (:func:`_pallas_decode_scatter`). Channel-sliced tiles (``Ct < C``,
    e.g. alpha slicing) stay kernel-eligible: the missing channels are
    restored from the reference by one on-device gather first. On a
    multi-device mesh pass ``mesh`` (with ``data_axis`` naming its batch
    axis): the kernel is wrapped in ``shard_map`` over that axis — each
    device decodes its local batch shard against the replicated
    reference, so the fast path survives scale-out (the kernel alone is
    not GSPMD-partitionable). Without ``mesh`` on multi-device, or when
    B doesn't divide by the axis size, auto-select falls back to the
    vmap'd XLA scatter, which partitions like any other op. Off TPU the
    kernels run in interpreter mode (what the virtual-mesh tests use).
    """
    import jax

    h, w, c = (int(s) for s in shape)
    th, tw = tiles.shape[-3], tiles.shape[-2]
    ct = tiles.shape[-1]
    gh, gw = tile_grid((h, w, c), (th, tw))
    b = idx.shape[0]
    n_axis = (
        int(np.prod([mesh.shape[a] for a in (data_axis,)]))
        if mesh is not None and data_axis in getattr(mesh, "shape", {})
        else 1
    )
    eligible_spatial = (tw * c) % 128 == 0 and th % 8 == 0
    eligible = eligible_spatial or (th * tw * c) % 1024 == 0
    if use_pallas is None:
        use_pallas = (
            jax.default_backend() == "tpu"
            and eligible
            and (
                jax.device_count() == 1
                or (mesh is not None and n_axis > 1 and b % n_axis == 0)
            )
        )
    if use_pallas and not eligible:
        # explicit request for a kernel that can't lower: fail loudly
        # rather than silently measuring/testing the XLA path
        raise ValueError(
            f"use_pallas=True but tile geometry {th}x{tw}x{c} is not "
            "kernel-eligible (needs tw*C % 128 == 0 and th % 8 == 0, "
            "or th*tw*C % 1024 == 0)"
        )
    if use_pallas:
        interpret = jax.default_backend() != "tpu"

        if ct < c:
            # Channel-sliced stream (e.g. alpha slicing): the producer
            # verified the trailing channels match the reference on
            # every changed tile, so restore them ON DEVICE from the
            # reference with one small gather — the stream then rides
            # the kernel path instead of silently dropping to the XLA
            # scatter (sentinel rows clamp to a real tile; their
            # content lands in the dropped slot either way).
            import jax.numpy as jnp

            rest = ref_tiles[..., ct:]  # (N, th, tw, C-Ct)
            filled = rest[jnp.minimum(idx, gh * gw - 1)]
            tiles = jnp.concatenate([tiles, filled], axis=-1)

        if eligible_spatial:
            def decode_fn(r, i, tl):
                return _pallas_decode_spatial(
                    r, i, tl, (h, w, c), interpret=interpret
                )
        else:
            def decode_fn(r, i, tl):
                return _pallas_decode_scatter(
                    r, i, tl, interpret=interpret
                ).reshape(-1, gh, gw, th, tw, c).transpose(
                    0, 1, 3, 2, 4, 5
                ).reshape(-1, h, w, c)

        if mesh is not None and n_axis > 1 and b % n_axis == 0:
            # Partition over the batch: each device runs the kernel on
            # its local shard against the replicated reference (the
            # kernel alone is not GSPMD-partitionable).
            from jax.sharding import PartitionSpec as P

            from blendjax.parallel.collectives import _shard_map

            # check=False: pallas_call's out_shape carries no varying-
            # mesh-axes annotation, which the VMA checker requires.
            decode_fn = _shard_map(
                decode_fn, mesh,
                in_specs=(P(), P(data_axis), P(data_axis)),
                out_specs=P(data_axis),
                check=False,
            )
        return decode_fn(ref_tiles, idx, tiles)

    def one(i, tl):
        if ct < c:
            return ref_tiles.at[i, :, :, :ct].set(tl, mode="drop")
        return ref_tiles.at[i].set(tl, mode="drop")

    out = jax.vmap(one)(idx, tiles)  # (B, N, th, tw, C)
    return out.reshape(b, gh, gw, th, tw, c).transpose(0, 1, 3, 2, 4, 5).reshape(
        b, h, w, c
    )
