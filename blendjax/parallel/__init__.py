"""ICI-plane parallelism: meshes, shardings, collectives, ring attention.

No reference counterpart exists — blendtorch's only "distributed backend"
is ZMQ between processes (SURVEY.md §2.4); the accelerator-side plane is
designed fresh for TPU: a named mesh (``data``/``fsdp``/``tensor``/
``seq``), ``NamedSharding`` annotations, XLA collectives via ``shard_map``,
and ring attention for sequence/context parallelism over ICI.
"""

from blendjax.parallel.mesh import MeshSpec, create_mesh
from blendjax.parallel.sharding import (
    batch_sharding,
    leading_shard_count,
    mesh_chip_count,
    param_sharding_rules,
    replicated,
    ring_sharding,
    shard_params,
    state_shardings,
)
from blendjax.parallel.collectives import (
    all_gather,
    all_reduce_mean,
    all_reduce_sum,
    ring_permute,
)
from blendjax.parallel.ring import ring_attention
from blendjax.parallel.ulysses import ulysses_attention
from blendjax.parallel.pipeline import pipeline_apply, stack_stage_params

__all__ = [
    "MeshSpec",
    "create_mesh",
    "batch_sharding",
    "replicated",
    "param_sharding_rules",
    "shard_params",
    "leading_shard_count",
    "mesh_chip_count",
    "ring_sharding",
    "state_shardings",
    "all_gather",
    "all_reduce_mean",
    "all_reduce_sum",
    "ring_permute",
    "ring_attention",
    "ulysses_attention",
    "pipeline_apply",
    "stack_stage_params",
]
