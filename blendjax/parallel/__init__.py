"""ICI-plane parallelism: meshes, shardings, collectives, ring attention.

No reference counterpart exists — blendtorch's only "distributed backend"
is ZMQ between processes (SURVEY.md §2.4); the accelerator-side plane is
designed fresh for TPU: a named mesh (``data``/``fsdp``/``tp``/
``seq``; ``tensor`` is the legacy ``tp`` spelling), :class:`Layout`
specs composing them (``data×fsdp``, ``data×tp``, ``data×fsdp×tp``)
with per-model :class:`PartitionRule` sets, ``NamedSharding``
annotations, XLA collectives via ``shard_map``, and ring attention for
sequence/context parallelism over ICI.
"""

from blendjax.parallel.mesh import MeshSpec, create_mesh
from blendjax.parallel.sharding import (
    DEFAULT_TP_RULES,
    LAYOUTS,
    Layout,
    PartitionRule,
    batch_sharding,
    leading_shard_count,
    mesh_chip_count,
    param_sharding_rules,
    replicated,
    resolve_layout,
    resolve_rules,
    ring_sharding,
    shard_params,
    state_resident_bytes,
    state_shardings,
    validate_batch_sharding,
)
from blendjax.parallel.collectives import (
    all_gather,
    all_reduce_mean,
    all_reduce_sum,
    ring_permute,
)
from blendjax.parallel.ring import ring_attention
from blendjax.parallel.ulysses import ulysses_attention
from blendjax.parallel.pipeline import pipeline_apply, stack_stage_params

__all__ = [
    "MeshSpec",
    "create_mesh",
    "DEFAULT_TP_RULES",
    "LAYOUTS",
    "Layout",
    "PartitionRule",
    "resolve_layout",
    "resolve_rules",
    "state_resident_bytes",
    "validate_batch_sharding",
    "batch_sharding",
    "replicated",
    "param_sharding_rules",
    "shard_params",
    "leading_shard_count",
    "mesh_chip_count",
    "ring_sharding",
    "state_shardings",
    "all_gather",
    "all_reduce_mean",
    "all_reduce_sum",
    "ring_permute",
    "ring_attention",
    "ulysses_attention",
    "pipeline_apply",
    "stack_stage_params",
]
