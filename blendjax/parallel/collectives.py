"""Thin shard_map wrappers over XLA collectives.

These are the TPU-native replacement for a NCCL/MPI-style backend: the
collectives ride ICI and are inserted/fused by XLA (SURVEY.md §5
"distributed communication backend"). Most code should just annotate
shardings and let pjit insert collectives; these helpers exist for
explicit SPMD regions (ring attention, metrics reduction) and for tests.
"""

from __future__ import annotations

import functools


def _resolve_shard_map():
    """``shard_map`` across jax versions: top-level ``jax.shard_map`` on
    current releases, ``jax.experimental.shard_map.shard_map`` before
    the promotion. ONE resolver for every SPMD region in the repo (ring,
    ulysses, pipeline parallel, the sharded tile decode)."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    return sm


def _shard_map(fn, mesh, in_specs, out_specs, check: bool = True):
    sm = _resolve_shard_map()
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if not check:
        # Replication of e.g. tiled all_gather output is not statically
        # inferred by the varying-manual-axes checker; the flag is named
        # check_vma on current JAX, check_rep on older releases.
        try:
            return sm(fn, check_vma=False, **kwargs)
        except TypeError:
            return sm(fn, check_rep=False, **kwargs)
    return sm(fn, **kwargs)


def all_reduce_sum(x, mesh, axis: str = "data"):
    """psum over ``axis``; input sharded on leading dim, result replicated."""
    import jax
    from jax.sharding import PartitionSpec as P

    f = _shard_map(
        lambda s: jax.lax.psum(s, axis),
        mesh,
        in_specs=P(axis),
        out_specs=P(),
    )
    return f(x)


def all_reduce_mean(x, mesh, axis: str = "data"):
    import jax
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    f = _shard_map(
        lambda s: jax.lax.psum(s, axis) / n,
        mesh,
        in_specs=P(axis),
        out_specs=P(),
    )
    return f(x)


def all_gather(x, mesh, axis: str = "data"):
    """Gather shards of the leading dim onto every device."""
    import jax
    from jax.sharding import PartitionSpec as P

    f = _shard_map(
        lambda s: jax.lax.all_gather(s, axis, axis=0, tiled=True),
        mesh,
        in_specs=P(axis),
        out_specs=P(),
        check=False,
    )
    return f(x)


def ring_permute(x, mesh, axis: str = "seq", shift: int = 1):
    """Rotate shards around the ring: device i's shard moves to i+shift
    (the primitive under ring attention / pipelined collectives)."""
    import jax
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]

    f = _shard_map(
        functools.partial(jax.lax.ppermute, axis_name=axis, perm=perm),
        mesh,
        in_specs=P(axis),
        out_specs=P(axis),
    )
    return f(x)
