"""Device mesh construction.

Axis vocabulary (scaling-book conventions):

- ``data``  — batch (DP); the streamed global batch is split here.
- ``fsdp``  — parameter/optimizer sharding (ZeRO-style), folded into
  the batch's leading dim as extra DP (every chip sees distinct rows).
- ``tp``    — intra-layer model parallelism (heads/MLP hidden/vocab);
  ``tensor`` is the legacy spelling and stays accepted everywhere.
- ``seq``   — sequence/context parallelism (SP; ring attention).

``create_mesh`` lays the requested axis sizes over the available devices
in ICI-friendly order (innermost axes change fastest so ``tp``/``seq``
neighbors are physically adjacent). It also accepts a
:class:`blendjax.parallel.Layout` (or its name string) directly, so
``create_mesh("data×fsdp")`` and ``create_mesh(Layout(fsdp=4))`` build
the 2-D mesh the layout commits to.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class MeshSpec:
    """Requested axis sizes; -1 axes absorb the remaining devices."""

    axes: dict = field(default_factory=lambda: {"data": -1})

    def resolve(self, n_devices: int) -> dict:
        sizes = dict(self.axes)
        known = int(np.prod([s for s in sizes.values() if s != -1]))
        free = [k for k, s in sizes.items() if s == -1]
        assert len(free) <= 1, "at most one -1 axis"
        if free:
            assert n_devices % known == 0, (
                f"{n_devices} devices not divisible by fixed axes {sizes}"
            )
            sizes[free[0]] = n_devices // known
        total = int(np.prod(list(sizes.values())))
        assert total == n_devices, (
            f"mesh {sizes} needs {total} devices, have {n_devices}"
        )
        return sizes


def create_mesh(spec: MeshSpec | dict | None = None, devices=None):
    """Build a ``jax.sharding.Mesh``.

    >>> mesh = create_mesh({"data": -1})                    # pure DP
    >>> mesh = create_mesh({"data": -1, "tp": 2})           # DP x TP
    >>> mesh = create_mesh({"data": 1, "seq": 8})           # ring SP
    >>> mesh = create_mesh("data×fsdp")                     # a Layout name
    """
    import jax
    from jax.sharding import Mesh

    if spec is None:
        spec = MeshSpec()
    elif isinstance(spec, str) or hasattr(spec, "mesh_axes"):
        from blendjax.parallel.sharding import resolve_layout

        spec = MeshSpec(resolve_layout(spec).mesh_axes())
    elif isinstance(spec, dict):
        spec = MeshSpec(dict(spec))
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve(len(devices))
    names = tuple(sizes.keys())
    shape = tuple(sizes.values())
    return Mesh(np.array(devices).reshape(shape), axis_names=names)
