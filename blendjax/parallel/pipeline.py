"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

No reference counterpart (SURVEY.md §2.4: "Pipeline parallelism: none") —
designed TPU-first rather than ported: each device on the ``pipe`` mesh
axis holds ONE stage's parameters (stacked pytree sharded on its leading
axis), and activations flow stage-to-stage over the ICI ring via
``ppermute`` while microbatches fill the pipeline (scaling-book-style
collective-permute pipeline). The whole schedule is a single ``lax.scan``
inside ``shard_map``, so it jits once, differentiates (reverse-mode flows
back through the ppermutes), and composes with ``data``/``tensor`` axes in
an outer pjit.

Schedule: step ``t`` runs microbatch ``m = t - s`` on stage ``s``; the
pipeline drains after ``M + S - 1`` steps (bubble fraction ``(S-1)/(M+S-1)``
— pick ``M >= 4*S`` to amortize).
"""

from __future__ import annotations

import functools


def stack_stage_params(stage_params: list):
    """Stack per-stage parameter pytrees along a new leading axis so the
    result can be sharded on the ``pipe`` mesh axis (leading dim =
    number of stages)."""
    import jax.numpy as jnp
    from jax import tree_util

    return tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *stage_params
    )


def _pipeline_local(params, x, *, stage_fn, axis_name: str, n_stages: int,
                    vary_axes: tuple = ()):
    """Per-device body (inside shard_map).

    params: stage pytree with leading dim 1 (this device's stage).
    x: (M, mb, ...) all microbatches (replicated over the pipe axis).
    Returns (M, mb_out...) — final-stage outputs, psum-replicated.
    """
    import jax
    import jax.numpy as jnp
    from jax import tree_util

    s = jax.lax.axis_index(axis_name)
    my_params = tree_util.tree_map(lambda a: a[0], params)
    m_total = x.shape[0]
    # Forward-only neighbor links: stage s -> s+1 (no wraparound; devices
    # with no inbound edge receive zeros, which the schedule masks out).
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    # Probe the output structure once to build the accumulator.
    out_shape = jax.eval_shape(stage_fn, my_params, x[0])

    def step(carry, t):
        buf, out = carry
        # Stage 0 reads fresh microbatch t; later stages read the buffer
        # their predecessor sent last step.
        mb = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, m_total - 1), axis=0, keepdims=False
        )
        inp = jnp.where(s == 0, mb, buf)
        y = stage_fn(my_params, inp)
        # Valid iff this stage is processing a real microbatch this step.
        m = t - s
        valid = (m >= 0) & (m < m_total)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        # Final stage deposits microbatch m into the output slot.
        is_last = s == n_stages - 1
        idx = jnp.clip(m, 0, m_total - 1)
        out = jax.lax.dynamic_update_index_in_dim(
            out,
            jnp.where(valid & is_last, y,
                      jax.lax.dynamic_index_in_dim(out, idx, 0, False)),
            idx, 0,
        )
        buf_next = jax.lax.ppermute(y, axis_name, perm)
        return (buf_next, out), None

    assert out_shape.shape == x.shape[1:], (
        "pipeline stages must be shape-preserving (activation ring buffer): "
        f"stage maps {x.shape[1:]} -> {out_shape.shape}"
    )
    buf0 = jnp.zeros(out_shape.shape, out_shape.dtype)
    out0 = jnp.zeros((m_total,) + out_shape.shape, out_shape.dtype)
    # Constant carries must be marked device-varying for shard_map's VMA
    # type checking (same dance as ring.py).
    if hasattr(jax.lax, "pcast"):
        buf0, out0 = (
            jax.lax.pcast(a, vary_axes, to="varying")
            for a in (buf0, out0)
        )
    elif hasattr(jax.lax, "pvary"):
        buf0, out0 = (jax.lax.pvary(a, vary_axes) for a in (buf0, out0))

    n_steps = m_total + n_stages - 1
    (_, out), _ = jax.lax.scan(
        step, (buf0, out0), jnp.arange(n_steps)
    )
    # Only the last stage holds real outputs; psum replicates them (every
    # other stage contributes zeros).
    mask = (s == n_stages - 1).astype(out.dtype)
    return jax.lax.psum(out * mask, axis_name)


def pipeline_apply(
    stage_fn,
    stacked_params,
    x,
    mesh,
    axis: str = "pipe",
    batch_axis: str | None = "data",
):
    """Run ``x`` through ``n_stages`` copies of ``stage_fn`` pipelined over
    mesh axis ``axis``.

    Args:
      stage_fn: ``(params, microbatch) -> microbatch_out``; all stages
        share this code (classic GPipe homogeneous stages), and each stage
        must be shape-preserving (the activation ring buffer is reused).
      stacked_params: pytree whose leaves have leading dim = mesh size of
        ``axis`` (one slice per stage; see :func:`stack_stage_params`).
      x: ``(num_microbatches, microbatch, ...)`` input. The microbatch
        dim (dim 1) stays sharded on ``batch_axis`` when that axis exists
        on the mesh, so dp x pp composes without gathering the batch.
      mesh: the device mesh; ``axis`` must be one of its names.

    Returns ``(num_microbatches, microbatch, ...)`` outputs, replicated
    over ``axis`` and sharded on ``batch_axis``. Any other mesh axes are
    treated as replicated inside the pipeline body.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    if axis not in mesh.axis_names:
        # Degenerate single-stage mesh: apply stages sequentially.
        import jax.numpy as jnp
        from jax import tree_util

        n = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        y = x
        for i in range(n):
            p_i = tree_util.tree_map(lambda a: a[i], stacked_params)
            y = jnp.stack([stage_fn(p_i, y[m]) for m in range(y.shape[0])])
        return y

    n_stages = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        assert leaf.shape[0] == n_stages, (
            f"stacked_params leading dim {leaf.shape[0]} != mesh axis "
            f"'{axis}' size {n_stages}; one stage slice per pipe device"
        )
    b_ax = batch_axis if (batch_axis and batch_axis in mesh.axis_names) else None
    vary_axes = tuple(a for a in (axis, b_ax) if a)
    body = functools.partial(
        _pipeline_local, stage_fn=stage_fn, axis_name=axis,
        n_stages=n_stages, vary_axes=vary_axes,
    )
    xspec = P(None, b_ax)
    from blendjax.parallel.collectives import _shard_map

    f = _shard_map(
        body,
        mesh,
        in_specs=(P(axis), xspec),
        out_specs=xspec,
    )
    return f(stacked_params, x)
