"""Ring attention: exact attention over sequences sharded across devices.

Sequence/context parallelism has no reference counterpart (SURVEY.md §5
"long-context: absent") and is designed TPU-first: the sequence axis is
sharded over the ``seq`` mesh axis; each device holds local Q/K/V blocks
and K/V blocks rotate around the ICI ring via ``ppermute`` while a
numerically-stable streaming softmax (flash-attention style running
max/sum) accumulates the exact result — compute on block *i* overlaps the
transfer of block *i+1* (XLA overlaps the ppermute with the einsums).

Memory per device is O(T/n) for activations, enabling context lengths n x
longer than a single chip holds.
"""

from __future__ import annotations

import functools

NEG_INF = -1e30


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, scale,
                          vary_axes: tuple = ()):
    """Per-device body (inside shard_map). Shapes: q (B, Tq, H, D);
    k/v (B, Tk, H, D) — the *local* sequence shards."""
    import jax
    import jax.numpy as jnp

    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = my * tq + jnp.arange(tq)  # global query positions

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        # K/V block currently held arrived from device (my - i) mod n.
        # Inputs stay in their wire dtype (bf16 halves the ppermute
        # bytes vs the old pre-shard_map f32 upcast); the MXU matmuls
        # ACCUMULATE in f32 via preferred_element_type, and the
        # streaming-softmax carries (o, m, l) are f32 throughout — the
        # numerical risk lives in accumulation, not in the operands.
        src = (my - i) % n
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_cur,
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            k_pos = src * tk + jnp.arange(tk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1.
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m - m_new)
        corr = jnp.where(m <= NEG_INF / 2, 0.0, corr)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32,
        )
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o_new, m_new, l_new, k_nxt, v_nxt

    o = jnp.zeros((b, h, tq, d), jnp.float32)
    m = jnp.full((b, h, tq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, tq), jnp.float32)
    # Constant-initialized carries must be marked device-varying to match
    # the loop body's types under shard_map's VMA checking.
    if hasattr(jax.lax, "pcast"):
        o, m, l = (
            jax.lax.pcast(x, vary_axes, to="varying") for x in (o, m, l)
        )
    elif hasattr(jax.lax, "pvary"):  # older JAX
        o, m, l = (jax.lax.pvary(x, vary_axes) for x in (o, m, l))
    o, m, l, _, _ = jax.lax.fori_loop(0, n, step, (o, m, l, k, v))
    o = o / jnp.maximum(l, 1e-30)[..., None]
    # back to (B, Tq, H, D), in the wire dtype (f32 in -> f32 out)
    return o.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(
    q,
    k,
    v,
    mesh,
    axis: str = "seq",
    causal: bool = False,
    scale: float | None = None,
    batch_axis: str | None = "data",
):
    """Exact multi-head attention with the sequence dim sharded on
    ``axis``. Inputs/outputs are (B, T, H, D) global arrays (T sharded).

    Also usable inside an outer pjit: apply to arrays whose sharding
    matches ``P(batch_axis, axis, None, None)``.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    d = q.shape[-1]
    scale = scale if scale is not None else d**-0.5
    if axis not in mesh.axis_names:
        # No sequence axis on this mesh: nothing to ring over — run plain
        # exact attention (same math, zero collectives; it keeps bf16
        # inputs on the MXU and does its softmax in f32 internally).
        return reference_attention(q, k, v, causal=causal, scale=scale)
    # Inputs enter shard_map in their OWN dtype: the old pre-shard_map
    # f32 upcast doubled the bytes every K/V ppermute hop moved over
    # ICI for bf16 models — the dominant ring cost. Numerical safety
    # lives inside the body instead: f32 score accumulation via
    # preferred_element_type and f32 streaming-softmax carries (see
    # _ring_attention_local), so bf16 in/bf16 out now rings at half the
    # wire bytes with the same f32 accumulation the reference path uses.
    from blendjax.parallel.collectives import _shard_map

    b_ax = batch_axis if (batch_axis and batch_axis in mesh.axis_names) else None
    spec = P(b_ax, axis)
    vary_axes = tuple(a for a in (b_ax, axis) if a in mesh.axis_names)
    body = functools.partial(
        _ring_attention_local, axis_name=axis, causal=causal, scale=scale,
        vary_axes=vary_axes,
    )
    # Releases without pcast/pvary can't mark the constant-initialized
    # fori carries device-varying, so their replication checker reports
    # a false carry mismatch (its own message suggests check_rep=False);
    # strict checking stays on wherever the marking primitives exist.
    import jax

    strict = hasattr(jax.lax, "pcast") or hasattr(jax.lax, "pvary")
    f = _shard_map(
        body, mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check=strict,
    )
    return f(q, k, v)


def reference_attention(q, k, v, causal: bool = False, scale=None):
    """Single-device exact attention for testing/fallback (B,T,H,D).

    Mixed precision: both matmuls run in the INPUT dtype (bf16 inputs
    keep the MXU at full rate — f32 matmuls cost ~4x on v5e and held
    the bench transformer row at half its MFU) while scores accumulate
    and the softmax computes in f32, which is where the numerical risk
    actually lives. f32 inputs behave exactly as before.
    """
    import jax.numpy as jnp

    d = q.shape[-1]
    scale = scale if scale is not None else d**-0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jnp.exp(s - s.max(axis=-1, keepdims=True))
    p = p / p.sum(axis=-1, keepdims=True)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(v.dtype)
