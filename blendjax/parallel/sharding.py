"""NamedSharding helpers, parameter partitioning rules, and layouts.

Two vocabularies live here:

- **Axes** — the named mesh dimensions (``data``/``fsdp``/``tp``/
  ``seq``/``expert``; ``tensor`` is the legacy spelling of ``tp`` and
  both resolve to whichever the mesh actually carries).
- **Layouts** — how a whole training run maps onto those axes: a
  :class:`Layout` names the composition (``data``, ``data×fsdp``,
  ``data×tp``, ``data×fsdp×tp``) plus the per-model
  :class:`PartitionRule` overrides that put attention heads / MLP
  hidden / vocab on the tensor axis. ``state_shardings(...,
  layout=)`` is the single pinning helper the step builders, the
  checkpoint restore path, and ``mesh_rl_step_kwargs`` all share, so
  one spelling of the layout governs params, optimizer moments, the
  donated jit boundary, and cross-layout resume.

The batch side never shards over model axes: data enters over
``data`` (with ``fsdp`` folded into the leading dim as extra data
parallelism — ZeRO-style, every chip still sees distinct rows).
:func:`validate_batch_sharding` is the build-time gate the AOT ladder
and the reservoir rings apply so a parameter-style rule on a *batch*
fails with a named error instead of deep inside jit.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field


def _np():
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding, PartitionSpec


#: every axis name a blendjax mesh may carry (docs/parallelism.md)
MESH_AXES = ("data", "fsdp", "tp", "tensor", "seq", "expert", "pipe")

#: axes that partition *parameters* — never a batch dimension
MODEL_AXES = ("fsdp", "tp", "tensor", "expert", "pipe")


def tensor_axis(mesh):
    """The mesh's tensor-parallel axis name (``tp`` preferred,
    ``tensor`` legacy), or None when the mesh has neither."""
    for ax in ("tp", "tensor"):
        if ax in mesh.axis_names:
            return ax
    return None


def batch_sharding(mesh, axis: str = "data"):
    """Shard the leading (batch) axis across ``axis`` — the layout the
    ingest pipeline feeds (SURVEY.md §2.4: per-host ingest -> global batch
    on the ``data`` axis). On an fsdp mesh the ``fsdp`` axis folds into
    the leading dim as extra data parallelism (ZeRO: params shard over
    ``fsdp``, batches split over it)."""
    NamedSharding, P = _np()
    names = [axis] if axis in mesh.axis_names else []
    if "fsdp" in mesh.axis_names and axis == "data":
        names.append("fsdp")  # fold fsdp into the batch axis for DP
    return NamedSharding(mesh, P(tuple(names) if names else None))


def replicated(mesh):
    NamedSharding, P = _np()
    return NamedSharding(mesh, P())


# -- per-model partition rules ------------------------------------------------

@dataclass(frozen=True)
class PartitionRule:
    """One explicit parameter-layout override.

    ``pattern`` is a regex searched against the ``/``-joined parameter
    path (``block0/qkv/kernel``); ``spec`` is a partition entry per
    *trailing* dimension (``("tp", None)`` puts the second-to-last dim
    on the tensor axis). Entries naming an axis the mesh lacks, or one
    whose size does not divide the dim, degrade to ``None`` — a rule
    set written for ``data×fsdp×tp`` is valid verbatim on a pure
    ``data`` mesh (where it does nothing)."""

    pattern: str
    spec: tuple


#: transformer layout (Megatron-style): attention heads and the MLP
#: hidden dim column-parallel over ``tp``, their output projections
#: row-parallel, the vocab/output head column-parallel. Matches the
#: flax param paths :class:`blendjax.models.StreamFormer` produces; a
#: model with its own naming ships its own ``partition_rules()``.
DEFAULT_TP_RULES = (
    PartitionRule(r"qkv/kernel$", ("tp", None)),        # heads dim
    PartitionRule(r"proj/kernel$", ("tp", None)),       # attn out, row
    PartitionRule(r"block\d+/Dense_0/kernel$", ("tp",)),  # MLP hidden
    PartitionRule(r"block\d+/Dense_1/kernel$", ("tp", None)),  # MLP out
)


def _path_str(path: tuple) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "name", k))) for k in path
    )


def _mesh_axis(mesh, name):
    """Resolve a rule's axis name onto the mesh (``tp`` <-> ``tensor``
    are interchangeable); None when absent or trivial (size 1)."""
    if name in ("tp", "tensor"):
        name = tensor_axis(mesh)
    if name is None or name not in mesh.axis_names:
        return None
    return name if mesh.shape[name] > 1 else None


def param_sharding_rules(mesh, path: tuple, value, rules=()) -> "object":
    """Parameter layout for one leaf.

    Explicit ``rules`` (:class:`PartitionRule`) are checked first —
    first match wins, its spec aligned to the leaf's trailing dims.
    The generic defaults then fill in (and handle every unmatched
    leaf):

    - ``expert`` axis: MoE parameters (name starts with ``expert_``,
      leading dim = num_experts) split on dim 0 — expert parallelism;
      GSPMD inserts the dispatch/combine all-to-alls.
    - ``tp``/``tensor`` axis: dense/conv kernels split on their
      output-feature (last) dimension when divisible — Megatron-style
      column parallel.
    - ``fsdp`` axis: remaining large params split on their largest
      divisible dimension (ZeRO-3 style) — the all-gather on use /
      reduce-scatter on grads is GSPMD's, derived from this placement.
    - small params (biases, norms) replicated.
    """
    NamedSharding, P = _np()
    shape = getattr(value, "shape", ())
    spec = [None] * len(shape)
    name = str(getattr(path[-1], "key", path[-1])) if path else ""
    matched = False
    if rules:
        pstr = _path_str(path)
        for rule in rules:
            if not re.search(rule.pattern, pstr):
                continue
            matched = True
            for i, ax in enumerate(reversed(rule.spec)):
                dim = len(shape) - 1 - i
                if dim < 0 or ax is None:
                    continue
                ax = _mesh_axis(mesh, ax)
                if ax is not None and shape[dim] % mesh.shape[ax] == 0:
                    spec[dim] = ax
            break
    if (
        not matched
        and "expert" in mesh.axis_names
        and name.startswith("expert_")
        and shape
        and shape[0] % mesh.shape["expert"] == 0
    ):
        spec[0] = "expert"
    if len(shape) >= 2:
        tp = tensor_axis(mesh)
        if not matched and tp is not None:
            ways = mesh.shape[tp]
            if ways > 1 and shape[-1] % ways == 0:
                spec[-1] = tp
        if "fsdp" in mesh.axis_names:
            fs = mesh.shape["fsdp"]
            if fs > 1:
                # biggest dim not already taken, divisible by fsdp
                order = sorted(
                    range(len(shape)), key=lambda i: -shape[i]
                )
                for i in order:
                    if spec[i] is None and shape[i] % fs == 0:
                        spec[i] = "fsdp"
                        break
    while spec and spec[-1] is None:  # canonical form: P() == replicated
        spec.pop()
    return NamedSharding(mesh, P(*spec))


def shard_params(mesh, params, rules=()):
    """Apply :func:`param_sharding_rules` over a pytree and device_put."""
    import jax

    def place(path, leaf):
        return jax.device_put(
            leaf, param_sharding_rules(mesh, path, leaf, rules=rules)
        )

    return jax.tree_util.tree_map_with_path(place, params)


# -- layouts ------------------------------------------------------------------

@dataclass(frozen=True)
class Layout:
    """How a run maps onto mesh axes: axis sizes + partition rules.

    ``data=-1`` absorbs whatever devices the model axes leave free, so
    one spelling (``Layout(fsdp=4)``) works on 8 chips and 256.
    ``rules`` are the per-model :class:`PartitionRule` overrides
    (``None`` -> ask the model via ``model.partition_rules()``, falling
    back to the generic defaults)."""

    name: str = "data"
    data: int = -1
    fsdp: int = 1
    tp: int = 1
    seq: int = 1
    rules: tuple | None = field(default=None, compare=False)

    def mesh_axes(self) -> dict:
        """Axis sizes for :func:`blendjax.parallel.create_mesh`, in
        ICI-friendly order — ``tp`` innermost so tensor-parallel
        neighbors are physically adjacent."""
        axes = {"data": self.data}
        if self.fsdp != 1:
            axes["fsdp"] = self.fsdp
        if self.seq != 1:
            axes["seq"] = self.seq
        if self.tp != 1:
            axes["tp"] = self.tp
        return axes

    def create_mesh(self, devices=None):
        from blendjax.parallel.mesh import create_mesh

        return create_mesh(self.mesh_axes(), devices=devices)


#: the canonical layout names (docs/parallelism.md "Choosing a layout")
LAYOUTS = ("data", "data×fsdp", "data×tp", "data×fsdp×tp")

_AXIS_SIZE_RE = re.compile(r"^([a-z]+?)(\d+)?$")

#: model axes named without a size in a layout string default to the
#: smallest nontrivial split; ``data`` without a size absorbs the rest
_DEFAULT_WAYS = 2


def resolve_layout(layout) -> Layout:
    """Normalize a layout spec to a :class:`Layout`.

    Accepts a :class:`Layout` (returned as-is), ``None`` (pure data
    parallelism), a dict of axis sizes, or a name string: axis names
    joined by ``×``/``x``/``_``/``*``/spaces, each optionally carrying
    a size (``"data×fsdp"``, ``"data2xfsdp4"``, ``"data4×tp2"``).
    Sizeless model axes split ``2``-way; sizeless ``data`` absorbs the
    remaining devices (``-1``)."""
    if layout is None:
        return Layout("data")
    if isinstance(layout, Layout):
        return layout
    if isinstance(layout, dict):
        sizes = dict(layout)
        name = "×".join(sizes) if sizes else "data"
        return Layout(
            name=name,
            data=int(sizes.pop("data", 1)),
            fsdp=int(sizes.pop("fsdp", 1)),
            tp=int(sizes.pop("tp", sizes.pop("tensor", 1))),
            seq=int(sizes.pop("seq", 1)),
        )
    text = str(layout).strip().lower().replace("×", "x")
    sizes: dict = {}
    for part in (p for p in re.split(r"[x_*\s+,]+", text) if p):
        m = _AXIS_SIZE_RE.match(part)
        axis = m.group(1) if m else part
        if axis == "tensor":
            axis = "tp"
        if m is None or axis not in ("data", "fsdp", "tp", "seq"):
            raise ValueError(
                f"unknown layout axis {part!r} in {layout!r} — compose "
                "from data/fsdp/tp/seq (optionally sized, e.g. "
                "'data2xfsdp4'); canonical layouts: "
                + ", ".join(LAYOUTS)
            )
        if m.group(2) is not None:
            sizes[axis] = int(m.group(2))
        else:
            sizes[axis] = -1 if axis == "data" else _DEFAULT_WAYS
    if "data" not in sizes:
        sizes["data"] = 1
    canonical = "×".join(
        ax for ax in ("data", "fsdp", "seq", "tp")
        if ax in sizes and (ax == "data" or sizes[ax] != 1)
    )
    return Layout(
        name=canonical or "data",
        data=sizes.get("data", -1),
        fsdp=sizes.get("fsdp", 1),
        tp=sizes.get("tp", 1),
        seq=sizes.get("seq", 1),
    )


def resolve_rules(rules=None, layout=None, model=None):
    """The partition-rule set for a build: explicit ``rules`` win, then
    the layout's, then the model's own ``partition_rules()``, then
    none (generic defaults only)."""
    if rules is not None:
        return tuple(rules)
    if layout is not None:
        lay = resolve_layout(layout)
        if lay.rules is not None:
            return tuple(lay.rules)
    pr = getattr(model, "partition_rules", None)
    if callable(pr):
        return tuple(pr())
    return ()


def mesh_chip_count(mesh) -> int:
    """Total participating chips (all processes): the factor live MFU
    and per-chip throughput figures scale by on a mesh run — the
    product over EVERY axis (``data×fsdp×tp`` runs the step on all of
    them), not the data-axis size."""
    import numpy as np

    return int(np.prod([int(s) for s in mesh.shape.values()])) if getattr(
        mesh, "shape", None
    ) else 1


def state_shardings(state, mesh=None, rules=None, layout=None):
    """The sharding pytree of a train state — what
    ``jax.jit(in_shardings=(state_shardings(state, mesh), ...),
    out_shardings=(state_shardings(state, mesh), ...))`` pins so a
    donated step can never silently reshard params/optimizer state
    mid-run (``blendjax.train.mesh_driver`` builds its steps on this).

    With ``rules``/``layout`` given (and a mesh), the tree is
    DERIVED rather than read: every array leaf's spec comes from
    :func:`param_sharding_rules` applied to its path — optimizer
    moments mirror the parameter tree's paths, so ``mu``/``nu`` land
    on the same partition as the params they track, and a *template*
    state (freshly initialized, any placement) yields the target
    layout's tree. This is the cross-layout restore path:
    ``restore(template, shardings=state_shardings(template, mesh=mesh,
    layout="data×fsdp"))`` resumes a pure-``data`` run fsdp-sharded
    and vice versa.

    With only ``mesh`` given the tree is normalized ONTO it: array
    leaves already holding a NamedSharding on this mesh keep it
    (params and optimizer moments under the mesh rules), every other
    array leaf — the step counters optax creates on the default
    device — pins to replicated on the SAME mesh, so the whole state
    lives on one device set (a jit mixing device sets refuses to run).
    Without ``mesh``, leaves map to their current sharding as-is.
    Non-array leaves (flax's integer ``step`` before the first update,
    ``apply_fn``) map to ``None`` — "unspecified", which jit infers."""
    import jax

    if layout is not None and rules is None:
        rules = resolve_rules(layout=layout)
    if mesh is not None and rules is not None:
        rules = tuple(rules)

        def derive(path, v):
            if not hasattr(v, "shape"):
                return None
            return param_sharding_rules(mesh, path, v, rules=rules)

        return jax.tree_util.tree_map_with_path(derive, state)
    if mesh is None:
        return jax.tree_util.tree_map(
            lambda v: getattr(v, "sharding", None), state
        )
    NamedSharding, P = _np()
    rep = NamedSharding(mesh, P())

    def pin(v):
        if not hasattr(v, "shape"):
            return None
        s = getattr(v, "sharding", None)
        if isinstance(s, NamedSharding) and getattr(s, "mesh", None) == mesh:
            return s
        return rep

    return jax.tree_util.tree_map(pin, state)


def state_resident_bytes(state) -> int:
    """Per-device resident bytes of a concrete state: the sum over
    leaves of ONE device's shard (replicated leaves count in full,
    ``fsdp``-sharded leaves at 1/|fsdp|) — the figure the device
    ledger's ``device.hbm_peak_bytes`` argument accounting reflects,
    computable without a compile. An fsdp layout's resident state is
    ~1/|fsdp| of the replicated figure; tests and the
    ``model_parallel_ab`` HBM-budget contract pin that ratio."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(state):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                shape = sharding.shard_shape(tuple(shape))
            except Exception:
                pass
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total


def leading_shard_count(sharding) -> int:
    """How many ways a sharding splits dim 0 (1 for ``None``/replicated)
    — the divisibility a global batch size / reservoir capacity must
    satisfy so every chip takes an equal shard. Multi-axis tolerant:
    a ``(data, fsdp)`` fold multiplies both axis sizes; model axes the
    batch does NOT cover (``tp`` on a ``data×tp`` mesh) contribute
    nothing, so batch divisibility never scales with chips the batch
    doesn't split over."""
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if not spec or mesh is None:
        return 1
    lead = spec[0]
    if lead is None:
        return 1
    total = 1
    for part in lead if isinstance(lead, tuple) else (lead,):
        if part is not None:
            total *= int(mesh.shape[part])
    return total


def validate_batch_sharding(sharding, data_axis: str = "data",
                            what: str = "batch"):
    """Build-time gate: data enters over ``data`` only.

    A *parameter*-style rule applied to a batch (``tp`` on the feature
    dim, ``fsdp`` without the data fold) compiles into a different —
    wrong — program and otherwise fails deep inside jit as an opaque
    shard-divisibility or layout-mismatch error. Accepted: replicated;
    dim 0 over ``data_axis`` (with the canonical ``fsdp`` fold); inner
    dims over ``seq`` (sequence parallelism pre-splits tokens). Any
    model axis elsewhere raises with the offending axis named. Returns
    ``sharding`` so call sites can validate inline."""
    spec = getattr(sharding, "spec", None)
    if not spec:
        return sharding
    for dim, entry in enumerate(spec):
        names = tuple(
            n for n in (entry if isinstance(entry, tuple) else (entry,))
            if n is not None
        )
        if not names:
            continue
        if dim == 0:
            bad = [n for n in names if n not in (data_axis, "fsdp")]
            if not bad and "fsdp" in names and data_axis not in names:
                bad = ["fsdp"]  # fsdp folds WITH data, never alone
        else:
            bad = [n for n in names if n != "seq"]
        if bad:
            raise ValueError(
                f"{what} sharding {tuple(spec)!r} puts mesh axis "
                f"{bad[0]!r} on dim {dim} — data enters over "
                f"{data_axis!r} (dim 0; fsdp folds in as extra DP) "
                "only. fsdp/tp partition parameters, not batches: use "
                "batch_sharding(mesh)/ring_sharding(mesh) for the "
                "batch side and Layout/partition rules for the state."
            )
    return sharding


def ring_sharding(mesh, axis: str = "data"):
    """Sharding for a device-resident sample ring: the capacity
    (leading) axis split over ``axis`` (folded with ``fsdp`` exactly
    like :func:`batch_sharding`), so reservoir storage scales with the
    mesh instead of replicating per chip."""
    return batch_sharding(mesh, axis=axis)
