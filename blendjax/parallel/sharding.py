"""NamedSharding helpers and parameter partitioning rules."""

from __future__ import annotations


def _np():
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding, PartitionSpec


def batch_sharding(mesh, axis: str = "data"):
    """Shard the leading (batch) axis across ``axis`` — the layout the
    ingest pipeline feeds (SURVEY.md §2.4: per-host ingest -> global batch
    on the ``data`` axis)."""
    NamedSharding, P = _np()
    names = [axis] if axis in mesh.axis_names else []
    if "fsdp" in mesh.axis_names and axis == "data":
        names.append("fsdp")  # fold fsdp into the batch axis for DP
    return NamedSharding(mesh, P(tuple(names) if names else None))


def replicated(mesh):
    NamedSharding, P = _np()
    return NamedSharding(mesh, P())


def param_sharding_rules(mesh, path: tuple, value) -> "object":
    """Default parameter layout:

    - ``expert`` axis: MoE parameters (name starts with ``expert_``,
      leading dim = num_experts) split on dim 0 — expert parallelism;
      GSPMD inserts the dispatch/combine all-to-alls.
    - ``tensor`` axis: dense/conv kernels split on their output-feature
      (last) dimension when divisible — Megatron-style column parallel.
    - ``fsdp`` axis: remaining large params split on their largest
      divisible dimension (ZeRO-3 style).
    - small params (biases, norms) replicated.
    """
    NamedSharding, P = _np()
    shape = getattr(value, "shape", ())
    spec = [None] * len(shape)
    name = str(getattr(path[-1], "key", path[-1])) if path else ""
    if (
        "expert" in mesh.axis_names
        and name.startswith("expert_")
        and shape
        and shape[0] % mesh.shape["expert"] == 0
    ):
        spec[0] = "expert"
    if len(shape) >= 2:
        if "tensor" in mesh.axis_names:
            tp = mesh.shape["tensor"]
            if tp > 1 and shape[-1] % tp == 0:
                spec[-1] = "tensor"
        if "fsdp" in mesh.axis_names:
            fs = mesh.shape["fsdp"]
            if fs > 1:
                # biggest dim not already taken, divisible by fsdp
                order = sorted(
                    range(len(shape)), key=lambda i: -shape[i]
                )
                for i in order:
                    if spec[i] is None and shape[i] % fs == 0:
                        spec[i] = "fsdp"
                        break
    while spec and spec[-1] is None:  # canonical form: P() == replicated
        spec.pop()
    return NamedSharding(mesh, P(*spec))


def shard_params(mesh, params):
    """Apply :func:`param_sharding_rules` over a pytree and device_put."""
    import jax

    def place(path, leaf):
        return jax.device_put(leaf, param_sharding_rules(mesh, path, leaf))

    return jax.tree_util.tree_map_with_path(place, params)


def mesh_chip_count(mesh) -> int:
    """Total participating chips (all processes): the factor live MFU
    and per-chip throughput figures scale by on a mesh run."""
    import numpy as np

    return int(np.prod([int(s) for s in mesh.shape.values()])) if getattr(
        mesh, "shape", None
    ) else 1


def state_shardings(state, mesh=None):
    """The sharding pytree of a concrete train state — what
    ``jax.jit(in_shardings=(state_shardings(state, mesh), ...),
    out_shardings=(state_shardings(state, mesh), ...))`` pins so a
    donated step can never silently reshard params/optimizer state
    mid-run (``blendjax.train.mesh_driver`` builds its steps on this).

    With ``mesh`` given the tree is normalized ONTO it: array leaves
    already holding a NamedSharding on this mesh keep it (params and
    optimizer moments under the mesh rules), every other array leaf —
    the step counters optax creates on the default device — pins to
    replicated on the SAME mesh, so the whole state lives on one
    device set (a jit mixing device sets refuses to run). Without
    ``mesh``, leaves map to their current sharding as-is. Non-array
    leaves (flax's integer ``step`` before the first update,
    ``apply_fn``) map to ``None`` — "unspecified", which jit infers."""
    import jax

    if mesh is None:
        return jax.tree_util.tree_map(
            lambda v: getattr(v, "sharding", None), state
        )
    NamedSharding, P = _np()
    rep = NamedSharding(mesh, P())

    def pin(v):
        if not hasattr(v, "shape"):
            return None
        s = getattr(v, "sharding", None)
        if isinstance(s, NamedSharding) and getattr(s, "mesh", None) == mesh:
            return s
        return rep

    return jax.tree_util.tree_map(pin, state)


def leading_shard_count(sharding) -> int:
    """How many ways a sharding splits dim 0 (1 for ``None``/replicated)
    — the divisibility a global batch size / reservoir capacity must
    satisfy so every chip takes an equal shard."""
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if not spec or mesh is None:
        return 1
    lead = spec[0]
    if lead is None:
        return 1
    total = 1
    for part in lead if isinstance(lead, tuple) else (lead,):
        if part is not None:
            total *= int(mesh.shape[part])
    return total


def ring_sharding(mesh, axis: str = "data"):
    """Sharding for a device-resident sample ring: the capacity
    (leading) axis split over ``axis`` (folded with ``fsdp`` exactly
    like :func:`batch_sharding`), so reservoir storage scales with the
    mesh instead of replicating per chip."""
    return batch_sharding(mesh, axis=axis)
