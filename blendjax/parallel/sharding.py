"""NamedSharding helpers and parameter partitioning rules."""

from __future__ import annotations


def _np():
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding, PartitionSpec


def batch_sharding(mesh, axis: str = "data"):
    """Shard the leading (batch) axis across ``axis`` — the layout the
    ingest pipeline feeds (SURVEY.md §2.4: per-host ingest -> global batch
    on the ``data`` axis)."""
    NamedSharding, P = _np()
    names = [axis] if axis in mesh.axis_names else []
    if "fsdp" in mesh.axis_names and axis == "data":
        names.append("fsdp")  # fold fsdp into the batch axis for DP
    return NamedSharding(mesh, P(tuple(names) if names else None))


def replicated(mesh):
    NamedSharding, P = _np()
    return NamedSharding(mesh, P())


def param_sharding_rules(mesh, path: tuple, value) -> "object":
    """Default parameter layout:

    - ``expert`` axis: MoE parameters (name starts with ``expert_``,
      leading dim = num_experts) split on dim 0 — expert parallelism;
      GSPMD inserts the dispatch/combine all-to-alls.
    - ``tensor`` axis: dense/conv kernels split on their output-feature
      (last) dimension when divisible — Megatron-style column parallel.
    - ``fsdp`` axis: remaining large params split on their largest
      divisible dimension (ZeRO-3 style).
    - small params (biases, norms) replicated.
    """
    NamedSharding, P = _np()
    shape = getattr(value, "shape", ())
    spec = [None] * len(shape)
    name = str(getattr(path[-1], "key", path[-1])) if path else ""
    if (
        "expert" in mesh.axis_names
        and name.startswith("expert_")
        and shape
        and shape[0] % mesh.shape["expert"] == 0
    ):
        spec[0] = "expert"
    if len(shape) >= 2:
        if "tensor" in mesh.axis_names:
            tp = mesh.shape["tensor"]
            if tp > 1 and shape[-1] % tp == 0:
                spec[-1] = "tensor"
        if "fsdp" in mesh.axis_names:
            fs = mesh.shape["fsdp"]
            if fs > 1:
                # biggest dim not already taken, divisible by fsdp
                order = sorted(
                    range(len(shape)), key=lambda i: -shape[i]
                )
                for i in order:
                    if spec[i] is None and shape[i] % fs == 0:
                        spec[i] = "fsdp"
                        break
    while spec and spec[-1] is None:  # canonical form: P() == replicated
        spec.pop()
    return NamedSharding(mesh, P(*spec))


def shard_params(mesh, params):
    """Apply :func:`param_sharding_rules` over a pytree and device_put."""
    import jax

    def place(path, leaf):
        return jax.device_put(leaf, param_sharding_rules(mesh, path, leaf))

    return jax.tree_util.tree_map_with_path(place, params)
