"""Ulysses-style all-to-all sequence parallelism.

The second context-parallel strategy next to :mod:`blendjax.parallel.ring`
(no reference counterpart — blendtorch has no sequence models, SURVEY.md
§2.4): instead of rotating K/V blocks around the ICI ring, two
``all_to_all`` collectives re-shard the tensors between a
*sequence-sharded* layout (B, T/n, H, D) and a *head-sharded* layout
(B, T, H/n, D). Attention itself then runs entirely locally over the full
sequence for the device's head slice — one collective before and one
after, instead of ``n`` ppermute steps.

Trade-off vs ring attention (both exact):

- Ulysses moves Q, K, V and O once each (4 tensor volumes over the ICI
  all-to-all) and needs ``num_heads % n == 0``; compute is a plain local
  attention, so it composes with any masking/attention variant for free.
- Ring moves K and V ``n-1`` times (2(n-1)/n volumes) but keeps the
  sequence axis sharded *through* the softmax, so per-device activation
  memory stays O(T/n) — the long-context scaling story. Ulysses peaks at
  O(T·H/n) for the attention scores.

Use ring for maximum context length, Ulysses when head count is large and
the mask/attention variant is exotic.
"""

from __future__ import annotations

import functools


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool, scale,
                   backend: str):
    """Per-device body (inside shard_map). Local shapes (B, T/n, H, D)."""
    import jax

    from blendjax.ops.attention import local_attention

    # Head-scatter / sequence-gather: split the head axis n ways, deliver
    # chunk j to device j, concatenate the received sequence blocks in
    # device (= global sequence) order -> (B, T, H/n, D).
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    qg, kg, vg = (a2a(x, split_axis=2, concat_axis=1) for x in (q, k, v))
    # The local attention here sees the FULL sequence (for its head
    # slice) — exactly the regime where the flash backend pays: long-T
    # Ulysses composes all-to-alls with the Pallas kernel under 'auto'.
    o = local_attention(qg, kg, vg, causal=causal, scale=scale,
                        backend=backend)
    # Inverse: sequence-scatter / head-gather back to (B, T/n, H, D).
    return a2a(o, split_axis=1, concat_axis=2)


def ulysses_attention(
    q,
    k,
    v,
    mesh,
    axis: str = "seq",
    causal: bool = False,
    scale: float | None = None,
    batch_axis: str | None = "data",
    backend: str = "auto",
):
    """Exact multi-head attention with the sequence dim sharded on
    ``axis``, via head-scatter/sequence-gather all-to-alls.

    Inputs/outputs are (B, T, H, D) global arrays with T sharded on
    ``axis`` (same contract as :func:`~blendjax.parallel.ring_attention`);
    requires ``H % mesh.shape[axis] == 0``. ``backend`` selects the
    per-device local attention after the all-to-all
    (:func:`blendjax.ops.attention.local_attention`). Note the policy
    input there is the POST-all-to-all shape — each device attends the
    full sequence for H/n heads, so the per-call score residual
    shrinks by the axis size: ``auto`` (memory-driven) keeps the
    materialized path until even that per-head-subset residual
    threatens HBM, and takes the Pallas flash kernel beyond (pass
    ``backend="flash"`` to force it).
    """
    import jax
    from jax.sharding import PartitionSpec as P

    d = q.shape[-1]
    scale = scale if scale is not None else d**-0.5
    if axis not in mesh.axis_names:
        from blendjax.ops.attention import local_attention

        return local_attention(q, k, v, causal=causal, scale=scale,
                               backend=backend)
    n = mesh.shape[axis]
    h = q.shape[2]
    assert h % n == 0, (
        f"ulysses needs num_heads ({h}) divisible by the '{axis}' axis "
        f"size ({n}); use ring_attention otherwise"
    )
    b_ax = batch_axis if (batch_axis and batch_axis in mesh.axis_names) else None
    spec = P(b_ax, axis)
    body = functools.partial(
        _ulysses_local, axis_name=axis, causal=causal, scale=scale,
        backend=backend,
    )
    from blendjax.parallel.collectives import _shard_map

    f = _shard_map(
        body, mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    return f(q, k, v)
