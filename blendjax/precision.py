"""Named mixed-precision policies, applied uniformly.

Before this module every model file carried its own dtype constants
(``dtype: type = jnp.bfloat16`` in cnn.py, transformer.py, moe.py, ...)
and the step builders had no say in what dtype gradients crossed the
mesh in. A policy names the whole discipline once (Micikevicius et al.
2018, "Mixed Precision Training") and the step builders + model
constructors resolve everything from it:

- ``f32`` — everything float32. The bit-exactness/reference policy
  (equivalence tests, the fused-vs-unfused loss-equality pins).
- ``bf16-compute`` — **the package default, identical to the previous
  per-file constants**: bf16 activations/matmul inputs on the MXU,
  f32 params, f32 gradients. Matmul accumulation is f32 where the repo
  controls it (``preferred_element_type`` in the attention kernels,
  f32 softmax/LayerNorm/loss), and the data-parallel gradient
  all-reduce runs on f32 grads.
- ``bf16-grads`` — everything in ``bf16-compute`` plus *bf16
  gradients across the mesh*: the step builders differentiate with
  respect to the policy-cast (bf16) params, so the backward-pass
  cotangents — and the cross-chip all-reduce GSPMD inserts for a
  ``data``-sharded batch — carry bf16, **halving gradient all-reduce
  bytes** the same way PR 8's bf16 ring attention halved ppermute
  bytes. Grads are cast back up to the f32 master params before the
  optimizer, and *accumulations stay f32*: the loss is f32
  (``corner_loss`` casts), gradient accumulation over microbatches
  sums into f32 zeros (``accum_steps``), and the matmul accumulators
  keep their ``preferred_element_type=f32`` from the kernels.

The policy binds at TWO points — don't pass it to only one:

- **model construction** owns the compute dtype: ``dtype=None``
  resolves through :func:`default_compute_dtype` to the *package
  default* policy (bf16), and an explicit
  ``Model(**policy.module_kwargs())`` overrides it. A step builder's
  ``precision=`` cannot reach inside an already-constructed model.
- **step builders** own the gradient/accumulation side via
  ``precision=`` (a name or a :class:`PrecisionPolicy`); ``None``
  keeps the default policy, which keeps today's numerics bit-for-bit.

So "run the f32 policy" means ``Model(**F32.module_kwargs())`` AND
``make_*_step(precision="f32")`` — the bench's ``precision_ab`` row
and the fused-vs-eager equality tests do exactly that.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """One named precision discipline.

    - ``compute_dtype``: activations and matmul inputs (the flax
      module ``dtype``).
    - ``param_dtype``: master params the optimizer updates (always f32
      here; a policy exists to make deviation explicit, not easy).
    - ``grad_reduce_dtype``: dtype the gradients carry through the
      backward pass — and therefore through the cross-chip all-reduce
      of a data-parallel step. ``None`` leaves grads in
      ``param_dtype``.
    - ``accum_dtype``: accumulator dtype for matmuls
      (``preferred_element_type``), microbatch gradient accumulation,
      and loss reductions. f32 in every shipped policy: bf16
      accumulation is how mixed precision diverges.
    """

    name: str
    compute_dtype: Any
    param_dtype: Any = jnp.float32
    grad_reduce_dtype: Any | None = None
    accum_dtype: Any = jnp.float32

    def module_kwargs(self) -> dict:
        """Constructor kwargs for the repo's flax models
        (``CubeRegressor(**policy.module_kwargs())``)."""
        return {"dtype": self.compute_dtype}


F32 = PrecisionPolicy("f32", compute_dtype=jnp.float32)
BF16_COMPUTE = PrecisionPolicy("bf16-compute", compute_dtype=jnp.bfloat16)
BF16_GRADS = PrecisionPolicy(
    "bf16-grads", compute_dtype=jnp.bfloat16,
    grad_reduce_dtype=jnp.bfloat16,
)

POLICIES: dict[str, PrecisionPolicy] = {
    p.name: p for p in (F32, BF16_COMPUTE, BF16_GRADS)
}

# The package-wide default: identical numerics to the per-file dtype
# constants it replaced.
DEFAULT_POLICY = BF16_COMPUTE


def resolve_policy(policy) -> PrecisionPolicy:
    """``None`` -> the default policy; a name -> its registry entry; a
    :class:`PrecisionPolicy` passes through."""
    if policy is None:
        return DEFAULT_POLICY
    if isinstance(policy, PrecisionPolicy):
        return policy
    try:
        return POLICIES[policy]
    except KeyError:
        raise ValueError(
            f"unknown precision policy {policy!r}; "
            f"known: {sorted(POLICIES)}"
        ) from None


def default_compute_dtype(dtype=None):
    """The ONE resolution rule for model ``dtype`` attributes: an
    explicit dtype wins; ``None`` takes the default policy's compute
    dtype. Models call this instead of baking their own constant."""
    return dtype if dtype is not None else DEFAULT_POLICY.compute_dtype


def cast_floating(tree, dtype):
    """Cast every floating leaf of a pytree to ``dtype``; integer/bool
    leaves (uint8 frames, step counters) pass through untouched."""
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)


def policy_value_and_grad(scalar_loss, params, policy: PrecisionPolicy,
                          has_aux: bool = False):
    """``jax.value_and_grad`` under a policy — the one grad path all
    step builders share.

    With ``grad_reduce_dtype`` unset this IS ``value_and_grad`` (the
    default policy changes nothing). With it set (``bf16-grads``), the
    differentiation runs with respect to the policy-cast params: the
    cotangents the backward pass produces — including the cross-chip
    gradient all-reduce GSPMD inserts when the batch is sharded over
    the mesh ``data`` axis — carry ``grad_reduce_dtype`` (half the
    all-reduce bytes at bf16), and the grads are cast back up to each
    master param's own dtype before the optimizer sees them (f32
    moments and updates; the accumulation discipline stays
    ``accum_dtype``).

    ``has_aux`` mirrors ``jax.value_and_grad``: ``scalar_loss``
    returns ``(loss, aux)`` and so does the value side — the RL step
    builders use it to carry per-row TD errors out of the loss for
    the in-jit priority write-back."""
    if policy.grad_reduce_dtype is None:
        return jax.value_and_grad(scalar_loss, has_aux=has_aux)(params)
    value, grads = jax.value_and_grad(scalar_loss, has_aux=has_aux)(
        cast_floating(params, policy.grad_reduce_dtype)
    )
    grads = jax.tree.map(
        lambda g, p: g.astype(p.dtype) if hasattr(p, "dtype") else g,
        grads, params,
    )
    return value, grads


__all__ = [
    "PrecisionPolicy",
    "POLICIES",
    "DEFAULT_POLICY",
    "F32",
    "BF16_COMPUTE",
    "BF16_GRADS",
    "resolve_policy",
    "default_compute_dtype",
    "cast_floating",
    "policy_value_and_grad",
]
