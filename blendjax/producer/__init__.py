"""Producer-side runtime: runs inside renderer processes.

Reference counterpart: ``pkg_blender/blendtorch/btb`` (the package installed
into Blender's embedded Python). blendjax generalizes it behind an *engine*
interface so the same lifecycle/publishing/env code drives either

- Blender (``blendjax.producer.bpy_engine``, importable only under ``bpy``), or
- the headless simulation engine (``blendjax.producer.sim``) used by tests,
  benchmarks, and any non-Blender renderer.

Import policy: nothing here imports ``jax`` or ``bpy`` at package level;
Blender-only modules are imported lazily/gated.
"""

from blendjax.launcher.arguments import parse_launch_args
from blendjax.producer.animation import AnimationController
from blendjax.producer.camera import Camera
from blendjax.producer.duplex import DuplexChannel
from blendjax.producer.env import BaseEnv, RemoteControlledAgent
from blendjax.producer.publisher import DataPublisher
from blendjax.producer.scenario import ScenarioApplicator
from blendjax.producer.signal import Signal
from blendjax.producer.tile_publisher import TileBatchPublisher

__all__ = [
    "parse_launch_args",
    "AnimationController",
    "Camera",
    "DataPublisher",
    "DuplexChannel",
    "ScenarioApplicator",
    "Signal",
    "BaseEnv",
    "RemoteControlledAgent",
    "TileBatchPublisher",
]
