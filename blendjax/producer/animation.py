"""Animation lifecycle controller — the producer's main loop.

Reference: ``pkg_blender/blendtorch/btb/animation.py:9-212``. It turns a
frame-stepped simulation into deterministic lifecycle events, asserted in
the reference's ``tests/test_animation.py:7-26``::

    pre_play -> [pre_animation -> (pre_frame -> post_frame) x N
                 -> post_animation] x E -> post_play

where an *episode* is one replay of the frame range. blendjax drives the
loop through an :class:`Engine` so the identical controller runs against
Blender (``BpyEngine``, non-blocking via ``bpy`` handlers, see
``bpy_engine.py``) or any headless simulator (``sim.SimEngine`` — the
blocking strategy the reference uses under ``--background``,
``animation.py:153-164``).
"""

from __future__ import annotations

from blendjax.producer.signal import Signal
from blendjax.utils.metrics import metrics


class Engine:
    """What the controller needs from a renderer/simulator.

    ``frame_set(i)`` must advance the scene/physics to frame ``i``; the
    controller invokes ``pre_frame`` before and ``post_frame`` after, so
    physics resolves between action application and observation — the
    contract the env layer depends on (reference ``btb/env.py:144-159``).
    """

    def frame_set(self, frame: int) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        """Rewind scene state to the start of the frame range (reference
        syncs rigid-body point caches here, ``animation.py:108-134``)."""


class CancelledError(Exception):
    """Raised internally to unwind a cancelled play loop."""


class AnimationController:
    """Drives episodes of a frame range over an :class:`Engine`.

    Signals (reference ``animation.py:33-40``): ``pre_play``,
    ``pre_animation``, ``pre_frame``, ``post_frame``, ``post_animation``,
    ``post_play``. Frame handlers receive the current frame number.
    """

    def __init__(self, engine: Engine):
        self.engine = engine
        self.pre_play = Signal()
        self.pre_animation = Signal()
        self.pre_frame = Signal()
        self.post_frame = Signal()
        self.post_animation = Signal()
        self.post_play = Signal()
        self.frameid: int | None = None
        self.episode = 0
        self._playing = False
        self._rewind_requested = False
        self._cancel_requested = False

    @property
    def playing(self) -> bool:
        return self._playing

    @property
    def cancelled(self) -> bool:
        """True once :meth:`cancel` was requested (thread-safe flag read;
        lets long-blocking frame handlers — e.g. the env RPC rendezvous —
        bail out promptly)."""
        return self._cancel_requested

    def rewind(self) -> None:
        """Restart the current episode's frame range at the next frame
        boundary (reference ``animation.py:166-184``); callable from
        within ``pre_frame``/``post_frame`` handlers."""
        self._rewind_requested = True

    def cancel(self) -> None:
        """Stop playing after the current frame (reference teardown
        ``animation.py:186-212``)."""
        self._cancel_requested = True

    def play(
        self,
        frame_range=(1, 250),
        num_episodes: int = -1,
        use_animation: bool | None = None,
    ) -> None:
        """Blocking play loop. ``num_episodes=-1`` plays forever (until
        :meth:`cancel`). ``use_animation`` is accepted for reference API
        compatibility (``animation.py:73-106``); engines that own their own
        clock (Blender UI mode) override :meth:`_run_loop` instead.
        """
        del use_animation
        assert not self._playing, "already playing"
        start, end = int(frame_range[0]), int(frame_range[1])
        assert end >= start, f"invalid frame range {frame_range}"
        self._playing = True
        self._cancel_requested = False
        self.episode = 0
        self.pre_play.invoke()
        try:
            self._run_loop(start, end, num_episodes)
        except CancelledError:
            pass
        finally:
            self._playing = False
            self.post_play.invoke()

    # -- internals ----------------------------------------------------------

    def _run_loop(self, start: int, end: int, num_episodes: int) -> None:
        while num_episodes < 0 or self.episode < num_episodes:
            self._play_episode(start, end)
            self.episode += 1
            if self._cancel_requested:
                break

    def _play_episode(self, start: int, end: int) -> None:
        self.engine.reset()
        self.pre_animation.invoke()
        frame = start
        while frame <= end:
            self._rewind_requested = False
            self.frameid = frame
            self.pre_frame.invoke(frame)
            # producer.frame = render + physics for one frame: the span
            # producers piggyback to consumers via the data-channel
            # telemetry snapshots (DataPublisherSocket.telemetry_every),
            # so a fleet-wide render-time view needs no extra socket.
            with metrics.span("producer.frame"):
                self.engine.frame_set(frame)
            self.post_frame.invoke(frame)
            if self._cancel_requested:
                raise CancelledError
            if self._rewind_requested:
                # Restart this episode's range without closing the episode
                # (reference ``rewind``, ``animation.py:166-184``).
                # ``pre_animation`` re-fires so env-layer reset hooks run
                # (reference resets env state there, ``btb/env.py:111-115``).
                self.engine.reset()
                self.pre_animation.invoke()
                frame = start
                continue
            frame += 1
        self.post_animation.invoke()
