"""Blender integration: engine, camera import, and scene-query helpers.

Importable only inside Blender's Python (``import bpy`` must succeed).
Reference counterparts: ``pkg_blender/blendtorch/btb/animation.py`` (the
handler-driven loop), ``camera.py:8-82`` (matrices from bpy), and
``utils.py`` (depsgraph coordinate/visibility queries).

Design note (tpu-first, not a port): the blendjax
:class:`~blendjax.producer.animation.AnimationController` owns a blocking
loop over an Engine, which corresponds to the reference's ``--background``
strategy (``animation.py:153-164``). The reference's non-blocking UI mode
(``frame_change_pre`` + ``SpaceView3D`` POST_PIXEL draw handler so GPU
reads are legal, ``animation.py:136-151``) is provided by
:class:`BpyAnimationDriver`, which replays the same signal lifecycle from
Blender's own clock.
"""

from __future__ import annotations

import numpy as np

try:
    import bpy  # noqa: F401
except ImportError as e:  # pragma: no cover - only runs outside Blender
    raise ImportError(
        "blendjax.producer.bpy_engine requires Blender's embedded Python "
        "(bpy). For headless use, see blendjax.producer.sim."
    ) from e

from blendjax.producer.animation import Engine
from blendjax.producer.utils import dehom, hom


class BpyEngine(Engine):
    """Drive Blender's scene from the blocking controller loop (background
    mode; offscreen rendering is unsupported there, reference
    ``animation.py:20-22``)."""

    def __init__(self, scene=None):
        self.scene = scene or bpy.context.scene

    def frame_set(self, frame: int) -> None:
        self.scene.frame_set(frame)

    def reset(self) -> None:
        start = self.scene.frame_start
        # Keep rigid-body point caches in sync with the replayed range
        # (reference ``setup_frame_range``, ``animation.py:108-134``).
        rb = getattr(self.scene, "rigidbody_world", None)
        if rb is not None and rb.point_cache is not None:
            rb.point_cache.frame_start = start
            rb.point_cache.frame_end = self.scene.frame_end
        self.scene.frame_set(start)


class BpyAnimationDriver:
    """Non-blocking playback under the Blender UI: hooks
    ``bpy.app.handlers.frame_change_pre`` for ``pre_frame`` and a
    ``SpaceView3D`` POST_PIXEL draw handler for GPU-safe ``post_frame``
    (reference ``animation.py:136-151``), emitting the same signal
    lifecycle as the blocking controller."""

    def __init__(self, controller, scene=None):
        self.controller = controller
        self.scene = scene or bpy.context.scene
        self._draw_handle = None
        self._pending_post = None

    def play(self, frame_range=(1, 250)) -> None:
        c = self.controller
        self.scene.frame_start, self.scene.frame_end = frame_range
        c.pre_play.invoke()
        c.pre_animation.invoke()
        bpy.app.handlers.frame_change_pre.append(self._on_frame_pre)
        space = find_first_view3d()
        self._draw_handle = space.draw_handler_add(
            self._on_draw, (), "WINDOW", "POST_PIXEL"
        )
        bpy.ops.screen.animation_play()

    def _on_frame_pre(self, scene, _=None):
        # Dedup guard: Blender can fire frame_change multiple times per
        # frame (reference ``skip_post_frame``, ``animation.py:56-65``).
        if self._pending_post == scene.frame_current:
            return
        self.controller.frameid = scene.frame_current
        self.controller.pre_frame.invoke(scene.frame_current)
        self._pending_post = scene.frame_current

    def _on_draw(self):
        if self._pending_post is None:
            return
        frame, self._pending_post = self._pending_post, None
        self.controller.post_frame.invoke(frame)
        if frame >= self.scene.frame_end:
            self.controller.post_animation.invoke()
            self.controller.episode += 1

    def cancel(self) -> None:
        bpy.ops.screen.animation_cancel(restore_frame=False)
        if self._on_frame_pre in bpy.app.handlers.frame_change_pre:
            bpy.app.handlers.frame_change_pre.remove(self._on_frame_pre)
        if self._draw_handle is not None:
            find_first_view3d().draw_handler_remove(self._draw_handle, "WINDOW")
            self._draw_handle = None
        self.controller.post_play.invoke()


# -- camera ----------------------------------------------------------------


def camera_from_bpy(cls, bpy_camera=None, shape=None):
    """Construct a :class:`blendjax.producer.camera.Camera` from a Blender
    camera object (reference ``camera.py:8-82``: matrices from bpy,
    ``shape_from_bpy`` honoring resolution_percentage)."""
    cam_obj = bpy_camera or bpy.context.scene.camera
    cam = cam_obj.data
    render = bpy.context.scene.render
    if shape is None:
        scale = render.resolution_percentage / 100.0
        shape = (
            int(render.resolution_y * scale),
            int(render.resolution_x * scale),
        )
    mw = np.asarray(cam_obj.matrix_world)
    kwargs = dict(
        position=mw[:3, 3],
        rotation=mw[:3, :3],
        shape=shape,
        clip_near=cam.clip_start,
        clip_far=cam.clip_end,
    )
    if cam.type == "ORTHO":
        kwargs["ortho_scale"] = cam.ortho_scale
    else:
        kwargs["focal_mm"] = cam.lens
        kwargs["sensor_mm"] = cam.sensor_width
    return cls(**kwargs)


# -- scene queries (evaluated depsgraph) -----------------------------------


def find_first_view3d():
    """First VIEW_3D space in any open window (reference
    ``utils.py:6-28``); needed for draw handlers and offscreen renders."""
    for window in bpy.context.window_manager.windows:
        for area in window.screen.areas:
            if area.type == "VIEW_3D":
                for space in area.spaces:
                    if space.type == "VIEW_3D":
                        return space
    raise RuntimeError("no VIEW_3D space found (is Blender in --background?)")


def world_coordinates(*objs, depsgraph=None) -> np.ndarray:
    """Evaluated world-space vertex coordinates of objects (reference
    ``utils.py:30-109``: the evaluated depsgraph resolves modifiers and
    physics before reading geometry)."""
    dg = depsgraph or bpy.context.evaluated_depsgraph_get()
    out = []
    for obj in objs:
        ev = obj.evaluated_get(dg)
        mesh = ev.to_mesh()
        n = len(mesh.vertices)
        co = np.empty(n * 3, dtype=np.float64)
        mesh.vertices.foreach_get("co", co)
        mw = np.asarray(ev.matrix_world)
        out.append(dehom(hom(co.reshape(n, 3)) @ mw.T))
        ev.to_mesh_clear()
    return np.concatenate(out) if out else np.empty((0, 3))


def bbox_world_coordinates(obj, depsgraph=None) -> np.ndarray:
    """World-space bounding-box corners of an object (reference
    ``utils.py:84-109``)."""
    dg = depsgraph or bpy.context.evaluated_depsgraph_get()
    ev = obj.evaluated_get(dg)
    mw = np.asarray(ev.matrix_world)
    corners = np.array([list(c) for c in ev.bound_box])
    return dehom(hom(corners) @ mw.T)


def compute_object_visibility(
    obj, camera_obj, n_samples: int = 32, depsgraph=None, rng=None
) -> float:
    """Monte-Carlo visibility: fraction of random surface points whose ray
    to the camera is unobstructed (reference ``utils.py:158-179``)."""
    rng = rng or np.random.default_rng()
    dg = depsgraph or bpy.context.evaluated_depsgraph_get()
    pts = world_coordinates(obj, depsgraph=dg)
    if len(pts) == 0:
        return 0.0
    idx = rng.integers(0, len(pts), size=min(n_samples, len(pts)))
    cam_pos = np.asarray(camera_obj.matrix_world)[:3, 3]
    scene = bpy.context.scene
    visible = 0
    for p in pts[idx]:
        d = cam_pos - p
        dist = np.linalg.norm(d)
        if dist < 1e-9:
            continue
        d = d / dist
        origin = p + d * 1e-4
        hit, *_ = scene.ray_cast(dg, origin.tolist(), d.tolist(), distance=dist - 1e-3)
        if not hit:
            visible += 1
    return visible / len(idx)


def scene_stats() -> dict:
    """Counts of objects/meshes/materials in the scene (reference
    ``utils.py:181-192``)."""
    return {
        "num_objects": len(bpy.data.objects),
        "num_meshes": len(bpy.data.meshes),
        "num_materials": len(bpy.data.materials),
        "num_images": len(bpy.data.images),
    }
