"""Camera model: project scene geometry to pixel-space annotations.

Reference: ``pkg_blender/blendtorch/btb/camera.py:8-204`` — view/projection
matrices from the Blender camera, ``world_to_ndc`` (+ linear depth),
``ndc_to_pixel`` with upper-left/lower-left origins, ``object_to_pixel`` /
``bbox_object_to_pixel`` compositions, and ``look_at``.

blendjax's camera is a standalone numpy model (Blender conventions: camera
looks down -Z, +Y is up) constructed from explicit intrinsics/extrinsics,
with a ``from_bpy`` hook for real Blender cameras (see ``bpy_engine.py``).
That makes annotation math testable against analytic ground truth instead
of a ``.blend`` fixture (reference ``tests/test_camera.py`` + ``cam.blend``).
"""

from __future__ import annotations

import numpy as np

from blendjax.producer.utils import dehom, hom, look_at_matrix


class Camera:
    """Pinhole or orthographic camera.

    Parameters
    ----------
    position, rotation:
        World-space camera origin and 3x3 world-from-camera rotation.
    shape:
        Image ``(height, width)`` (reference ``camera.py:57-66`` derives it
        from render settings x resolution_percentage).
    focal_mm / sensor_mm:
        Pinhole intrinsics, Blender-style (perspective only).
    ortho_scale:
        World-units width of the view volume (orthographic only).
    """

    def __init__(
        self,
        position=(0.0, 0.0, 0.0),
        rotation=None,
        shape=(480, 640),
        focal_mm: float = 50.0,
        sensor_mm: float = 36.0,
        ortho_scale: float | None = None,
        clip_near: float = 0.1,
        clip_far: float = 100.0,
    ):
        self.position = np.asarray(position, np.float64)
        self.rotation = (
            np.eye(3) if rotation is None else np.asarray(rotation, np.float64)
        )
        self.shape = (int(shape[0]), int(shape[1]))
        self.focal_mm = float(focal_mm)
        self.sensor_mm = float(sensor_mm)
        self.ortho_scale = None if ortho_scale is None else float(ortho_scale)
        self.clip_near = float(clip_near)
        self.clip_far = float(clip_far)

    # -- constructors -------------------------------------------------------

    @classmethod
    def look_at(cls, eye, target, up=(0, 0, 1), **kwargs) -> "Camera":
        """Camera positioned at ``eye`` aimed at ``target`` (reference
        ``camera.py:191-204``)."""
        return cls(
            position=eye, rotation=look_at_matrix(eye, target, up), **kwargs
        )

    @classmethod
    def from_bpy(cls, bpy_camera=None, shape=None) -> "Camera":
        """Build from a Blender camera object (requires ``bpy``; reference
        ``camera.py:8-82``)."""
        from blendjax.producer.bpy_engine import camera_from_bpy

        return camera_from_bpy(cls, bpy_camera, shape)

    # -- matrices -----------------------------------------------------------

    @property
    def view_matrix(self) -> np.ndarray:
        """4x4 camera-from-world (reference ``camera.py:68-74``)."""
        m = np.eye(4)
        rt = self.rotation.T
        m[:3, :3] = rt
        m[:3, 3] = -rt @ self.position
        return m

    @property
    def proj_matrix(self) -> np.ndarray:
        """4x4 OpenGL-style projection (reference ``camera.py:76-82``)."""
        h, w = self.shape
        aspect = w / h
        n, f = self.clip_near, self.clip_far
        p = np.zeros((4, 4))
        if self.ortho_scale is not None:
            r = self.ortho_scale / 2.0
            t = r / aspect
            p[0, 0] = 1.0 / r
            p[1, 1] = 1.0 / t
            p[2, 2] = -2.0 / (f - n)
            p[2, 3] = -(f + n) / (f - n)
            p[3, 3] = 1.0
        else:
            sx = self.sensor_mm
            sy = self.sensor_mm / aspect
            p[0, 0] = 2.0 * self.focal_mm / sx
            p[1, 1] = 2.0 * self.focal_mm / sy
            p[2, 2] = -(f + n) / (f - n)
            p[2, 3] = -2.0 * f * n / (f - n)
            p[3, 2] = -1.0
        return p

    # -- projections --------------------------------------------------------

    def _matrices(self):
        """(view, proj) with transparent caching: parameters are plain
        mutable attributes, so the cache keys on their VALUES (a dozen
        doubles — the key build is ~1us where the property rebuilds cost
        ~50us and run twice per rendered frame)."""
        key = (
            # coerce: users may assign plain sequences to these attrs
            np.asarray(self.position, np.float64).tobytes(),
            np.asarray(self.rotation, np.float64).tobytes(),
            self.shape,
            self.focal_mm, self.sensor_mm, self.ortho_scale,
            self.clip_near, self.clip_far,
        )
        if getattr(self, "_mat_key", None) != key:
            view = self.view_matrix
            proj = self.proj_matrix
            # key assigned LAST: an exception above must not poison the
            # cache with a key whose matrices were never stored
            self._view_cached = view
            self._proj_cached = proj
            self._mat_key = key
        return self._view_cached, self._proj_cached

    def world_to_ndc(self, xyz_world) -> tuple[np.ndarray, np.ndarray]:
        """Project world points to NDC; also return linear depth (positive
        distance along the view direction; reference ``camera.py:84-112``)."""
        xyz_world = np.atleast_2d(np.asarray(xyz_world, np.float64))
        view, proj = self._matrices()
        cam = hom(xyz_world) @ view.T
        depth = -cam[:, 2]
        ndc = dehom(cam @ proj.T)
        return ndc, depth

    def ndc_to_pixel(self, ndc, origin: str = "upper-left") -> np.ndarray:
        """NDC -> pixel coordinates (reference ``camera.py:115-136``)."""
        assert origin in ("upper-left", "lower-left")
        h, w = self.shape
        ndc = np.atleast_2d(np.asarray(ndc, np.float64))
        x = (ndc[:, 0] + 1.0) * 0.5 * w
        y01 = (ndc[:, 1] + 1.0) * 0.5
        y = (1.0 - y01) * h if origin == "upper-left" else y01 * h
        return np.stack([x, y], axis=1)

    def world_to_pixel(
        self, xyz_world, origin: str = "upper-left", return_depth: bool = False
    ):
        """Compose projection to pixels (reference ``object_to_pixel``,
        ``camera.py:138-189``, without the bpy object dereference)."""
        ndc, depth = self.world_to_ndc(xyz_world)
        px = self.ndc_to_pixel(ndc, origin=origin)
        return (px, depth) if return_depth else px

    def bbox_world_to_pixel(self, xyz_world, origin: str = "upper-left"):
        """Axis-aligned pixel bbox ``(xmin, ymin, xmax, ymax)`` of points
        (reference ``bbox_object_to_pixel``, ``camera.py:162-189``)."""
        px = self.world_to_pixel(xyz_world, origin=origin)
        mins, maxs = px.min(axis=0), px.max(axis=0)
        return np.array([mins[0], mins[1], maxs[0], maxs[1]])
