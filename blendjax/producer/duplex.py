"""Producer end of the duplex control channel (PAIR, bind side).

Reference: ``pkg_blender/blendtorch/btb/duplex.py:8-66`` — identical to the
consumer twin except it binds, and uses the shorter producer default
timeout (``btb/constants.py:4``).
"""

from __future__ import annotations

from blendjax import constants
from blendjax.transport import PairChannel


class DuplexChannel(PairChannel):
    def __init__(
        self,
        addr: str,
        btid: int | None = None,
        lingerms: int = 0,
        hwm: int = constants.DEFAULT_SEND_HWM,
        codec: str = "tensor",
        allow_pickle: bool = True,
    ):
        # ``allow_pickle`` defaults True for reference-producer compat;
        # network-facing control consumers (the scenario applicator's
        # channel, whose address may be announced off-host) pass False
        # so a pickled payload can never execute in the producer.
        super().__init__(
            addr,
            btid=btid,
            bind=True,
            hwm=hwm,
            lingerms=lingerms,
            codec=codec,
            default_timeoutms=constants.DEFAULT_PRODUCER_TIMEOUTMS,
            allow_pickle=allow_pickle,
        )
