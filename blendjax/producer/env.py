"""Producer-side environment base: remote-controlled simulation episodes.

Reference: ``pkg_blender/blendtorch/btb/env.py``. The defining pattern
(SURVEY.md §3.2): a *blocking* REQ/REP rendezvous embedded in a frame-
callback world. One remote ``step()`` = one simulated frame; ``step`` is
split into a pre-frame half (apply action) and a post-frame half (collect
observation) so physics resolves in between (``btb/env.py:144-159``).

:class:`RemoteControlledAgent` is the REP-side state machine
(``btb/env.py:179-252``): it owes a reply after every accepted request
(STATE_REP), sends the freshly-computed context at the next frame
boundary, then waits for the next command (STATE_REQ). ``real_time=True``
degrades to non-blocking receives, substituting ``(CMD_STEP, None)`` when
the consumer is slow (``btb/env.py:222-233``) so the simulation clock never
stalls.
"""

from __future__ import annotations

import time

from blendjax import constants
from blendjax.producer.animation import AnimationController, Engine
from blendjax.transport import RpcServer

CMD_STEP = "step"
CMD_RESTART = "restart"


class BaseEnv:
    """Wire an agent into the animation lifecycle.

    Subclasses implement (reference ``btb/env.py:137-176``):

    - ``_env_reset()`` — reset scene state at episode start.
    - ``_env_prepare_step(action)`` — apply the action before physics.
    - ``_env_post_step()`` — return the post-physics context dict
      (``obs``/``reward``/``done``/extras).
    """

    def __init__(self, agent):
        self.agent = agent
        self.events: AnimationController | None = None
        self.ctx: dict = {}
        self.renderer = None
        self.render_every: int = 1

    # -- lifecycle wiring ---------------------------------------------------

    def run(self, engine: Engine, frame_range=(1, 2_147_483_647)) -> None:
        """Play frames forever under ``engine`` (reference ``run`` plays to
        INT32_MAX, ``btb/env.py:55-77``)."""
        self.events = AnimationController(engine)
        self.events.pre_frame.add(self._pre_frame)
        self.events.pre_animation.add(self._pre_animation)
        self.events.post_frame.add(self._post_frame)
        self.events.play(frame_range=frame_range, num_episodes=-1)

    def attach_default_renderer(self, every_nth: int = 1, renderer=None):
        """Attach an rgb renderer whose output rides along as
        ``rgb_array`` every ``every_nth`` frames (reference
        ``btb/env.py:79-95``). With ``renderer=None`` the env's default is
        used: :meth:`_default_renderer`, which subclasses backed by a sim
        scene override (Blender envs get an ``OffScreenRenderer``)."""
        self.renderer = renderer or self._default_renderer()
        if self.renderer is None:
            raise ValueError(
                "no renderer: pass renderer=... or override _default_renderer"
            )
        self.render_every = max(1, int(every_nth))

    def _default_renderer(self):
        """Return a zero-arg callable producing an HxWxC uint8 frame, or
        None. Under Blender, builds the offscreen Eevee renderer."""
        try:
            from blendjax.producer.offscreen import OffScreenRenderer

            return OffScreenRenderer().render
        except ImportError:
            return None

    def stop(self) -> None:
        if self.events is not None:
            self.events.cancel()

    # -- signal handlers ----------------------------------------------------

    def _pre_animation(self) -> None:
        # Episode start: reset env state + context (``btb/env.py:111-115``).
        self.ctx = {}
        seed = getattr(self.agent, "reset_seed", None)
        if seed is not None:
            self.agent.reset_seed = None
            self._env_seed(seed)
        self._env_reset()

    def _pre_frame(self, frame: int) -> None:
        # (``btb/env.py:97-109``)
        cmd, action = self.agent(self, **self.ctx)
        if cmd == CMD_RESTART:
            self.events.rewind()
        elif cmd == CMD_STEP:
            if action is not None:
                self._env_prepare_step(action)
            # Simulation time = frame id (``btb/env.py:99``).
            self.ctx["time"] = frame

    def _post_frame(self, frame: int) -> None:
        # (``btb/env.py:117-131``)
        if self.renderer is not None and frame % self.render_every == 0:
            self.ctx["rgb_array"] = self.renderer()
        self.ctx.update(self._env_post_step())

    # -- to be implemented by scene envs ------------------------------------

    def _env_seed(self, seed: int) -> None:
        """Reseed the episode RNG before ``_env_reset`` (the remote
        ``reset(seed=)`` landing point). Default: reseed ``self.scene``
        when it exposes the sim-scene ``reseed`` hook; scene-less envs
        override."""
        scene = getattr(self, "scene", None)
        reseed = getattr(scene, "reseed", None)
        if reseed is not None:
            reseed(seed)

    def _env_reset(self) -> None:
        raise NotImplementedError

    def _env_prepare_step(self, action) -> None:
        raise NotImplementedError

    def _env_post_step(self) -> dict:
        raise NotImplementedError


class RemoteControlledAgent:
    """REP-side state machine bridging blocking remote calls to frames.

    Reference: ``btb/env.py:179-252``.
    """

    STATE_INIT = 0  # nothing received yet this episode
    STATE_REQ = 1  # waiting for the next command
    STATE_REP = 2  # a reply is owed after the current frame

    def __init__(
        self,
        bind_addr: str,
        real_time: bool = False,
        timeoutms: int = constants.DEFAULT_PRODUCER_TIMEOUTMS,
    ):
        self.server = RpcServer(bind_addr)
        self.addr = self.server.addr
        self.real_time = real_time
        self.timeoutms = timeoutms
        self.state = self.STATE_INIT
        # a reset(seed=) parks its seed here until the next episode
        # start consumes it (BaseEnv._pre_animation)
        self.reset_seed: int | None = None

    def __call__(self, env: BaseEnv, **ctx):
        if self.state == self.STATE_REP:
            if not ctx:
                # A reply is owed but the fresh episode hasn't produced an
                # observation yet (ctx was reset in pre_animation): run one
                # defaults-step so post_frame fills ctx, reply next frame.
                return CMD_STEP, None
            self.server.reply(**self._wire_ctx(ctx))
            self.state = self.STATE_REQ

        req = self._next_request(env)
        if req is None:
            # real_time only: consumer too slow — free-run the simulation
            # with a default step (``btb/env.py:222-233``).
            return CMD_STEP, None

        cmd = req.get("cmd")
        if cmd == "reset":
            seed = req.get("seed")
            if seed is not None:
                # Parked for the next _pre_animation: the env reads and
                # clears it before _env_reset, so the fresh episode's
                # initial state draws from the requested seed (the
                # Gymnasium reset(seed=) contract, producer side).
                self.reset_seed = int(seed)
            if self.state == self.STATE_INIT and seed is None:
                # Episode just started and nothing was stepped: don't
                # rewind again; step once so fresh obs exist to reply with
                # (reset-dedup, ``btb/env.py:241-246``). A SEEDED reset
                # must rewind regardless — the just-started episode drew
                # from the launch seed, not the requested one.
                self.state = self.STATE_REP
                return CMD_STEP, None
            self.state = self.STATE_REP
            return CMD_RESTART, None
        if cmd == "step":
            self.state = self.STATE_REP
            return CMD_STEP, req.get("action")
        # Unknown command: reply with an error, keep waiting next frame.
        self.server.reply(error=f"unknown cmd {cmd!r}")
        return CMD_STEP, None

    def _next_request(self, env: BaseEnv):
        if self.real_time:
            return self.server.recv(timeoutms=0)
        # Blocking mode: wait (in pollable slices so cancel/ctrl-c work)
        # until the consumer sends the next command.
        while True:
            req = self.server.recv(timeoutms=min(self.timeoutms, 100))
            if req is not None:
                return req
            if env.events is not None and env.events.cancelled:
                return None
            time.sleep(0)  # yield; keep waiting like the reference REP

    @staticmethod
    def _wire_ctx(ctx: dict) -> dict:
        # ``done`` must be a plain bool for the wire; numpy bools arrive
        # from user env code.
        out = dict(ctx)
        if "done" in out:
            out["done"] = bool(out["done"])
        return out

    def close(self) -> None:
        self.server.close()
