"""Real-time offscreen rendering inside the Blender UI (Eevee).

Reference: ``pkg_blender/blendtorch/btb/offscreen.py:9-112`` — a
``gpu.types.GPUOffScreen`` target, view3d drawn with the camera's
matrices, pixels read back into a preallocated ``np.uint8`` H×W×C buffer.
The reference reads through PyOpenGL's ``glGetTexImage`` because
``bgl.Buffer`` lacks the buffer protocol (``offscreen.py:88-91``); modern
Blender (3.x+) exposes ``texture_color.read()`` which returns a
buffer-protocol object, so blendjax uses that and keeps the GL fallback.

Must be called from a POST_PIXEL draw-handler context
(``offscreen.py:16-19``); offscreen rendering is unavailable under
``--background`` (``animation.py:20-22``) — use the headless sim renderer
there instead.

Gamma correction is deliberately NOT done here: the reference burns CPU on
it (``offscreen.py:97-98,105-112``); blendjax ships linear ``uint8`` and
applies gamma on-device (``blendjax.ops.image.gamma``), which is both free
(fused into the input cast) and keeps the producer hot loop lean.
"""

from __future__ import annotations

import numpy as np

try:
    import bpy
    import gpu
except ImportError as e:  # pragma: no cover
    raise ImportError(
        "blendjax.producer.offscreen requires Blender (bpy/gpu). "
        "Use blendjax.producer.sim for headless rendering."
    ) from e

from blendjax.producer.bpy_engine import find_first_view3d


class OffScreenRenderer:
    def __init__(self, camera=None, mode: str = "rgb", origin: str = "upper-left"):
        assert mode in ("rgb", "rgba")
        self.camera = camera or bpy.context.scene.camera
        self.channels = 3 if mode == "rgb" else 4
        self.origin = origin
        render = bpy.context.scene.render
        scale = render.resolution_percentage / 100.0
        self.shape = (
            int(render.resolution_y * scale),
            int(render.resolution_x * scale),
        )
        h, w = self.shape
        self.offscreen = gpu.types.GPUOffScreen(w, h)
        self.buffer = np.empty((h, w, 4), dtype=np.uint8)
        self.space = find_first_view3d()
        self.area = None
        self.region = None

    def set_render_style(self, shading: str = "RENDERED", overlays: bool = False):
        """(reference ``offscreen.py:101``)"""
        self.space.shading.type = shading
        self.space.overlay.show_overlays = overlays

    def render(self) -> np.ndarray:
        """Draw the view through ``self.camera`` and return H×W×C uint8.

        The returned array's origin follows ``self.origin`` — Blender/GL
        give lower-left scanlines, so 'upper-left' flips vertically
        (reference ``offscreen.py:95-96``).
        """
        scene = bpy.context.scene
        view_m = self.camera.matrix_world.inverted()
        proj_m = self.camera.calc_matrix_camera(
            bpy.context.evaluated_depsgraph_get(),
            x=self.shape[1],
            y=self.shape[0],
        )
        with self.offscreen.bind():
            self.offscreen.draw_view3d(
                scene,
                bpy.context.view_layer,
                self.space,
                self.region or bpy.context.region,
                view_m,
                proj_m,
                do_color_management=True,
            )
            tex = getattr(self.offscreen, "texture_color", None)
            if tex is not None:
                buf = tex.read()
                buf.dimensions = self.shape[0] * self.shape[1] * 4
                arr = np.asarray(buf, dtype=np.uint8)
            else:
                # Blender 2.8x/2.9x: no texture_color — read the bound
                # color attachment through GL like the reference does
                # (``offscreen.py:68-99``: ``bgl.Buffer`` lacks the
                # buffer protocol, hence PyOpenGL's glGetTexImage there;
                # glReadPixels on the bound FBO needs neither).
                arr = self._read_pixels_gl()
        arr = arr.reshape(self.shape[0], self.shape[1], 4)
        if self.origin == "upper-left":
            arr = np.flipud(arr)
        return arr[..., : self.channels]

    def _read_pixels_gl(self) -> np.ndarray:
        """Legacy readback for Blender builds predating
        ``GPUOffScreen.texture_color`` (reference counterpart:
        ``btb/offscreen.py:68-99``). ``glReadPixels`` into a numpy
        buffer while the offscreen FBO is bound — PyOpenGL accepts any
        writable buffer-protocol object, sidestepping the bgl.Buffer
        limitation the reference works around via glGetTexImage."""
        try:
            from OpenGL import GL
        except ImportError as e:  # pragma: no cover - legacy-Blender only
            raise RuntimeError(
                "this Blender's GPUOffScreen has no texture_color and "
                "PyOpenGL is not importable; pip-install PyOpenGL into "
                "Blender's Python (scripts/install_producer.py does)"
            ) from e
        h, w = self.shape
        GL.glReadPixels(
            0, 0, w, h, GL.GL_RGBA, GL.GL_UNSIGNED_BYTE, self.buffer
        )
        # Copy: render() must return memory the next render won't
        # overwrite — the zero-copy publish path (DataPublisher
        # copy=False) queues frames by reference, and the modern
        # texture_color path returns fresh memory per call.
        return self.buffer.reshape(-1).copy()
