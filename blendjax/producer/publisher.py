"""Producer data publisher.

Reference: ``pkg_blender/blendtorch/btb/publisher.py:4-43``. Thin facade
over :class:`blendjax.transport.DataPublisherSocket` keeping the reference
call shape ``DataPublisher(bind_addr, btid).publish(**kwargs)`` while
defaulting to the zero-copy tensor codec instead of pickle.
"""

from __future__ import annotations

from blendjax import constants
from blendjax.transport import DataPublisherSocket
from blendjax.transport.wire import DEFAULT_COMPRESS_MIN_BYTES


class DataPublisher(DataPublisherSocket):
    def __init__(
        self,
        bind_addr: str,
        btid: int | None = None,
        send_hwm: int = constants.DEFAULT_SEND_HWM,
        lingerms: int = 0,
        codec: str = "tensor",
        copy: bool = False,
        compress_level: int = 0,
        compress_min_bytes: int = DEFAULT_COMPRESS_MIN_BYTES,
        compress_rle: bool = False,
        rle_cap: int | None = None,
        quantize_f16=(),
        lineage: bool = True,
        telemetry_every: int = 64,
        trace_every: int = 64,
        shm=None,
        shm_timeout_s: float = 5.0,
    ):
        # lineage/telemetry_every: publish-time stamps + the periodic
        # producer-metrics piggyback (docs/observability.md) — on by
        # default so every producer in a fleet shows up in the
        # consumer's staleness/gap/telemetry view without opting in.
        # trace_every: sampled distributed frame tracing (every Nth
        # message carries a `_trace` context downstream stages stamp in
        # place — docs/observability.md "Tracing a frame"; 0 disables).
        super().__init__(
            bind_addr,
            btid=btid,
            send_hwm=send_hwm,
            codec=codec,
            lingerms=lingerms,
            copy=copy,
            compress_level=compress_level,
            compress_min_bytes=compress_min_bytes,
            compress_rle=compress_rle,
            rle_cap=rle_cap,
            quantize_f16=quantize_f16,
            lineage=lineage,
            telemetry_every=telemetry_every,
            trace_every=trace_every,
            shm=shm,
            shm_timeout_s=shm_timeout_s,
        )
