"""Producer-side scenario applicator: poll, sample, apply, stamp.

Runs inside renderer processes (Blender's embedded Python or the
synthetic tier) with no jax dependency. The loop mirrors densityopt's
producer (reference ``supershape.blend.py:26-37`` polls the duplex
channel with ``timeoutms=0`` each frame):

1. :meth:`poll` drains the duplex channel; a ``scenario_space`` message
   replaces the local replica (latest version wins) and is acked with
   ``{"scenario_ack": version}``;
2. :meth:`sample` draws ``(scenario, params, theta)`` from the latest
   space with the producer's own seeded RNG and applies the params to
   the scene through the ``apply`` callable (for the built-in scenes,
   ``scene.apply_scenario``; Blender scripts pass their own);
3. :meth:`stamp` returns the ``_scenario`` message field — scenario id
   + the space version that produced the draw + the theta vector — so
   the consumer's exact per-scenario accounting and the curriculum's
   score-function update both ride the data stream with no extra
   socket.

``wait_for_space`` lets a producer hold publishing until the first
space arrives: the fleet-controller contract (a scaled-up newcomer's
FIRST counted frame already carries the current space version) depends
on it.
"""

from __future__ import annotations

import time

import numpy as np

from blendjax.scenario.accounting import SCENARIO_KEY
from blendjax.scenario.space import ScenarioSpace
from blendjax.utils.logging import get_logger

logger = get_logger("producer")


class ScenarioDraw:
    """One applied draw: what :meth:`ScenarioApplicator.stamp` encodes."""

    __slots__ = ("scenario", "version", "params", "theta")

    def __init__(self, scenario: str, version: int, params: dict, theta):
        self.scenario = scenario
        self.version = version
        self.params = params
        self.theta = theta

    def stamp(self) -> dict:
        s = {"id": self.scenario, "ver": int(self.version)}
        if self.theta:
            s["theta"] = [float(t) for t in self.theta]
        return s


class ScenarioApplicator:
    """Apply the consumer-published scenario space to a scene.

    - ``channel``: the producer's duplex channel
      (:class:`blendjax.producer.DuplexChannel`, bind side — or any
      object with ``recv(timeoutms)``/``send(**kwargs)``).
    - ``apply``: ``fn(params: dict) -> None`` mutating the scene (the
      built-in scenes expose ``apply_scenario``).
    - ``rng``: seed (or Generator) for scenario/param draws — seeded
      from the launcher's per-instance seed ladder so producer fleets
      decorrelate deterministically.
    """

    def __init__(self, channel, apply=None, rng=0):
        self.channel = channel
        sock = getattr(channel, "sock", None)
        if sock is not None:
            # bounded ack sends: a dead consumer leaves the PAIR peer
            # mute, and a default (timeout-less) send would BLOCK the
            # render loop forever — un-drainable even on SIGTERM. The
            # consumer-side service applies the same bound.
            import zmq

            sock.setsockopt(zmq.SNDTIMEO, 500)
        self.apply = apply
        self.rng = (
            rng if isinstance(rng, np.random.Generator)
            else np.random.default_rng(rng)
        )
        self.space: ScenarioSpace | None = None
        self.version = 0
        self.last_draw: ScenarioDraw | None = None
        self.received = 0

    # -- protocol --------------------------------------------------------------

    def poll(self, timeoutms: int = 0) -> bool:
        """Drain pending duplex messages; adopt (and ack) the newest
        space. Returns True when the space changed. Non-space control
        messages are ignored (the channel may be shared with other
        producer control traffic)."""
        changed = False
        while True:
            try:
                msg = self.channel.recv(timeoutms=timeoutms)
            except Exception:
                # a malformed (or pickle-bearing, under the channel's
                # allow_pickle=False) control message is refused, not
                # fatal — but return rather than retry: a PERSISTENT
                # recv error (closed socket, ETERM) that consumes no
                # message would spin this loop at 100% CPU forever;
                # the caller's next poll retries either way (the same
                # bounded-error escape as the service-side drain).
                logger.exception("malformed scenario control message")
                return changed
            timeoutms = 0  # only the first recv may block
            if msg is None:
                return changed
            wire = msg.get("scenario_space")
            if wire is None:
                continue
            try:
                space = ScenarioSpace.from_wire(wire)
            except Exception:
                logger.exception("malformed scenario space; ignoring")
                continue
            self.received += 1
            # latest version wins; a stale re-delivery is acked anyway
            # (the consumer tracks the HIGHEST acked version)
            if self.space is None or space.version >= self.version:
                self.space = space
                self.version = space.version
                changed = True
            try:
                self.channel.send(scenario_ack=int(space.version))
            except Exception:
                # mute peer (consumer gone, pipe full past the send
                # timeout): the space was still adopted — rendering
                # continues; the consumer's wait_acked sees the gap
                logger.exception("scenario ack send failed")

    def wait_for_space(self, timeout_s: float = 15.0) -> bool:
        """Block (polling) until the first space arrives — the
        'current version before the first frame' guarantee. Returns
        False on timeout (callers degrade to unstamped publishing)."""
        deadline = time.monotonic() + timeout_s
        while self.space is None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            self.poll(timeoutms=int(min(remaining, 0.25) * 1000))
        return True

    # -- sampling --------------------------------------------------------------

    def sample(self) -> ScenarioDraw | None:
        """Draw one scenario + params from the latest space, apply it
        to the scene, and remember the draw for :meth:`stamp`. None
        while no space has arrived."""
        if self.space is None:
            return None
        name, params, theta = self.space.sample(self.rng)
        if self.apply is not None:
            self.apply(params)
        self.last_draw = ScenarioDraw(name, self.version, params, theta)
        return self.last_draw

    def next_scenario(self) -> dict:
        """Per-batch convenience: poll, sample+apply, and return the
        message fields to merge into the publish — ``{}`` while no
        space is held, ``{"_scenario": {...}}`` after."""
        self.poll()
        draw = self.sample()
        if draw is None:
            return {}
        return {SCENARIO_KEY: draw.stamp()}

    def close(self) -> None:
        self.channel.close()


__all__ = ["ScenarioApplicator", "ScenarioDraw"]
