"""Packaged producer scripts for registry-made environments.

The Gymnasium registry (:mod:`blendjax.env.registry`) needs producer
scripts that exist wherever blendjax is installed — not only in an
examples checkout — so the built-in environments live here, under
:mod:`blendjax.producer` (NOT :mod:`blendjax.env`): producer processes
import this package, and the env package's import-time Gymnasium
registration must never ride along into every spawned producer. Each
module is both importable (tests reuse the env classes) and runnable as
a launcher script (the launcher spawns the file path directly with the
package root on ``PYTHONPATH``).
"""
