"""Producer script: a remote-controlled cartpole environment.

Headless counterpart of the reference's ``examples/control/
cartpole.blend.py`` (physics cartpole whose motor velocity is the remote
action, ``cartpole.blend.py:38-43``): physics run in
:class:`blendjax.producer.sim.CartpoleScene`, the episode/RPC machinery is
the standard BaseEnv + RemoteControlledAgent pair.

Packaged (rather than examples-only) so the Gymnasium registry entry
``blendjax/Cartpole-v0`` resolves on any install
(:mod:`blendjax.env.registry`, which launches this file directly); the
reference kept its equivalent inside
``examples/control/cartpole_gym/envs/``.

Flags: ``--real-time`` switches the agent to free-running mode;
``--render-every N`` attaches the scene renderer for rgb_array frames.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from blendjax.transport import term_context
from blendjax.producer import BaseEnv, RemoteControlledAgent, parse_launch_args
from blendjax.producer.sim import CartpoleScene, SimEngine


class CartpoleEnv(BaseEnv):
    def __init__(self, agent, scene: CartpoleScene):
        super().__init__(agent)
        self.scene = scene

    def _env_reset(self):
        self.scene.reset()

    def _env_prepare_step(self, action):
        self.scene.apply_motor(float(np.asarray(action).reshape(())))

    def _env_post_step(self):
        x, x_dot, th, th_dot = self.scene.state
        done = bool(abs(th) > 0.4 or abs(x) > 3.0)
        return {
            "obs": self.scene.observation_vector(),
            "reward": 0.0 if done else 1.0,
            "done": done,
        }

    def _default_renderer(self):
        return self.scene.render


def main() -> None:
    args, remainder = parse_launch_args(sys.argv)
    ap = argparse.ArgumentParser()
    ap.add_argument("--real-time", action="store_true", default=False)
    ap.add_argument("--no-real-time", dest="real_time", action="store_false")
    ap.add_argument("--render-every", type=int, default=0)
    opts = ap.parse_args(remainder)

    scene = CartpoleScene(seed=args.btseed)
    agent = RemoteControlledAgent(
        args.btsockets["GYM"], real_time=opts.real_time
    )
    env = CartpoleEnv(agent, scene)
    if opts.render_every > 0:
        env.attach_default_renderer(every_nth=opts.render_every)
    try:
        env.run(SimEngine(scene))
    finally:
        agent.close()
        term_context()


if __name__ == "__main__":
    main()
