"""Minimal slot/callback dispatcher.

Reference: ``pkg_blender/blendtorch/btb/signal.py:3-54`` — ``add`` with
partial argument binding, ``remove``, ``invoke``. Used by the animation
controller to expose lifecycle events.
"""

from __future__ import annotations

import functools


class Signal:
    """An observable event: handlers are invoked in registration order."""

    def __init__(self):
        self._slots: list = []

    def add(self, fn, *args, **kwargs):
        """Register ``fn``; extra args are partially bound (reference
        ``signal.py:20-37``). Returns the registered handle for removal."""
        handle = functools.partial(fn, *args, **kwargs) if args or kwargs else fn
        self._slots.append(handle)
        return handle

    def remove(self, handle) -> None:
        self._slots.remove(handle)

    def clear(self) -> None:
        self._slots.clear()

    def invoke(self, *args, **kwargs) -> None:
        for slot in list(self._slots):
            slot(*args, **kwargs)

    def __len__(self) -> int:
        return len(self._slots)
