"""Headless simulation engine: a Blender stand-in for tests/benchmarks.

The reference can only produce data through a real Blender process, which
makes its whole test suite Blender-bound (SURVEY.md §4). blendjax ships
this small software renderer + physics so the full stack — launcher,
transport, ingest, training, RL — exercises hermetically, and so the
benchmark producer is CPU-cheap enough to saturate the TPU ingest path.

Scenes mirror the reference examples:

- :class:`CubeScene` — the benchmark scene (``benchmarks/benchmark.py``,
  ``examples/datagen/cube.blend.py``): one rotating colored cube, publishes
  ``image`` + corner-pixel annotations ``xy``.
- :class:`FallingCubesScene` — ``examples/datagen/falling_cubes.blend.py``:
  N cubes under gravity with ground bounce.
- :class:`SupershapeScene` — ``examples/densityopt/supershape.blend.py``:
  a 2D supershape (superformula) whose parameters arrive over the duplex
  channel.
- :class:`CartpoleScene` — ``examples/control/cartpole.blend.py``: cart +
  pole dynamics with a motor action, for the RL env layer.
"""

from __future__ import annotations

import numpy as np

from blendjax.producer.animation import Engine
from blendjax.producer.camera import Camera

# ---------------------------------------------------------------------------
# Rasterizer
# ---------------------------------------------------------------------------

_CUBE_FACES = np.array(
    [  # quads as vertex indices into the (-1,+1)^3 corner ordering of
        # producer.utils.cube_vertices (x-major): 0:(---) 1:(--+) 2:(-+-)
        # 3:(-++) 4:(+--) 5:(+-+) 6:(++-) 7:(+++)
        [0, 1, 3, 2],  # -x
        [4, 6, 7, 5],  # +x
        [0, 4, 5, 1],  # -y
        [2, 3, 7, 6],  # +y
        [0, 2, 6, 4],  # -z
        [1, 5, 7, 3],  # +z
    ]
)


# Each face quad (a, b, c, d) splits into triangles (a, b, c), (a, c, d);
# precomputed as one (12, 3) vertex-index table so cube_triangles is a
# single fancy-index instead of a Python loop building nested lists
# (this runs per frame in the producer hot loop).
_CUBE_TRI_IDX = np.array(
    [
        idx
        for quad in _CUBE_FACES
        for idx in ([quad[0], quad[1], quad[2]], [quad[0], quad[2], quad[3]])
    ]
)
_CUBE_TRI_FACE = np.repeat(np.arange(len(_CUBE_FACES)), 2)


def cube_triangles(center, half_extent: float, rotation=None):
    """World-space triangles (12,3,3) + face index per triangle (12,)."""
    from blendjax.producer.utils import cube_vertices

    verts = cube_vertices((0, 0, 0), half_extent)
    if rotation is not None:
        verts = verts @ np.asarray(rotation, np.float64).T
    verts = verts + np.asarray(center, np.float64)
    return verts[_CUBE_TRI_IDX], _CUBE_TRI_FACE.copy()


def rotation_xyz(rx: float, ry: float, rz: float) -> np.ndarray:
    cx, sx = np.cos(rx), np.sin(rx)
    cy, sy = np.cos(ry), np.sin(ry)
    cz, sz = np.cos(rz), np.sin(rz)
    mx = np.array([[1, 0, 0], [0, cx, -sx], [0, sx, cx]])
    my = np.array([[cy, 0, sy], [0, 1, 0], [-sy, 0, cy]])
    mz = np.array([[cz, -sz, 0], [sz, cz, 0], [0, 0, 1]])
    return mz @ my @ mx


class Rasterizer:
    """Tiny z-buffered flat-shaded triangle rasterizer.

    The whole frame renders in ONE C++ call when the native accelerator
    builds (``blendjax/_native/rasterizer.cpp`` ``bjx_render_frame``:
    projection, flat shading, near culling, dirty-rect clear, span-
    solved fill — the producer-side hot loop); the numpy/Python
    orchestration below is the always-available fallback with identical
    output.
    """

    def __init__(self, shape=(480, 640), background=(0, 0, 0, 255)):
        self.shape = (int(shape[0]), int(shape[1]))
        self.background = np.ascontiguousarray(background, np.uint8)
        h, w = self.shape
        self._color = np.empty((h, w, 4), np.uint8)
        self._depth = np.empty((h, w), np.float32)
        self._light = np.array([0.4, -0.35, 0.85])
        self._light = self._light / np.linalg.norm(self._light)
        # Dirty-rect state: the target buffer of the last render and the
        # pixel rect it drew (y0, y1, x0, x1). When the next render hits
        # the same buffer, only union(last drawn, new geometry bbox) needs
        # clearing — everything else is still background by induction.
        # The buffer reference is held (compared with ``is``): comparing
        # id() of a temporary view would false-match a freed view whose
        # address got reused, skipping a needed full clear.
        self._prev_target: np.ndarray | None = None
        self.last_drawn: tuple | None = None
        from blendjax._native import load_render_frame

        # One-call frame path: projection + shading + cull + clear + fill
        # in a single FFI crossing (the numpy glue for a 12-triangle
        # scene costs as much as the fill itself on 1-core hosts). The
        # fallback when the toolchain is absent is the pure numpy/Python
        # orchestration below — same math, identical output.
        self._native_frame = load_render_frame()
        self._rect_prev = np.empty(4, np.int64)
        self._rect_out = np.empty(4, np.int64)

    def render(self, camera: Camera, triangles, colors, out=None) -> np.ndarray:
        """Render world-space ``triangles`` (N,3,3) filled with ``colors``
        (N,3|4 uint8); returns HxWx4 uint8 (origin upper-left, like the
        reference's flipped GL readback, ``offscreen.py:95-96``).

        With ``out`` (contiguous HxWx4 uint8, e.g. a slot of a batch
        buffer) pixels are written there directly and no copy is made —
        the zero-copy path for batched producers.

        Re-rendering into the same buffer uses dirty-rect clears, which
        assume the buffer was not mutated by anyone else in between. If
        external code wrote into it, call :meth:`invalidate` first to
        force the next render to repaint fully."""
        h, w = self.shape
        if out is None:
            target = self._color
        else:
            target = out
            # Raise (not assert): the raw pointer goes to native code, so
            # the check must survive ``python -O``.
            if not (
                target.shape == (h, w, 4)
                and target.dtype == np.uint8
                and target.flags.c_contiguous
            ):
                raise ValueError(
                    f"out must be contiguous ({h}, {w}, 4) uint8; got "
                    f"shape={target.shape} dtype={target.dtype} "
                    f"contiguous={target.flags.c_contiguous}"
                )
        triangles = np.asarray(triangles, np.float64)
        # The one-call native path is an exact twin of the Python
        # orchestration ONLY under its preconditions: the camera's pixel
        # mapping matches the framebuffer, colors are uint8 (shading
        # truncation order is observable for floats), and one color row
        # per triangle (C++ cannot bounds-check the caller's buffer).
        # Anything else takes the Python path — identical output where
        # both are defined, loud IndexError where the input is wrong.
        if self._native_frame is not None and camera.shape == self.shape:
            cv = np.asarray(colors) if triangles.size else None
            if triangles.size == 0 or (
                cv.dtype == np.uint8
                and cv.ndim == 2
                and cv.shape[1] in (3, 4)
                and len(cv) == len(triangles)
            ):
                return self._render_frame_native(
                    camera, triangles, cv, target, out
                )
        if triangles.size == 0:
            px = depth = colors_v = shade_v = None
            bbox = None
        else:
            colors = np.asarray(colors)
            if colors.shape[1] == 3:
                colors = np.concatenate(
                    [colors, np.full((len(colors), 1), 255, colors.dtype)],
                    axis=1,
                )
            flat = triangles.reshape(-1, 3)
            px, depth = camera.world_to_pixel(
                flat, origin="upper-left", return_depth=True
            )
            px = px.reshape(-1, 3, 2)
            depth = depth.reshape(-1, 3)

            # Flat shading from world-space normals.
            e1 = triangles[:, 1] - triangles[:, 0]
            e2 = triangles[:, 2] - triangles[:, 0]
            n = np.cross(e1, e2)
            nn = np.linalg.norm(n, axis=1, keepdims=True)
            n = np.divide(n, nn, out=np.zeros_like(n), where=nn > 1e-12)
            shade = 0.35 + 0.65 * np.abs(n @ self._light)

            visible = ~np.any(depth <= camera.clip_near, axis=1)
            px, depth = px[visible], depth[visible]
            colors_v, shade_v = colors[visible], shade[visible]
            if len(px):
                y0 = max(int(np.floor(px[:, :, 1].min())), 0)
                y1 = min(int(np.ceil(px[:, :, 1].max())) + 1, h)
                x0 = max(int(np.floor(px[:, :, 0].min())), 0)
                x1 = min(int(np.ceil(px[:, :, 0].max())) + 1, w)
                bbox = (y0, y1, x0, x1) if y0 < y1 and x0 < x1 else None
            else:
                bbox = None

        self._clear(target, bbox)

        if px is not None and len(px):
            for i in range(len(px)):
                self._fill(target, px[i], depth[i], colors_v[i], shade_v[i])
        self._prev_target = target
        self.last_drawn = bbox
        return target.copy() if out is None else target

    def _render_frame_native(self, camera, triangles, colors, target, out):
        """One-FFI-call render: the C++ side projects, shades, culls,
        clears (dirty-rect) and fills — identical output to the numpy
        orchestration below (same math, same rounding contract)."""
        h, w = self.shape
        n = len(triangles)
        if colors is None:
            colors = np.empty((0, 4), np.uint8)
        if colors.shape[-1] == 3:
            colors = np.concatenate(
                [colors, np.full((n, 1), 255, colors.dtype)], axis=1
            )
        colors = np.ascontiguousarray(colors)
        tri = np.ascontiguousarray(triangles)
        view, proj = camera._matrices()
        if self._prev_target is target:
            if self.last_drawn is None:
                self._rect_prev[0] = -1
            else:
                self._rect_prev[:] = self.last_drawn
        else:
            self._rect_prev[0] = -2
        # Addresses read per call: `background` is a public attribute a
        # caller may reassign, and a cached pointer would dangle on the
        # freed old array (the .ctypes.data reads are noise next to the
        # FFI call itself).
        self._native_frame(
            tri.ctypes.data, colors.ctypes.data, n,
            self._light.ctypes.data, view.ctypes.data, proj.ctypes.data,
            float(camera.clip_near),
            target.ctypes.data, self._depth.ctypes.data, h, w,
            self.background.ctypes.data, self._rect_prev.ctypes.data,
            self._rect_out.ctypes.data,
        )
        self._prev_target = target
        self.last_drawn = (
            None if self._rect_out[0] < 0
            else tuple(int(v) for v in self._rect_out)
        )
        return target.copy() if out is None else target

    def invalidate(self) -> None:
        """Forget the dirty-rect state: the next render performs a full
        clear (call after mutating the last render target externally)."""
        self._prev_target = None
        self.last_drawn = None

    def _clear(self, target, new_bbox) -> None:
        """Restore background + z where needed before drawing.

        Same-buffer re-render only clears union(previously drawn rect,
        incoming geometry bbox) — the rest of the frame is untouched
        background by induction. Any other buffer gets the full clear.
        """
        rect = None
        if self._prev_target is target:
            rects = [r for r in (self.last_drawn, new_bbox) if r]
            if not rects:
                return  # nothing was drawn and nothing will be
            rect = (
                min(r[0] for r in rects), max(r[1] for r in rects),
                min(r[2] for r in rects), max(r[3] for r in rects),
            )
        if rect is not None:
            y0, y1, x0, x1 = rect
            target[y0:y1, x0:x1] = self.background
            self._depth[y0:y1, x0:x1] = np.inf
        else:
            target[:] = self.background
            self._depth[:] = np.inf

    def _fill(self, target, tri_px, tri_depth, color, shade):
        h, w = self.shape
        xmin = max(int(np.floor(tri_px[:, 0].min())), 0)
        xmax = min(int(np.ceil(tri_px[:, 0].max())) + 1, w)
        ymin = max(int(np.floor(tri_px[:, 1].min())), 0)
        ymax = min(int(np.ceil(tri_px[:, 1].max())) + 1, h)
        if xmin >= xmax or ymin >= ymax:
            return
        xs = np.arange(xmin, xmax) + 0.5
        ys = np.arange(ymin, ymax) + 0.5
        gx, gy = np.meshgrid(xs, ys)
        (x0, y0), (x1, y1), (x2, y2) = tri_px
        area = (x1 - x0) * (y2 - y0) - (x2 - x0) * (y1 - y0)
        if abs(area) < 1e-12:
            return
        w0 = ((x1 - gx) * (y2 - gy) - (x2 - gx) * (y1 - gy)) / area
        w1 = ((x2 - gx) * (y0 - gy) - (x0 - gx) * (y2 - gy)) / area
        w2 = 1.0 - w0 - w1
        inside = (w0 >= 0) & (w1 >= 0) & (w2 >= 0)
        if not inside.any():
            return
        # Screen-space affine depth interpolation (adequate for annotation
        # ground truth; not perspective-correct).
        z = (w0 * tri_depth[0] + w1 * tri_depth[1] + w2 * tri_depth[2]).astype(
            np.float32
        )
        zbuf = self._depth[ymin:ymax, xmin:xmax]
        cbuf = target[ymin:ymax, xmin:xmax]
        closer = inside & (z < zbuf)
        if not closer.any():
            return
        zbuf[closer] = z[closer]
        shaded = np.array(
            [*(np.asarray(color[:3], np.float64) * shade), color[3]]
        ).astype(np.uint8)
        cbuf[closer] = shaded


# ---------------------------------------------------------------------------
# Scenes
# ---------------------------------------------------------------------------


class SimScene:
    """Base: a camera, a rasterizer, and per-frame state."""

    def __init__(self, shape=(480, 640), seed: int = 0, camera: Camera = None):
        self.rng = np.random.default_rng(seed)
        self.camera = camera or Camera.look_at(
            eye=(6.0, -6.0, 4.0), target=(0, 0, 0), shape=shape
        )
        self.raster = Rasterizer(shape=shape)
        self.reset()

    def reset(self) -> None:  # rewind hook (AnimationController/Engine)
        pass

    def reseed(self, seed: int) -> None:
        """Replace the episode RNG — the landing point for a remote
        ``reset(seed=)`` (:meth:`blendjax.producer.env.BaseEnv
        ._env_seed`): two seeded resets start bit-identical episodes."""
        self.rng = np.random.default_rng(int(seed))

    def step(self, frame: int) -> None:
        """Advance physics/randomization to ``frame``."""
        raise NotImplementedError

    def render(self) -> np.ndarray:
        raise NotImplementedError

    def background_image(self) -> np.ndarray:
        """The scene with no dynamic geometry — the reference frame for
        tile-delta streaming (``blendjax.ops.tiles``). Scenes with static
        scenery should override to include it."""
        return self.raster.render(
            self.camera, np.zeros((0, 3, 3)), np.zeros((0, 4), np.uint8)
        )


class CubeScene(SimScene):
    """The benchmark scene: a unit cube, randomly rotated each frame.

    Mirrors ``examples/datagen/cube.blend.py:6-39`` (randomize rotation in
    ``pre_frame``, publish image + projected corner coords in
    ``post_frame``).
    """

    def __init__(self, shape=(480, 640), seed: int = 0, half_extent=1.0):
        self.half_extent = half_extent
        self.rotation = np.eye(3)
        self.color = np.array([200, 80, 40], np.uint8)
        # Domain-randomization hooks (blendjax.scenario): label noise in
        # pixels — the knob that makes a scenario irreducibly harder —
        # applied in observation()/observation_into().
        self.xy_jitter = 0.0
        super().__init__(shape=shape, seed=seed)
        # apply_scenario reverts unnamed known params to these — a
        # scenario draw is a complete description, never a delta on the
        # previous draw's state
        self._scenario_defaults = {
            "half_extent": float(half_extent),
            "background": self.raster.background.copy(),
        }

    def reset(self) -> None:
        self.rotation = np.eye(3)

    def apply_scenario(self, params: dict) -> None:
        """Apply one sampled scenario-parameter dict (the
        :class:`blendjax.producer.scenario.ScenarioApplicator` hook).
        Known params: ``half_extent`` (cube size), ``xy_jitter`` (label
        noise sigma, pixels; clamped >= 0), ``bg`` (background gray
        level 0-255). Unknown params are ignored — a space may carry
        params for scenes of several kinds.

        A draw describes the scene COMPLETELY for the known keys:
        params absent from this draw revert to their defaults. Without
        the revert, a scenario that doesn't name ``xy_jitter`` would
        silently keep the PREVIOUS scenario's noise — cross-scenario
        state leakage that flattens the per-scenario loss gap the
        curriculum feeds on (observed: both scenarios converged to the
        same loss and the weights wandered)."""
        self.half_extent = float(
            params.get("half_extent", self._scenario_defaults["half_extent"])
        )
        self.xy_jitter = max(0.0, float(params.get("xy_jitter", 0.0)))
        bg = params.get("bg")
        g = (
            self._scenario_defaults["background"] if bg is None
            else np.ascontiguousarray(
                [int(np.clip(float(bg), 0, 255))] * 3 + [255], np.uint8
            )
        )
        if not np.array_equal(g, self.raster.background):
            self.raster.background = g
            # dirty-rect clears assume a constant background: force a
            # full repaint so stale pixels of the old background die
            self.raster.invalidate()

    def step(self, frame: int) -> None:
        self.rotation = rotation_xyz(*self.rng.uniform(0, 2 * np.pi, size=3))
        self.color = self.rng.integers(40, 255, size=3).astype(np.uint8)

    def corners_world(self) -> np.ndarray:
        from blendjax.producer.utils import cube_vertices

        return cube_vertices((0, 0, 0), self.half_extent) @ self.rotation.T

    def render(self, out=None) -> np.ndarray:
        tris, faces = cube_triangles((0, 0, 0), self.half_extent, self.rotation)
        base = self.color.astype(np.float64)
        # slight per-face tint so orientation is visually distinct
        tint = 1.0 - 0.08 * (faces % 3)
        colors = np.clip(base[None, :] * tint[:, None], 0, 255).astype(np.uint8)
        return self.raster.render(self.camera, tris, colors, out=out)

    def _label_xy(self) -> np.ndarray:
        xy = self.camera.world_to_pixel(self.corners_world())
        if self.xy_jitter:
            # irreducible label noise: the scenario axis a curriculum
            # can detect purely from training loss
            xy = xy + self.rng.normal(0.0, self.xy_jitter, xy.shape)
        return xy

    def observation(self, frame: int) -> dict:
        img = self.render()
        return {
            "image": img,
            "xy": self._label_xy().astype(np.float32),
            "frameid": frame,
        }

    def observation_into(self, frame: int, buf: dict, i: int) -> None:
        """Write frame ``frame``'s observation into slot ``i`` of a batch
        buffer dict (``image`` (B,H,W,4) u8, ``xy`` (B,8,2) f32, ``frameid``
        (B,) i64) — the zero-copy path for batch-publishing producers."""
        self.render(out=buf["image"][i])
        buf["xy"][i] = self._label_xy()
        buf["frameid"][i] = frame


class FallingCubesScene(SimScene):
    """N cubes under gravity with ground bounce
    (``examples/datagen/falling_cubes.blend.py``)."""

    def __init__(self, shape=(480, 640), seed: int = 0, num_cubes: int = 8):
        self.num_cubes = num_cubes
        super().__init__(shape=shape, seed=seed)

    def reset(self) -> None:
        n = self.num_cubes
        self.pos = np.stack(
            [
                self.rng.uniform(-3, 3, n),
                self.rng.uniform(-3, 3, n),
                self.rng.uniform(4, 9, n),
            ],
            axis=1,
        )
        self.vel = np.zeros((n, 3))
        self.rot = self.rng.uniform(0, 2 * np.pi, (n, 3))
        self.rotvel = self.rng.uniform(-2, 2, (n, 3))
        self.colors = self.rng.integers(40, 255, (n, 3)).astype(np.uint8)
        self.half = 0.5

    def step(self, frame: int, dt: float = 1 / 25) -> None:
        g = np.array([0, 0, -9.81])
        self.vel += g * dt
        self.pos += self.vel * dt
        self.rot += self.rotvel * dt
        low = self.pos[:, 2] < self.half
        self.pos[low, 2] = self.half
        self.vel[low, 2] *= -0.5  # inelastic bounce

    def render(self) -> np.ndarray:
        all_tris, all_cols = [], []
        for i in range(self.num_cubes):
            tris, faces = cube_triangles(
                self.pos[i], self.half, rotation_xyz(*self.rot[i])
            )
            all_tris.append(tris)
            all_cols.append(np.repeat(self.colors[i][None], 12, axis=0))
        return self.raster.render(
            self.camera, np.concatenate(all_tris), np.concatenate(all_cols)
        )

    def observation(self, frame: int) -> dict:
        return {
            "image": self.render(),
            "xy": self.camera.world_to_pixel(self.pos).astype(np.float32),
            "frameid": frame,
        }


def supershape_radius(theta, m, n1, n2, n3, a=1.0, b=1.0):
    """Superformula (Gielis). Matches the reference's dependency
    ('supershape' pkg, ``examples/densityopt/supershape.blend.py``)."""
    t = np.abs(np.cos(m * theta / 4.0) / a) ** n2 + np.abs(
        np.sin(m * theta / 4.0) / b
    ) ** n3
    return t ** (-1.0 / n1)


class SupershapeScene(SimScene):
    """2D supershape silhouette; parameters are set over the duplex channel
    (``examples/densityopt``: TPU process optimizes sim params)."""

    def __init__(self, shape=(256, 256), seed: int = 0, segments: int = 72):
        self.segments = segments
        self.params = np.array([6.0, 1.0, 1.0, 1.0])  # m, n1, n2, n3
        self.shape_id = -1
        cam = Camera.look_at(
            eye=(0, 0, 8.0), target=(0, 0, 0), up=(0, 1, 0), shape=shape
        )
        super().__init__(shape=shape, seed=seed, camera=cam)

    def set_params(self, params, shape_id: int) -> None:
        self.params = np.asarray(params, np.float64)
        self.shape_id = int(shape_id)

    def step(self, frame: int) -> None:
        pass  # shape changes only via set_params

    def render(self) -> np.ndarray:
        theta = np.linspace(0, 2 * np.pi, self.segments, endpoint=False)
        r = supershape_radius(theta, *self.params)
        r = np.nan_to_num(r, nan=0.0, posinf=0.0) * 2.0
        pts = np.stack([r * np.cos(theta), r * np.sin(theta), np.zeros_like(r)], 1)
        center = np.zeros(3)
        tris = np.stack(
            [
                np.broadcast_to(center, (self.segments, 3)),
                pts,
                np.roll(pts, -1, axis=0),
            ],
            axis=1,
        )
        colors = np.repeat(
            np.array([[230, 230, 230]], np.uint8), self.segments, axis=0
        )
        return self.raster.render(self.camera, tris, colors)

    def observation(self, frame: int) -> dict:
        return {
            "image": self.render(),
            "shape_id": self.shape_id,
            "frameid": frame,
        }


class CartpoleScene(SimScene):
    """Cart-pole on a rail with a velocity-controlled motor
    (``examples/control/cartpole.blend.py:38-43`` constrains the cart with
    a motor whose target velocity is the action)."""

    GRAVITY = 9.81
    MASS_CART = 1.0
    MASS_POLE = 0.1
    POLE_LEN = 1.0  # half-length
    DT = 1 / 60

    def __init__(self, shape=(240, 320), seed: int = 0):
        cam = Camera.look_at(
            eye=(0, -8.0, 1.0), target=(0, 0, 1.0), shape=shape
        )
        super().__init__(shape=shape, seed=seed, camera=cam)

    def reset(self) -> None:
        # x, x_dot, theta (rad from upright), theta_dot
        self.state = self.rng.uniform(-0.05, 0.05, size=4)
        self.motor_velocity = 0.0

    def apply_motor(self, velocity: float) -> None:
        self.motor_velocity = float(np.clip(velocity, -5.0, 5.0))

    def step(self, frame: int) -> None:
        x, x_dot, th, th_dot = self.state
        # Velocity-servo cart (strong motor): cart accelerates toward the
        # commanded velocity; pole swings from cart acceleration + gravity.
        x_acc = 20.0 * (self.motor_velocity - x_dot)
        th_acc = (
            self.GRAVITY * np.sin(th) - x_acc * np.cos(th)
        ) / self.POLE_LEN
        dt = self.DT
        x_dot += x_acc * dt
        x += x_dot * dt
        th_dot += th_acc * dt
        th += th_dot * dt
        self.state = np.array([x, x_dot, th, th_dot])

    def observation_vector(self) -> np.ndarray:
        return self.state.astype(np.float32)

    def render(self) -> np.ndarray:
        x, _, th, _ = self.state
        cart_c = np.array([x, 0.0, 0.5])
        cart_tris, _ = cube_triangles(cart_c, 0.3)
        tip = cart_c + np.array([np.sin(th), 0.0, np.cos(th)]) * (
            2 * self.POLE_LEN
        )
        mid = (cart_c + tip) / 2
        d = tip - cart_c
        zaxis = d / (np.linalg.norm(d) + 1e-9)
        xaxis = np.cross([0, 1, 0], zaxis)
        xaxis /= np.linalg.norm(xaxis) + 1e-9
        yaxis = np.cross(zaxis, xaxis)
        rot = np.stack([xaxis, yaxis, zaxis], axis=1)
        pole_tris, _ = cube_triangles((0, 0, 0), 1.0, rotation=None)
        scale = np.diag([0.05, 0.05, np.linalg.norm(d) / 2])
        pole_tris = pole_tris @ (rot @ scale).T + mid
        tris = np.concatenate([cart_tris, pole_tris])
        colors = np.concatenate(
            [
                np.repeat(np.array([[80, 80, 220]], np.uint8), 12, axis=0),
                np.repeat(np.array([[220, 180, 40]], np.uint8), 12, axis=0),
            ]
        )
        return self.raster.render(self.camera, tris, colors)


# ---------------------------------------------------------------------------
# Engine adapter
# ---------------------------------------------------------------------------


class SimEngine(Engine):
    """Drive a :class:`SimScene` from an AnimationController (the headless
    counterpart of Blender's frame clock)."""

    def __init__(self, scene: SimScene):
        self.scene = scene

    def frame_set(self, frame: int) -> None:
        self.scene.step(frame)

    def reset(self) -> None:
        self.scene.reset()
