"""Producer-side sparse streaming: batch + tile-delta-encode + publish.

The producer half of the tile-delta path (``blendjax.ops.tiles``; the
consumer half is ``blendjax.data.TileStreamDecoder``). Feed it one frame
at a time; every ``batch_size`` frames it publishes one pre-batched
message carrying only the tiles that changed vs the reference image —
plus the reference itself, once, in the stream's first message (ZMQ PUSH
is FIFO per producer, so the ref always arrives first).

Wire-size behaviors, all transparent to the consumer:

- **Sticky capacity**: every distinct tile-count capacity is a new array
  shape, and each shape costs one jit compilation of the consumer's
  decode — so the capacity is a per-stream high-water mark (with ~30%
  initial headroom) that only grows on overflow.
- **Alpha slicing**: when every frame's alpha channel matches the
  reference's (verified per batch), only RGB crosses the wire and the
  consumer restores alpha from the reference — still bit-exact.
"""

from __future__ import annotations

import numpy as np

from blendjax.ops.tiles import (
    PALETTE_SUFFIX,
    TILE,
    TILEIDX_SUFFIX,
    TILEPAL_SUFFIXES,
    TILEREF_SUFFIX,
    TILES_SUFFIX,
    TILESHAPE_SUFFIX,
    TileDeltaEncoder,
    pack_batch,
    pack_palette_indices,
    palettize_tiles,
    tileshape_wire,
)


class TileBatchPublisher:
    """Accumulates frames and publishes tile-delta batch messages.

    ``publisher``: a :class:`blendjax.producer.DataPublisher` (owned by the
    caller; not closed here). ``ref``: the (H, W, C) uint8 reference image
    (typically ``scene.background_image()``). ``field``: the image field
    name the consumer will see after on-device reconstruction.

    ``alpha_slice=False`` keeps full RGBA tiles on the wire even when
    the alpha channel is static (~33% more bytes on the raw-tile wire).
    Since r4 channel-sliced tiles are ALSO Pallas-kernel-eligible (the
    consumer restores the missing channels from the reference on
    device), so the main reason to disable slicing is the fused
    scan+palettize producer path, which needs full-channel tiles and
    ships palette indices — making the channel count nearly free on
    the wire.

    ``ref_interval=N`` re-attaches the reference image every N batches
    (video-keyframe style). With a single consumer the one-shot default
    suffices (PUSH is FIFO per producer), but fair fan-in across several
    consumers/workers delivers the one ref to only one of them — a
    keyframe interval lets the others sync (they skip tile batches until
    a ref arrives) at ~``ref_bytes / N`` amortized overhead.

    ``palette=True`` (default) palette-compresses tile payloads when
    changed tiles hold few distinct colors (flat-shaded frames usually
    do): <=4 colors ship as 2-bit indices (16x fewer bytes), <=16 as
    4-bit (8x), <=256 as bytes (4x); more falls back to raw tiles. Lossless either way — the
    consumer's decode gathers through the palette on device. With
    full-channel tiles (``alpha_slice=False``) and the native helpers
    available, palettization FUSES into the changed-tile scan (one
    pass, no raw-tile materialization) with PER-FRAME color tables:
    each row of the batch ships its own palette (the wire carries a
    ``(B, cap, C)`` palette array), so a single frame's color count —
    not the whole batch's — picks the index width; a >256-color frame
    falls back to raw tiles transparently.

    ``capacity`` pins the per-frame tile capacity from the first batch
    (it still grows on overflow). Every distinct capacity is a distinct
    wire/array shape — one consumer decode compilation, and a chunk-group
    boundary — so a fleet of producers streaming the same scene should
    share an explicit capacity rather than each settling its own
    high-water mark.
    """

    def __init__(self, publisher, ref: np.ndarray, batch_size: int,
                 tile=TILE, field: str = "image",
                 alpha_slice: bool = True, ref_interval: int = 0,
                 palette: bool = True, capacity: int | None = None):
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self.publisher = publisher
        self.batch_size = int(batch_size)
        self.field = field
        self.alpha_slice = bool(alpha_slice)
        self.ref_interval = max(0, int(ref_interval))
        self.palette = bool(palette)
        self._palette_misses = 0  # latch: stop paying the scan if futile
        self.encoder = TileDeltaEncoder(ref, tile=tile)
        # tile pixel dims: int side, or (th, tw) — rectangular (16, 32)
        # tiles at C=4 unlock the consumer's direct-spatial decode
        self.th, self.tw = self.encoder.th, self.encoder.tw
        self._ref = self.encoder.ref
        if self._ref.shape[2] == 4:
            # Tiled view of the reference's alpha plane, indexed by flat
            # tile id — the alpha-static check then touches only the
            # tiles each frame actually changed.
            gh, gw = self.encoder.grid
            th, tw = self.th, self.tw
            self._ref_tile_alpha = np.ascontiguousarray(
                self._ref[:, :, 3]
                .reshape(gh, th, gw, tw)
                .transpose(0, 2, 1, 3)
                .reshape(gh * gw, th, tw)
            )
        else:
            self._ref_tile_alpha = None
        self._deltas: list = []
        self._extras: dict = {}
        self._alpha_static = True
        self._ref_sent = False
        self._capacity: int | None = (
            min(int(capacity), self.encoder.num_tiles)
            if capacity else None
        )
        self.batches_published = 0
        # Direct-pack fast path: once the capacity is fixed, frames
        # encode straight into these (B, cap, ...) batch arrays — one
        # copy per frame (staging -> row) instead of the buffered path's
        # two plus two allocations. The arrays never leave the process
        # (publish ships palette-packed or copied views), so one set is
        # safe to reuse across batches even with zero-copy sends.
        self._batch_idx: np.ndarray | None = None
        self._batch_tiles: np.ndarray | None = None
        self._row = 0
        # Fused scan+palettize (encoder.encode_palidx, native): one pass
        # both finds changed tiles and emits PER-FRAME palette indices
        # (the table resets at each frame, so neither color drift across
        # a batch nor across an animation can exhaust it) — the separate
        # whole-batch palettize pass and the raw-tile materialization
        # disappear.
        # Engages when palettization is on and full-channel tiles stream
        # (alpha slicing needs raw tiles for its check); a >256-color
        # batch falls back to raw tiles, repeated fallbacks latch the
        # path off like the two-pass miss latch.
        self._fused_ok = (
            self.palette
            # alpha slicing is inert without an alpha plane: RGB streams
            # keep the fused path under the default alpha_slice=True
            and not (self.alpha_slice and self._ref_tile_alpha is not None)
            and self.encoder.palidx_available()
        )
        self._raw_batch = False  # this batch fell back to raw tiles
        self._batch_pal: np.ndarray | None = None
        # per-row palette snapshots (fused path): colors + counts per
        # frame of the current batch
        self._row_pals: list = [None] * self.batch_size
        self._row_counts: list = [0] * self.batch_size

    def add(self, image: np.ndarray, hint=None, **extras) -> None:
        """Add one frame plus its per-frame sidecar fields (annotations,
        frame ids, ...); publishes automatically when the batch fills.
        ``hint`` optionally bounds the changed-tile scan to a pixel rect
        (see :meth:`TileDeltaEncoder.encode`)."""
        if (
            self._fused_ok
            and not self._raw_batch
            and self._capacity is not None
        ):
            # PER-FRAME palette: each frame indexes its own fresh table,
            # so a single frame's color count (not the whole batch's)
            # decides 4-bit vs 8-bit packing — flat-shaded scenes whose
            # batches drift past 16 colors still ship nibbles (halves
            # the dominant wire term). The per-row palettes ride the
            # wire as one (B, cap, C) array.
            self.encoder.reset_palette()
            out = self.encoder.encode_palidx(image, hint=hint)
            if out is not None:
                fi, fpal = out
                k = len(fi)
                if k > self._capacity:
                    self._grow(k)
                self._ensure_batch_arrays()
                i = self._row
                self._batch_idx[i, :k] = fi
                self._batch_idx[i, k:] = self.encoder.num_tiles
                self._batch_pal[i, :k] = fpal
                self._batch_pal[i, k:] = 0
                self._row_counts[i] = self.encoder.palette_count
                self._row_pals[i] = self.encoder.palette[
                    : self.encoder.palette_count
                ].copy()
                self._row += 1
                for key, v in extras.items():
                    self._extras.setdefault(key, []).append(v)
                if self._row == self.batch_size:
                    self._publish()
                return
            # >256 colors in this batch: reconstruct raw tiles for the
            # rows already packed and finish the batch raw (batch-level
            # palettize may still engage at publish). Repeated overflows
            # latch the fused path off like the two-pass miss latch.
            self._raw_batch = True
            self._palette_misses += 1
            if self._palette_misses >= 8:
                self._fused_ok = False
            self._depalettize_rows()
        fi, ft = self.encoder.encode(image, hint=hint)
        if self._ref_tile_alpha is not None and self._alpha_static:
            # Unchanged tiles are byte-identical to the ref by definition,
            # so whole-frame alpha equality reduces to the changed tiles.
            self._alpha_static = np.array_equal(
                ft[..., 3], self._ref_tile_alpha[fi]
            )
        if self._capacity is not None:
            k = len(fi)
            if k > self._capacity:
                self._grow(k)
            self._ensure_batch_arrays()
            i = self._row
            self._batch_idx[i, :k] = fi
            self._batch_idx[i, k:] = self.encoder.num_tiles  # sentinel
            self._batch_tiles[i, :k] = ft
            self._batch_tiles[i, k:] = 0
            self._row += 1
        else:
            # No pinned capacity yet: buffer the first batch's deltas,
            # _publish fixes the sticky capacity, and every later frame
            # takes the direct path above.
            self._deltas.append((fi.copy(), ft.copy()))
        for key, v in extras.items():
            self._extras.setdefault(key, []).append(v)
        if self._row + len(self._deltas) == self.batch_size:
            self._publish()

    def _ensure_batch_arrays(self) -> None:
        if self._batch_idx is None:
            c = self._ref.shape[2]
            self._batch_idx = np.empty(
                (self.batch_size, self._capacity), np.int32
            )
            self._batch_tiles = np.empty(
                (self.batch_size, self._capacity, self.th, self.tw, c),
                np.uint8,
            )
        if self._fused_ok and self._batch_pal is None:
            self._batch_pal = np.empty(
                (self.batch_size, self._capacity, self.th * self.tw),
                np.uint8,
            )

    def _grow(self, kmax: int) -> None:
        """Overflow: widen the sticky capacity (32-tile steps) and
        migrate any rows already packed this batch."""
        new_cap = min(-(-kmax // 32) * 32, self.encoder.num_tiles)
        old_idx, old_tiles, n = self._batch_idx, self._batch_tiles, self._row
        old_pal = self._batch_pal
        self._capacity = new_cap
        self._batch_idx = None
        self._batch_pal = None
        self._ensure_batch_arrays()
        if n and old_idx is not None:
            self._batch_idx[:n, : old_idx.shape[1]] = old_idx[:n]
            self._batch_idx[:n, old_idx.shape[1]:] = self.encoder.num_tiles
            self._batch_tiles[:n, : old_tiles.shape[1]] = old_tiles[:n]
            self._batch_tiles[:n, old_tiles.shape[1]:] = 0
            if old_pal is not None and self._batch_pal is not None:
                self._batch_pal[:n, : old_pal.shape[1]] = old_pal[:n]
                self._batch_pal[:n, old_pal.shape[1]:] = 0

    def _depalettize_rows(self) -> None:
        """Fused -> raw fallback mid-batch: reconstruct raw tiles for the
        rows already packed as palette indices (lossless gather). Each
        row gathers through ITS OWN per-frame palette snapshot."""
        n = self._row
        if not n or self._batch_pal is None:
            return
        self._ensure_batch_arrays()
        c = self._ref.shape[2]
        for i in range(n):
            colors = np.zeros((256, c), np.uint8)
            rp = self._row_pals[i]
            if rp is not None:
                colors[: len(rp)] = rp
            self._batch_tiles[i] = colors[self._batch_pal[i]].reshape(
                self._capacity, self.th, self.tw, c
            )
        # padding slots must ship zeroed tiles (pack contract), not
        # palette color 0
        pad = self._batch_idx[:n] == self.encoder.num_tiles
        self._batch_tiles[:n][pad] = 0

    def flush(self) -> None:
        """Publish any buffered partial batch (call when a finite stream
        ends so trailing frames aren't dropped; the consumer's ingest
        passes the ragged batch through)."""
        if self._deltas or self._row:
            self._publish()

    def _finish_publish(self, msg: dict) -> None:
        """Shared tail of both publish forms: sidecar extras, keyframe
        reference attachment, per-batch state reset, publish."""
        for k, vals in self._extras.items():
            msg[k] = np.stack([np.asarray(v) for v in vals])
        keyframe = (
            self.ref_interval > 0
            and self.batches_published % self.ref_interval == 0
        )
        if not self._ref_sent or keyframe:
            msg[self.field + TILEREF_SUFFIX] = self._ref
            self._ref_sent = True
        self._deltas.clear()
        self._extras = {}
        self._alpha_static = True
        self._row = 0
        self._raw_batch = False
        self._row_pals = [None] * self.batch_size
        self._row_counts = [0] * self.batch_size
        self.publisher.publish(**msg)
        self.batches_published += 1

    def _publish(self) -> None:
        if (
            self._fused_ok
            and not self._raw_batch
            and self._row
            and not self._deltas
        ):
            # Fused path: rows are already palette indices against the
            # encoder's per-batch table — no raw tiles ever materialized.
            n = self._row
            h, w, c = self._ref.shape
            idx = self._batch_idx[:n].copy()
            pal_idx = self._batch_pal[:n]
            # palette success resets the miss latch (matching the
            # two-pass path; an overflow-only latch would defeat it)
            self._palette_misses = 0
            # Per-frame palettes: the LARGEST row count picks the index
            # width for the whole batch (one wire shape), but each row
            # ships (and the consumer gathers through) its own colors.
            counts = self._row_counts[:n]
            cmax = max(counts) if counts else 0
            tt = self.th * self.tw
            if cmax <= 4 and tt % 4 == 0:
                # four 2-bit indices per byte (flat-shaded frames often
                # hold <=4 colors: background + a few faces)
                bits, cap_colors = 2, 4
            elif cmax <= 16 and tt % 2 == 0:
                bits, cap_colors = 4, 16
            else:
                bits, cap_colors = 8, 256
            suffix = TILEPAL_SUFFIXES[bits]
            # fresh allocation either way: pal_idx is a reused batch
            # array and publish hands buffers to the IO thread by ref
            packed = (
                pack_palette_indices(pal_idx, bits)
                if bits < 8 else pal_idx.copy()
            )
            # (B, cap, C), zero-padded past each row's count (the wire
            # contract; row tables are snapshots taken per frame)
            pal = np.zeros((n, cap_colors, c), np.uint8)
            for i in range(n):
                pal[i, : counts[i]] = self._row_pals[i]
            self._finish_publish({
                "_prebatched": True,
                self.field + TILEIDX_SUFFIX: idx,
                self.field + TILESHAPE_SUFFIX: tileshape_wire(
                    h, w, c, (self.th, self.tw)
                ),
                self.field + suffix: packed,
                self.field + PALETTE_SUFFIX: pal,
            })
            return
        if self._deltas:
            # First batch without a pinned capacity: fix the sticky
            # capacity BEFORE the pack so every message of the stream
            # (first included) shares one shape = one consumer decode
            # compilation; grow in 32-tile steps only on overflow.
            kmax = max((len(i) for i, _ in self._deltas), default=0)
            if self._capacity is None:
                kmax = max(int(kmax * 1.3), 1)
            if self._capacity is None or kmax > self._capacity:
                self._capacity = min(
                    -(-kmax // 32) * 32, self.encoder.num_tiles
                )
            idx, tiles = pack_batch(
                self._deltas, self.encoder.num_tiles,
                capacity=self._capacity,
            )
            fresh = True  # pack_batch allocated these; safe to ship
        else:
            n = self._row
            # idx is tiny (~KB): copy so the reused batch array never
            # rides a zero-copy send. tiles is copied below only on the
            # raw-wire path (the palette path ships fresh arrays).
            idx = self._batch_idx[:n].copy()
            tiles = self._batch_tiles[:n]
            fresh = False
        if (
            self.alpha_slice
            and self._alpha_static
            and self._ref_tile_alpha is not None
        ):
            tiles = np.ascontiguousarray(tiles[..., :3])
            fresh = True
        h, w, c = self._ref.shape
        msg = {
            "_prebatched": True,
            self.field + TILEIDX_SUFFIX: idx,
            self.field + TILESHAPE_SUFFIX: tileshape_wire(
                h, w, c, (self.th, self.tw)
            ),
        }
        compressed = palettize_tiles(tiles) if self.palette else None
        if compressed is not None:
            self._palette_misses = 0
            packed, pal, bits = compressed
            suffix = TILEPAL_SUFFIXES[bits]
            msg[self.field + suffix] = packed
            msg[self.field + PALETTE_SUFFIX] = pal
        else:
            if self.palette:
                # Color-rich scene: after enough consecutive misses stop
                # paying the palette scan on every batch.
                self._palette_misses += 1
                if self._palette_misses >= 8:
                    self.palette = False
            msg[self.field + TILES_SUFFIX] = tiles if fresh else tiles.copy()
        self._finish_publish(msg)
