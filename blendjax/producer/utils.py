"""Geometry and domain-randomization helpers.

Reference: ``pkg_blender/blendtorch/btb/utils.py``. The math helpers
(``hom``/``dehom`` ``utils.py:112-121``, spherical sampling
``utils.py:123-156``) are pure numpy here; the depsgraph-dependent scene
queries (``object_coordinates`` ``utils.py:30-109``, visibility ray-casts
``utils.py:158-179``, ``scene_stats`` ``utils.py:181-192``) live in
``bpy_engine.py`` because they are meaningless without Blender's evaluated
scene graph.
"""

from __future__ import annotations

import numpy as np


def hom(x: np.ndarray, value: float = 1.0) -> np.ndarray:
    """Append a homogeneous coordinate (reference ``utils.py:112-116``)."""
    x = np.asarray(x, dtype=np.float64)
    return np.concatenate(
        [x, np.full((*x.shape[:-1], 1), value, dtype=x.dtype)], axis=-1
    )


def dehom(x: np.ndarray) -> np.ndarray:
    """Divide out the homogeneous coordinate (reference ``utils.py:118-121``)."""
    x = np.asarray(x, dtype=np.float64)
    return x[..., :-1] / x[..., -1:]


def random_spherical_loc(
    radius_range=(6.0, 10.0),
    theta_range=(0.0, np.pi),
    phi_range=(0.0, 2 * np.pi),
    center=(0.0, 0.0, 0.0),
    rng: np.random.Generator | None = None,
) -> np.ndarray:
    """Uniform random location in a spherical shell around ``center`` —
    the reference's camera domain-randomization helper
    (``utils.py:123-156``; e.g. ``falling_cubes.blend.py``)."""
    rng = rng or np.random.default_rng()
    r = rng.uniform(*radius_range)
    # Uniform on the sphere segment: sample cos(theta) uniformly.
    ct0, ct1 = np.cos(theta_range[0]), np.cos(theta_range[1])
    theta = np.arccos(rng.uniform(min(ct0, ct1), max(ct0, ct1)))
    phi = rng.uniform(*phi_range)
    return np.asarray(center, dtype=np.float64) + r * np.array(
        [np.sin(theta) * np.cos(phi), np.sin(theta) * np.sin(phi), np.cos(theta)]
    )


def look_at_matrix(eye, target, up=(0.0, 0.0, 1.0)) -> np.ndarray:
    """World-from-camera rotation whose -Z axis points from ``eye`` to
    ``target`` (Blender camera convention: -Z forward, +Y up; reference
    ``camera.py:191-204`` implements the same via quaternion tracking)."""
    eye = np.asarray(eye, np.float64)
    target = np.asarray(target, np.float64)
    fwd = target - eye
    norm = np.linalg.norm(fwd)
    assert norm > 1e-12, "eye and target coincide"
    fwd = fwd / norm
    upv = np.asarray(up, np.float64)
    right = np.cross(fwd, upv)
    rnorm = np.linalg.norm(right)
    if rnorm < 1e-9:  # looking straight along up: pick any perpendicular
        upv = np.array([0.0, 1.0, 0.0]) if abs(fwd[2]) > 0.9 else np.array(
            [0.0, 0.0, 1.0]
        )
        right = np.cross(fwd, upv)
        rnorm = np.linalg.norm(right)
    right /= rnorm
    true_up = np.cross(right, fwd)
    # Columns: camera X (right), Y (up), Z (backward).
    return np.stack([right, true_up, -fwd], axis=1)


def cube_vertices(center, half_extent: float) -> np.ndarray:
    """The 8 corners of an axis-aligned cube (scene/label helper)."""
    c = np.asarray(center, np.float64)
    h = float(half_extent)
    corners = np.array(
        [[sx, sy, sz] for sx in (-h, h) for sy in (-h, h) for sz in (-h, h)]
    )
    return c + corners
