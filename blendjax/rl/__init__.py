"""blendjax.rl — device-resident trajectory replay and the mesh
actor-learner stack that trains the gym layer.

The last unopened workload from the paper's layer map (PAPER.md L4:
``env/vector.py``, ``RemoteEnv``, the cartpole scene) wired into the
machinery every prior PR built: transitions live in a donated sharded
device ring generalized from the echo reservoir
(:class:`TrajectoryReservoir`, uniform + prioritized sampling with
in-jit TD-error priority updates), background actors drive
fleet-admittable vector envs against a host-side policy snapshot
(:class:`ActorPool`), and the learner samples at full step rate
through ONE fused jit per step — gather + loss + donated update +
priority write-back (:func:`make_dqn_step` / :func:`make_pg_step`,
:class:`RLTrainDriver`). The fleet controller autoscales on the RL
verdict vocabulary (:func:`diagnose_rl`: env-bound vs learner-bound),
and the whole run checkpoints/resumes through the session store.

See docs/rl.md for the end-to-end anatomy; the ``live_rl`` bench row
trains cartpole end-to-end (local + 8-device CPU mesh + kill→resume)
with ``dispatch_per_step == 1.0`` CI-asserted.
"""

from blendjax.rl.actor import ActorPool, HostQPolicy, np_mlp_forward
from blendjax.rl.doctor import (
    RL_VERDICTS,
    diagnose_rl,
    diagnose_rl_current,
)
from blendjax.rl.learner import RLTrainDriver
from blendjax.rl.replay import TrajectoryReservoir
from blendjax.rl.steps import (
    RLTrainState,
    make_dqn_step,
    make_pg_step,
    make_rl_train_state,
    mesh_rl_step_kwargs,
)

__all__ = [
    "ActorPool",
    "HostQPolicy",
    "RLTrainDriver",
    "RLTrainState",
    "RL_VERDICTS",
    "TrajectoryReservoir",
    "diagnose_rl",
    "diagnose_rl_current",
    "make_dqn_step",
    "make_pg_step",
    "make_rl_train_state",
    "mesh_rl_step_kwargs",
    "np_mlp_forward",
]
