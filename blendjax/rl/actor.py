"""ActorPool: background env-stepping threads feeding the reservoir.

The decoupled actor-learner shape (IMPALA, Espeholt et al., 2018)
applied to the gym layer: a background thread drives a
:class:`~blendjax.env.vector.BatchedRemoteEnv` (local producers plus
any fleet-admitted remote envs) in lockstep, turns each vector step
into a batch of transitions, and inserts them into a
:class:`~blendjax.rl.replay.TrajectoryReservoir` without ever blocking
the learner — the reservoir insert is a donated device scatter under
the reservoir's lock, the same cost profile as the echo drain thread.

The hot-loop rule this module is the canonical citizen of (bjx-lint
**BJX115** ``host-materialization-in-actor-loop``): the actor step
loop touches NO device values. Action selection runs against a
**host-side policy snapshot** — a numpy pytree of params the learner
pushes via :meth:`update_policy` every ``sync_every`` learner steps
(the one sanctioned device fetch, on the LEARNER's thread at a
declared cadence) — evaluated by a pure-numpy policy such as
:class:`HostQPolicy`. A per-env-step jitted inference call would put a
device round trip plus a host materialization of its result inside
the tightest loop in the system; the snapshot pattern keeps actor
throughput at the env layer's native rendezvous rate (~5-6k steps/s
in the ``rl_hz`` probe) regardless of device contention.

Bootstrap correctness: auto-reset discards the terminal observation
from the stacked ``obs`` return, so the pool reads each done row's
``infos[i]["final_observation"]`` (the vector-env contract
``BatchedRemoteEnv`` implements) for ``next_obs`` — bootstrapped
targets never see the fresh episode's first observation as the old
episode's successor.

Metrics: counter ``rl.env_steps`` (vector rows stepped), histograms
``rl.episode_return`` / ``rl.episode_length``, gauge ``rl.epsilon``,
counter ``rl.policy_syncs``.
"""

from __future__ import annotations

# bjx: actor-hot-path (BJX115: no .item()/np.asarray/block_until_ready
# on policy or reservoir outputs inside the step loop — actions come
# from the host-side snapshot, accounting from host scalars)

import threading

import numpy as np

from blendjax.utils.logging import get_logger
from blendjax.utils.metrics import metrics
from blendjax.utils.tg import guard

logger = get_logger("rl")


def np_mlp_forward(params: dict, x: np.ndarray,
                   activation=None) -> np.ndarray:
    """Pure-numpy forward of a flax ``Dense`` stack (``Dense_0`` ..
    ``Dense_k``, relu between, linear head) — how the actor evaluates
    the learner's host-side param snapshot without a device dispatch.
    Works for :class:`blendjax.models.QNetwork` and any same-shaped
    MLP head."""
    act = activation if activation is not None else (
        lambda v: np.maximum(v, 0.0)
    )
    layers = sorted(
        (k for k in params if k.startswith("Dense_")),
        key=lambda k: int(k.split("_")[1]),
    )
    if not layers:
        raise ValueError(
            f"no Dense_* layers in snapshot (keys: {sorted(params)})"
        )
    x = np.asarray(x, np.float32)
    for i, name in enumerate(layers):
        layer = params[name]
        x = x @ np.asarray(layer["kernel"]) + np.asarray(layer["bias"])
        if i < len(layers) - 1:
            x = act(x)
    return x


class HostQPolicy:
    """Epsilon-greedy action selection over a host Q-network snapshot.

    ``epsilon`` anneals linearly from ``eps_start`` to ``eps_end`` over
    ``eps_steps`` policy calls; before the first snapshot arrives every
    action is uniform random (the warmup exploration phase). Returns
    int32 ACTION INDICES — map them onto env actions with the pool's
    ``action_map``."""

    def __init__(self, n_actions: int, eps_start: float = 1.0,
                 eps_end: float = 0.05, eps_steps: int = 2000,
                 seed: int = 0):
        self.n_actions = int(n_actions)
        self.eps_start = float(eps_start)
        self.eps_end = float(eps_end)
        self.eps_steps = max(1, int(eps_steps))
        self.calls = 0
        self._rng = np.random.default_rng(seed)

    @property
    def epsilon(self) -> float:
        frac = min(self.calls / self.eps_steps, 1.0)
        return self.eps_start + (self.eps_end - self.eps_start) * frac

    def __call__(self, snapshot, obs: np.ndarray) -> np.ndarray:
        n = obs.shape[0]
        eps = self.epsilon
        self.calls += 1
        metrics.gauge("rl.epsilon", round(eps, 4))
        random_a = self._rng.integers(0, self.n_actions, size=n)
        if snapshot is None:
            return random_a.astype(np.int32)
        q = np_mlp_forward(snapshot, obs)
        greedy = np.argmax(q, axis=-1)
        explore = self._rng.random(n) < eps
        return np.where(explore, random_a, greedy).astype(np.int32)

    def state_dict(self) -> dict:
        return {
            "calls": self.calls,
            "rng": self._rng.bit_generator.state,
        }

    def load_state_dict(self, d: dict) -> None:
        self.calls = int(d["calls"])
        self._rng.bit_generator.state = d["rng"]


class ActorPool:
    """Drive a vector env from a background thread into a reservoir.

    - ``env``: a ``BatchedRemoteEnv``-shaped vector env (``reset() ->
      (obs, infos)``, ``step(actions) -> (obs, reward, done, infos)``
      with auto-reset + ``final_observation`` infos).
    - ``reservoir``: the :class:`~blendjax.rl.replay
      .TrajectoryReservoir` transitions land in.
    - ``policy``: host callable ``fn(snapshot, obs (N, D)) -> actions``
      (e.g. :class:`HostQPolicy`). The snapshot is whatever the learner
      last pushed through :meth:`update_policy` (``None`` until then).
    - ``action_map``: optional per-index env-action lookup (a sequence
      or ``fn(indices) -> env_actions``) — the reservoir stores the
      policy's raw action indices, the env receives mapped actions
      (e.g. discrete index -> motor velocity for the cartpole DQN).
    - ``extra_fields``: optional ``fn(obs, actions, reward, done,
      infos) -> dict`` appended to each transition batch (bootstrap
      metadata beyond the standard five fields).

    Exact accounting: every vector row stepped increments
    ``rl.env_steps`` AND becomes exactly one inserted transition
    (``rl.transitions``), so ``env_steps == reservoir.inserts`` for a
    pool that owns its reservoir — the seq-style identity the bench
    asserts.
    """

    def __init__(self, env, reservoir, policy, action_map=None,
                 extra_fields=None, return_tail: int = 256):
        self.env = env
        # lock discipline, enforced at runtime under threadguard: every
        # reservoir touch from this pool happens inside `with
        # self.reservoir.lock:` (the insert+accounting cut); the lock
        # handle itself is exempt — it must be fetchable to acquire.
        self.reservoir = guard(
            reservoir, name="rl.reservoir", lock=reservoir.lock,
            exempt=("lock",),
        )
        self.policy = policy
        if action_map is not None and not callable(action_map):
            table = np.asarray(action_map)
            action_map = lambda idx: table[np.asarray(idx)]  # noqa: E731
        self.action_map = action_map
        self.extra_fields = extra_fields
        self.env_steps = 0
        self.episodes = 0
        self.policy_version = 0
        self.return_tail = int(return_tail)
        self.episode_returns: list = []  # (env_steps_at_done, return)
        self._snapshot = None
        self._ep_ret = None
        self._ep_len = None
        self._obs = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None

    # -- learner-side surface -------------------------------------------------

    def update_policy(self, snapshot) -> None:
        """Install a fresh host-side param snapshot (a numpy pytree —
        the learner calls ``jax.device_get`` on ITS thread at the
        ``sync_every`` cadence and hands the result here; reference
        swap only, no locks needed for the reader)."""
        # Deliberate lock-free publish: a single atomic reference
        # swap; the actor reads whole snapshots only.
        # bjx: ignore[BJX117] — atomic reference publish
        self._snapshot = snapshot
        # ...but the version counter is read-modify-write: share the
        # accounting cut's lock so stats() reads a consistent pair
        with self.reservoir.lock:
            self.policy_version += 1
        metrics.count("rl.policy_syncs")

    # -- the actor loop -------------------------------------------------------

    def _transition(self, obs, actions, nobs, reward, done, infos) -> dict:
        next_obs = np.asarray(nobs)
        if done.any():
            # auto-reset handed back the FRESH episode's first obs;
            # bootstrap targets need the terminal one the vector-env
            # contract parks in infos (satellite: final_observation)
            next_obs = next_obs.copy()
            for i in np.flatnonzero(done):
                fin = infos[i].get("final_observation")
                if fin is not None:
                    next_obs[i] = np.asarray(fin)
        out = {
            "obs": np.asarray(obs, np.float32),
            "action": np.asarray(actions),
            "reward": np.asarray(reward, np.float32),
            "done": np.asarray(done, bool),
            "next_obs": next_obs.astype(np.float32),
        }
        if self.extra_fields is not None:
            out.update(
                self.extra_fields(obs, actions, reward, done, infos)
            )
        return out

    def _account_episodes(self, reward, done) -> None:
        self._ep_ret += reward
        self._ep_len += 1
        for i in np.flatnonzero(done):
            ret = float(self._ep_ret[i])
            self.episodes += 1
            self.episode_returns.append((self.env_steps, ret))
            del self.episode_returns[: -self.return_tail]
            metrics.observe("rl.episode_return", ret)
            metrics.observe("rl.episode_length", int(self._ep_len[i]))
            self._ep_ret[i] = 0.0
            self._ep_len[i] = 0

    def _run(self) -> None:
        try:
            if self._obs is None:
                obs, _ = self.env.reset()
                self._obs = np.asarray(obs, np.float32)
                n = self._obs.shape[0]
                self._ep_ret = np.zeros(n, np.float64)
                self._ep_len = np.zeros(n, np.int64)
            while not self._stop.is_set():
                obs = self._obs
                actions = self.policy(self._snapshot, obs)
                env_actions = (
                    self.action_map(actions)
                    if self.action_map is not None else actions
                )
                nobs, reward, done, infos = self.env.step(env_actions)
                trans = self._transition(
                    obs, actions, nobs, reward, done, infos
                )
                # insert + counter/episode accounting as ONE cut under
                # the reservoir lock (reentrant — insert takes it too):
                # a checkpoint snapshotting reservoir-then-actor under
                # the same lock can never capture inserts and
                # env_steps mid-update, which would break the exact
                # env_steps == inserts identity forever after a resume
                with self.reservoir.lock:
                    self.reservoir.insert(trans)
                    self.env_steps += len(done)
                    self._account_episodes(reward, done)
                metrics.count("rl.env_steps", len(done))
                self._obs = np.asarray(nobs, np.float32)
        except BaseException as e:  # surfaced by the learner's check()
            if not self._stop.is_set():
                # Single-writer atomic reference publish; check()
                # only ever reads None -> exception.
                # bjx: ignore[BJX117] — atomic reference publish
                self._error = e
                logger.exception("actor loop died")

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "ActorPool":
        assert self._thread is None, "already started"
        # a restart after a transient death must come up healthy: a
        # stale error would make every check() re-raise forever
        self._error = None
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="blendjax-rl-actor", daemon=True
        )
        self._thread.start()
        return self

    def check(self) -> None:
        """Raise the actor thread's error into the caller (the learner
        polls this between steps — a dead actor must not starve the
        run silently)."""
        if self._error is not None:
            raise RuntimeError("actor loop died") from self._error

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "ActorPool":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- observability / session snapshot -------------------------------------

    @property
    def stats(self) -> dict:
        # Same critical section as the actor's insert+accounting cut:
        # an unlocked read here could pair a post-episode `episodes`
        # with a pre-episode `episode_returns` (BJX117).
        with self.reservoir.lock:
            recent = [r for _, r in self.episode_returns[-32:]]
            return {
                "env_steps": self.env_steps,
                "episodes": self.episodes,
                "policy_version": self.policy_version,
                "mean_return": (
                    round(float(np.mean(recent)), 3) if recent else None
                ),
            }

    def state_dict(self) -> dict:
        """Host counters + the reward-curve tail + the policy's
        exploration state, read under the reservoir lock so the cut is
        consistent with the actor's insert+accounting critical section
        (and with a reservoir snapshot taken under the same lock —
        :meth:`RLTrainDriver._session_state` holds it across both).
        Env processes restart fresh on resume (their episodes are
        transient by design — lineage reads producer restarts, not
        drops), so no env state is persisted."""
        with self.reservoir.lock:
            return self._state_dict_locked()

    def _state_dict_locked(self) -> dict:
        d = {
            "env_steps": self.env_steps,
            "episodes": self.episodes,
            "policy_version": self.policy_version,
            "episode_returns": [
                [int(s), float(r)] for s, r in self.episode_returns
            ],
        }
        pol_sd = getattr(self.policy, "state_dict", None)
        if pol_sd is not None:
            d["policy"] = pol_sd()
        return d

    def load_state_dict(self, d: dict) -> None:
        if self._thread is not None:
            raise RuntimeError(
                "load_state_dict must run before the actor starts"
            )
        # The actor thread can't be running (checked above), but the
        # restore still takes the accounting cut's lock so a concurrent
        # stats()/state_dict() reader sees old-or-new, never a mix.
        with self.reservoir.lock:
            self.env_steps = int(d["env_steps"])
            self.episodes = int(d["episodes"])
            self.policy_version = int(d.get("policy_version", 0))
            self.episode_returns = [
                (int(s), float(r)) for s, r in d.get("episode_returns", [])
            ]
        pol = d.get("policy")
        if pol is not None and hasattr(self.policy, "load_state_dict"):
            self.policy.load_state_dict(pol)


__all__ = ["ActorPool", "HostQPolicy", "np_mlp_forward"]
