"""RL bottleneck doctor: env-bound vs learner-bound, for the fleet loop.

The supervised pipeline's stall doctor (:mod:`blendjax.obs.doctor`)
discriminates producer- from step-bound regimes; the actor-learner
stack has its own two-sided failure vocabulary, decided by the two
signals the ISSUE names — **reservoir fill rate vs sample wait**:

===============  ==========================================================
verdict          evidence
===============  ==========================================================
env-bound        the learner waited on the reservoir for a meaningful
                 FRACTION of its draws (``rl.sample_waits`` relative to
                 ``rl.draws`` — a lifetime-counter comparison would read
                 env-bound forever off the single warmup wait every run
                 starts with) — actors can't produce transitions fast
                 enough. The fleet lever: admit or launch more env
                 producers (scale UP).
learner-bound    zero sample waits while actors insert faster than the
                 learner consumes (``rl.transitions`` outrunning
                 ``rl.fresh + rl.replayed`` by ``surplus``×) — fresh
                 transitions are overwritten before they're ever drawn.
                 The lever: fewer producers (scale DOWN) or a faster
                 learner step.
rl-balanced      neither side dominates — replay absorbs the rate gap,
                 which is what it's for.
rl-idle          no rl.* evidence yet.
===============  ==========================================================

Like the pipeline doctor, :func:`diagnose_rl` is pure over a plain
:meth:`Metrics.report` dict so tests drive every arm synchronously,
and it returns the same :class:`~blendjax.obs.doctor.Verdict` shape —
so a :class:`~blendjax.fleet.FleetController` built with
``diagnose=diagnose_rl_current`` and ``policy=FleetPolicy.rl()``
autoscales the env fleet on RL evidence with zero controller changes
(docs/rl.md has the verdict table).
"""

from __future__ import annotations

from blendjax.obs.doctor import Verdict
from blendjax.utils.metrics import metrics

#: Verdict kinds, in the order the decision procedure tests them.
RL_VERDICTS = ("env-bound", "learner-bound", "rl-balanced", "rl-idle")

#: Insert/draw surplus above which a wait-free run reads learner-bound:
#: actors producing this many times more transitions than the learner
#: consumes means fresh data dies undrawn in the ring.
DEFAULT_SURPLUS = 1.5

#: Fraction of learner draws that blocked on the reservoir above which
#: the run reads env-bound. Every run starts with one warmup wait at
#: min_fill, so the signal must DILUTE as healthy draws accumulate —
#: a bare ``waits > 0`` test would ratchet the fleet to max_instances
#: off that single wait and never let it scale back down.
DEFAULT_WAIT_FRACTION = 0.05


def diagnose_rl(report: dict, surplus: float = DEFAULT_SURPLUS,
                wait_fraction: float = DEFAULT_WAIT_FRACTION,
                min_evidence: int = 1) -> Verdict:
    """Classify one metrics snapshot of an actor-learner run."""
    counters = report.get("counters", {})
    spans = report.get("spans", {})
    inserted = int(counters.get("rl.transitions", 0))
    drawn = int(counters.get("rl.fresh", 0)) + int(
        counters.get("rl.replayed", 0)
    )
    draws = int(counters.get("rl.draws", 0))
    waits = int(counters.get("rl.sample_waits", 0))
    frac = waits / max(draws, 1)
    shares = {
        "inserted": inserted,
        "drawn": drawn,
        "draws": draws,
        "sample_waits": waits,
        "wait_fraction": round(frac, 4),
        "sample_wait_ms": round(
            spans.get("rl.sample_wait", {}).get("total_ms", 0.0), 1
        ),
    }
    if inserted < min_evidence and drawn < min_evidence:
        return Verdict(
            "rl-idle", "no rl.* transition or draw evidence yet",
            "start the actors/learner (or wait for warmup)", shares,
        )
    if waits > 0 and (frac >= wait_fraction or draws == 0):
        return Verdict(
            "env-bound",
            f"the learner blocked on the reservoir {waits}x "
            f"({frac:.1%} of {draws} draws, "
            f"{shares['sample_wait_ms']}ms total) — "
            f"{inserted} transitions inserted vs {drawn} drawn",
            "scale UP env producers (fleet) or raise actor throughput",
            shares,
        )
    if drawn and inserted > drawn * surplus:
        return Verdict(
            "learner-bound",
            f"actors inserted {inserted} transitions while the learner "
            f"drew {drawn} (> {surplus}x surplus, zero sample waits) — "
            "fresh transitions are overwritten before first use",
            "scale DOWN env producers or speed up the learner step",
            shares,
        )
    return Verdict(
        "rl-balanced",
        f"{inserted} inserted / {drawn} drawn with zero sample waits — "
        "replay absorbs the rate gap",
        "no action needed", shares,
    )


def diagnose_rl_current(**kwargs) -> Verdict:
    """:func:`diagnose_rl` over the process-wide metrics registry —
    the ``diagnose=`` hook a fleet controller takes."""
    return diagnose_rl(metrics.report(), **kwargs)


__all__ = [
    "DEFAULT_SURPLUS",
    "RL_VERDICTS",
    "diagnose_rl",
    "diagnose_rl_current",
]
