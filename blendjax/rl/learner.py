"""RLTrainDriver: the actor-learner loop as a MeshTrainDriver variant.

The consumer side of the decoupled actor-learner stack: actors
(:class:`~blendjax.rl.actor.ActorPool`) feed the
:class:`~blendjax.rl.replay.TrajectoryReservoir` from their own
thread while THIS driver samples at full step rate — every learner
step is one token draw (host index composition) plus one fused
dispatch (gather + loss + donated update + priority write-back, a
:mod:`blendjax.rl.steps` builder), riding the completion-tracked
dispatch ring, device-timeline metrics, and checkpoint plumbing the
supervised :class:`~blendjax.train.MeshTrainDriver` already proved.

What this subclass adds:

- **token discipline**: :meth:`train_step` holds the reservoir lock
  across ``compose -> draw_token -> submit`` so a concurrent actor
  insert can never donate the ring out from under an un-dispatched
  token (the echo pipeline gets this for free from its single-thread
  draw loop; the actor-learner split needs the lock).
- **policy sync**: every ``sync_every`` learner steps the actors get a
  fresh HOST-side param snapshot (``jax.device_get`` on the learner's
  thread, under the ``rl.policy_sync`` span — the one sanctioned
  device fetch of the loop, at a declared cadence; the actor loop
  itself stays device-free, the BJX115 contract).
- **sample-wait accounting**: when the reservoir can't yet supply a
  batch the learner blocks under the ``rl.sample_wait`` span and
  counts ``rl.sample_waits`` — one half of the env-bound vs
  learner-bound verdict (:func:`blendjax.rl.doctor.diagnose_rl`) the
  fleet controller autoscales on.
- **session state**: the default checkpoint session bundles the
  reservoir, the actor pool, and the driver counters, so an RL run
  checkpoints and resumes through the PR 11 session store like any
  supervised run (``docs/rl.md`` "Checkpoint and resume").
"""

from __future__ import annotations

# bjx: driver-hot-path (BJX106/BJX108 hold here exactly as in
# driver.py; the policy-sync fetch below is the declared cadence sync)

import time

from blendjax.train.mesh_driver import MeshTrainDriver
from blendjax.utils.metrics import metrics


def _require_jax():
    import jax

    return jax


class RLTrainDriver(MeshTrainDriver):
    """Drive an RL learner against a reservoir + actor pool.

    ``step`` is a :func:`blendjax.rl.steps.make_dqn_step` /
    :func:`~blendjax.rl.steps.make_pg_step` product (its reservoir
    must be THIS driver's ``reservoir``); ``state`` an
    :class:`~blendjax.rl.steps.RLTrainState`. ``mesh`` defaults to a
    pure-DP mesh over the available devices (size 1 single-chip), so
    the same driver runs the laptop loop and the 8-device leg.

    - ``batch_size``: transitions per learner step.
    - ``min_fill``: reservoir transitions required before the first
      step (defaults to ``batch_size``) — the warmup gate.
    - ``sync_every`` doubles as BOTH the loss-sync cadence the base
      driver keeps and the actor policy-refresh cadence.
    - ``sample_timeout_s``: max seconds to block waiting for the
      reservoir before raising (a dead actor pool must fail the run,
      not hang it; :meth:`ActorPool.check` errors surface here too).
    """

    def __init__(self, step, state, reservoir, actors=None, *,
                 mesh=None, batch_size: int = 32,
                 min_fill: int | None = None,
                 sample_timeout_s: float = 60.0, **driver_kwargs):
        if mesh is None:
            from blendjax.parallel import create_mesh

            mesh = create_mesh({"data": -1})
        self.reservoir = reservoir
        self.actors = actors
        self.batch_size = int(batch_size)
        self.min_fill = int(min_fill if min_fill is not None
                            else batch_size)
        self.sample_timeout_s = float(sample_timeout_s)
        self.sample_waits = 0
        driver_kwargs.setdefault("session_state", self._session_state)
        super().__init__(step, state, mesh, **driver_kwargs)

    # -- the learner loop -----------------------------------------------------

    def _wait_for_batch(self):
        """Block (bounded) until the reservoir can compose a batch —
        the learner's only wait, counted and spanned as the env-bound
        evidence the RL doctor reads. A dead actor thread surfaces
        HERE on every step (fast path included): a filled reservoir
        keeps composing batches, and without the check the run would
        silently train to completion on a frozen replay buffer."""
        if self.actors is not None:
            self.actors.check()
        # ONE warmup gate (min_fill), checked BEFORE composing: a
        # compose advances the sampling RNG (and can pay a priority-
        # mirror refresh), so a below-fill call must not burn either
        # just to discard the result
        if self.reservoir.size >= self.min_fill:
            composed = self.reservoir.compose(self.batch_size)
            if composed is not None:
                return composed
        self.sample_waits += 1
        metrics.count("rl.sample_waits")
        deadline = time.monotonic() + self.sample_timeout_s
        with metrics.span("rl.sample_wait"):
            while True:
                if self.actors is not None:
                    self.actors.check()
                if self.reservoir.size >= self.min_fill:
                    composed = self.reservoir.compose(self.batch_size)
                    if composed is not None:
                        return composed
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"reservoir never reached {self.min_fill} "
                        f"transitions within {self.sample_timeout_s}s "
                        f"(size={self.reservoir.size}) — are the "
                        "actors running?"
                    )
                time.sleep(0.002)

    def train_step(self) -> None:
        """One learner step: compose host indices, draw a token, and
        dispatch the fused step — all under the reservoir lock, so a
        concurrent actor insert can't donate the token's ring buffers
        before the dispatch consumes them. The dispatch ring's
        full-wait runs BEFORE the lock (``ensure_ring_slot``): in
        steady state the ring IS full and submit would otherwise block
        on device completion while holding the lock, serializing actor
        inserts with learner device time — the locked section holds
        only host index work + the async dispatch enqueue, so actor
        inserts resume within microseconds."""
        composed = self._wait_for_batch()
        idx, weights = composed
        self.ensure_ring_slot()
        with self.reservoir.lock:
            token = self.reservoir.draw_token(idx, weights)
            self.submit(token, post=False)
        # the cadenced step-boundary work — the blocking loss fetch
        # and the checkpoint's session clone — runs OUTSIDE the lock:
        # both can wait on the device, and an actor insert must not
        # wait on them
        self.post_dispatch()
        if (
            self.actors is not None and self.sync_every
            and self.steps % self.sync_every == 0
        ):
            self._sync_policy()

    def _sync_policy(self) -> None:
        """Push a fresh host-side param snapshot to the actors — the
        declared cadence fetch (every ``sync_every`` steps), blocking
        only on the newest state's readiness like the loss sync does.
        NOT part of the actor loop: BJX115 guards the other side.

        The snapshot goes through a DEVICE-side copy first
        (``jnp.array`` per leaf, then the host fetch reads the copy):
        on the CPU backend a direct ``device_get``/``np.array`` of the
        live params yields zero-copy views that alias — and therefore
        pin — the donated param buffers, and a pinned buffer can't be
        reused in place, so the next donated update silently
        reallocated the whole state at exactly the sync cadence (the
        donation audit caught this; the copy-then-fetch keeps the
        audit's pointer-stability contract on every backend, sharded
        params included)."""
        jax = _require_jax()
        import jax.numpy as jnp

        with metrics.span("rl.policy_sync"):
            # bjx: ignore[BJX106] — the sanctioned sync point, mirror
            # of _sync_oldest: cadence-bounded by sync_every
            snapshot = jax.device_get(
                jax.tree.map(jnp.array, self.state.params)
            )
        self.actors.update_policy(snapshot)

    def run_steps(self, n: int, max_seconds: float | None = None):
        """Run ``n`` learner steps (bounded by ``max_seconds``);
        returns the drained final loss."""
        deadline = (
            time.monotonic() + max_seconds if max_seconds else None
        )
        for _ in range(int(n)):
            self.train_step()
            if deadline is not None and time.monotonic() > deadline:
                break
        return self.drain()

    # -- session snapshot (blendjax.checkpoint) -------------------------------

    def _session_state(self) -> dict:
        """Default checkpoint session for an RL run: reservoir ring +
        priorities + draw state, actor counters + reward curve, and
        (via the base driver) the step numbering — the PR 11 session
        store carries all of it, so a killed run resumes mid-curve.

        Both components snapshot under ONE hold of the reservoir lock:
        taken separately, an actor insert landing between the two
        state_dicts would leave the saved ``env_steps`` and reservoir
        ``inserts`` permanently out of step after resume (the exact
        accounting identity the bench asserts)."""
        with self.reservoir.lock:
            session = {"replay": self.reservoir.state_dict()}
            if self.actors is not None:
                session["actor"] = self.actors.state_dict()
            return session

    def restore_session(self, session: dict) -> list:
        """Load the RL slices of a restored session (the inverse of
        :meth:`_session_state`; driver counters restore through the
        base ``load_state_dict`` under the ``driver`` key)."""
        from blendjax.checkpoint.session import restore_session

        return restore_session(
            session, replay=self.reservoir, actor=self.actors,
            driver=self,
        )

    @property
    def stats(self) -> dict:
        s = MeshTrainDriver.stats.fget(self)
        s["sample_waits"] = self.sample_waits
        s["reservoir"] = self.reservoir.stats
        if self.actors is not None:
            s["actor"] = self.actors.stats
        return s


__all__ = ["RLTrainDriver"]
