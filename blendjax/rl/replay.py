"""TrajectoryReservoir: device-resident replay over pytree transitions.

The echo :class:`~blendjax.data.echo.SampleReservoir` is structurally a
replay buffer — a donated sharded device ring with host-chosen indices
and a traceable in-jit draw hook — and this class is its RL
generalization (ROADMAP item 1): transitions are PYTREES
(``obs``/``action``/``reward``/``done``/``next_obs`` plus any bootstrap
metadata the actor attaches), storage is the shared ring core
(:mod:`blendjax.data.ring`) preallocated on device and optionally
sharded over the mesh ``data`` axis, and sampling supports uniform AND
prioritized replay (Schaul et al., 2016) where the per-slot priority
vector ALSO lives on device and is updated **in-jit** from TD error
inside the learner's own dispatch — the scenario curriculum's
loss-feedback pattern applied to replay.

Invariants, inherited from echo and enforced the same ways:

- ``insert`` is ONE jitted donated scatter that writes the transition
  rows AND stamps the new slots' priorities to the running max in the
  same dispatch — the ring and priority buffers are allocated once and
  updated in place forever (the donation audit pins their pointers).
- a learner step costs ONE device dispatch: :meth:`draw_token` hands
  the builders (:mod:`blendjax.rl.steps`) the ring pytree + host index
  vector, the gather happens inside the fused train jit, and the
  priority write-back rides the same jit (the step commits the donated
  priority buffer back via :meth:`commit_priorities`).
- indices are chosen on the HOST: uniform draws from the filled-slot
  set, prioritized draws from a host mirror of the device priorities
  refreshed every ``priority_refresh_every`` draws (one small bounded
  fetch at a declared cadence, under the ``rl.priority_sync`` span —
  the standard slightly-stale distribution of distributed PER, never a
  per-step sync). All fresh/replayed accounting runs against those
  host indices, so the hot loop makes zero device round trips (the
  BJX108/BJX115 discipline).

Threading: the actor pool inserts from its own thread while the
learner draws — every buffer-touching operation runs under one
reentrant ``lock``, and the learner holds it across
``draw_token -> fused dispatch -> commit_priorities`` (see
:meth:`RLTrainDriver.train_step <blendjax.rl.learner.RLTrainDriver>`)
so an insert can never donate the ring out from under an un-dispatched
token.

Metrics (the ``rl.*`` catalog, docs/observability.md): counters
``rl.transitions`` (rows inserted) / ``rl.fresh`` / ``rl.replayed``
(first-use vs repeat draws; ``fresh + replayed == draws * batch``
exactly), gauges ``rl.reservoir_fill`` / ``rl.replay_ratio``,
histogram ``rl.sample_age_s``, spans ``rl.insert`` / ``rl.sample`` /
``rl.priority_sync``.
"""

from __future__ import annotations

# bjx: driver-hot-path (BJX106/BJX108: accounting runs on host-chosen
# indices; the one sanctioned priority-mirror fetch is cadence-bounded
# and marked below)

import threading
import time

import numpy as np

from blendjax.utils.logging import get_logger
from blendjax.utils.metrics import metrics

logger = get_logger("rl")


def _require_jax():
    import jax  # deferred: producer processes never import jax

    return jax


# bjx: thread-shared (actor pool inserts from its thread while the
# learner draws: every public entry point must hold `lock` — BJX117)
class TrajectoryReservoir:
    """Device-resident ring of the last ``capacity`` transitions.

    - ``capacity``: ring size in transitions (must divide the sharded
      axis when ``mesh``/``sharding`` is given).
    - ``prioritized``: enable proportional prioritized sampling
      (``p_i^alpha``); priorities start at the running max for new
      rows (every transition is drawn at least once at full weight)
      and are overwritten in-jit by the learner's TD magnitudes.
    - ``alpha`` / ``beta``: the usual PER exponents — sampling
      sharpness and importance-weight correction. Weights are
      normalized by their batch max and ride the draw token as
      ``_rl_weights`` (all-ones under uniform sampling, so one loss
      implementation serves both modes).
    - ``priority_refresh_every``: draws between host-mirror refreshes
      of the device priority vector.
    - ``mesh`` / ``sharding``: shard ring + priorities over the mesh
      ``data`` axis (:func:`blendjax.parallel.ring_sharding`) —
      capacity scales with the mesh and drawn batches leave in the
      feeder's batch layout, exactly like the echo ring.
    """

    def __init__(
        self,
        capacity: int,
        rng=0,
        mesh=None,
        sharding=None,
        prioritized: bool = False,
        alpha: float = 0.6,
        beta: float = 0.4,
        priority_eps: float = 1e-3,
        priority_refresh_every: int = 16,
    ):
        from blendjax.data.ring import validate_ring_capacity

        self.capacity = int(capacity)
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if sharding is None and mesh is not None:
            from blendjax.parallel.sharding import ring_sharding

            sharding = ring_sharding(mesh)
        validate_ring_capacity(self.capacity, sharding)
        self.sharding = sharding
        self.prioritized = bool(prioritized)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.priority_eps = float(priority_eps)
        self.priority_refresh_every = max(1, int(priority_refresh_every))
        self.lock = threading.RLock()
        seed = rng if isinstance(rng, int) else 0
        self._np_rng = np.random.default_rng(seed)
        self._buffers = None  # device ring pytree (dict)
        self._priorities = None  # device (capacity,) f32
        self._spec: dict | None = None
        self._treedef = None
        self._insert_fn = None
        self._gather_fn = None
        self._cursor = 0
        self.size = 0
        self.inserts = 0  # transitions inserted, lifetime
        self._draws = 0  # draw-token/sample calls, lifetime
        self._pmax = 1.0  # running max priority (host scalar)
        # Host-side per-slot accounting (numpy, never device values):
        self._use = np.zeros(self.capacity, np.int64)
        self._t_insert = np.zeros(self.capacity, np.float64)
        self._filled = np.zeros(self.capacity, bool)
        # Host mirror of the device priorities, refreshed at cadence —
        # the distribution prioritized composition samples from.
        self._prio_host = np.ones(self.capacity, np.float32)
        self._draws_at_refresh = 0
        # lifetime stats (mirrored into the registry as exact counters)
        self.fresh = 0
        self.replayed = 0

    # -- lazy jit construction ----------------------------------------------

    def _build(self, fields: dict, initial=None, prio_initial=None) -> None:
        jax = _require_jax()
        import jax.numpy as jnp

        from blendjax.data.ring import (
            allocate_ring,
            make_ring_gather,
            ring_slot_update,
            ring_spec,
        )

        self._spec = ring_spec(fields)
        self._treedef = jax.tree.structure(fields)
        self._buffers = allocate_ring(
            self.capacity, fields=fields, sharding=self.sharding,
            initial=initial,
        )
        if prio_initial is not None:
            prio = jnp.asarray(np.asarray(prio_initial, np.float32))
        else:
            prio = jnp.ones((self.capacity,), jnp.float32)
        if self.sharding is not None:
            prio = jax.device_put(prio, self.sharding)
        self._priorities = prio
        capacity = self.capacity

        # ONE donated dispatch writes the transition rows AND the new
        # slots' priorities (stamped to the running max so fresh
        # transitions are drawn at full weight before their first TD
        # evidence exists). Donating both keeps ring + priority memory
        # flat and their buffer pointers stable — the audit contract.
        def _insert(bufs, prio, batch, cursor, pmax):
            bufs = ring_slot_update(capacity, bufs, batch, cursor)
            lead = jax.tree.leaves(batch)[0].shape[0]
            idx = (cursor + jnp.arange(lead)) % capacity
            return bufs, prio.at[idx].set(pmax)

        sh = self.sharding
        self._insert_fn = jax.jit(
            _insert, donate_argnums=(0, 1),
            **({"out_shardings": (sh, sh)} if sh is not None else {}),
        )
        self._gather_fn = make_ring_gather(sh)

    # -- operations -----------------------------------------------------------

    def insert(self, transitions: dict) -> np.ndarray:
        """Write one batch of transitions (pytree of arrays sharing a
        leading dim); returns the HOST slot-index vector for the
        caller's accounting. Thread-safe: the actor pool calls this
        from its own thread while the learner draws."""
        jax = _require_jax()

        leaves = jax.tree.leaves(transitions)
        if not leaves:
            raise ValueError("insert() needs at least one array field")
        lead = int(leaves[0].shape[0])
        if any(v.shape[0] != lead for v in leaves):
            raise ValueError(
                "transition fields must share one leading dim; got "
                f"{[v.shape[0] for v in leaves]}"
            )
        if lead > self.capacity:
            transitions = jax.tree.map(
                lambda v: v[-self.capacity:], transitions
            )
            lead = self.capacity
        with self.lock:
            if self._buffers is None:
                self._build(transitions)
            else:
                from blendjax.data.ring import ring_spec

                if jax.tree.structure(transitions) != self._treedef:
                    raise ValueError(
                        "transition structure changed: reservoir holds "
                        f"{self._treedef}, insert got "
                        f"{jax.tree.structure(transitions)}"
                    )
                spec = ring_spec(transitions)
                for k, (shape, dtype) in spec.items():
                    eshape, edtype = self._spec[k]
                    if shape != eshape or dtype != edtype:
                        raise ValueError(
                            f"field {k}: got {shape}/{dtype}, reservoir "
                            f"holds {eshape}/{edtype}"
                        )
            with metrics.span("rl.insert"):
                self._buffers, self._priorities = self._insert_fn(
                    self._buffers, self._priorities, transitions,
                    np.int32(self._cursor % self.capacity),
                    np.float32(self._pmax),
                )
            slots = (self._cursor + np.arange(lead)) % self.capacity
            self._cursor = (self._cursor + lead) % self.capacity
            self.size = min(self.size + lead, self.capacity)
            self.inserts += lead
            self._use[slots] = 0
            self._t_insert[slots] = time.monotonic()
            self._filled[slots] = True
            self._prio_host[slots] = self._pmax
            fill = int(self._filled.sum())
        metrics.count("rl.transitions", lead)
        metrics.gauge("rl.reservoir_fill", fill)
        return slots

    # -- host-side draw composition -------------------------------------------

    def _refresh_priorities(self) -> None:
        """Cadence-bounded host mirror of the device priority vector —
        the sanctioned fetch prioritized composition samples from. One
        small (capacity,) transfer every ``priority_refresh_every``
        draws, never per step."""
        with metrics.span("rl.priority_sync"):
            # bjx: ignore[BJX108] — the declared cadence-bounded mirror
            # fetch, not a per-draw materialization (np.array copies:
            # the zero-copy asarray view of a jax buffer is read-only)
            self._prio_host = np.array(self._priorities, np.float32)
        np.maximum(self._prio_host, self.priority_eps, out=self._prio_host)
        if self._filled.any():
            # the TRUE running max, even once converged |TD| falls
            # below 1.0 — a floor here would stamp every fresh insert
            # far above the real distribution and skew sampling toward
            # recency (an empty ring keeps the previous pmax)
            self._pmax = float(self._prio_host[self._filled].max())
        self._draws_at_refresh = self._draws

    def compose(self, batch_size: int):
        """Pick ``batch_size`` slot indices (with replacement, the
        replay-buffer convention — a batch may exceed the resident
        count) plus their importance weights, or ``None`` while the
        reservoir is empty (the learner's ``min_fill`` gate decides
        how much warmup beyond non-empty to demand). Host work only."""
        b = int(batch_size)
        with self.lock:
            if self.size < 1 or self._buffers is None:
                return None
            slots = np.flatnonzero(self._filled)
            if self.prioritized:
                if (
                    self._draws - self._draws_at_refresh
                    >= self.priority_refresh_every
                ):
                    self._refresh_priorities()
                p = self._prio_host[slots].astype(np.float64) ** self.alpha
                p /= p.sum()
                idx = self._np_rng.choice(slots, size=b, p=p)
                # importance correction against the stale mirror (the
                # same distribution the draw used), max-normalized
                chosen = p[np.searchsorted(slots, idx)]
                w = (len(slots) * chosen) ** -self.beta
                weights = (w / w.max()).astype(np.float32)
            else:
                idx = self._np_rng.choice(slots, size=b)
                weights = np.ones(b, np.float32)
        return np.asarray(idx, np.int32), weights

    # -- draws ----------------------------------------------------------------

    def _account_draw(self, idx: np.ndarray) -> None:
        # Accounting runs on the HOST index vector (BJX108): fresh
        # counts FIRST USES — a slot drawn twice in one batch is one
        # fresh + one replay, so fresh can never exceed inserts and
        # fresh + replayed == draws * batch exactly.
        first = np.zeros(len(idx), bool)
        first[np.unique(idx, return_index=True)[1]] = True
        fresh_rows = first & (self._use[idx] == 0)
        fresh_n = int(fresh_rows.sum())
        np.add.at(self._use, idx, 1)
        self.fresh += fresh_n
        self.replayed += len(idx) - fresh_n
        self._draws += 1
        metrics.count("rl.draws")
        metrics.count("rl.fresh", fresh_n)
        metrics.count("rl.replayed", len(idx) - fresh_n)
        metrics.observe_many(
            "rl.sample_age_s", time.monotonic() - self._t_insert[idx]
        )
        drawn = self.fresh + self.replayed
        metrics.gauge(
            "rl.replay_ratio",
            round(self.replayed / drawn, 4) if drawn else 0.0,
        )

    def draw_token(self, idx, weights=None) -> dict:
        """Compose one fused-draw token — the dict the
        :mod:`blendjax.rl.steps` builders consume: ring pytree +
        device priorities (donated into the learner jit for the in-jit
        TD write-back) + host indices + importance weights. No device
        work happens here.

        Lifetime: like the echo token, the buffers ride by reference
        and the NEXT donated insert consumes them — hold :attr:`lock`
        from token creation through the fused dispatch (the learner
        driver does)."""
        idx = np.asarray(idx, np.int32)
        if weights is None:
            weights = np.ones(len(idx), np.float32)
        with self.lock:
            if self._buffers is None:
                raise RuntimeError("reservoir is empty: insert() first")
            self._account_draw(idx)
            return {
                "_rl_buffers": self._buffers,
                "_rl_prio": self._priorities,
                "_rl_idx": idx,
                "_rl_weights": np.asarray(weights, np.float32),
            }

    def commit_priorities(self, new_priorities) -> None:
        """Accept the learner jit's updated (donated-in-place) priority
        buffer back. Called by the step wrapper while the learner holds
        :attr:`lock`."""
        with self.lock:
            self._priorities = new_priorities

    def draw(self, buffers, idx):
        """The traceable gather body — the hook the step builders call
        INSIDE the fused learner jit (same pattern as
        ``SampleReservoir.draw`` / ``make_echo_fused_step``)."""
        from blendjax.data.ring import ring_gather

        return ring_gather(buffers, idx)

    def sample(self, idx) -> dict:
        """Eager jitted gather of ``idx`` rows (inspection/tests; the
        learner hot path fuses the gather via :meth:`draw_token`).
        Advances the same accounting the fused path uses."""
        idx = np.asarray(idx, np.int32)
        with self.lock:
            if self._buffers is None:
                raise RuntimeError("reservoir is empty: insert() first")
            self._account_draw(idx)
            with metrics.span("rl.sample"):
                return self._gather_fn(self._buffers, idx)

    @property
    def fields(self) -> tuple:
        with self.lock:
            return tuple(self._spec) if self._spec else ()

    @property
    def stats(self) -> dict:
        # Under the lock like every other entry point: an actor-thread
        # insert racing an unlocked read here handed out torn
        # fresh/replayed/size cuts (the state_dict-vs-draw race shape
        # BJX117 now flags).
        with self.lock:
            drawn = self.fresh + self.replayed
            return {
                "size": self.size,
                "inserts": self.inserts,
                "draws": self._draws,
                "fresh": self.fresh,
                "replayed": self.replayed,
                "replay_ratio": (
                    round(self.replayed / drawn, 4) if drawn else None
                ),
                "prioritized": self.prioritized,
                "pmax": round(self._pmax, 6),
            }

    # -- session snapshot (blendjax.checkpoint) -------------------------------

    def state_dict(self) -> dict:
        """Ring + priorities + host accounting + RNG state — everything
        a resumed RL run needs to keep drawing the same distribution.

        Unlike the echo reservoir (whose inserts run on the same
        thread that snapshots), the ring here is donated-into by the
        ACTOR thread — so the snapshot takes device-side CLONES under
        the lock rather than riding by reference: a by-reference ring
        would be deleted by the next actor insert before the snapshot
        writer could materialize it. A few copy dispatches at
        checkpoint cadence, never in the learner hot loop. Insert
        times are stored as ages; monotonic clocks don't survive a
        process boundary."""
        import jax.numpy as jnp

        with self.lock:
            now = time.monotonic()
            d = {
                "capacity": self.capacity,
                "cursor": self._cursor,
                "size": self.size,
                "inserts": self.inserts,
                "draws": self._draws,
                "fresh": self.fresh,
                "replayed": self.replayed,
                "pmax": self._pmax,
                "prioritized": self.prioritized,
                "use": self._use.copy(),
                "filled": self._filled.copy(),
                "age_s": now - self._t_insert,
                "prio_host": self._prio_host.copy(),
                "rng": self._np_rng.bit_generator.state,
                "built": self._buffers is not None,
            }
            if self._buffers is not None:
                jax = _require_jax()
                d["buffers"] = jax.tree.map(jnp.array, dict(self._buffers))
                d["priorities"] = jnp.array(self._priorities)
            return d

    def load_state_dict(self, d: dict) -> None:
        """Rebuild under the CURRENT sharding (an 8-chip snapshot
        restores onto a 4-chip ring by plain re-placement). Restoring
        the draw counters + RNG state makes the resumed sampling
        sequence continue the uninterrupted run's."""
        if int(d["capacity"]) != self.capacity:
            raise ValueError(
                f"snapshot reservoir capacity {d['capacity']} != "
                f"configured {self.capacity}"
            )
        with self.lock:
            self._cursor = int(d["cursor"])
            self.size = int(d["size"])
            self.inserts = int(d["inserts"])
            self._draws = int(d["draws"])
            self.fresh = int(d.get("fresh", 0))
            self.replayed = int(d.get("replayed", 0))
            self._pmax = float(d.get("pmax", 1.0))
            self._use = np.asarray(d["use"], np.int64).copy()
            self._filled = np.asarray(d["filled"], bool).copy()
            now = time.monotonic()
            self._t_insert = now - np.asarray(d["age_s"], np.float64)
            self._prio_host = np.asarray(
                d["prio_host"], np.float32
            ).copy()
            self._np_rng.bit_generator.state = d["rng"]
            self._draws_at_refresh = self._draws
            if not d.get("built"):
                return
            jax = _require_jax()
            bufs = jax.tree.map(np.asarray, d["buffers"])
            self._build(
                bufs, initial=bufs,
                prio_initial=np.asarray(d["priorities"], np.float32),
            )


__all__ = ["TrajectoryReservoir"]
