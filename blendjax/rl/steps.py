"""RL learner steps: gather + loss + donated update + priority
write-back in ONE jit.

Built in the :mod:`blendjax.train.steps` idiom — a ``make_*_step``
factory returns ``step(state, token) -> (state, metrics)`` that
composes with :class:`~blendjax.train.TrainDriver` unchanged — with
the echo-fusion trick applied to replay: the ``token`` is what
:meth:`TrajectoryReservoir.draw_token
<blendjax.rl.replay.TrajectoryReservoir.draw_token>` yields (ring
pytree + device priorities + host indices + importance weights), the
transition gather happens INSIDE the train jit via the reservoir's
traceable ``draw`` hook, and — the new piece — the per-slot priority
vector is DONATED into the same jit and scattered with fresh
``|TD|`` magnitudes before it returns. Sampling, loss, update, and
the prioritized-replay feedback loop are one device dispatch
(``dispatch_per_step == 1.0`` on the learner path, CI-asserted in
the bench ``live_rl`` row).

Two losses:

- :func:`make_dqn_step` — (double) DQN over
  ``{obs, action, reward, done, next_obs}`` transitions with Huber TD
  loss, importance weights, and an in-jit Polyak target network (the
  target params live INSIDE the train state —
  :class:`RLTrainState` — so target maintenance never costs a second
  dispatch or a host-cadence clone).
- :func:`make_pg_step` — REINFORCE-style policy gradient over
  transitions carrying a precomputed ``ret`` (discounted return)
  field, softmax over discrete actions + entropy bonus. Priorities
  update to ``|advantage|`` so prioritized draws favor surprising
  episodes.

Mesh path: pass ``state_sharding`` (from
:func:`blendjax.parallel.state_shardings`) and the reservoir's ring
sharding is pinned into the jit's buffer/priority arguments
automatically — the same pinned-layout discipline as
``make_mesh_echo_fused_step``, so the donated update can never drift
the (potentially multi-GB) ring's placement mid-run.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax.training.train_state import TrainState

from blendjax.train.precision import policy_value_and_grad, resolve_policy


class RLTrainState(TrainState):
    """TrainState + the DQN target network, as ONE pytree.

    Keeping ``target_params`` inside the state means the Polyak update
    rides the fused learner jit (no separate target-sync dispatch, no
    donated-buffer cloning at a host cadence) and the pinned
    ``state_shardings`` tree covers it for free on the mesh path."""

    target_params: Any = None


def make_rl_train_state(model, example_obs, optimizer=None,
                        learning_rate: float = 1e-3, rng=None,
                        mesh=None, target: bool = True,
                        rules=None, layout=None) -> RLTrainState:
    """Init an :class:`RLTrainState` (params sharded onto ``mesh`` per
    the partition rules — ``rules``/``layout`` select fsdp/tp layouts
    exactly as :func:`blendjax.train.make_train_state` does, so big
    policies shard too; ``target=True`` clones them into the target
    network — distinct buffers, both donated through the step)."""
    from blendjax.parallel.sharding import (
        param_sharding_rules,
        resolve_rules,
    )

    rng = rng if rng is not None else jax.random.key(0)
    optimizer = optimizer or optax.adam(learning_rate)
    params = model.init(rng, example_obs)["params"]
    if mesh is not None:
        resolved = resolve_rules(rules=rules, layout=layout, model=model)
        params = jax.tree_util.tree_map_with_path(
            lambda p, v: jax.device_put(
                v, param_sharding_rules(mesh, p, v, rules=resolved)
            ),
            params,
        )
    target_params = (
        jax.tree.map(jnp.array, params) if target else None
    )
    return RLTrainState.create(
        apply_fn=model.apply, params=params, tx=optimizer,
        target_params=target_params,
    )


def _rl_jit_kwargs(state_sharding, buffer_sharding,
                   with_prio_out: bool = True) -> dict:
    """jit kwargs pinning the learner step's layouts: the state tree
    explicit, the ring buffers + priority vector pinned to the ring
    sharding (a drifted placement fails loudly at dispatch instead of
    silently resharding the ring every step), host idx/weights left
    for jit to infer. ``None`` everywhere keeps the plain
    propagate-from-arrays jit."""
    if state_sharding is None and buffer_sharding is None:
        return {}
    # args: (state, buffers, prio, idx, weights)
    in_sh = [state_sharding, buffer_sharding, buffer_sharding, None, None]
    out = [state_sharding]
    if with_prio_out:
        out.append(buffer_sharding)
    out.append(None)  # metrics
    return {"in_shardings": tuple(in_sh), "out_shardings": tuple(out)}


def mesh_rl_step_kwargs(state, mesh, data_axis: str = "data",
                        rules=None, layout=None) -> dict:
    """The mesh hook pair for either builder, mirroring
    :func:`blendjax.train.mesh_driver.make_mesh_echo_fused_step`:
    ``state_sharding`` pinned from the concrete state (the donated
    update can never drift layouts) and a ``draw_constraint`` that
    re-shards the just-gathered transition batch over the batch axis
    inside the jit. ``rules``/``layout`` derive the state tree from
    partition rules instead of reading concrete placements — the SAME
    fsdp/tp layouts the supervised path trains under, so big policies
    shard identically. Usage::

        step = make_dqn_step(reservoir, model.apply,
                             **mesh_rl_step_kwargs(state, mesh))
    """
    from blendjax.parallel.sharding import batch_sharding, state_shardings

    if data_axis not in mesh.axis_names:
        # same build-time failure as make_mesh_fused_step: a typo'd
        # batch axis would silently train replicated
        raise ValueError(
            f"data_axis {data_axis!r} is not an axis of mesh "
            f"{dict(mesh.shape)}"
        )
    bs = batch_sharding(mesh, axis=data_axis)

    def _pin_drawn_batch(batch):
        return jax.tree.map(
            lambda v: (
                jax.lax.with_sharding_constraint(v, bs)
                if getattr(v, "ndim", 0) >= 1 else v
            ),
            batch,
        )

    return {
        "state_sharding": state_shardings(
            state, mesh=mesh, rules=rules, layout=layout
        ),
        "draw_constraint": _pin_drawn_batch,
    }


def make_dqn_step(
    reservoir,
    apply_fn,
    gamma: float = 0.99,
    tau: float = 0.01,
    double: bool = True,
    priority_eps: float = 1e-3,
    donate: bool = True,
    precision=None,
    state_sharding=None,
    draw_constraint=None,
):
    """Build the one-dispatch DQN learner step.

    ``reservoir`` is the :class:`~blendjax.rl.replay
    .TrajectoryReservoir` whose tokens this step consumes — its
    traceable ``draw`` hook runs inside the jit, and its updated
    priority buffer is committed back after each dispatch (the step
    wrapper holds that handshake so callers never see the donated
    buffer). ``apply_fn`` is the Q-network's ``model.apply``;
    transitions must carry ``obs``/``action`` (int indices)/
    ``reward``/``done``/``next_obs``.

    ``tau`` is the per-step Polyak coefficient for the in-state target
    network (``tau=1.0`` degenerates to no target, ``tau=0`` freezes
    it); ``double=True`` selects actions with the online net and
    evaluates them with the target (van Hasselt et al., 2016).
    ``draw_constraint`` re-shards the just-gathered batch on the mesh
    path (the ``make_mesh_echo_fused_step`` hook)."""
    policy = resolve_policy(precision)
    pin = draw_constraint or (lambda b: b)
    draw = reservoir.draw
    buffer_sharding = reservoir.sharding

    def _fused(state, buffers, prio, idx, weights):
        batch = pin(draw(buffers, idx))
        obs = batch["obs"].astype(jnp.float32)
        act = batch["action"].astype(jnp.int32).reshape(-1)
        reward = batch["reward"].astype(jnp.float32).reshape(-1)
        done = batch["done"].astype(jnp.float32).reshape(-1)
        next_obs = batch["next_obs"].astype(jnp.float32)

        def scalar_loss(params):
            q = apply_fn({"params": params}, obs)
            qa = jnp.take_along_axis(q, act[:, None], axis=1)[:, 0]
            q_next_t = apply_fn({"params": state.target_params}, next_obs)
            if double:
                q_next_o = apply_fn({"params": params}, next_obs)
                a_star = jnp.argmax(q_next_o, axis=-1)
                next_v = jnp.take_along_axis(
                    q_next_t, a_star[:, None], axis=1
                )[:, 0]
            else:
                next_v = q_next_t.max(axis=-1)
            target = reward + gamma * (1.0 - done) * next_v
            td = qa - jax.lax.stop_gradient(target)
            loss = (weights * optax.huber_loss(td)).mean()
            return loss, td

        (loss, td), grads = policy_value_and_grad(
            scalar_loss, state.params, policy, has_aux=True
        )
        state = state.apply_gradients(grads=grads)
        state = state.replace(
            target_params=jax.tree.map(
                lambda t, p: (1.0 - tau) * t + tau * p,
                state.target_params, state.params,
            )
        )
        # the prioritized-replay feedback: per-slot |TD| scattered into
        # the donated priority buffer INSIDE this same dispatch — the
        # curriculum's loss-feedback pattern applied to replay
        new_prio = prio.at[idx].set(jnp.abs(td) + priority_eps)
        return state, new_prio, {"loss": loss}

    fused = jax.jit(
        _fused,
        donate_argnums=(0, 2) if donate else (),
        **_rl_jit_kwargs(state_sharding, buffer_sharding),
    )

    def step(state, token):
        state, new_prio, m = fused(
            state, token["_rl_buffers"], token["_rl_prio"],
            token["_rl_idx"], token["_rl_weights"],
        )
        reservoir.commit_priorities(new_prio)
        return state, m

    return step


def make_pg_step(
    reservoir,
    apply_fn,
    entropy_coef: float = 0.01,
    priority_eps: float = 1e-3,
    donate: bool = True,
    precision=None,
    state_sharding=None,
    draw_constraint=None,
):
    """Build the one-dispatch policy-gradient learner step.

    REINFORCE over reservoir transitions that carry a precomputed
    discounted-return ``ret`` field (the actor's ``extra_fields`` hook
    attaches it at episode end): softmax policy over discrete
    ``action`` indices, loss ``-(w * logpi(a|s) * ret).mean()`` minus
    an entropy bonus. ``apply_fn`` maps obs to action logits (a
    :class:`~blendjax.models.QNetwork`-shaped head works). Priorities
    update to ``|ret - baseline|`` (the batch-mean baseline), so
    prioritized draws favor surprising episodes. Same token protocol,
    donation, and pinned-sharding treatment as :func:`make_dqn_step` —
    and the same single-dispatch contract."""
    policy = resolve_policy(precision)
    pin = draw_constraint or (lambda b: b)
    draw = reservoir.draw
    buffer_sharding = reservoir.sharding

    def _fused(state, buffers, prio, idx, weights):
        batch = pin(draw(buffers, idx))
        obs = batch["obs"].astype(jnp.float32)
        act = batch["action"].astype(jnp.int32).reshape(-1)
        ret = batch["ret"].astype(jnp.float32).reshape(-1)

        def scalar_loss(params):
            logits = apply_fn({"params": params}, obs)
            logp = jax.nn.log_softmax(logits, axis=-1)
            lp_a = jnp.take_along_axis(logp, act[:, None], axis=1)[:, 0]
            adv = ret - jax.lax.stop_gradient(ret.mean())
            pg = -(weights * lp_a * jax.lax.stop_gradient(adv)).mean()
            entropy = -(jnp.exp(logp) * logp).sum(-1).mean()
            return pg - entropy_coef * entropy, adv

        (loss, adv), grads = policy_value_and_grad(
            scalar_loss, state.params, policy, has_aux=True
        )
        state = state.apply_gradients(grads=grads)
        new_prio = prio.at[idx].set(jnp.abs(adv) + priority_eps)
        return state, new_prio, {"loss": loss}

    fused = jax.jit(
        _fused,
        donate_argnums=(0, 2) if donate else (),
        **_rl_jit_kwargs(state_sharding, buffer_sharding),
    )

    def step(state, token):
        state, new_prio, m = fused(
            state, token["_rl_buffers"], token["_rl_prio"],
            token["_rl_idx"], token["_rl_weights"],
        )
        reservoir.commit_priorities(new_prio)
        return state, m

    return step


__all__ = [
    "RLTrainState",
    "make_dqn_step",
    "make_pg_step",
    "make_rl_train_state",
    "mesh_rl_step_kwargs",
]
