"""blendjax.scenario — closed-loop domain randomization (docs/scenarios.md).

blendtorch's signature capability (the densityopt example's consumer-
driven simulation-parameter optimization over the duplex channel)
promoted to a subsystem spanning the whole pipeline:

- :mod:`~blendjax.scenario.space` — a declarative, versioned,
  pickle-free :class:`ScenarioSpace`: named scenarios over
  uniform/gaussian/categorical/mixture parameter distributions with
  mixture weights, plus the compact CLI grammar;
- :mod:`~blendjax.scenario.service` — :class:`ScenarioService`
  publishes the space (version-stamped, acked) to every producer over
  the existing PAIR duplex sockets, including producers that join or
  leave mid-run via the fleet controller / admission server;
- producer side: :class:`blendjax.producer.scenario.ScenarioApplicator`
  samples from the latest space, applies the draw to the scene
  (Blender or the synthetic tier), and stamps ``_scenario`` into every
  message;
- :mod:`~blendjax.scenario.accounting` — exact per-scenario row counts,
  fresh-vs-echoed splits (echoed rows carry the anchor row's scenario),
  per-scenario loss histograms, per-version attribution — bounded
  keying, never dynamic metric names (bjx-lint BJX113);
- :mod:`~blendjax.scenario.curriculum` — :class:`ScenarioCurriculum`
  feeds per-scenario losses back into mixture weights (bandit) and the
  continuous params (REINFORCE, generalizing
  ``train.score.GaussianSimParams``), re-published on a cadence.

Import-cheap: numpy/stdlib only — producer processes import the space
and the stamp keys without jax.
"""

from __future__ import annotations

from blendjax.scenario.accounting import (  # noqa: F401
    SCENARIO_KEY,
    SCENARIO_ROWS_KEY,
    ScenarioAccounting,
    accounting,
    batch_row_scenarios,
)
from blendjax.scenario.curriculum import ScenarioCurriculum  # noqa: F401
from blendjax.scenario.service import ScenarioService  # noqa: F401
from blendjax.scenario.space import (  # noqa: F401
    Choice,
    Const,
    Dist,
    Gaussian,
    Mixture,
    Scenario,
    ScenarioSpace,
    Uniform,
)

__all__ = [
    "SCENARIO_KEY",
    "SCENARIO_ROWS_KEY",
    "ScenarioAccounting",
    "accounting",
    "batch_row_scenarios",
    "ScenarioCurriculum",
    "ScenarioService",
    "Choice",
    "Const",
    "Dist",
    "Gaussian",
    "Mixture",
    "Scenario",
    "ScenarioSpace",
    "Uniform",
]
