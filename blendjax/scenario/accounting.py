"""Exact per-scenario accounting: counts, fresh/echoed splits, losses.

The consumer-side ledger that makes scenario diversity *evidenced*
rather than assumed: every train row is attributed to the scenario (and
space version) that produced it, with the same exactness contract the
echo counters carry — ``fresh + echoed == rows drawn``, per scenario,
always (CI-asserted by the bench ``live_scenario`` row).

Cardinality discipline (the shape bjx-lint BJX113 enforces): scenario
ids are **dict keys in this tracker's own bounded structures** — exactly
like :mod:`blendjax.obs.lineage` keys per-producer state by btid — never
interpolated into metric-registry names. The registry sees only constant
names (``scenario.rows`` / ``scenario.fresh`` / ``scenario.echoed`` /
``scenario.unstamped_rows`` / ``scenario.overflow_rows``); per-scenario
detail rides :meth:`ScenarioAccounting.report` into the bench row and
the reporter archive. Ids are bounded by the declared space
(:meth:`declare`); ids that never appeared in any declared space are
accepted up to ``max_scenarios`` distinct values, then folded into one
overflow bucket so a misbehaving producer can't balloon the ledger.

Wire shape: producers stamp ``_scenario = {"id": name, "ver": version,
"theta": [floats]}`` on each (batch) message; the ingest path carries it
per item inside ``_meta``; the echo reservoir keeps a host-side per-slot
sidecar so echoed rows are attributed to their TRUE scenario (the
anchor row's), not the emitting batch's. Frames stamped with an older
space version are accounted under THAT version — a curriculum update
never relabels in-flight frames.
"""

from __future__ import annotations

import collections
import threading

from blendjax.utils.metrics import Histogram, metrics

#: Batch/message-level stamp key (a dict: {"id", "ver", "theta"}).
SCENARIO_KEY = "_scenario"
#: Per-row sidecar the echo pipeline attaches to drawn batches: a list
#: of per-row stamp dicts (or None for unstamped rows), host-side only.
SCENARIO_ROWS_KEY = "_scenario_rows"

#: Single buckets for rows that can't be attributed to a declared id.
OVERFLOW_ID = "__overflow__"


def batch_row_scenarios(batch: dict, lead: int):
    """Per-row scenario stamps of one batch: a list of ``lead`` stamp
    dicts (or ``None`` entries), or ``None`` when the batch carries no
    scenario stamps at all.

    Sources, in precedence order: an explicit per-row sidecar
    (``_scenario_rows``), per-item ``_meta`` entries (the assembled-
    batch path), or one batch-level ``_scenario`` stamp replicated to
    every row (the prebatched/passthrough path)."""
    rows = batch.get(SCENARIO_ROWS_KEY)
    if rows is not None:
        return list(rows)
    meta = batch.get("_meta")
    if isinstance(meta, list) and meta:
        out = None
        if any(isinstance(m, dict) and SCENARIO_KEY in m for m in meta):
            out = [
                m.get(SCENARIO_KEY) if isinstance(m, dict) else None
                for m in meta
            ]
        else:
            out = _flatten_chunk_meta(meta)
        if out is not None:
            # _meta's length is authoritative for assembled batches; pad
            # defensively if a caller passed a foreign lead
            if len(out) < lead:
                out.extend([None] * (lead - len(out)))
            return out[:lead]
    stamp = batch.get(SCENARIO_KEY)
    if isinstance(stamp, dict):
        return [stamp] * lead
    return None


def _flatten_chunk_meta(meta):
    """Chunked (K, B, ...) superbatches carry ``_meta`` as a list of K
    per-sub-batch REST dicts, each nesting that sub-batch's per-item
    ``_meta`` list (and, for prebatched producers, possibly a
    sub-batch-level ``_scenario`` stamp). Flatten to per-row stamps so
    a tile/chunk pipeline's scenario accounting doesn't silently read
    zero. Returns None when no stamp exists anywhere."""
    flat: list = []
    found = False
    for rest in meta:
        if not isinstance(rest, dict):
            return None  # not the chunk-rests shape
        sub = rest.get("_meta")
        sub_stamp = rest.get(SCENARIO_KEY)
        if isinstance(sub, list) and sub:
            for m in sub:
                s = m.get(SCENARIO_KEY) if isinstance(m, dict) else None
                if s is None:
                    s = sub_stamp if isinstance(sub_stamp, dict) else None
                flat.append(s)
                found = found or s is not None
        elif isinstance(sub_stamp, dict):
            # sub-batch-level stamp with no per-item meta: row count
            # unknown from here — one entry per sub-batch is the best
            # honest attribution (callers with exactness needs carry
            # per-item meta)
            flat.append(sub_stamp)
            found = True
        else:
            return None
    return flat if found else None


def _stamp_parts(stamp):
    """``(sid, ver, theta)`` of one stamp dict (tolerant of partial
    stamps from foreign producers)."""
    if not isinstance(stamp, dict):
        return None, None, None
    sid = stamp.get("id")
    ver = stamp.get("ver")
    theta = stamp.get("theta")
    return (
        str(sid) if sid is not None else None,
        int(ver) if ver is not None else None,
        theta,
    )


class _ScenarioStats:
    """Per-scenario ledger entry (guarded by the tracker's lock)."""

    __slots__ = (
        "rows", "fresh", "echoed", "loss", "win_loss_sum", "win_rows",
        "theta", "versions",
    )

    def __init__(self) -> None:
        self.rows = 0
        self.fresh = 0
        self.echoed = 0
        self.loss = Histogram()  # one observe per scored row
        # curriculum window: consumed (and zeroed) by window_losses()
        self.win_loss_sum = 0.0
        self.win_rows = 0
        # (theta, loss) pairs for the score-function update, bounded
        self.theta: collections.deque = collections.deque(maxlen=256)
        self.versions: dict = {}  # space version -> rows


class ScenarioAccounting:
    """Process-wide scenario ledger (one per process, like the metrics
    registry and frame lineage; thread-safe — the echo draw loop and a
    train loop may both account)."""

    def __init__(self, max_scenarios: int = 256):
        self._lock = threading.Lock()
        self._sc: dict = {}
        self._declared: set = set()
        self.max_scenarios = int(max_scenarios)
        self.space_version = 0

    # -- declaration ---------------------------------------------------------

    def declare(self, space) -> None:
        """Register a space's scenario names (the bounded key set) and
        its version. The service calls this on every publish; direct
        users may call it once up front. Ids outside every declared
        space still count (up to ``max_scenarios`` distinct), but are
        reported as undeclared."""
        with self._lock:
            for name in space.names:
                self._declared.add(str(name))
                if str(name) not in self._sc:
                    self._sc[str(name)] = _ScenarioStats()
            self.space_version = max(self.space_version, space.version)
        metrics.gauge("scenario.space_version", space.version)

    def _entry(self, sid: str):
        """Ledger entry for ``sid`` (the overflow bucket once the cap
        is hit); returns ``(stats, resolved_sid)``. Pure lookup — the
        overflow METRIC is counted once per overflowed row in
        :meth:`observe_rows` only, never here (both observe_rows and
        observe_loss resolve the same rows through this)."""
        st = self._sc.get(sid)
        if st is None:
            if len(self._sc) >= self.max_scenarios:
                sid = OVERFLOW_ID
                st = self._sc.get(sid)
                if st is None:
                    st = self._sc[sid] = _ScenarioStats()
                return st, sid
            st = self._sc[sid] = _ScenarioStats()
        return st, sid

    # -- row accounting --------------------------------------------------------

    def observe_rows(self, stamps, fresh=None) -> int:
        """Account a vector of per-row stamps (dicts or None). ``fresh``
        is a per-row boolean sequence (None = every row is a first use,
        the non-echo path). Returns the number of stamped rows."""
        stamped = fresh_n = echoed_n = overflowed = 0
        with self._lock:
            for i, stamp in enumerate(stamps):
                sid, ver, _ = _stamp_parts(stamp)
                if sid is None:
                    continue
                stamped += 1
                st, resolved = self._entry(sid)
                if resolved != sid:
                    overflowed += 1
                st.rows += 1
                is_fresh = True if fresh is None else bool(fresh[i])
                if is_fresh:
                    st.fresh += 1
                    fresh_n += 1
                else:
                    st.echoed += 1
                    echoed_n += 1
                if ver is not None:
                    # stale-version frames land under the version that
                    # PRODUCED them, not the current one
                    st.versions[ver] = st.versions.get(ver, 0) + 1
        if stamped:
            metrics.count("scenario.rows", stamped)
            if fresh is None:
                metrics.count("scenario.fresh", stamped)
            else:
                metrics.count("scenario.fresh", fresh_n)
                metrics.count("scenario.echoed", echoed_n)
        if overflowed:
            metrics.count("scenario.overflow_rows", overflowed)
        unstamped = len(stamps) - stamped
        if unstamped:
            metrics.count("scenario.unstamped_rows", unstamped)
        return stamped

    def observe_loss(self, stamps, loss) -> None:
        """Attribute one scalar training loss to the scenarios present
        in the batch, weighted by their row counts: each stamped row
        contributes one histogram observation (histogram count == rows
        scored — the exact-histogram contract) and one row of weight to
        the curriculum's windowed per-scenario mean. Theta-stamped rows
        additionally record ``(theta, loss)`` pairs for the
        score-function update."""
        loss = float(loss)
        with self._lock:
            for stamp in stamps:
                sid, _, theta = _stamp_parts(stamp)
                if sid is None:
                    continue
                st, _ = self._entry(sid)
                st.loss.observe(loss)
                st.win_loss_sum += loss
                st.win_rows += 1
                if theta:
                    st.theta.append((list(theta), loss))

    def account_batch(self, batch: dict, loss=None, lead=None) -> int:
        """One-call accounting for a train batch: extract the per-row
        stamps, count rows (echo-drawn batches arrive pre-counted via
        the ``_scenario_rows`` sidecar — only their loss is recorded
        here), and attribute ``loss`` when given. Returns the stamped
        row count (0 when the batch carries no scenario stamps)."""
        if lead is None:
            lead = _batch_lead(batch)
        if not lead:
            return 0
        rows = batch_row_scenarios(batch, lead)
        if rows is None:
            return 0
        pre_counted = SCENARIO_ROWS_KEY in batch
        n = 0
        if not pre_counted:
            n = self.observe_rows(rows)
        else:
            n = sum(1 for r in rows if isinstance(r, dict))
        if loss is not None:
            self.observe_loss(rows, loss)
        return n

    # -- curriculum consumption ------------------------------------------------

    def window_losses(self, reset: bool = True, min_rows: int = 1) -> dict:
        """``{sid: (mean_loss, rows)}`` accumulated since the last
        consume — the curriculum's evidence window. Scenarios with
        fewer than ``min_rows`` scored rows are neither returned NOR
        reset: a floored low-weight scenario keeps accumulating across
        windows until it has enough evidence, so weight adaptation can
        always reverse (discarding sub-threshold windows would freeze a
        starved scenario out of every future update)."""
        out = {}
        with self._lock:
            for sid, st in self._sc.items():
                if st.win_rows >= max(1, min_rows):
                    out[sid] = (st.win_loss_sum / st.win_rows, st.win_rows)
                    if reset:
                        st.win_loss_sum = 0.0
                        st.win_rows = 0
        return out

    def theta_samples(self, sid: str, drain: bool = True) -> list:
        """Recorded ``(theta, loss)`` pairs for one scenario (drained by
        default so each curriculum update sees fresh evidence)."""
        with self._lock:
            st = self._sc.get(str(sid))
            if st is None:
                return []
            out = list(st.theta)
            if drain:
                st.theta.clear()
            return out

    # -- snapshots -------------------------------------------------------------

    def totals(self) -> dict:
        """``{sid: (fresh, echoed)}`` — the exactness check's view."""
        with self._lock:
            return {
                sid: (st.fresh, st.echoed) for sid, st in self._sc.items()
                if st.rows
            }

    def report(self) -> dict:
        with self._lock:
            scenarios = {}
            for sid, st in self._sc.items():
                if not st.rows and not st.loss.count:
                    continue
                scenarios[sid] = {
                    "rows": st.rows,
                    "fresh": st.fresh,
                    "echoed": st.echoed,
                    "declared": sid in self._declared,
                    "versions": dict(sorted(st.versions.items())),
                    "loss": st.loss.summary(),
                }
            return {
                "space_version": self.space_version,
                "declared": sorted(self._declared),
                "scenarios": scenarios,
            }

    def reset(self) -> None:
        """Drop all ledger state (bench measured-window resets); the
        declared-name set survives — the space didn't change."""
        with self._lock:
            declared = self._declared
            self._sc = {sid: _ScenarioStats() for sid in declared}

    # -- session snapshot (blendjax.checkpoint) -------------------------------

    def state_dict(self) -> dict:
        """The full ledger for the session store: per-scenario exact
        counts, per-version attribution, the curriculum's evidence
        window (win_loss_sum/win_rows) and bounded theta ring, and the
        exact loss histograms — so a resumed curriculum update sees
        the same evidence the uninterrupted run would have."""
        with self._lock:
            return {
                "space_version": self.space_version,
                "declared": sorted(self._declared),
                "scenarios": {
                    sid: {
                        "rows": st.rows,
                        "fresh": st.fresh,
                        "echoed": st.echoed,
                        "win_loss_sum": st.win_loss_sum,
                        "win_rows": st.win_rows,
                        "theta": [
                            [list(t), float(l)] for t, l in st.theta
                        ],
                        "versions": {
                            int(k): int(v) for k, v in st.versions.items()
                        },
                        "loss": st.loss.state_dict(),
                    }
                    for sid, st in self._sc.items()
                },
            }

    def load_state_dict(self, d: dict) -> None:
        with self._lock:
            self._declared = {str(s) for s in d.get("declared", [])}
            self.space_version = int(d.get("space_version", 0))
            self._sc = {}
            for sid, e in d.get("scenarios", {}).items():
                st = _ScenarioStats()
                st.rows = int(e["rows"])
                st.fresh = int(e["fresh"])
                st.echoed = int(e["echoed"])
                st.win_loss_sum = float(e["win_loss_sum"])
                st.win_rows = int(e["win_rows"])
                st.theta.extend(
                    (list(t), float(l)) for t, l in e.get("theta", [])
                )
                st.versions = {
                    int(k): int(v)
                    for k, v in e.get("versions", {}).items()
                }
                if "loss" in e:
                    st.loss.load_state_dict(e["loss"])
                self._sc[str(sid)] = st
        metrics.gauge("scenario.space_version", self.space_version)


def _batch_lead(batch: dict) -> int:
    meta = batch.get("_meta")
    if isinstance(meta, list) and meta:
        if all(
            isinstance(m, dict) and isinstance(m.get("_meta"), list)
            for m in meta
        ):
            # chunked superbatch: K rest dicts each nesting a per-item
            # list — the row count is their SUM, not K
            return sum(len(m["_meta"]) for m in meta)
        return len(meta)
    rows = batch.get(SCENARIO_ROWS_KEY)
    if rows is not None:
        return len(rows)
    idx = batch.get("_echo_idx")
    if idx is not None:
        return int(idx.shape[0])
    lead = 0
    for k, v in batch.items():
        if not k.startswith("_") and getattr(v, "ndim", 0) >= 1:
            lead = max(lead, int(v.shape[0]))
    return lead


#: Default process-wide ledger (like ``metrics`` and ``lineage``).
accounting = ScenarioAccounting()


__all__ = [
    "SCENARIO_KEY", "SCENARIO_ROWS_KEY", "OVERFLOW_ID",
    "ScenarioAccounting", "accounting", "batch_row_scenarios",
]
