"""Loss-driven scenario curriculum: close the domain-randomization loop.

Generalizes :class:`blendjax.train.score.GaussianSimParams` — the
densityopt example's score-function (REINFORCE) update — into a
first-class curriculum over a :class:`~blendjax.scenario.space.
ScenarioSpace`:

- **mixture weights** (which scenario to render) update by a bandit-
  style multiplicative-weights rule toward HIGH-loss scenarios —
  curriculum learning targets what the model currently finds hard —
  with an exploration floor so no scenario starves (the math is in
  docs/scenarios.md);
- **continuous parameters** (each scenario's Gaussian dists) update by
  REINFORCE on the ``(theta, loss)`` pairs producers stamp alongside
  the scenario id: ``grad log p(theta) * (loss - baseline)``, exactly
  the densityopt update, minimizing expected loss per scenario. (The
  renderer stays non-differentiable; only the sampling distribution
  moves.)

Every update bumps the space version and re-publishes through the
:class:`~blendjax.scenario.service.ScenarioService`, so producers pick
the new distribution up on their next poll and the accounting ledger
attributes frames to the version that actually produced them.

``frozen=True`` is eval mode: the curriculum observes but never
mutates or republishes — fixed-distribution measurement runs and the
bench's A/B "fixed uniform mixture" leg use it.
"""

from __future__ import annotations

import numpy as np

from blendjax.scenario.accounting import accounting as default_accounting
from blendjax.utils.logging import get_logger
from blendjax.utils.metrics import metrics

logger = get_logger("scenario")


class ScenarioCurriculum:
    """Adapt a scenario space from per-scenario training losses.

    - ``space``: the authoritative :class:`ScenarioSpace` (mutated in
      place; every update bumps its version).
    - ``service``: optional :class:`ScenarioService` — updates
      re-publish through it.
    - ``every_steps``: cadence for :meth:`step`-driven updates.
    - ``weight_lr``: multiplicative-weights learning rate (0 disables
      mixture-weight adaptation).
    - ``weight_floor``: per-scenario minimum share of the mixture
      (exploration floor; ``floor * n_scenarios`` must stay < 1).
    - ``param_lr`` / ``baseline_decay``: the REINFORCE update's knobs
      (see :class:`~blendjax.train.score.GaussianSimParams`).
    - ``adapt_params``: set False to adapt weights only (no jax
      dependency on the update path then).
    - ``min_rows``: scenarios with fewer scored rows in the window are
      held out of that update (their weight is untouched).
    - ``frozen``: observe-only eval mode.
    """

    def __init__(
        self,
        space,
        service=None,
        ledger=default_accounting,
        every_steps: int = 50,
        weight_lr: float = 1.0,
        weight_floor: float = 0.05,
        param_lr: float = 5e-2,
        baseline_decay: float = 0.9,
        adapt_params: bool = True,
        min_rows: int = 8,
        frozen: bool = False,
    ):
        self.space = space
        self.service = service
        self.ledger = ledger
        self.every_steps = max(1, int(every_steps))
        self.weight_lr = float(weight_lr)
        if weight_floor < 0:
            raise ValueError(f"weight_floor must be >= 0, got {weight_floor}")
        # the floors must sum below 1 to leave room for adaptation: on
        # wide spaces the per-scenario default is clamped down instead
        # of raising (20 scenarios x the 0.05 default would sum to 1)
        self.weight_floor = min(
            float(weight_floor), 0.9 / len(space.names)
        )
        self.param_lr = float(param_lr)
        self.baseline_decay = float(baseline_decay)
        self.adapt_params = bool(adapt_params)
        self.min_rows = max(1, int(min_rows))
        self.frozen = bool(frozen)
        self.updates = 0
        self._since = 0
        self._sim: dict = {}  # scenario name -> GaussianSimParams
        # REINFORCE baselines restored from a session snapshot, applied
        # when a scenario's GaussianSimParams is (re)built lazily.
        self._restored_baselines: dict = {}
        self.ledger.declare(space)
        if service is not None and service.version < space.version:
            service.publish(space)

    # -- cadence ----------------------------------------------------------------

    def step(self, n: int = 1):
        """Advance the step counter; runs :meth:`update` every
        ``every_steps`` train steps. Returns the update report when one
        ran, else None."""
        self._since += int(n)
        if self._since < self.every_steps:
            return None
        self._since = 0
        return self.update()

    # -- the update -------------------------------------------------------------

    def update(self):
        """One curriculum update from the accounting window; returns a
        report dict (or None when frozen / no evidence)."""
        if self.frozen:
            # eval mode: leave the window accumulating for reporting
            return None
        # PEEK the evidence first; consume only when an update actually
        # lands. Sub-min_rows windows stay ACCUMULATING either way (a
        # floored low-weight scenario gathers evidence across several
        # windows and eventually re-enters the update), and a no-op
        # cadence (tied losses, one eligible scenario, nothing gaussian
        # to adapt) must not drain the OTHER scenarios' windows — that
        # would bias the eventual first comparison toward whichever
        # side kept its history.
        losses = self.ledger.window_losses(
            reset=False, min_rows=self.min_rows
        )
        eligible = {
            sid: mean for sid, (mean, rows) in losses.items()
            if sid in self.space.scenarios
        }
        if not eligible:
            return None
        moved = {}
        if self.weight_lr > 0 and len(eligible) >= 2:
            moved = self._update_weights(eligible)
        adapted = {}
        if self.adapt_params:
            adapted = self._update_params()
        if not moved and not adapted:
            # nothing changed: bumping + republishing an identical
            # space would be pure version churn — per-version
            # accounting would fragment over versions that never
            # differed — and the untouched windows keep accumulating
            return None
        self.ledger.window_losses(reset=True, min_rows=self.min_rows)
        version = self.space.bump()
        if self.service is not None:
            self.service.publish(self.space)
        else:
            self.ledger.declare(self.space)
        self.updates += 1
        metrics.count("scenario.curriculum_updates")
        metrics.gauge("scenario.space_version", version)
        report = {
            "version": version,
            "losses": {k: round(v, 6) for k, v in eligible.items()},
            "weights": {
                k: round(v, 4) for k, v in self.space.weights().items()
            },
            "params_adapted": adapted,
            "weights_moved": moved,
        }
        logger.info("scenario curriculum update: %s", report)
        return report

    def _update_weights(self, losses: dict) -> dict:
        """Multiplicative weights toward high loss, with an exploration
        floor: ``w_i ∝ w_i * exp(eta * adv_i)`` where ``adv`` is the
        scenario's loss normalized to [-1, 1] across the window, then
        ``w = (1 - K*floor) * w_norm + floor`` so every scenario keeps
        a guaranteed share."""
        names = list(self.space.names)
        w = np.asarray(
            [self.space.scenarios[n].weight for n in names], np.float64
        )
        w = w / w.sum()
        vals = np.asarray(
            [losses.get(n, np.nan) for n in names], np.float64
        )
        seen = ~np.isnan(vals)
        lo, hi = np.nanmin(vals), np.nanmax(vals)
        if not hi > lo:
            return {}  # tied losses: no signal, no move, no version bump
        adv = np.zeros(len(names))
        adv[seen] = 2.0 * (vals[seen] - lo) / (hi - lo) - 1.0
        w = w * np.exp(self.weight_lr * adv)
        w = w / w.sum()
        k = len(names)
        w = (1.0 - k * self.weight_floor) * w + self.weight_floor
        self.space.set_weights(dict(zip(names, w.tolist())))
        return {
            n: round(float(a), 4) for n, a in zip(names, adv) if seen[
                names.index(n)
            ]
        }

    def _update_params(self) -> dict:
        """Per-scenario REINFORCE over the stamped ``(theta, loss)``
        pairs: each scenario's Gaussian params form one diagonal-
        Gaussian ``GaussianSimParams`` whose mu/log_sigma update is
        written back into the space's dists."""
        from blendjax.train.score import GaussianSimParams

        adapted = {}
        for name, sc in self.space.scenarios.items():
            gauss = sc.gaussian_params()
            if not gauss:
                continue
            # peek-then-drain: a scenario short of evidence KEEPS its
            # (bounded) theta ring accumulating for the next cadence
            samples = self.ledger.theta_samples(name, drain=False)
            samples = [
                (t, l) for t, l in samples if len(t) == len(gauss)
            ]
            if len(samples) < max(2, self.min_rows // 4):
                continue
            self.ledger.theta_samples(name, drain=True)
            sim = self._sim.get(name)
            mus = [d.mu for _, d in gauss]
            sigmas = [max(d.sigma, 1e-6) for _, d in gauss]
            if sim is None or len(sim.mu) != len(gauss):
                sim = self._sim[name] = GaussianSimParams(
                    mu=mus, log_sigma=np.log(sigmas),
                    learning_rate=self.param_lr,
                    baseline_decay=self.baseline_decay,
                )
                b0 = self._restored_baselines.pop(name, None)
                if b0 is not None:
                    # resume continuity: the running-mean baseline the
                    # uninterrupted run would carry into this update
                    sim.baseline = float(b0)
            else:
                # the space is the source of truth between updates (a
                # peer may have edited it); resync before stepping
                import jax.numpy as jnp

                sim.mu = jnp.asarray(mus, jnp.float32)
                sim.log_sigma = jnp.asarray(
                    np.log(sigmas), jnp.float32
                )
            theta = np.asarray([t for t, _ in samples], np.float32)
            losses = np.asarray([l for _, l in samples], np.float32)
            sim.update(theta, losses)
            new_mu = np.asarray(sim.mu, np.float64)
            new_sigma = np.exp(np.asarray(sim.log_sigma, np.float64))
            for (key, dist), mu, sigma in zip(gauss, new_mu, new_sigma):
                dist.mu = float(mu)
                dist.sigma = float(max(sigma, 1e-6))
            adapted[name] = {
                k: [round(float(m), 4), round(float(s), 4)]
                for (k, _), m, s in zip(gauss, new_mu, new_sigma)
            }
        return adapted

    # -- session snapshot (blendjax.checkpoint) -------------------------------

    def state_dict(self) -> dict:
        """Session snapshot: the authoritative space (wire form —
        already pickle-free and versioned), the update cadence
        position, and the per-scenario REINFORCE baselines. The
        evidence windows and theta rings live in the LEDGER's snapshot
        (``ScenarioAccounting.state_dict``) — one owner per fact."""
        return {
            "updates": self.updates,
            "since": self._since,
            "space": self.space.to_wire(),
            "baselines": {
                name: float(sim.baseline)
                for name, sim in self._sim.items()
                if sim.baseline is not None
            },
        }

    def load_state_dict(self, d: dict) -> None:
        """Restore IN PLACE: the space object's scenarios/weights/
        version are replaced on the existing instance, so the service,
        ledger, and any producer-side references keep pointing at the
        authoritative copy. When a service is attached the restored
        space re-publishes immediately — producers that outlived the
        consumer (remote fleet) adopt the resumed version on their
        next poll."""
        self.updates = int(d.get("updates", 0))
        self._since = int(d.get("since", 0))
        if "space" in d:
            restored = type(self.space).from_wire(d["space"])
            self.space.scenarios = restored.scenarios
            self.space.version = restored.version
            self.ledger.declare(self.space)
            if self.service is not None:
                self.service.publish(self.space)
        self._sim = {}
        self._restored_baselines = {
            str(k): float(v)
            for k, v in (d.get("baselines") or {}).items()
        }


__all__ = ["ScenarioCurriculum"]
