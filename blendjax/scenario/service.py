"""ScenarioService: publish the scenario space to every producer.

The consumer side of the closed loop (docs/scenarios.md): one PAIR
duplex channel per producer — the same sockets densityopt fans
parameter samples over (reference ``densityopt.py:95-107``) — carrying
a two-verb, version-stamped protocol:

- consumer -> producer: ``{"scenario_space": <wire form>,
  "scenario_version": v}`` — the full space, republished on every
  curriculum update AND on every membership change (a newcomer must
  hold the CURRENT version before its first frame is counted);
- producer -> consumer: ``{"scenario_ack": v}`` — the producer applied
  version ``v``; the service records per-member acked versions so
  :meth:`wait_acked` can gate a run on fleet-wide convergence.

Thread model (the BJX104 invariant): ALL zmq sockets live on one
private service thread. ``attach``/``detach``/``publish`` enqueue
commands from any thread (the fleet controller's control thread, the
curriculum running in the train loop) and the service thread applies
them — the same queued-membership pattern ``RemoteStream`` uses for its
runtime connect/disconnect.

Elastic membership: :class:`~blendjax.fleet.controller.FleetController`
accepts ``scenario_service=`` and calls :meth:`attach` before admitting
a scaled-up/announced producer's data address (so the space reaches the
newcomer before its frames do) and :meth:`detach` when an instance
retires — the duplex channel closes cleanly on the owning thread.
"""

from __future__ import annotations

import queue
import threading
import time

from blendjax.scenario.accounting import accounting
from blendjax.scenario.space import ScenarioSpace
from blendjax.utils.logging import get_logger
from blendjax.utils.metrics import metrics
from blendjax.utils.tg import guard

logger = get_logger("scenario")

_TICK_S = 0.02


class ScenarioService:
    """Versioned scenario-space distribution over per-producer duplex
    channels.

    ``space`` is the initial :class:`~blendjax.scenario.space.
    ScenarioSpace` (optional — it can arrive later via
    :meth:`publish`). ``ledger`` is the accounting instance new spaces
    are declared into (defaults to the process-wide one).
    """

    def __init__(self, space: ScenarioSpace | None = None,
                 ledger=accounting, ack_timeout_s: float = 10.0):
        self.ledger = ledger
        self.ack_timeout_s = float(ack_timeout_s)
        self._lock = threading.Lock()
        self._space_wire: dict | None = None
        self._version = 0
        self.space: ScenarioSpace | None = None
        # threadguard wiring: membership/ack bookkeeping only under
        # `_lock` (guard() is identity unless BLENDJAX_THREADGUARD=1)
        self._members: dict = guard(  # btid -> addr (bookkeeping view)
            {}, name="scenario.members", lock=self._lock
        )
        self._acked: dict = guard(  # btid -> highest acked version
            {}, name="scenario.acked", lock=self._lock
        )
        self._cmds: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if space is not None:
            self.publish(space)

    # -- public API (any thread) ----------------------------------------------

    def attach(self, btid, ctrl_addr: str) -> None:
        """Admit one producer's duplex endpoint; the service thread
        connects and immediately sends the current space (if any), so
        membership changes re-publish by construction."""
        with self._lock:
            self._members[btid] = ctrl_addr
        self._ensure_thread()
        self._cmds.put(("attach", btid, ctrl_addr))

    def detach(self, btid) -> None:
        """Retire one producer's duplex endpoint (closed on the owning
        thread; unknown btids are a no-op)."""
        with self._lock:
            self._members.pop(btid, None)
            self._acked.pop(btid, None)
        if self._thread is not None:
            self._cmds.put(("detach", btid))

    def publish(self, space: ScenarioSpace) -> int:
        """Publish ``space`` (at its CURRENT version) to every member;
        returns the version sent. Snapshot semantics: the wire form is
        taken here, so later in-place curriculum mutations don't race
        the send."""
        wire = space.to_wire()
        with self._lock:
            self.space = space
            self._space_wire = wire
            self._version = space.version
        self.ledger.declare(space)
        metrics.gauge("scenario.space_version", space.version)
        self._ensure_thread()
        self._cmds.put(("publish", wire, space.version))
        return space.version

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def acked_versions(self) -> dict:
        with self._lock:
            return dict(self._acked)

    def members(self) -> dict:
        with self._lock:
            return dict(self._members)

    def wait_acked(self, version: int | None = None, btids=None,
                   timeout: float | None = None) -> bool:
        """Block until every member in ``btids`` (default: all current
        members) acked ``version`` (default: the latest published).
        Returns False on timeout — a producer that never acks is a
        liveness signal, not an exception."""
        deadline = time.monotonic() + (
            self.ack_timeout_s if timeout is None else timeout
        )
        while True:
            with self._lock:
                v = self._version if version is None else int(version)
                targets = (
                    list(self._members) if btids is None else list(btids)
                )
                ok = all(self._acked.get(b, -1) >= v for b in targets)
            if ok:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def state(self) -> dict:
        """Reporter-friendly snapshot (rides the StatsReporter archive
        beside the fleet state)."""
        with self._lock:
            return {
                "version": self._version,
                "members": {str(k): v for k, v in self._members.items()},
                "acked": {str(k): v for k, v in self._acked.items()},
            }

    # -- service thread --------------------------------------------------------

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._serve, name="blendjax-scenario-service",
                daemon=True,
            )
            self._thread.start()

    def _serve(self) -> None:
        # btid -> PairChannel; created, used, and closed ONLY here.
        import zmq

        from blendjax.transport import PairChannel

        channels: dict = {}

        def send_space(btid, chan, wire, version) -> None:
            try:
                chan.send(scenario_space=wire, scenario_version=version)
                metrics.count("scenario.publishes")
            except Exception:
                # incl. zmq.Again from the send timeout below: a dead/
                # wedged member must cost one bounded send, never the
                # whole fleet's distribution thread
                logger.exception(
                    "scenario publish to %r failed (kept attached; the "
                    "next publish retries)", btid,
                )

        try:
            while not self._stop.is_set():
                try:
                    cmd = self._cmds.get(timeout=_TICK_S)
                except queue.Empty:
                    cmd = None
                if cmd is not None:
                    op = cmd[0]
                    if op == "attach":
                        _, btid, addr = cmd
                        old = channels.pop(btid, None)
                        if old is not None:
                            old.close()
                        try:
                            # creator affinity: the duplex socket is
                            # born, used, and closed ONLY on this
                            # service thread (BJX104; threadguard
                            # enforces it at runtime when enabled)
                            chan = guard(
                                PairChannel(
                                    addr, bind=False, allow_pickle=False,
                                    default_timeoutms=0,
                                ),
                                name=f"scenario.chan[{btid}]",
                                affinity="creator",
                            )
                            # bounded sends: a PAIR socket whose peer
                            # died (no 'leave') or whose pipe filled
                            # BLOCKS on send by default — one such
                            # member would wedge this thread for every
                            # producer. With a send timeout the send
                            # raises Again and send_space logs+skips.
                            chan.sock.setsockopt(zmq.SNDTIMEO, 500)
                        except Exception:
                            logger.exception(
                                "scenario attach to %r at %r failed",
                                btid, addr,
                            )
                            with self._lock:
                                self._members.pop(btid, None)
                            continue
                        channels[btid] = chan
                        with self._lock:
                            wire, version = self._space_wire, self._version
                        if wire is not None:
                            # membership change == re-publish: the
                            # newcomer holds the current space before
                            # its data address is even admitted
                            send_space(btid, chan, wire, version)
                    elif op == "detach":
                        chan = channels.pop(cmd[1], None)
                        if chan is not None:
                            chan.close()
                    elif op == "publish":
                        _, wire, version = cmd
                        for btid, chan in channels.items():
                            send_space(btid, chan, wire, version)
                # drain acks from every channel (non-blocking). The
                # WHOLE per-message handling sits in the try: a remote
                # member controls its own ctrl endpoint, and one
                # malformed ack ({"scenario_ack": "junk"}, a non-dict
                # payload) must be refused, not kill the fleet's only
                # distribution thread.
                for btid, chan in channels.items():
                    while True:
                        try:
                            msg = chan.recv(timeoutms=0)
                        except Exception:
                            # recv-level failure (incl. a refused
                            # pickle frame): break, not continue — a
                            # persistent socket error would otherwise
                            # spin this loop forever; the next 20 ms
                            # tick retries the drain
                            logger.exception(
                                "scenario ack recv from %r failed", btid
                            )
                            break
                        if msg is None:
                            break
                        try:
                            ver = msg.get("scenario_ack")
                            if ver is None:
                                continue
                            ver = int(ver)
                        except Exception:
                            logger.exception(
                                "malformed scenario ack from %r", btid
                            )
                            continue
                        metrics.count("scenario.acks")
                        with self._lock:
                            if ver > self._acked.get(btid, -1):
                                self._acked[btid] = ver
                with self._lock:
                    metrics.gauge("scenario.members", len(self._members))
        finally:
            for chan in channels.values():
                chan.close()

    # -- lifecycle -------------------------------------------------------------

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "ScenarioService":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


__all__ = ["ScenarioService"]
