"""Declarative, versioned scenario spaces for domain randomization.

A :class:`ScenarioSpace` is the unit the consumer publishes to its
producer fleet over the duplex channel (:mod:`blendjax.scenario.service`):
a set of **named scenarios** — each a dict of named simulation parameters
drawn from uniform / gaussian / categorical / mixture distributions —
plus **mixture weights** over the scenarios themselves. Producers sample
from the latest space per batch (:class:`blendjax.producer.scenario.
ScenarioApplicator`), apply the draw to their scene, and stamp the
scenario id + space version into the published message, which is how the
consumer's exact per-scenario accounting
(:mod:`blendjax.scenario.accounting`) re-associates frames with the
distribution that produced them — the generalization of densityopt's
``shape_id`` round trip (reference ``densityopt.py:99-103,119``).

Serialization is **pickle-free by contract**: ``to_wire()`` emits only
msgpack-native values (dicts, lists, strings, numbers, bools), so a space
rides the tensor codec's ``obj`` entries and decodes under
``allow_pickle=False`` — the duplex channel stays safe on untrusted
networks, exactly like the admission endpoint.

Versioning: every space carries an integer ``version``; re-publishing
after a curriculum update bumps it (:meth:`ScenarioSpace.bump`).
Producers ack the version they applied, and frames stamped with an older
version are accounted under THAT version — a space update never
retroactively relabels in-flight frames.

The compact **space grammar** (``docs/scenarios.md``) builds small spaces
from a CLI string::

    easy:half_extent=u(0.8,1.2) / hard*2:half_extent=u(0.8,1.2),xy_jitter=g(6,0.5)

— scenarios separated by ``/``, an optional ``*weight`` suffix on the
name, and per-param distributions ``u(lo,hi)`` (uniform), ``g(mu,sigma)``
(gaussian), ``c(a|b|c)`` (categorical), ``m(<dist>@w|<dist>@w)``
(mixture), or a bare number (constant).
"""

from __future__ import annotations

import numpy as np

# ---------------------------------------------------------------------------
# Distributions
# ---------------------------------------------------------------------------


class Dist:
    """One named simulation parameter's sampling distribution."""

    kind: str = ""

    def sample(self, rng: np.random.Generator):
        raise NotImplementedError

    def to_wire(self) -> list:
        raise NotImplementedError

    @staticmethod
    def from_wire(entry) -> "Dist":
        if not isinstance(entry, (list, tuple)) or not entry:
            raise ValueError(f"malformed distribution entry {entry!r}")
        kind = entry[0]
        cls = _DIST_KINDS.get(kind)
        if cls is None:
            raise ValueError(f"unknown distribution kind {kind!r}")
        return cls._from_wire(entry)


class Uniform(Dist):
    kind = "u"

    def __init__(self, lo: float, hi: float):
        self.lo, self.hi = float(lo), float(hi)
        if not self.hi >= self.lo:
            raise ValueError(f"uniform needs hi >= lo, got ({lo}, {hi})")

    def sample(self, rng):
        return float(rng.uniform(self.lo, self.hi))

    def to_wire(self):
        return ["u", self.lo, self.hi]

    @classmethod
    def _from_wire(cls, e):
        return cls(e[1], e[2])

    def __repr__(self):
        return f"u({self.lo}, {self.hi})"


class Gaussian(Dist):
    """Mutable mu/sigma: the curriculum's REINFORCE update writes the
    adapted parameters back in place before re-publishing the space."""

    kind = "g"

    def __init__(self, mu: float, sigma: float):
        self.mu, self.sigma = float(mu), float(sigma)
        if not self.sigma >= 0:
            raise ValueError(f"gaussian needs sigma >= 0, got {sigma}")

    def sample(self, rng):
        return float(rng.normal(self.mu, self.sigma))

    def to_wire(self):
        return ["g", self.mu, self.sigma]

    @classmethod
    def _from_wire(cls, e):
        return cls(e[1], e[2])

    def __repr__(self):
        return f"g({self.mu}, {self.sigma})"


class Choice(Dist):
    """Categorical over arbitrary msgpack-native values (numbers or
    strings), optionally weighted."""

    kind = "c"

    def __init__(self, values, probs=None):
        self.values = list(values)
        if not self.values:
            raise ValueError("categorical needs at least one value")
        if probs is not None:
            probs = [float(p) for p in probs]
            if len(probs) != len(self.values):
                raise ValueError("probs must match values 1:1")
            total = sum(probs)
            if total <= 0:
                raise ValueError("probs must sum > 0")
            probs = [p / total for p in probs]
        self.probs = probs

    def sample(self, rng):
        i = int(rng.choice(len(self.values), p=self.probs))
        return self.values[i]

    def to_wire(self):
        return ["c", list(self.values), self.probs]

    @classmethod
    def _from_wire(cls, e):
        return cls(e[1], e[2] if len(e) > 2 else None)

    def __repr__(self):
        return f"c({self.values})"


class Mixture(Dist):
    """Weighted mixture of component distributions."""

    kind = "m"

    def __init__(self, components, weights=None):
        self.components = list(components)
        if not self.components:
            raise ValueError("mixture needs at least one component")
        if weights is None:
            weights = [1.0] * len(self.components)
        weights = [float(w) for w in weights]
        if len(weights) != len(self.components):
            raise ValueError("weights must match components 1:1")
        total = sum(weights)
        if total <= 0:
            raise ValueError("mixture weights must sum > 0")
        self.weights = [w / total for w in weights]

    def sample(self, rng):
        i = int(rng.choice(len(self.components), p=self.weights))
        return self.components[i].sample(rng)

    def to_wire(self):
        return ["m", [c.to_wire() for c in self.components],
                list(self.weights)]

    @classmethod
    def _from_wire(cls, e):
        return cls([Dist.from_wire(c) for c in e[1]], e[2])

    def __repr__(self):
        return f"m({self.components}, {self.weights})"


class Const(Dist):
    """A fixed value (bare numbers/strings in the grammar)."""

    kind = "k"

    def __init__(self, value):
        self.value = value

    def sample(self, rng):
        return self.value

    def to_wire(self):
        return ["k", self.value]

    @classmethod
    def _from_wire(cls, e):
        return cls(e[1])

    def __repr__(self):
        return f"const({self.value!r})"


_DIST_KINDS = {c.kind: c for c in (Uniform, Gaussian, Choice, Mixture, Const)}


def as_dist(value) -> Dist:
    """Lift a bare number/string to :class:`Const`; pass Dists through."""
    if isinstance(value, Dist):
        return value
    return Const(value)


# ---------------------------------------------------------------------------
# Scenarios and the space
# ---------------------------------------------------------------------------


class Scenario:
    """One named parameter set: ``{param_name: Dist}`` plus a mixture
    weight relative to the other scenarios in the space."""

    def __init__(self, name: str, params: dict, weight: float = 1.0):
        self.name = str(name)
        if not self.name:
            raise ValueError("scenario needs a non-empty name")
        self.params = {str(k): as_dist(v) for k, v in params.items()}
        self.weight = float(weight)
        if not self.weight > 0:
            raise ValueError(f"scenario weight must be > 0, got {weight}")

    def sample(self, rng: np.random.Generator) -> dict:
        return {k: d.sample(rng) for k, d in self.params.items()}

    def gaussian_params(self) -> list:
        """``[(key, Gaussian), ...]`` in declaration order — the
        continuous parameters the curriculum's score-function update
        adapts, and the order ``theta`` vectors are stamped in."""
        return [
            (k, d) for k, d in self.params.items()
            if isinstance(d, Gaussian)
        ]

    def to_wire(self) -> dict:
        return {
            "name": self.name,
            "weight": self.weight,
            "params": {k: d.to_wire() for k, d in self.params.items()},
        }

    @classmethod
    def from_wire(cls, d: dict) -> "Scenario":
        return cls(
            d["name"],
            {k: Dist.from_wire(v) for k, v in d["params"].items()},
            weight=d.get("weight", 1.0),
        )

    def __repr__(self):
        return f"Scenario({self.name!r}, {self.params}, w={self.weight:.3f})"


class ScenarioSpace:
    """Named scenarios + mixture weights + a monotonic version.

    The one object both ends of the duplex protocol share: the consumer
    owns the authoritative copy (and mutates it through the curriculum),
    producers hold the latest acked replica. ``sample(rng)`` draws one
    scenario by the normalized mixture weights, then each of its params,
    returning ``(name, params, theta)`` where ``theta`` lists the drawn
    values of the scenario's Gaussian params in declaration order — the
    score-function update's sample vector, stamped alongside the
    scenario id so the consumer can run REINFORCE without a second
    channel.
    """

    def __init__(self, scenarios, version: int = 1):
        scenarios = list(scenarios)
        if not scenarios:
            raise ValueError("a ScenarioSpace needs at least one scenario")
        self.scenarios = {s.name: s for s in scenarios}
        if len(self.scenarios) != len(scenarios):
            raise ValueError("scenario names must be unique")
        self.version = int(version)

    # -- structure ------------------------------------------------------------

    @property
    def names(self) -> tuple:
        return tuple(self.scenarios)

    def weights(self) -> dict:
        """Normalized mixture weights ``{name: w}`` (sum to 1)."""
        total = sum(s.weight for s in self.scenarios.values())
        return {n: s.weight / total for n, s in self.scenarios.items()}

    def set_weights(self, weights: dict) -> None:
        """Replace mixture weights (un-normalized ok; missing names keep
        their current weight)."""
        for name, w in weights.items():
            if name not in self.scenarios:
                raise KeyError(f"unknown scenario {name!r}")
            if not w > 0:
                raise ValueError(f"weight for {name!r} must be > 0, got {w}")
            self.scenarios[name].weight = float(w)

    def bump(self) -> int:
        """Advance the version (call before re-publishing a mutation)."""
        self.version += 1
        return self.version

    # -- sampling -------------------------------------------------------------

    def sample(self, rng: np.random.Generator):
        """Draw ``(scenario_name, params_dict, theta_list)``."""
        names = list(self.scenarios)
        w = np.asarray(
            [self.scenarios[n].weight for n in names], np.float64
        )
        name = names[int(rng.choice(len(names), p=w / w.sum()))]
        sc = self.scenarios[name]
        params = sc.sample(rng)
        theta = [float(params[k]) for k, _ in sc.gaussian_params()]
        return name, params, theta

    # -- wire form (msgpack-native; decodes under allow_pickle=False) --------

    def to_wire(self) -> dict:
        return {
            "version": self.version,
            "scenarios": [s.to_wire() for s in self.scenarios.values()],
        }

    @classmethod
    def from_wire(cls, d: dict) -> "ScenarioSpace":
        if not isinstance(d, dict) or "scenarios" not in d:
            raise ValueError(f"malformed scenario-space wire form: {d!r}")
        return cls(
            [Scenario.from_wire(s) for s in d["scenarios"]],
            version=int(d.get("version", 1)),
        )

    # -- the grammar ----------------------------------------------------------

    @classmethod
    def parse(cls, spec: str, version: int = 1) -> "ScenarioSpace":
        """Build a space from the compact CLI grammar (module docstring;
        full reference in docs/scenarios.md)."""
        scenarios = []
        # paren-aware like every other level of the grammar: a '/'
        # inside c(...)/m(...) (asset paths as categorical values) must
        # not split the scenario list
        for chunk in _split_top(str(spec), "/"):
            chunk = chunk.strip()
            if not chunk:
                continue
            head, sep, body = chunk.partition(":")
            if not sep:
                raise ValueError(
                    f"scenario {chunk!r} needs 'name:params' (use "
                    "'name:' for a parameter-less scenario)"
                )
            name, _, wtxt = head.strip().partition("*")
            weight = float(wtxt) if wtxt else 1.0
            params = {}
            for kv in _split_top(body, ","):
                if not kv.strip():
                    continue
                key, eq, val = kv.partition("=")
                if not eq:
                    raise ValueError(
                        f"param {kv!r} in scenario {name!r} needs key=value"
                    )
                params[key.strip()] = _parse_dist(val.strip())
            scenarios.append(Scenario(name.strip(), params, weight=weight))
        if not scenarios:
            raise ValueError(f"empty scenario spec {spec!r}")
        return cls(scenarios, version=version)

    def __repr__(self):
        return (
            f"ScenarioSpace(v{self.version}, "
            f"{list(self.scenarios.values())})"
        )


def _split_top(text: str, sep: str) -> list:
    """Split on ``sep`` outside parentheses (param lists contain commas
    inside ``u(...)``/``g(...)`` calls)."""
    out, depth, cur = [], 0, []
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == sep and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def _entry_weights(entries) -> list | None:
    """``@w`` weights of split ``value[@w]`` entries: None when no
    entry is weighted, else a full weight vector with UNWEIGHTED
    entries defaulting to 1.0 — a mixed spec like ``c(a@0.9|b)`` must
    honor the weights it names, not silently fall back to uniform."""
    if not any(len(e) > 1 for e in entries):
        return None
    return [float(e[1]) if len(e) > 1 else 1.0 for e in entries]


def _parse_scalar(txt: str):
    txt = txt.strip()
    try:
        f = float(txt)
    except ValueError:
        return txt  # bare string (categorical value)
    return int(f) if f.is_integer() and "." not in txt and "e" not in txt.lower() else f


def _parse_dist(txt: str) -> Dist:
    txt = txt.strip()
    if "(" in txt and txt.endswith(")"):
        kind, _, inner = txt.partition("(")
        inner = inner[:-1]
        kind = kind.strip()
        if kind == "u":
            lo, hi = (float(p) for p in _split_top(inner, ","))
            return Uniform(lo, hi)
        if kind == "g":
            mu, sigma = (float(p) for p in _split_top(inner, ","))
            return Gaussian(mu, sigma)
        if kind == "c":
            entries = [_split_top(e, "@") for e in _split_top(inner, "|")]
            values = [_parse_scalar(e[0]) for e in entries]
            return Choice(values, _entry_weights(entries))
        if kind == "m":
            entries = [_split_top(e, "@") for e in _split_top(inner, "|")]
            comps = [_parse_dist(e[0]) for e in entries]
            return Mixture(comps, _entry_weights(entries))
        raise ValueError(f"unknown distribution {txt!r} (u/g/c/m)")
    return Const(_parse_scalar(txt))


__all__ = [
    "Dist", "Uniform", "Gaussian", "Choice", "Mixture", "Const",
    "as_dist", "Scenario", "ScenarioSpace",
]
