"""Hermetic test doubles for the Blender-facing surface.

The reference's entire test suite needs a real Blender install
(``/root/reference/.travis.yml:15-24``, ``scripts/install_blender.sh``);
blendjax additionally ships a faithful in-process stand-in so the
``bpy``/``gpu``-dependent half of the producer package — and any user's
``*.blend.py`` producer script — executes in plain CPython:

- :func:`install_fake_bpy` registers stub ``bpy``/``gpu`` modules
  (``fake_bpy``/``fake_gpu``) implementing exactly the API surface
  blendjax's Blender integration uses: scene/object/camera graph,
  evaluated-depsgraph queries, frame-change + draw handlers, offscreen
  render readback, AABB ray casts.
- ``python -m blendjax.testing.fake_blender`` emulates the Blender CLI
  (``--version``, ``--background``, ``--python``, ``--python-expr``) on
  top of those stubs, and :func:`write_fake_blender` drops a ``blender``
  wrapper onto a directory so ``discover_blender`` and the production
  :class:`~blendjax.launcher.launcher.BlenderLauncher` drive it through
  the exact real-Blender code path.

The real-Blender tier (``pytest -m blender``) remains the ground truth;
this tier is what keeps those code paths executed in every CI run.

:mod:`blendjax.testing.donation` is the odd one out: not a Blender
double but a runtime audit helper — it tracks device buffer pointers
across the feeder -> reservoir insert -> fused draw/step chain to
prove donation reuses buffers in place (imported lazily below: it
needs jax, which the Blender-side doubles must never pull in).
"""

from blendjax.testing.fake_blender import write_fake_blender
from blendjax.testing.fake_bpy import install as install_fake_bpy
from blendjax.testing.fake_bpy import reset as reset_fake_bpy

__all__ = [
    "install_fake_bpy",
    "reset_fake_bpy",
    "write_fake_blender",
    "DonationAudit",
]


def __getattr__(name):
    # lazy: the donation audit imports jax, and producer-side users of
    # this package (fake bpy/gpu, the blender CLI emulator) must stay
    # importable in Blender's Python where jax does not exist
    if name == "DonationAudit":
        from blendjax.testing.donation import DonationAudit

        return DonationAudit
    raise AttributeError(name)
