"""Runtime donation audit: prove the hot path's device buffers are
REUSED in place, never silently copied.

Three donation contracts keep the live loop's device memory flat and
its dispatch path zero-copy, and all three are invisible to ordinary
tests until they regress as a 2x memory footprint or a per-step
realloc stall:

- the reservoir ring (``blendjax.data.echo.SampleReservoir``) is
  allocated once and every ``insert`` scatter updates it in place
  (donated buffer args) — its per-field device pointers never change;
- the fused echo draw (``blendjax.train.make_echo_fused_step``) reads
  the ring as a NON-donated argument — drawing must not move or copy
  the (potentially multi-GB) ring either;
- the donated train step writes the updated state back into the SAME
  buffers it consumed (``donate_argnums=(0,)`` + matching in/out
  layouts), so params/optimizer memory is one copy for the whole run.

:class:`DonationAudit` tracks ``unsafe_buffer_pointer()`` snapshots
per labeled pytree across the feeder -> reservoir insert -> fused
draw/step chain and asserts pointer stability; the bench's driver rows
surface the same check as the ``train.donation_reuse`` gauge
(docs/observability.md) so a donation regression shows up in the
record, not just in a test run.

Pointer reads are host-side metadata (no device sync); arrays whose
backend can't expose a pointer audit as ``None`` and are skipped
rather than failed, so the helper degrades gracefully off
CPU/TPU-local runtimes.
"""

from __future__ import annotations

import jax


def _leaf_pointer(leaf):
    """One leaf's buffer identity: the flat pointer for single-device
    arrays, a ``((device_id, pointer), ...)`` tuple per addressable
    shard for sharded ones (``unsafe_buffer_pointer`` itself raises on
    sharded arrays — without the per-shard read, a mesh-path audit
    would see nothing and report vacuous success). ``None`` when the
    runtime exposes neither."""
    get = getattr(leaf, "unsafe_buffer_pointer", None)
    if get is not None:
        try:
            return int(get())
        except Exception:
            pass
    shards = getattr(leaf, "addressable_shards", None)
    if shards is not None:
        try:
            return tuple(
                (s.device.id, int(s.data.unsafe_buffer_pointer()))
                for s in shards
            )
        except Exception:
            pass
    return None


def tree_pointers(tree) -> dict:
    """``{leaf path: buffer identity}`` for every array leaf of
    ``tree`` (:func:`_leaf_pointer`; ``None`` where the runtime can't
    expose one). Host metadata only — reading a pointer never syncs
    the device."""
    return {
        jax.tree_util.keystr(path): _leaf_pointer(leaf)
        for path, leaf in jax.tree_util.tree_leaves_with_path(tree)
    }


def pointers_stable(before: dict, after: dict) -> bool:
    """True when every leaf whose pointer is known on BOTH sides kept
    it — the in-place-update contract (donated scatter, donated step,
    non-donated fused read). Requires at least one leaf actually
    compared: a tree the runtime can't introspect at all (every
    pointer ``None``) is NOT evidence of reuse and reads unstable, the
    same rule as the empty-tree case."""
    keys = set(before) & set(after)
    compared = [
        k for k in keys
        if before[k] is not None and after[k] is not None
    ]
    if not compared:
        return False  # nothing auditable is not evidence of reuse
    return all(before[k] == after[k] for k in compared)


class DonationAudit:
    """Labeled pointer snapshots across a run.

    >>> audit = DonationAudit()
    >>> audit.snapshot("ring", reservoir._buffers)
    >>> ...  # inserts, fused draws/steps
    >>> audit.snapshot("ring", reservoir._buffers)
    >>> audit.stable("ring")
    True

    ``report()`` summarizes every label (snapshot count, distinct
    pointer sets, stability verdict) — the dict the bench embeds
    beside the ``train.donation_reuse`` gauge. ``assert_stable()``
    raises with the offending leaves named, for test use."""

    def __init__(self) -> None:
        self._snaps: dict[str, list[dict]] = {}

    def snapshot(self, label: str, tree) -> dict:
        ptrs = tree_pointers(tree)
        self._snaps.setdefault(label, []).append(ptrs)
        return ptrs

    def stable(self, label: str) -> bool:
        snaps = self._snaps.get(label, [])
        if len(snaps) < 2:
            return False  # one snapshot proves nothing
        return all(
            pointers_stable(snaps[0], later) for later in snaps[1:]
        )

    def assert_stable(self, label: str) -> None:
        snaps = self._snaps.get(label, [])
        if len(snaps) < 2:
            raise AssertionError(
                f"donation audit {label!r}: need >= 2 snapshots, "
                f"have {len(snaps)}"
            )
        first = snaps[0]
        for i, later in enumerate(snaps[1:], start=1):
            compared = [
                k for k in set(first) & set(later)
                if first[k] is not None and later[k] is not None
            ]
            if not compared:
                # same rule as pointers_stable: an un-introspectable
                # tree must FAIL the audit, not pass it vacuously
                raise AssertionError(
                    f"donation audit {label!r}: no leaf exposed a "
                    f"buffer pointer between snapshot 0 and {i} — "
                    "reuse is unverifiable on this runtime, which is "
                    "not evidence of reuse"
                )
            moved = sorted(
                k for k in compared if first[k] != later[k]
            )
            if moved:
                raise AssertionError(
                    f"donation audit {label!r}: buffers moved between "
                    f"snapshot 0 and {i} (copied, not reused): {moved}"
                )

    def report(self) -> dict:
        out: dict = {}
        for label, snaps in self._snaps.items():
            distinct = len({
                tuple(sorted(s.items())) for s in snaps
            })
            out[label] = {
                "snapshots": len(snaps),
                "distinct_pointer_sets": distinct,
                "stable": self.stable(label),
            }
        return out


__all__ = ["DonationAudit", "pointers_stable", "tree_pointers"]
