"""Shared numeric-equivalence scaffold for sharded parallelism modes.

One implementation of the contract "a sharded step reproduces the
single-device run of the identical model/batch", used by BOTH the test
suite (``tests/test_equivalence.py``) and the driver dry run
(``__graft_entry__.dryrun_multichip``) so the two can never assert
different tolerances. Finiteness alone would pass a wrong-math sharding
rule with a plausible loss; these gates are the self-made ground truth
net-new parallel code needs (SURVEY.md §2.4 implication b).
"""

from __future__ import annotations

import numpy as np


def normalized_spec(sharding) -> tuple:
    """A sharding's PartitionSpec as a plain comparable tuple: some jax
    releases canonicalize spec entries to 1-tuples, so ``P(None,
    'data')`` arrives as ``P(None, ('data',))`` — assertions comparing
    layouts go through this ONE normalizer (the dryrun and the test
    suite must not drift on the next canonicalization quirk)."""
    return tuple(
        e[0] if isinstance(e, tuple) and len(e) == 1 else e
        for e in tuple(getattr(sharding, "spec", sharding))
    )


def max_tree_diff(a, b) -> float:
    """Max abs elementwise difference across two equal-structure trees."""
    import jax

    diffs = jax.tree_util.tree_map(
        lambda x, y: float(np.max(np.abs(np.asarray(x) - np.asarray(y)))),
        a, b,
    )
    return max(jax.tree_util.tree_leaves(diffs))


def loss_and_grads(model, params, images, xy, sharding=None):
    """Corner-MSE loss value + grads for ``model`` on one batch; with
    ``sharding`` the batch is placed on the mesh first (params carry
    their own layouts)."""
    import jax
    import jax.numpy as jnp

    if sharding is not None:
        images = jax.device_put(images, sharding)
        xy = jax.device_put(xy, sharding)

    @jax.jit
    def lg(p):
        def loss(p):
            pred = model.apply({"params": p}, images)
            return jnp.mean((pred.reshape(-1, 8, 2) - xy) ** 2)

        return jax.value_and_grad(loss)(p)

    loss, grads = lg(params)
    return float(loss), jax.tree_util.tree_map(np.asarray, grads)


def assert_sharded_matches_single_device(
    sharded_model,
    single_model,
    mesh,
    images,
    xy,
    tol_loss: float = 1e-5,
    tol_grad: float = 1e-4,
):
    """Same init key -> identical params; assert the sharded model's
    loss/grads match the single-device model's within RELATIVE
    tolerances (collective/reduction reorders shift the last float32
    bits of a ~1e2-magnitude loss; wrong sharding math is orders of
    magnitude away). Returns ``(loss_diff, max_grad_diff)``."""
    import jax

    from blendjax.parallel import batch_sharding
    from blendjax.train import make_train_state

    ref_state = make_train_state(single_model, images)
    sh_state = make_train_state(sharded_model, images, mesh=mesh)
    assert max_tree_diff(ref_state.params, sh_state.params) == 0.0, (
        "ref/sharded init diverged — models are not identical"
    )

    ref_loss, ref_grads = loss_and_grads(
        single_model, ref_state.params, images, xy
    )
    sh_loss, sh_grads = loss_and_grads(
        sharded_model, sh_state.params, images, xy,
        sharding=batch_sharding(mesh),
    )
    loss_diff = abs(sh_loss - ref_loss)
    assert loss_diff < tol_loss * max(1.0, abs(ref_loss)), (
        sh_loss, ref_loss,
    )
    grad_diff = max_tree_diff(ref_grads, sh_grads)
    grad_scale = max(
        float(np.max(np.abs(g)))
        for g in jax.tree_util.tree_leaves(ref_grads)
    )
    assert grad_diff < tol_grad * max(1.0, grad_scale), (
        f"max grad diff {grad_diff} (grad scale {grad_scale})"
    )
    return loss_diff, grad_diff


def moe_per_token_reference(params, x) -> np.ndarray:
    """Dense per-token reference for MoE top-1 routing with ample
    capacity: each token goes through its argmax expert's MLP alone,
    scaled by the gate probability (float32; no capacity drops)."""
    import jax
    import jax.numpy as jnp

    c = x.shape[-1]
    tokens = np.asarray(x, np.float32).reshape(-1, c)
    logits = tokens @ np.asarray(params["router"]["kernel"]) + np.asarray(
        params["router"]["bias"]
    )
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    idx = probs.argmax(-1)
    gate = probs.max(-1)
    # Gather each token's expert weights, then ONE batched pass (a
    # per-token Python loop would pay one device dispatch per token —
    # seconds of pure latency on tunneled backends).
    w1 = np.asarray(params["expert_wi"])[idx]   # (N, C, H)
    b1 = np.asarray(params["expert_bi"])[idx]   # (N, H)
    w2 = np.asarray(params["expert_wo"])[idx]   # (N, H, C)
    b2 = np.asarray(params["expert_bo"])[idx]   # (N, C)
    hidden = np.einsum("nc,nch->nh", tokens, w1) + b1
    hidden = np.asarray(jax.nn.gelu(jnp.asarray(hidden)))
    out = np.einsum("nh,nhc->nc", hidden, w2) + b2
    out = gate[:, None] * out
    return out.reshape(np.asarray(x).shape)
