"""Emulate the Blender CLI on top of the fake ``bpy``/``gpu`` stubs.

``python -m blendjax.testing.fake_blender`` accepts the exact argument
shapes blendjax's launcher/finder produce (reference command shape,
``pkg_pytorch/blendtorch/btt/launcher.py:137-161`` and
``btt/finder.py:44-69``):

- ``--version``                       -> a parseable "Blender X.Y.Z" line
- ``--background``                    -> build the windowless context
  (``find_first_view3d`` fails there, like real Blender)
- ``--python-expr EXPR``              -> exec EXPR (the finder's zmq/msgpack
  smoke test runs in THIS interpreter's env)
- ``[scene.blend] --python SCRIPT -- ARGS`` -> run SCRIPT with the fake
  runtime installed and ``sys.argv`` set Blender-style (full argv, the
  script splits at ``--`` via ``parse_launch_args``)

:func:`write_fake_blender` drops an executable ``blender`` wrapper into a
directory, so ``discover_blender(additional_blender_paths=[dir])`` and the
production ``BlenderLauncher`` exercise their real subprocess paths
against the stub.
"""

from __future__ import annotations

import os
import runpy
import stat
import sys

VERSION = "3.6.5"


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if "--version" in args:
        print(f"Blender {VERSION} (blendjax fake-bpy stub)")
        return 0
    background = "--background" in args
    expr = script = None
    if "--python-expr" in args:
        expr = args[args.index("--python-expr") + 1]
    if "--python" in args:
        script = args[args.index("--python") + 1]

    from blendjax.testing import fake_bpy

    # A real `blender` launch without a .blend opens the stock startup
    # scene (Cube/Camera/Light) — scene scripts rely on it.
    fake_bpy.install(background=background, default_scene=True)
    if expr is not None:
        exec(compile(expr, "<python-expr>", "exec"), {"__name__": "__main__"})
    if script is not None:
        # Blender hands scripts its FULL argv; producer scripts split at
        # '--' (``blendjax/launcher/arguments.py:49-50``).
        sys.argv = ["blender"] + args
        runpy.run_path(script, run_name="__main__")
    return 0


def write_fake_blender(directory: str) -> str:
    """Write an executable ``blender`` wrapper into ``directory`` and
    return its path. The wrapper pins this interpreter and makes the
    package importable regardless of the caller's install mode."""
    os.makedirs(directory, exist_ok=True)
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    path = os.path.join(directory, "blender")
    # -c instead of -m: the package __init__ already imports this module,
    # and runpy would warn about re-executing a cached submodule.
    cmd = ("from blendjax.testing import fake_blender; "
           "import sys; sys.exit(fake_blender.main())")
    with open(path, "w") as f:
        f.write(
            "#!/bin/sh\n"
            f'PYTHONPATH="{pkg_root}${{PYTHONPATH:+:$PYTHONPATH}}" '
            f'exec "{sys.executable}" -c "{cmd}" "$@"\n'
        )
    os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR | stat.S_IXGRP
             | stat.S_IXOTH)
    return path


if __name__ == "__main__":
    sys.exit(main())
