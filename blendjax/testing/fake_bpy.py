"""A faithful, minimal ``bpy`` stand-in for hermetic producer tests.

Implements the exact API surface used by ``blendjax.producer.bpy_engine``,
``blendjax.producer.offscreen``, and the ``tests/blender/*.blend.py``
fixtures (which mirror the reference's fixtures,
``/root/reference/tests/blender/``). Semantics are pinned to the
**Blender 3.6 LTS** API — the version the opt-in real-Blender tier
installs (``scripts/install_blender.sh``) and the ground truth this
stub is certified against; the member-by-member conformance table
(documented behavior -> fake behavior -> known deviation) lives in
``docs/architecture.md`` "Fake-bpy conformance". Highlights:

- objects carry LOCAL mesh data; world placement lives in
  ``matrix_world`` composed from ``location`` + XYZ ``rotation_euler``,
- ``scene.frame_set`` fires ``frame_change_pre``/``frame_change_post``
  app handlers with ``(scene, depsgraph)``,
- ``ops.screen.animation_play`` drives the frame clock and the
  registered ``SpaceView3D`` draw handlers. Real Blender returns to its
  event loop; the stub plays SYNCHRONOUSLY until
  ``animation_cancel`` — the one documented deviation, chosen so the
  UI-mode code path (``BpyAnimationDriver``) is drivable from a plain
  test function,
- ``scene.ray_cast`` intersects world-space AABBs of scene meshes (an
  occluder between object and camera registers; the queried object's
  own box is skipped the way the 1e-4 surface offset does in Blender).

Use :func:`install` to register ``bpy``/``gpu`` into ``sys.modules``
(idempotent), :func:`reset` for a fresh scene between tests.
"""

from __future__ import annotations

import math
import sys
import types

import numpy as np

_MAX_PLAY_TICKS = 1_000_000  # hung-test guard for the synchronous clock


# -- math types -------------------------------------------------------------


class Matrix:
    """4x4 matrix with the slice of mathutils.Matrix blendjax touches:
    ``np.asarray(m)``, row iteration, ``inverted()``."""

    def __init__(self, values):
        self._m = np.asarray(values, dtype=np.float64).reshape(4, 4)

    def __array__(self, dtype=None, copy=None):
        return self._m.astype(dtype) if dtype is not None else self._m

    def __iter__(self):
        return iter(self._m.tolist())

    def __getitem__(self, i):
        return self._m.tolist()[i]

    def inverted(self) -> "Matrix":
        return Matrix(np.linalg.inv(self._m))

    @property
    def translation(self) -> np.ndarray:
        return self._m[:3, 3]

    def to_euler(self, order: str = "XYZ") -> Euler:
        """XYZ euler extraction for M = Rz @ Ry @ Rx (Blender's default
        order; scale assumed uniform-positive for the surface we fake)."""
        assert order == "XYZ", f"unsupported euler order {order!r}"
        r = self._m[:3, :3]
        # strip scale (columns are basis vectors times per-axis scale)
        norms = np.linalg.norm(r, axis=0)
        r = r / np.where(norms > 1e-12, norms, 1.0)
        y = math.asin(np.clip(-r[2, 0], -1.0, 1.0))
        x = math.atan2(r[2, 1], r[2, 2])
        z = math.atan2(r[1, 0], r[0, 0])
        return Euler((x, y, z))

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Matrix({self._m.tolist()!r})"


class Euler(list):
    """Mutable XYZ euler triple (``obj.rotation_euler[2] = ...``)."""

    def __init__(self, xyz=(0.0, 0.0, 0.0)):
        super().__init__(float(v) for v in xyz)

    def to_matrix3(self) -> np.ndarray:
        x, y, z = self
        cx, sx = math.cos(x), math.sin(x)
        cy, sy = math.cos(y), math.sin(y)
        cz, sz = math.cos(z), math.sin(z)
        rx = np.array([[1, 0, 0], [0, cx, -sx], [0, sx, cx]])
        ry = np.array([[cy, 0, sy], [0, 1, 0], [-sy, 0, cy]])
        rz = np.array([[cz, -sz, 0], [sz, cz, 0], [0, 0, 1]])
        return rz @ ry @ rx  # Blender XYZ order: X applied first


# -- data-block types -------------------------------------------------------


class FakeVertex:
    __slots__ = ("co",)

    def __init__(self, co):
        self.co = np.asarray(co, dtype=np.float64)


class FakeVertices(list):
    def foreach_get(self, attr: str, flat) -> None:
        assert attr == "co", f"unsupported vertex attr {attr!r}"
        out = np.asarray(flat)
        out[:] = np.concatenate([v.co for v in self]) if self else out[:0]

    def foreach_set(self, attr: str, flat) -> None:
        assert attr == "co", f"unsupported vertex attr {attr!r}"
        co = np.asarray(flat, dtype=np.float64).reshape(len(self), 3)
        for v, c in zip(self, co):
            v.co = c.copy()


class FakeMesh:
    def __init__(self, name: str, verts=()):
        self.name = name
        self.vertices = FakeVertices(FakeVertex(v) for v in verts)
        self.polygons: list = []
        self.materials: list = []  # supports .append like bpy's slots

    def from_pydata(self, verts, edges, faces) -> None:
        """Geometry-from-arrays (used by procedural scene scripts, e.g.
        the supershape example)."""
        del edges
        # slice-assign: self.vertices' identity carries foreach_* support
        self.vertices[:] = (FakeVertex(v) for v in verts)
        self.polygons = [tuple(f) for f in faces]

    def update(self) -> None:  # recalc normals etc. — nothing cached here
        pass


class FakeMaterial:
    def __init__(self, name: str):
        self.name = name
        self.diffuse_color = (0.8, 0.8, 0.8, 1.0)


class FakeRigidBody:
    """``obj.rigid_body`` surface (``bpy.types.RigidBodyObject``)."""

    def __init__(self, type: str = "ACTIVE"):
        self.type = type
        self.mass = 1.0
        self.kinematic = False


class FakeRigidBodyConstraint:
    """``obj.rigid_body_constraint`` surface — the slider/motor and
    hinge parameters the cartpole rig drives."""

    def __init__(self, type: str):
        self.type = type
        self.object1 = None
        self.object2 = None
        self.enabled = True
        self.use_motor_lin = False
        self.motor_lin_max_impulse = 0.0
        self.motor_lin_target_velocity = 0.0
        # pinned at creation by the simulator (see _physics_step)
        self._pin = None
        self._hinge_arm = None
        self._prev_v = 0.0
        self._theta = None
        self._omega = 0.0


class FakeCameraData:
    """Mirrors ``bpy.types.Camera`` defaults (lens 50mm, 36mm sensor)."""

    def __init__(self, name: str):
        self.name = name
        self.type = "PERSP"
        self.lens = 50.0
        self.sensor_width = 36.0
        self.clip_start = 0.1
        self.clip_end = 1000.0
        self.ortho_scale = 6.0


class FakeObject:
    def __init__(self, name: str, data=None):
        self.name = name
        self.data = data
        self._location = np.zeros(3)
        self._rotation = Euler()
        self._scale = np.ones(3)
        self.rigid_body = None
        self.rigid_body_constraint = None
        self.active_material = None

    # location / rotation are assignable as tuples, mutable per-component
    @property
    def location(self):
        return self._location

    @location.setter
    def location(self, value):
        self._location = np.asarray(value, dtype=np.float64).copy()

    @property
    def rotation_euler(self):
        return self._rotation

    @rotation_euler.setter
    def rotation_euler(self, value):
        self._rotation = Euler(value)

    @property
    def scale(self):
        return self._scale

    @scale.setter
    def scale(self, value):
        self._scale = np.asarray(value, dtype=np.float64).copy()

    @property
    def matrix_world(self) -> Matrix:
        m = np.eye(4)
        m[:3, :3] = self._rotation.to_matrix3() @ np.diag(self._scale)
        m[:3, 3] = self._location
        return Matrix(m)

    # evaluated-depsgraph protocol: no modifiers/physics in the stub, so
    # the evaluated object IS the object (reference reads geometry through
    # this path, ``utils.py:30-109``)
    def evaluated_get(self, _depsgraph) -> "FakeObject":
        return self

    def to_mesh(self) -> FakeMesh:
        assert isinstance(self.data, FakeMesh), f"{self.name} has no mesh"
        return self.data

    def to_mesh_clear(self) -> None:
        pass

    @property
    def bound_box(self):
        """8 LOCAL-space corners (Blender convention: local, not world)."""
        verts = np.stack([v.co for v in self.to_mesh().vertices])
        lo, hi = verts.min(0), verts.max(0)
        return [
            [x, y, z] for x in (lo[0], hi[0]) for y in (lo[1], hi[1])
            for z in (lo[2], hi[2])
        ]

    # camera-object protocol (offscreen.py:70-75)
    def calc_matrix_camera(self, _depsgraph, x: int = 1, y: int = 1) -> Matrix:
        cam = self.data
        aspect = y / x
        if cam.type == "ORTHO":
            half_w = cam.ortho_scale / 2.0
            half_h = half_w * aspect
            n, f = cam.clip_start, cam.clip_end
            m = np.diag([1.0 / half_w, 1.0 / half_h, -2.0 / (f - n), 1.0])
            m[2, 3] = -(f + n) / (f - n)
            return Matrix(m)
        n, f = cam.clip_start, cam.clip_end
        half_w = n * (cam.sensor_width / 2.0) / cam.lens
        half_h = half_w * aspect
        m = np.zeros((4, 4))
        m[0, 0] = n / half_w
        m[1, 1] = n / half_h
        m[2, 2] = -(f + n) / (f - n)
        m[2, 3] = -2.0 * f * n / (f - n)
        m[3, 2] = -1.0
        return Matrix(m)


class FakeCollection:
    """Name-keyed data-block collection (``bpy.data.objects`` et al.)."""

    def __init__(self, factory=None):
        self._items: list = []
        self._factory = factory

    def new(self, name: str, data=None):
        assert self._factory is not None, "collection is not creatable"
        item = self._factory(name) if data is None else self._factory(
            name, data
        )
        self._items.append(item)
        return item

    def _append(self, item):
        self._items.append(item)

    def __contains__(self, name: str) -> bool:
        return any(i.name == name for i in self._items)

    def __getitem__(self, name: str):
        for i in self._items:
            if i.name == name:
                return i
        raise KeyError(name)

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self):
        return iter(list(self._items))


# -- scene / context --------------------------------------------------------


class FakeRender:
    def __init__(self):
        self.resolution_x = 1920
        self.resolution_y = 1080
        self.resolution_percentage = 100
        self.fps = 24  # Blender default


class FakeSceneObjects:
    """``scene.collection.objects`` — linking makes an object part of the
    scene (drawn, ray-castable)."""

    def __init__(self, scene):
        self._scene = scene

    def link(self, obj: FakeObject) -> None:
        if obj not in self._scene.objects:
            self._scene.objects.append(obj)


class FakeSceneCollection:
    def __init__(self, scene):
        self.objects = FakeSceneObjects(scene)


class FakeScene:
    def __init__(self, bpy_mod):
        self._bpy = bpy_mod
        self.name = "Scene"
        self.frame_start = 1
        self.frame_end = 250
        self.frame_current = 1
        self.render = FakeRender()
        self.camera: FakeObject | None = None
        self.rigidbody_world = None  # set by ops.rigidbody.world_add
        self.objects: list[FakeObject] = []
        self.collection = FakeSceneCollection(self)
        self._phys_frame = 1
        self._vel: dict = {}  # id(obj) -> velocity (free ACTIVE bodies)

    def frame_set(self, frame: int) -> None:
        frame = int(frame)
        # frame_current updates BEFORE the pre handlers fire (handlers
        # read scene.frame_current — the UI driver's dedup relies on it)
        self.frame_current = frame
        dg = self._bpy.context.evaluated_depsgraph_get()
        for h in list(self._bpy.app.handlers.frame_change_pre):
            h(self, dg)
        rb = self.rigidbody_world
        if rb is not None and getattr(rb, "enabled", False):
            # Blender order: pre handlers, then the scene (physics)
            # evaluates for the new frame, then post handlers. Rewinds
            # restart the sim from the cached start state (velocities
            # zeroed; positions are whatever the script set).
            df = frame - self._phys_frame
            if df > 0:
                if df > 10_000:  # loud, not a silent truncation
                    raise RuntimeError(
                        f"fake physics: frame jump of {df} exceeds the "
                        "10k-step guard — seek in smaller increments"
                    )
                dt = 1.0 / self.render.fps
                for _ in range(df):
                    _physics_step(self, dt)
            elif df < 0:
                # Rewind restarts the sim from the cached start state
                # (velocities zeroed); df == 0 is a plain re-evaluation
                # (the common frame_set(frame_current) idiom) and keeps
                # all dynamic state, like real Blender.
                self._vel.clear()
                for obj in self.objects:
                    rc = obj.rigid_body_constraint
                    if rc is not None:
                        rc._prev_v = 0.0
                        rc._omega = 0.0
                        rc._theta = None
        self._phys_frame = frame
        for h in list(self._bpy.app.handlers.frame_change_post):
            h(self, dg)

    def ray_cast(self, _depsgraph, origin, direction,
                 distance: float = 1.70141e38):
        """Slab-method ray vs world AABB of every scene mesh. Boxes the
        origin sits inside are skipped (mirrors the surface-offset idiom
        rays cast FROM an object use, ``bpy_engine.py:204``)."""
        o = np.asarray(origin, dtype=np.float64)
        d = np.asarray(direction, dtype=np.float64)
        best_t, best_obj = None, None
        for obj in self.objects:
            if not isinstance(obj.data, FakeMesh):
                continue
            corners = np.asarray(obj.bound_box, dtype=np.float64)
            mw = np.asarray(obj.matrix_world)
            world = corners @ mw[:3, :3].T + mw[:3, 3]
            lo, hi = world.min(0), world.max(0)
            with np.errstate(divide="ignore", invalid="ignore"):
                t1 = (lo - o) / d
                t2 = (hi - o) / d
            tmin = np.nanmax(np.minimum(t1, t2))
            tmax = np.nanmin(np.maximum(t1, t2))
            if not np.isfinite(tmin) or tmax < tmin:
                continue
            if tmin <= 1e-9:  # origin inside/on the box: skip (see above)
                continue
            if tmin <= distance and (best_t is None or tmin < best_t):
                best_t, best_obj = tmin, obj
        if best_obj is None:
            return (False, None, None, -1, None, None)
        return (
            True, tuple(o + best_t * d), (0.0, 0.0, 1.0), 0, best_obj,
            best_obj.matrix_world,
        )


_GRAVITY = 9.81


def _half_extent_z(obj) -> float:
    if not isinstance(obj.data, FakeMesh) or not obj.data.vertices:
        return 0.0
    zs = np.array([v.co[2] for v in obj.data.vertices])
    return float((zs.max() - zs.min()) / 2.0 * obj._scale[2])


def _physics_step(scene, dt: float) -> None:
    """One fixed step of the miniature rigid-body world.

    Deliberately simple but honest dynamics (documented approximation,
    NOT Bullet): gravity + rest-on-passive-plane for free ACTIVE bodies
    (no body-body collision, no tumbling), a SLIDER constraint pinning
    its object to x-translation with a linear motor, and a HINGE
    modeled as a pendulum about y driven by gravity and the carrier's
    acceleration — the classic cart-pole linkage. Enough for the
    example physics scenes to exhibit their qualitative behavior
    (cubes fall and come to rest; an uninverted pole stays down; an
    inverted pole diverges and the cart responds to motor commands)."""
    objs = scene.objects
    plane_z = None
    for o in objs:
        if o.rigid_body is not None and o.rigid_body.type == "PASSIVE":
            top = o._location[2] + _half_extent_z(o)
            plane_z = top if plane_z is None else max(plane_z, top)

    constrained: set = set()
    sliders = []
    hinges = []
    for o in objs:
        rc = o.rigid_body_constraint
        if rc is None or not rc.enabled:
            continue
        if rc.type == "SLIDER" and rc.object2 is not None:
            sliders.append((o, rc))
            constrained.add(id(rc.object2))
        elif rc.type == "HINGE" and rc.object2 is not None:
            hinges.append((o, rc))
            constrained.add(id(rc.object2))

    # free ACTIVE bodies: gravity + rest on the highest passive plane
    for o in objs:
        rb = o.rigid_body
        if (
            rb is None or rb.type != "ACTIVE" or rb.kinematic
            or id(o) in constrained
        ):
            continue
        v = scene._vel.setdefault(id(o), np.zeros(3))
        v[2] -= _GRAVITY * dt
        o._location += v * dt
        if plane_z is not None:
            rest = plane_z + _half_extent_z(o)
            if o._location[2] < rest:
                o._location[2] = rest
                v[:] = 0.0  # land and rest (no bounce/tumble)

    # sliders: x-translation only, linear motor sets velocity
    for holder, rc in sliders:
        body = rc.object2
        if rc._pin is None:
            rc._pin = (float(body._location[1]), float(body._location[2]))
        v = rc.motor_lin_target_velocity if rc.use_motor_lin else rc._prev_v
        rc._accel = (v - rc._prev_v) / dt
        rc._prev_v = v
        body._location[0] += v * dt
        body._location[1], body._location[2] = rc._pin

    # hinges: pendulum about y at the holder's anchor on the carrier
    for holder, rc in hinges:
        pole, cart = rc.object2, rc.object1
        if rc._hinge_arm is None:
            anchor = holder._location.copy()
            rc._anchor_off = (
                anchor - (cart._location if cart is not None else 0.0)
            )
            arm = pole._location - anchor
            rc._hinge_arm = float(np.linalg.norm(arm)) or 1e-6
            rc._theta = float(pole._rotation[1])
        if rc._theta is None:
            rc._theta = float(pole._rotation[1])
        # carrier acceleration couples in through the pivot (the slider
        # constraint lives on its holder empty, keyed by object2)
        a_cart = 0.0
        if cart is not None:
            for _, src in sliders:
                if src.object2 is cart:
                    a_cart = getattr(src, "_accel", 0.0)
        L = rc._hinge_arm
        th = rc._theta
        rc._omega += (
            (_GRAVITY * math.sin(th) - a_cart * math.cos(th)) / L
        ) * dt
        rc._theta = th + rc._omega * dt
        pole._rotation[1] = rc._theta
        anchor = (
            cart._location + rc._anchor_off
            if cart is not None else rc._anchor_off
        )
        # in place: obj.location references must keep tracking the body
        pole._location[:] = anchor + np.array(
            [L * math.sin(rc._theta), 0.0, L * math.cos(rc._theta)]
        )


class FakeViewLayer:
    def __init__(self):
        self.objects = types.SimpleNamespace(active=None)

    def update(self) -> None:  # matrices recompute lazily; nothing cached
        pass


class FakeDepsgraph:
    pass


# -- UI graph (windows / areas / spaces / draw handlers) --------------------


class FakeShading:
    def __init__(self):
        self.type = "SOLID"


class FakeOverlay:
    def __init__(self):
        self.show_overlays = True


class FakeSpaceView3D:
    type = "VIEW_3D"

    def __init__(self):
        self.shading = FakeShading()
        self.overlay = FakeOverlay()
        self._draw_handlers: dict = {}
        self._next_handle = 0

    def draw_handler_add(self, cb, args, region_type: str, draw_type: str):
        assert region_type == "WINDOW" and draw_type == "POST_PIXEL", (
            "stub supports the POST_PIXEL/WINDOW handlers blendjax uses"
        )
        handle = self._next_handle
        self._next_handle += 1
        self._draw_handlers[handle] = (cb, tuple(args))
        return handle

    def draw_handler_remove(self, handle, region_type: str) -> None:
        assert region_type == "WINDOW"
        self._draw_handlers.pop(handle, None)

    def _invoke_draw(self) -> None:
        for cb, args in list(self._draw_handlers.values()):
            cb(*args)


class FakeArea:
    type = "VIEW_3D"

    def __init__(self):
        self.spaces = [FakeSpaceView3D()]


class FakeScreen:
    def __init__(self, with_view3d: bool):
        self.areas = [FakeArea()] if with_view3d else []
        self.is_animation_playing = False


class FakeWindow:
    def __init__(self, screen):
        self.screen = screen


class FakeWindowManager:
    def __init__(self, screen, with_windows: bool):
        self.windows = [FakeWindow(screen)] if with_windows else []


class FakeRegion:
    def __init__(self):
        self.width = 0
        self.height = 0


class FakeContext:
    def __init__(self, bpy_mod, background: bool):
        self.scene = FakeScene(bpy_mod)
        self.view_layer = FakeViewLayer()
        self.region = None if background else FakeRegion()
        self._depsgraph = FakeDepsgraph()
        # --background has no windows: find_first_view3d must fail there
        # exactly like real Blender (reference ``animation.py:20-22``).
        self.screen = FakeScreen(with_view3d=not background)
        self.window_manager = FakeWindowManager(
            self.screen, with_windows=not background
        )
        self.collection = self.scene.collection

    # context.active_object and view_layer.objects.active are the same
    # thing in Blender; keep one source of truth.
    @property
    def active_object(self):
        return self.view_layer.objects.active

    @active_object.setter
    def active_object(self, obj):
        self.view_layer.objects.active = obj

    def evaluated_depsgraph_get(self) -> FakeDepsgraph:
        return self._depsgraph


# -- operators --------------------------------------------------------------


class _MeshOps:
    def __init__(self, bpy_mod):
        self._bpy = bpy_mod

    def _add(self, base_name, verts, location):
        bpy = self._bpy
        name = base_name
        n = 1
        while name in bpy.data.objects:
            name, n = f"{base_name}.{n:03d}", n + 1
        mesh = FakeMesh(name, verts)
        bpy.data.meshes._append(mesh)
        obj = FakeObject(name, mesh)
        obj.location = location
        bpy.data.objects._append(obj)
        bpy.context.collection.objects.link(obj)
        bpy.context.active_object = obj
        return {"FINISHED"}

    def primitive_cube_add(self, size: float = 2.0,
                           location=(0.0, 0.0, 0.0), **_kw):
        h = size / 2.0
        verts = [
            (x, y, z) for x in (-h, h) for y in (-h, h) for z in (-h, h)
        ]
        return self._add("Cube", verts, location)

    def primitive_plane_add(self, size: float = 2.0,
                            location=(0.0, 0.0, 0.0), **_kw):
        h = size / 2.0
        verts = [(x, y, 0.0) for x in (-h, h) for y in (-h, h)]
        return self._add("Plane", verts, location)


class _RigidbodyOps:
    def __init__(self, bpy_mod):
        self._bpy = bpy_mod

    def world_add(self, **_kw):
        scene = self._bpy.context.scene
        scene.rigidbody_world = types.SimpleNamespace(
            enabled=True,
            point_cache=types.SimpleNamespace(
                frame_start=scene.frame_start, frame_end=scene.frame_end
            ),
        )
        return {"FINISHED"}

    def object_add(self, type: str = "ACTIVE", **_kw):
        obj = self._bpy.context.active_object
        assert obj is not None, "rigidbody.object_add needs an active object"
        obj.rigid_body = FakeRigidBody(type)
        return {"FINISHED"}

    def constraint_add(self, type: str = "FIXED", **_kw):
        obj = self._bpy.context.active_object
        assert obj is not None, (
            "rigidbody.constraint_add needs an active object"
        )
        obj.rigid_body_constraint = FakeRigidBodyConstraint(type)
        return {"FINISHED"}


class _ScreenOps:
    def __init__(self, bpy_mod):
        self._bpy = bpy_mod

    def animation_play(self, **_kw):
        """Synchronous playback clock (see module docstring): advance
        frames start..end, wrapping, firing frame handlers then draw
        handlers, until ``animation_cancel``."""
        bpy = self._bpy
        screen = bpy.context.screen
        scene = bpy.context.scene
        screen.is_animation_playing = True
        frame = scene.frame_start
        ticks = 0
        while screen.is_animation_playing:
            ticks += 1
            if ticks > _MAX_PLAY_TICKS:  # pragma: no cover - test guard
                raise RuntimeError(
                    "fake animation_play exceeded the tick guard — "
                    "nothing called animation_cancel"
                )
            scene.frame_set(frame)
            for window in bpy.context.window_manager.windows:
                for area in window.screen.areas:
                    for space in area.spaces:
                        if space.type == "VIEW_3D":
                            space._invoke_draw()
            frame = (
                frame + 1 if frame < scene.frame_end else scene.frame_start
            )
        return {"FINISHED"}

    def animation_cancel(self, restore_frame: bool = True):
        self._bpy.context.screen.is_animation_playing = False
        if restore_frame:
            scene = self._bpy.context.scene
            scene.frame_current = scene.frame_start
        return {"FINISHED"}


# -- module assembly --------------------------------------------------------


def _default_startup_scene(bpy) -> None:
    """Blender's stock startup scene: Cube at the origin, Camera at its
    default pose, a (mesh-less) Light — what a real ``blender`` launch
    opens when no ``.blend`` is given, and what reference-style scene
    scripts assume (e.g. ``bpy.data.objects["Cube"]``,
    ``examples/datagen/cube.blend.py``)."""
    bpy.ops.mesh.primitive_cube_add(size=2.0, location=(0.0, 0.0, 0.0))
    cam = bpy.data.objects.new("Camera", bpy.data.cameras.new("Camera"))
    bpy.context.collection.objects.link(cam)
    cam.location = (7.3589, -6.9258, 4.9583)  # Blender's default pose
    cam.rotation_euler = (1.1093, 0.0, 0.8149)
    bpy.context.scene.camera = cam
    light = bpy.data.objects.new("Light")
    light.location = (4.0762, 1.0055, 5.9039)
    bpy.context.collection.objects.link(light)
    # like real Blender's startup file, the Cube is the active object
    bpy.context.active_object = bpy.data.objects["Cube"]


def _build_bpy(background: bool, default_scene: bool) -> types.ModuleType:
    bpy = types.ModuleType("bpy")
    bpy.__doc__ = "blendjax fake bpy (see blendjax.testing.fake_bpy)"

    app = types.SimpleNamespace(
        version=(3, 6, 5),
        background=background,
        handlers=types.SimpleNamespace(
            frame_change_pre=[], frame_change_post=[]
        ),
    )
    data = types.SimpleNamespace(
        objects=FakeCollection(FakeObject),
        meshes=FakeCollection(FakeMesh),
        materials=FakeCollection(FakeMaterial),
        images=FakeCollection(),
        cameras=FakeCollection(FakeCameraData),
    )
    bpy.app = app
    bpy.data = data
    bpy.context = FakeContext(bpy, background=background)
    bpy.ops = types.SimpleNamespace(
        mesh=_MeshOps(bpy), screen=_ScreenOps(bpy),
        rigidbody=_RigidbodyOps(bpy),
    )
    bpy.types = types.SimpleNamespace(
        Camera=FakeCameraData, Object=FakeObject, Mesh=FakeMesh,
        SpaceView3D=FakeSpaceView3D,
    )
    bpy._is_fake = True
    bpy._background = background
    bpy._default_scene = default_scene
    if default_scene:
        _default_startup_scene(bpy)
    return bpy


def install(background: bool = False,
            default_scene: bool = False) -> types.ModuleType:
    """Register fake ``bpy``/``gpu`` modules into ``sys.modules``
    (idempotent; refuses to shadow a real Blender runtime).
    ``default_scene=True`` opens Blender's stock startup scene the way a
    real launch without a ``.blend`` does (the fake ``blender`` CLI
    passes it); the in-process default stays an empty graph."""
    existing = sys.modules.get("bpy")
    if existing is not None and not getattr(existing, "_is_fake", False):
        raise RuntimeError(
            "a real bpy module is already loaded; the fake must not "
            "shadow it"
        )
    if existing is None:
        sys.modules["bpy"] = _build_bpy(background, default_scene)
        from blendjax.testing import fake_gpu

        sys.modules["gpu"] = fake_gpu.build(sys.modules["bpy"])
    elif (
        existing._background != background
        or existing._default_scene != default_scene
    ):
        # Mutate the installed module in place (like reset): modules that
        # did ``import bpy`` hold a reference to the OBJECT, so rebinding
        # sys.modules would leave them on a stale scene graph.
        reset(background=background, default_scene=default_scene)
    return sys.modules["bpy"]


def reset(background: bool | None = None,
          default_scene: bool | None = None) -> types.ModuleType:
    """Fresh scene graph (new ``bpy.context``/``bpy.data``), keeping the
    installed module identity so prior ``import bpy`` references update."""
    bpy = sys.modules.get("bpy")
    assert bpy is not None and getattr(bpy, "_is_fake", False), (
        "fake bpy is not installed"
    )
    if background is None:
        background = bpy._background
    if default_scene is None:
        default_scene = bpy._default_scene
    fresh = _build_bpy(background, default_scene)
    for attr in ("app", "data", "context", "ops", "types",
                 "_background", "_default_scene"):
        setattr(bpy, attr, getattr(fresh, attr))
    # ops/context captured the fresh module; point them back at the live one
    for op_ns in vars(bpy.ops).values():
        if hasattr(op_ns, "_bpy"):
            op_ns._bpy = bpy
    bpy.context.scene._bpy = bpy
    return bpy
