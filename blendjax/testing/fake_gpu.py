"""A ``gpu`` module stand-in for hermetic offscreen-render tests.

Implements the surface ``blendjax.producer.offscreen`` uses (reference
``pkg_blender/blendtorch/btb/offscreen.py:49-99``): ``types.GPUOffScreen``
with ``bind()``, ``draw_view3d``, and ``texture_color.read()`` returning a
buffer-protocol-ish object with a settable ``dimensions`` attribute.

The draw is not a no-op: it clears to the viewport background and splats
one pixel per visible scene-mesh vertex, projected through the EXACT
view/projection matrices the caller passed — so a consumer test can
assert the readback against blendjax's own analytic Camera, pinning the
whole matrix-plumbing + GL-origin + flip chain, not just array shapes.
Scanline order is GL-style bottom-up (row 0 = bottom), which is what
makes ``OffScreenRenderer``'s ``flipud`` observable.
"""

from __future__ import annotations

import contextlib
import hashlib
import types

import numpy as np

BACKGROUND = (60, 60, 60, 255)  # viewport-ish grey


def _object_color(name: str):
    """Stable, bright per-object splat color."""
    h = hashlib.sha256(name.encode()).digest()
    return (128 + h[0] // 2, 128 + h[1] // 2, 128 + h[2] // 2, 255)


class _Buffer:
    """What ``texture.read()`` yields: exposes ``dimensions`` (the caller
    sets it before converting) and converts via ``np.asarray``."""

    def __init__(self, flat: np.ndarray):
        self._flat = flat
        self.dimensions = int(flat.size)

    def __array__(self, dtype=None, copy=None):
        arr = self._flat[: int(self.dimensions)]
        return arr.astype(dtype) if dtype is not None else arr


class _Texture:
    def __init__(self, offscreen: "GPUOffScreen"):
        self._off = offscreen

    def read(self) -> _Buffer:
        assert self._off._bound, "texture read outside offscreen.bind()"
        return _Buffer(self._off._pixels.reshape(-1).copy())


class GPUOffScreen:
    def __init__(self, width: int, height: int):
        self.width = int(width)
        self.height = int(height)
        # GL-ordered scanlines: row 0 is the BOTTOM of the image
        self._pixels = np.empty((self.height, self.width, 4), np.uint8)
        self._pixels[:] = BACKGROUND
        self._bound = False
        self.texture_color = _Texture(self)
        self.last_draw: dict | None = None  # test introspection

    @contextlib.contextmanager
    def bind(self):
        self._bound = True
        try:
            yield self
        finally:
            self._bound = False

    def draw_view3d(self, scene, view_layer, view3d, region,
                    view_matrix, projection_matrix,
                    do_color_management: bool = False) -> None:
        assert self._bound, "draw_view3d outside offscreen.bind()"
        del view_layer, view3d, region, do_color_management
        v = np.asarray(view_matrix, dtype=np.float64)
        p = np.asarray(projection_matrix, dtype=np.float64)
        self.last_draw = {"view": v, "proj": p, "scene": scene}
        self._pixels[:] = BACKGROUND
        for obj in getattr(scene, "objects", []):
            mesh = getattr(obj, "data", None)
            verts = getattr(mesh, "vertices", None)
            if not verts:
                continue
            local = np.stack([vx.co for vx in verts])
            mw = np.asarray(obj.matrix_world)
            world = local @ mw[:3, :3].T + mw[:3, 3]
            hom = np.concatenate(
                [world, np.ones((len(world), 1))], axis=1
            )
            clip = hom @ (p @ v).T
            w = clip[:, 3]
            ok = w > 1e-9
            ndc = clip[ok, :3] / w[ok, None]
            inside = np.all(np.abs(ndc) <= 1.0, axis=1)
            color = _object_color(obj.name)
            for x, y in ndc[inside, :2]:
                px = min(int((x + 1.0) / 2.0 * self.width), self.width - 1)
                py = min(int((y + 1.0) / 2.0 * self.height), self.height - 1)
                self._pixels[py, px] = color  # GL: py counts from bottom

    def free(self) -> None:
        pass


def build(_bpy_mod) -> types.ModuleType:
    gpu = types.ModuleType("gpu")
    gpu.__doc__ = "blendjax fake gpu (see blendjax.testing.fake_gpu)"
    gpu.types = types.SimpleNamespace(GPUOffScreen=GPUOffScreen)
    gpu._is_fake = True
    return gpu
