"""Runtime thread-affinity / lock-discipline sanitizer.

The dynamic half of the BJX117/BJX104 story (docs/static-analysis.md
"Whole-program rules"): the static pass proves code *shape*, this
module checks the same conventions at runtime, ThreadSanitizer-style,
on the objects the conventions are ABOUT. A guarded object is wrapped
in a delegating proxy that, on every attribute access, records the
accessing thread and (in lock mode) the required lock's ownership, and
raises immediately on a violation — turning a once-in-a-soak data race
into a deterministic test failure at the exact access site.

Two disciplines:

- **affinity** — the object belongs to ONE thread: ``"creator"`` binds
  it to the constructing thread (the libzmq socket contract, BJX104),
  ``"first-use"`` to whichever thread touches it first (the
  ``RemoteStream`` deferred-socket pattern: born on the ingest thread
  that drains it).
- **lock** — every access must run with the given lock held by the
  accessing thread (the one-RLock-per-object discipline BJX117 checks
  statically). ``RLock``/``Condition`` ownership is exact
  (``_is_owned``); a plain ``Lock`` degrades to ``locked()`` — held by
  *someone* — since CPython records no owner for it.

Production wiring goes through :mod:`blendjax.utils.tg`, which
re-exports :func:`guard` ONLY when ``BLENDJAX_THREADGUARD=1`` and is
an identity function otherwise — the disabled path adds zero per-
access cost and never imports this module. The threaded tier-1 suites
run under the env var in the (non-required) ``threadguard`` CI job.

stdlib-only, like the analyzer.
"""

from __future__ import annotations

import threading

__all__ = [
    "LockDisciplineError",
    "ThreadAffinityError",
    "ThreadGuardError",
    "guard",
    "unguard",
]


class ThreadGuardError(AssertionError):
    """Base: a guarded object was accessed against its declaration."""


class ThreadAffinityError(ThreadGuardError):
    """A single-thread object was touched from a second thread."""


class LockDisciplineError(ThreadGuardError):
    """A lock-guarded object was touched without its lock held."""


# Serializes first-use binding (a check-then-act) across all guards;
# module-wide is fine — binding happens once per guarded object.
_BIND_LOCK = threading.Lock()


def _lock_held(lock: object) -> bool:
    """Best-effort 'does the CALLING thread hold this lock'. RLock and
    Condition expose exact ownership; a plain Lock only knows whether
    anyone holds it."""
    is_owned = getattr(lock, "_is_owned", None)
    if is_owned is not None:
        return bool(is_owned())
    locked = getattr(lock, "locked", None)
    if locked is not None:
        return bool(locked())
    raise TypeError(f"not a lock: {lock!r}")


class _Guarded:
    """Delegating proxy: every attribute access runs the declared
    checks, then forwards to the wrapped object."""

    __slots__ = (
        "_tg_obj",
        "_tg_name",
        "_tg_mode",
        "_tg_lock",
        "_tg_thread",
        "_tg_thread_name",
        "_tg_exempt",
    )

    def __init__(self, obj, name, affinity, lock, exempt):
        object.__setattr__(self, "_tg_obj", obj)
        object.__setattr__(self, "_tg_name", name)
        object.__setattr__(self, "_tg_mode", affinity)
        object.__setattr__(self, "_tg_lock", lock)
        object.__setattr__(self, "_tg_exempt", frozenset(exempt or ()))
        bound = threading.current_thread() if affinity == "creator" else None
        object.__setattr__(
            self, "_tg_thread", bound.ident if bound else None
        )
        object.__setattr__(
            self, "_tg_thread_name", bound.name if bound else None
        )

    # -- the check ---------------------------------------------------------

    def _tg_check(self, attr: str) -> None:
        if attr in self._tg_exempt:
            return
        lock = self._tg_lock
        if lock is not None and not _lock_held(lock):
            raise LockDisciplineError(
                f"threadguard: '{self._tg_name}.{attr}' accessed from "
                f"thread '{threading.current_thread().name}' without "
                "holding the declared lock"
            )
        if self._tg_mode is not None:
            me = threading.current_thread()
            owner = self._tg_thread
            if owner is None:  # first-use: bind now
                # Binding is check-then-act: without the bind lock, two
                # threads racing the FIRST access would both pass and
                # the sanitizer would miss exactly the race it exists
                # to catch. One-time cost, never on the bound path.
                with _BIND_LOCK:
                    owner = self._tg_thread
                    if owner is None:
                        object.__setattr__(self, "_tg_thread", me.ident)
                        object.__setattr__(
                            self, "_tg_thread_name", me.name
                        )
                        return
            if owner != me.ident:
                raise ThreadAffinityError(
                    f"threadguard: '{self._tg_name}.{attr}' accessed "
                    f"from thread '{me.name}' but the object is bound "
                    f"to thread '{self._tg_thread_name}' "
                    f"({self._tg_mode} affinity)"
                )

    # -- delegation --------------------------------------------------------

    def __getattr__(self, name):
        if name in self._tg_exempt:
            return getattr(self._tg_obj, name)
        self._tg_check(name)
        value = getattr(self._tg_obj, name)
        if callable(value) and not isinstance(value, type):
            # Re-check at CALL time, not just fetch time: a bound
            # method handed to another thread (``Thread(target=
            # guarded.method)``) must still trip the guard when it
            # actually runs.
            def checked(*args, **kwargs):
                self._tg_check(name)
                return value(*args, **kwargs)

            return checked
        return value

    def __setattr__(self, name, value):
        self._tg_check(name)
        setattr(self._tg_obj, name, value)

    def __getitem__(self, key):
        self._tg_check("__getitem__")
        return self._tg_obj[key]

    def __setitem__(self, key, value):
        self._tg_check("__setitem__")
        self._tg_obj[key] = value

    def __delitem__(self, key):
        self._tg_check("__delitem__")
        del self._tg_obj[key]

    def __contains__(self, key):
        self._tg_check("__contains__")
        return key in self._tg_obj

    def __iter__(self):
        self._tg_check("__iter__")
        return iter(self._tg_obj)

    def __len__(self):
        self._tg_check("__len__")
        return len(self._tg_obj)

    def __bool__(self):
        self._tg_check("__bool__")
        return bool(self._tg_obj)

    def __call__(self, *args, **kwargs):
        self._tg_check("__call__")
        return self._tg_obj(*args, **kwargs)

    def __enter__(self):
        self._tg_check("__enter__")
        return self._tg_obj.__enter__()

    def __exit__(self, *exc):
        self._tg_check("__exit__")
        return self._tg_obj.__exit__(*exc)

    def __repr__(self):
        return (
            f"<threadguard {self._tg_name!r} "
            f"{self._tg_mode or 'lock'}: {self._tg_obj!r}>"
        )


def guard(
    obj,
    *,
    name: str | None = None,
    affinity: str | None = None,
    lock: object | None = None,
    exempt: tuple = (),
):
    """Wrap ``obj`` in a checking proxy.

    - ``affinity="creator"`` — bind to the calling thread now.
    - ``affinity="first-use"`` — bind to the first accessing thread.
    - ``lock=some_lock`` — every access must hold ``some_lock``
      (composable with affinity).
    - ``exempt=("close", "lock")`` — attribute names skipped by the
      checks (teardown surfaces that legitimately cross threads, or
      the lock handle a caller must fetch BEFORE holding it).

    Idempotent: guarding a guard returns it unchanged. At least one of
    ``affinity``/``lock`` is required — an uncheckable guard is a bug
    in the wiring, not a permissive mode.
    """
    if isinstance(obj, _Guarded):
        return obj
    if affinity not in (None, "creator", "first-use"):
        raise ValueError(f"unknown affinity {affinity!r}")
    if affinity is None and lock is None:
        raise ValueError("guard() needs affinity= and/or lock=")
    return _Guarded(
        obj, name or type(obj).__name__, affinity, lock, exempt
    )


def unguard(obj):
    """The raw object behind a guard (identity for anything else)."""
    return obj._tg_obj if isinstance(obj, _Guarded) else obj
