"""pjit train loops + checkpointing for streamed data.

No direct reference counterpart (the reference defers training to user
torch code, e.g. ``examples/densityopt/densityopt.py:257-331``); this
package is the consumer-side training half of the north star: jitted,
donated, mesh-sharded steps fed by ``blendjax.data``.
"""

from blendjax.train.aot import (
    AotStepSet,
    build_aot_step,
    configure_compilation_cache,
)
from blendjax.train.steps import (
    corner_loss,
    make_chunked_supervised_step,
    make_echo_fused_step,
    make_eval_step,
    make_fused_tile_step,
    make_train_state,
    make_supervised_step,
)
from blendjax.checkpoint import (
    PreemptionGuard,
    PreemptionRequested,
    SnapshotManager,
)
from blendjax.train.checkpoint import CheckpointManager
from blendjax.train.driver import TrainDriver
from blendjax.train.mesh_driver import (
    MeshTrainDriver,
    make_mesh_echo_fused_step,
    make_mesh_fused_step,
    make_mesh_supervised_step,
)
from blendjax.train.precision import (
    DEFAULT_POLICY,
    POLICIES,
    PrecisionPolicy,
    resolve_policy,
)

__all__ = [
    "AotStepSet",
    "build_aot_step",
    "configure_compilation_cache",
    "make_train_state",
    "make_supervised_step",
    "make_chunked_supervised_step",
    "make_echo_fused_step",
    "make_eval_step",
    "make_fused_tile_step",
    "corner_loss",
    "CheckpointManager",
    "SnapshotManager",
    "PreemptionGuard",
    "PreemptionRequested",
    "TrainDriver",
    "MeshTrainDriver",
    "make_mesh_echo_fused_step",
    "make_mesh_fused_step",
    "make_mesh_supervised_step",
    "PrecisionPolicy",
    "POLICIES",
    "DEFAULT_POLICY",
    "resolve_policy",
]
