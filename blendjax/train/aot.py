"""AOT step compilation with a persistent on-disk cache.

Cold start used to pay the full jit trace+compile of the step set on the
first batch of every shape in the bucket ladder — seconds of wall time the
checkpoint/elastic-resume and fleet subsystems re-pay on every restart.
This module closes that gap in two layers:

1. **AOT set** — :func:`build_aot_step` lowers the jitted step against
   abstract ``jax.ShapeDtypeStruct`` trees derived from the *concrete*
   state plus the ``pad_to_bucket`` ladder (every batch shape the driver
   can dispatch: the full batch unmasked, and each bucket size with its
   ``_mask``), and compiles all of them before step 0.  Dispatch then hits
   a precompiled executable keyed by the batch signature; an unseen shape
   falls back to the wrapped jit (counted ``train.aot_fallbacks``) so
   correctness never depends on the ladder being complete.
2. **Persistent cache** — :func:`configure_compilation_cache` points
   ``jax.config``'s compilation cache at a directory (thresholds zeroed so
   CPU-sized test steps persist too), and a keyed *manifest* over
   ``(model class, precision policy, mesh layout, decode plan, bucket
   ladder, jax version, backend)`` records which step signatures were
   compiled under that key — the warm/cold distinction behind the
   ``train.aot_cache_hits`` / ``train.aot_cache_misses`` counters and the
   CI-gated ``live_start`` warm-vs-cold ratio.

All compile wall time runs under the ``train.compile_ms`` span so the
doctor and bench stage breakdowns can tell a cold-start-dominated run from
a genuinely step-bound one.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
import time

import jax
import numpy as np

from blendjax.data.batcher import bucket_sizes
from blendjax.obs.devledger import ledger
from blendjax.utils.metrics import metrics

logger = logging.getLogger(__name__)

__all__ = [
    "AotStepSet",
    "build_aot_step",
    "batch_specs_for_ladder",
    "configure_compilation_cache",
    "cache_key",
]

_MANIFEST = "aot_manifest.json"


# -- persistent cache wiring --------------------------------------------------

def configure_compilation_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Zeroes the min-compile-time / min-entry-size thresholds so the small
    CPU-sized steps the CI bench compiles are persisted too (the defaults
    only cache "expensive" compiles).  Each knob is applied independently
    and version-drift-tolerantly: an option a given JAX build does not know
    is skipped, not fatal.  Returns True when the cache directory itself
    was accepted.
    """
    os.makedirs(cache_dir, exist_ok=True)
    ok = False
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        ok = True
    except Exception as e:  # pragma: no cover - depends on jax build
        logger.warning("persistent compilation cache unavailable: %s", e)
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
        # without this the CPU backend never writes cache entries at all
        ("jax_persistent_cache_enable_xla_caches", "all"),
    ):
        try:
            jax.config.update(knob, value)
        except Exception:  # pragma: no cover - knob renamed/absent
            pass
    # JAX latches the cache state on the first compile of the process: if
    # anything compiled before the dir was set (state init always does),
    # the "no cache" decision sticks and every later knob is ignored.
    # Resetting re-reads the config on next use.
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # pragma: no cover - private module moved
        pass
    return ok


def cache_key(
    *,
    model: object = None,
    precision: object = None,
    mesh: object = None,
    decode_plan: object = None,
    buckets: tuple | list | None = None,
    layout: object = None,
    rules: tuple | list | None = None,
) -> str:
    """Stable manifest key over everything that invalidates compiled steps.

    Anatomy (see docs/performance.md): model class qualname, precision
    policy, mesh layout (axis names x sizes), the named Layout + its
    partition-rule set (two rule sets on the SAME mesh are different
    programs), decode plan, bucket ladder, plus the JAX version and
    backend — change any one and the key moves, so a stale cache can
    never serve a mismatched executable.
    """
    if model is not None and not isinstance(model, str):
        model = f"{type(model).__module__}.{type(model).__qualname__}"
    if mesh is not None and not isinstance(mesh, str):
        try:
            mesh = ",".join(
                f"{ax}={n}" for ax, n in
                zip(mesh.axis_names, mesh.devices.shape)
            )
        except Exception:
            mesh = repr(mesh)
    if layout is not None and not isinstance(layout, str):
        layout = getattr(layout, "name", None) or repr(layout)
    parts = {
        "model": model,
        "precision": str(precision) if precision is not None else None,
        "mesh": mesh,
        "layout": layout,
        "rules": [
            (r.pattern, list(r.spec)) if hasattr(r, "pattern") else repr(r)
            for r in rules
        ] if rules else None,
        "decode_plan": str(decode_plan) if decode_plan is not None else None,
        "buckets": list(buckets) if buckets is not None else None,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
    }
    blob = json.dumps(parts, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:32]


def _load_manifest(cache_dir: str) -> dict:
    try:
        with open(os.path.join(cache_dir, _MANIFEST)) as f:
            data = json.load(f)
        return data if isinstance(data, dict) else {}
    except (OSError, ValueError):
        return {}


def _save_manifest(cache_dir: str, manifest: dict) -> None:
    """Atomic write (tmp + rename) so concurrent children never see a torn
    manifest — the bench's cold and warm legs share one cache dir."""
    try:
        os.makedirs(cache_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=cache_dir, suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(manifest, f, sort_keys=True)
        os.replace(tmp, os.path.join(cache_dir, _MANIFEST))
    except OSError as e:  # cache dir is best-effort, never fatal
        logger.warning("could not persist aot manifest: %s", e)


# -- abstract shape ladders ---------------------------------------------------

def _is_batch_array(key: str, value) -> bool:
    """The array fields a step consumes: leading-dim tensors plus the
    bucket-padding ``_mask``; every other underscore stamp is host-side."""
    if key == "_mask":
        return True
    return not key.startswith("_") and getattr(value, "ndim", 0) >= 1


def _abstract(tree):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(
            np.shape(x), x.dtype, sharding=getattr(x, "sharding", None),
        )
        if hasattr(x, "dtype")
        else x,
        tree,
    )


def batch_specs_for_ladder(
    example_batch: dict,
    buckets: tuple | list | None = None,
    data_axis: str = "data",
) -> list[dict]:
    """Every batch signature the driver can dispatch, as ShapeDtypeStructs.

    From a concrete example batch (full batch size ``B``): the full batch
    without ``_mask`` (the steady-state shape) plus each ``pad_to_bucket``
    ladder size *with* its f32 ``_mask`` — partial tails always carry the
    mask, full batches from normal assembly never do.

    A committed batch sharding over a MODEL axis (``fsdp`` without the
    data fold, ``tp`` anywhere) is rejected here, at build time:
    lowering the ladder against it would compile a wrong program and
    the error would otherwise surface deep inside jit at the first
    dispatch (:func:`blendjax.parallel.validate_batch_sharding`).
    """
    from blendjax.parallel.sharding import validate_batch_sharding

    fields = {
        k: v for k, v in example_batch.items()
        if k != "_mask" and _is_batch_array(k, v)
    }
    if not fields:
        raise ValueError("example batch has no array fields to lower against")
    for k, v in fields.items():
        sh = getattr(v, "sharding", None)
        if sh is not None:
            validate_batch_sharding(
                sh, data_axis=data_axis, what=f"ladder batch field {k!r}"
            )
    lead = next(iter(fields.values())).shape[0]
    ladder = tuple(buckets) if buckets else bucket_sizes(lead)
    specs = []

    def _field_sharding(v, shape):
        """Carry the example batch's committed sharding into the spec —
        a mesh run's live batches arrive sharded over the data axis,
        and an executable lowered against a replicated batch is a
        different program (no grad-sync collectives, rejected layouts
        at dispatch). Only reused when the bucketed lead still divides
        over it; numpy example batches have no sharding and lower
        exactly as before."""
        sharding = getattr(v, "sharding", None)
        if sharding is not None:
            try:
                sharding.shard_shape(tuple(shape))
            except Exception:
                sharding = None
        return sharding

    def _spec(size: int, with_mask: bool) -> dict:
        out = {}
        for k, v in fields.items():
            shape = (size,) + tuple(v.shape[1:])
            out[k] = jax.ShapeDtypeStruct(
                shape, np.dtype(v.dtype),
                sharding=_field_sharding(v, shape),
            )
        if with_mask:
            out["_mask"] = jax.ShapeDtypeStruct((size,), np.dtype(np.float32))
        return out

    specs.append(_spec(lead, with_mask=False))
    for size in ladder:
        specs.append(_spec(int(size), with_mask=True))
    return specs


def _signature(fields: dict) -> tuple:
    return tuple(
        sorted(
            (k, tuple(np.shape(v)), np.dtype(v.dtype).str)
            for k, v in fields.items()
        )
    )


# -- the AOT step set ---------------------------------------------------------

class AotStepSet:
    """Precompiled executables per batch signature, jit fallback elsewhere.

    ``jit(...).lower(...).compile()`` does **not** seed the jit wrapper's
    own dispatch cache, so holding the compiled executables and dispatching
    to them directly is what actually makes step 0 instant.  The wrapped
    jit remains the safety net for shapes outside the ladder (and for any
    compiled-call failure): slower, never wrong.
    """

    def __init__(self, step, compiled: dict, compile_ms: float,
                 cache_hits: int, cache_misses: int) -> None:
        self._step = step
        self._compiled = compiled
        self.compile_ms = compile_ms
        self.cache_hits = cache_hits
        self.cache_misses = cache_misses
        self.ledger_entries: list = []
        self._warned: set = set()

    @property
    def signatures(self) -> tuple:
        return tuple(self._compiled)

    def __call__(self, state, batch):
        fields = {k: v for k, v in batch.items() if _is_batch_array(k, v)}
        sig = _signature(fields)
        exe = self._compiled.get(sig)
        if exe is not None:
            try:
                return exe(state, fields)
            except Exception:  # pragma: no cover - layout drift safety net
                if sig not in self._warned:
                    self._warned.add(sig)
                    logger.warning(
                        "aot executable rejected the batch; "
                        "falling back to jit", exc_info=True,
                    )
        else:
            metrics.count("train.aot_fallbacks")
        return self._step(state, fields)


def build_aot_step(
    step,
    state,
    example_batch: dict,
    *,
    buckets: tuple | list | None = None,
    cache_dir: str | None = None,
    key: str | None = None,
    mesh=None,
    data_axis: str = "data",
    ledger_name: str = "aot_step",
) -> AotStepSet:
    """Compile ``step`` for every ladder signature before step 0.

    ``step`` must be a ``jax.jit`` wrapper (lowerable); ``state`` the
    concrete train state (its shapes/dtypes/shardings become the abstract
    state); ``example_batch`` a concrete full-size batch dict.  With
    ``cache_dir`` set, the persistent compilation cache is configured and
    the keyed manifest decides hit/miss per signature — a warm manifest
    entry means XLA will be served from disk, and ``train.aot_cache_hits``
    counts it; a cold one counts ``train.aot_cache_misses``.

    Every compiled executable is registered with the device ledger
    (cost/memory/collective accounting published as ``device.*`` gauges;
    ``mesh`` enables per-axis collective attribution) — the entries land
    on ``AotStepSet.ledger_entries`` so the drivers can derive the
    cost-model MFU numerator. Registration is accounting only and can
    never fail the build.
    """
    manifest: dict = {}
    seen: set = set()
    if cache_dir:
        configure_compilation_cache(cache_dir)
        manifest = _load_manifest(cache_dir)
        key = key or cache_key()
        seen = set(manifest.get(key, ()))

    state_spec = _abstract(state)
    specs = batch_specs_for_ladder(example_batch, buckets, data_axis=data_axis)
    compiled: dict = {}
    hits = misses = 0
    t0 = time.monotonic()
    with metrics.span("train.compile_ms"):
        for spec in specs:
            sig = _signature(spec)
            if sig in compiled:
                continue
            sig_hash = hashlib.sha256(repr(sig).encode()).hexdigest()[:16]
            if cache_dir:
                if sig_hash in seen:
                    hits += 1
                    metrics.count("train.aot_cache_hits")
                else:
                    misses += 1
                    metrics.count("train.aot_cache_misses")
                    seen.add(sig_hash)
            compiled[sig] = step.lower(state_spec, spec).compile()
    compile_ms = (time.monotonic() - t0) * 1e3
    if cache_dir:
        manifest[key] = sorted(seen)
        _save_manifest(cache_dir, manifest)
    logger.info(
        "aot step set: %d signatures compiled in %.0f ms (%d warm, %d cold)",
        len(compiled), compile_ms, hits, misses,
    )
    step_set = AotStepSet(step, compiled, compile_ms, hits, misses)
    try:
        step_set.ledger_entries = ledger.register_aot_set(
            ledger_name, compiled, mesh=mesh
        )
    except Exception:  # pragma: no cover - accounting must not fail builds
        logger.debug("device ledger registration failed", exc_info=True)
    return step_set
