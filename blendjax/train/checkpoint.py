"""Train-state checkpointing via orbax (optional extra).

The real checkpoint subsystem is :mod:`blendjax.checkpoint`
(docs/checkpointing.md): async sharded snapshots, the pickle-free
session store, elastic resume, preemption wiring — self-contained on
the core numpy+msgpack dependencies. This module remains as a thin
wrapper for runs that want orbax's on-disk FORMAT (interop with
orbax-based tooling, multi-host GCS writes); it needs the
``orbax-checkpoint`` package, installed via the ``blendjax[orbax]``
extra (or ``blendjax[tpu]``, which includes it).
"""

from __future__ import annotations

import os

import jax


class CheckpointManager:
    """Thin orbax wrapper: ``save(step, state)`` / ``restore(state)``."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        try:
            import orbax.checkpoint as ocp
        except ImportError as e:
            # Fail at CONSTRUCTION with a way forward, not mid-init
            # with a bare ModuleNotFoundError three frames deep.
            raise ImportError(
                "orbax-checkpoint is not installed; the orbax-backed "
                "CheckpointManager is an optional extra. Either "
                "`pip install blendjax[orbax]` (or `[tpu]`, which "
                "includes it), or use the dependency-free "
                "blendjax.checkpoint.SnapshotManager — the subsystem "
                "documented in docs/checkpointing.md."
            ) from e

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state) -> None:
        """Asynchronous: serialization overlaps subsequent train steps;
        :meth:`wait`/:meth:`close` (and :meth:`restore`) synchronize."""
        self.manager.save(
            step, args=self._ocp.args.StandardSave(state)
        )

    def wait(self) -> None:
        self.manager.wait_until_finished()

    def latest_step(self):
        self.manager.wait_until_finished()
        return self.manager.latest_step()

    def restore(self, target_state):
        """Restore the latest checkpoint into the structure/shardings of
        ``target_state`` (pass a freshly-initialized state)."""
        self.manager.wait_until_finished()
        step = self.manager.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=getattr(x, "sharding", None),
            )
            if hasattr(x, "shape")
            else x,
            target_state,
        )
        return self.manager.restore(
            step, args=self._ocp.args.StandardRestore(abstract)
        )

    def close(self):
        self.manager.wait_until_finished()
        self.manager.close()
