"""Train-state checkpointing (orbax).

The reference has no model checkpointing (SURVEY.md §5 — its only
persistence is the data-stream recorder, covered by
``blendjax.data.replay``); this adds the standard orbax save/restore the
train-loop layer needs, including sharded multi-host states.
"""

from __future__ import annotations

import os

import jax


class CheckpointManager:
    """Thin orbax wrapper: ``save(step, state)`` / ``restore(state)``."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state) -> None:
        """Asynchronous: serialization overlaps subsequent train steps;
        :meth:`wait`/:meth:`close` (and :meth:`restore`) synchronize."""
        self.manager.save(
            step, args=self._ocp.args.StandardSave(state)
        )

    def wait(self) -> None:
        self.manager.wait_until_finished()

    def latest_step(self):
        self.manager.wait_until_finished()
        return self.manager.latest_step()

    def restore(self, target_state):
        """Restore the latest checkpoint into the structure/shardings of
        ``target_state`` (pass a freshly-initialized state)."""
        self.manager.wait_until_finished()
        step = self.manager.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=getattr(x, "sharding", None),
            )
            if hasattr(x, "shape")
            else x,
            target_state,
        )
        return self.manager.restore(
            step, args=self._ocp.args.StandardRestore(abstract)
        )

    def close(self):
        self.manager.wait_until_finished()
        self.manager.close()
