"""Async overlap driver: keep donated train-step dispatches in flight.

The live-loop gap this closes (BENCH_r05): ``mfu_step_alone`` 0.4724 vs
``mfu_live`` 0.0085 — a ~55x gap — because the consumer loop ran
dispatch-SYNC-dispatch: every step's loss was fetched (or its buffers
blocked on) before the next batch was even requested, and the on-device
decode dispatched as a separate jit call that serialized with the step.
With the decode fused into the step (``make_fused_tile_step``: exactly
one device dispatch per step) and this driver keeping up to ``inflight``
of those dispatches outstanding, H2D transfer, fused decode+step
compute, and host ingest all overlap; the host touches device results
only every ``sync_every`` steps and when the ring is genuinely full.

Rules of the hot loop (enforced by bjx-lint BJX106 on this module):
never host-sync a value dispatched in the same loop iteration —
completion is tracked per in-flight entry (non-blocking ``is_ready``
polls retire finished work), and blocking waits target the OLDEST
entry only, which was dispatched ``inflight`` steps ago and is usually
long done.
"""

from __future__ import annotations

# bjx: driver-hot-path (BJX106 flags same-iteration host syncs on step
# outputs inside this module's dispatch loops)

import collections
import logging
import threading
import time

import numpy as np

from blendjax.obs.devledger import RetraceAudit, default_peak_flops
from blendjax.obs.trace import (
    TERMINAL_STAGE,
    pop_traces as trace_pop,
    stage as trace_stage,
    tracer,
)
from blendjax.utils.metrics import metrics

logger = logging.getLogger(__name__)

_LOGGED_ONCE: set = set()


def _log_once(fn, msg: str, *args) -> None:
    """Per-process dedup for build-time knob advice — a bench that
    constructs dozens of drivers should name a missing knob once, not
    once per leg."""
    if msg not in _LOGGED_ONCE:
        _LOGGED_ONCE.add(msg)
        fn(msg, *args)


class TrainDriver:
    """Dispatch-ahead wrapper around a ``step(state, batch) ->
    (state, metrics)`` callable (any :mod:`blendjax.train.steps`
    builder; pair with :func:`make_fused_tile_step` +
    ``StreamDataPipeline(emit_packed=True)`` for the one-dispatch-per-
    step fused path).

    - ``inflight``: how many step dispatches may be outstanding. The
      ring is bounded by completion tracking, not serialization:
      finished entries retire via a non-blocking readiness poll, and the
      driver blocks (once, on the oldest entry) only when the ring is
      genuinely full of unfinished work. ``inflight=1`` reproduces the
      old dispatch-wait-dispatch loop for A/B comparison.
    - ``sync_every``: fetch one loss value to host every N steps (the
      oldest in flight — the least-blocking real number). 0 disables
      periodic syncs; :meth:`finish`/:meth:`drain` still fetch the final
      loss, which transitively syncs the whole donated-state chain.
    - ``pad_partial``: bucket-pad `_partial` tail batches that reach the
      driver unmasked (``blendjax.data.batcher.pad_to_bucket``), so a
      finite stream's ragged tail cannot recompile the step mid-run.
      Pipelines constructed with ``pad_partial=True`` (the default)
      already deliver masked bucket shapes and skip this path.

    Stats (:attr:`stats`): ``steps``/``dispatches`` (one device call per
    step on the fused path), ``inflight_hwm`` (steps-in-flight
    high-water mark), ``host_blocks`` (genuine ring-full waits — near
    zero when the device keeps up), ``syncs`` (periodic loss fetches).

    Device-timeline metrics: each ring entry is timed dispatch ->
    retirement (the moment the completion poll/fetch observes it done),
    feeding the ``train.step_device_ms`` histogram — an upper bound on
    per-step device latency that converges on it while the ring cycles
    (a finished entry is examined again within one submit). Given
    ``flops_per_image`` (hand-fed, or derived by :meth:`build` from
    the device ledger's ``compiled.cost_analysis()`` entries —
    :mod:`blendjax.obs.devledger`) and ``peak_flops`` (explicit, or
    defaulted from the known-chip peaks table), retirements
    additionally maintain a live ``train.mfu`` gauge (retired
    images/s x flops_per_image / peak_flops over ~1 s windows), so
    MFU is an always-on run metric the SLO watchdog can bound, not
    just a bench artifact.
    """

    def __init__(self, step, state, inflight: int = 4,
                 sync_every: int = 32, pad_partial: bool = True,
                 buckets=None, flops_per_image: float | None = None,
                 peak_flops: float | None = None,
                 checkpoint=None, checkpoint_every: int = 0,
                 session_state=None, place=None):
        self.step = step
        self.state = state
        # Placement folded into the dispatch (docs/performance.md
        # "Closing the live-MFU gap", lever 3): when `place` is set —
        # typically ``pipeline.feeder.place`` with
        # ``StreamDataPipeline(place_in_driver=True)`` — submit()
        # receives HOST batches and commits the one grouped async
        # ``device_put`` right before the step dispatch, so the
        # transfer overlaps the in-flight steps this ring tracks
        # instead of running as a separate host-blocking feeder stage.
        # Retirement readiness already polls the step's global output,
        # which transitively covers the transfer.
        self.place = place
        self.inflight = max(1, int(inflight))
        self.sync_every = max(0, int(sync_every or 0))
        self.pad_partial = bool(pad_partial)
        self.buckets = buckets
        self.flops_per_image = (
            float(flops_per_image) if flops_per_image else None
        )
        self.peak_flops = float(peak_flops) if peak_flops else None
        # Where the MFU numerator came from: "hand-fed" (caller knob),
        # "cost-model" (build() derived it from the device ledger's
        # cost_analysis entries), or None (gauge off).
        self.mfu_source = "hand-fed" if self.flops_per_image else None
        self._resolve_peak_flops()
        # Retrace audit (blendjax.obs.devledger): watches the step's
        # jit dispatch cache per submit — on the AOT path that is the
        # fallback jit, so any growth IS the unbucketed-shape signal.
        # None when the step isn't a watchable jit wrapper.
        self.retrace_audit = RetraceAudit.for_step(step)
        # Checkpointing (blendjax.checkpoint, docs/checkpointing.md):
        # every `checkpoint_every` steps — and whenever
        # request_checkpoint() was called from any thread — submit()
        # hands the freshly-retired state to the SnapshotManager at
        # the step boundary. save_async clones the device leaves
        # before returning, so the NEXT dispatch's donation can never
        # invalidate a snapshot mid-write, and the serialization runs
        # on the manager's own thread: ckpt.save_ms never lands
        # inside a step dispatch.
        self.checkpoint = checkpoint
        self.checkpoint_every = max(0, int(checkpoint_every or 0))
        self.session_state = session_state
        self.checkpoints = 0
        self._ckpt_request = threading.Event()
        # A PreemptionGuard (blendjax.checkpoint.preempt) attaches
        # itself here; submit() honors the flag at the next step
        # boundary with a drain + synchronous snapshot.
        self.preempt = None
        # ring entries: [loss, t_dispatch_mono, images, traces]
        self._pending: collections.deque = collections.deque()
        self.losses: list = []
        self.steps = 0
        self.dispatches = 0
        self.inflight_hwm = 0
        self.host_blocks = 0
        self.images_retired = 0
        self._mfu_mark: tuple | None = None  # (t_mono, images_retired)
        self._t_first_dispatch: float | None = None
        # Cold-start accounting (docs/performance.md "Instant start"):
        # build() stamps startup_ms (model init + step AOT-compile wall
        # time); the first retirement stamps time_to_first_step_ms
        # relative to construction. Both surface in `stats` and the
        # live_start bench row, where the warm-vs-cold persistent-cache
        # ratio is CI-gated.
        self._t_created = time.monotonic()
        self._t_first_retire: float | None = None
        self.startup_ms: float | None = None

    def _resolve_peak_flops(self) -> None:
        """The ``train.mfu`` gauge needs BOTH knobs; historically
        ``flops_per_image`` without ``peak_flops`` silently published
        nothing. Now the denominator defaults from the known-chip peaks
        table (x ``self.chips`` on mesh drivers) when the backend is
        identifiable, and otherwise the missing knob is named once at
        build time instead of the gauge vanishing without a word."""
        if not self.flops_per_image or self.peak_flops:
            return
        chips = max(1, int(getattr(self, "chips", 1) or 1))
        default = default_peak_flops()
        if default:
            peak, label = default
            self.peak_flops = peak * chips
            _log_once(
                logger.info,
                "train.mfu: peak_flops defaulted to %.4g "
                "(%s known-chip peak x %d chip(s))",
                self.peak_flops, label, chips,
            )
        else:
            _log_once(
                logger.warning,
                "train.mfu gauge disabled: flops_per_image is set but "
                "peak_flops=None and this backend's chip is not in the "
                "known-peaks table — pass peak_flops= to the driver",
            )

    @classmethod
    def build(cls, model, example_batch, *, loss_fn=None, optimizer=None,
              learning_rate: float = 1e-3, rng=None, augment=None,
              augment_rng=None, precision=None, aot: bool = True,
              aot_cache_dir: str | None = None, resume: bool = False,
              **driver_kwargs):
        """Model -> ready driver, with the step set AOT-compiled.

        One call covers init, restore, and warm-up: ``make_train_state``
        from ``example_batch["image"]``, an optional checkpoint restore
        (``resume=True`` with ``checkpoint=`` in ``driver_kwargs`` —
        restored driver counters are loaded and the session dict is left
        on ``driver.resumed_session`` for the caller's lineage restore),
        then ``blendjax.train.aot.build_aot_step`` compiles every
        bucket-ladder shape before step 0 — behind the persistent
        compilation cache when ``aot_cache_dir`` is set, so elastic
        resume and preemption churn pay milliseconds, not re-trace
        time. The total build wall time lands on ``driver.startup_ms``.
        """
        from blendjax.train.steps import (
            make_supervised_step,
            make_train_state,
        )

        t0 = time.monotonic()
        if not isinstance(example_batch, dict) or "image" not in example_batch:
            raise TypeError(
                "build() needs a full example batch dict (at least "
                "'image' + the loss's fields) to derive the AOT ladder"
            )
        state = make_train_state(
            model, example_batch["image"], optimizer=optimizer,
            learning_rate=learning_rate, rng=rng,
        )
        session = None
        mgr = driver_kwargs.get("checkpoint")
        if resume and mgr is not None:
            restored = mgr.restore(state)
            if restored is not None:
                state = restored.state
                session = restored.session
        step = make_supervised_step(
            loss_fn=loss_fn, augment=augment, augment_rng=augment_rng,
            precision=precision,
        )
        if aot:
            from blendjax.train.aot import build_aot_step, cache_key

            buckets = driver_kwargs.get("buckets")
            step = build_aot_step(
                step, state, example_batch, buckets=buckets,
                cache_dir=aot_cache_dir,
                key=cache_key(
                    model=model, precision=precision, buckets=buckets,
                ) if aot_cache_dir else None,
                ledger_name=f"{type(model).__name__}.supervised_step",
            )
        drv = cls(step, state, **driver_kwargs)
        drv._adopt_cost_model_flops(step, example_batch)
        drv._t_created = t0  # cold-start clock starts at build entry
        drv.startup_ms = (time.monotonic() - t0) * 1e3
        drv.resumed_session = session
        if isinstance(session, dict) and session.get("driver"):
            drv.load_state_dict(session["driver"])
        return drv

    def _adopt_cost_model_flops(self, step, example_batch,
                                entries=None) -> None:
        """Cost-model MFU numerator from the device ledger: when the
        caller hand-fed no ``flops_per_image``, the AOT build's ledger
        entries already hold XLA's own FLOPs count per signature — use
        the full-batch entry's flops / batch as the numerator (hand-fed
        stays the override). Accounting only; never fails a build."""
        if self.flops_per_image:
            return
        try:
            if entries is None:
                entries = getattr(step, "ledger_entries", None) or []
            entries = [
                e for e in entries
                if isinstance(e.get("flops"), float) and e.get("batch_images")
            ]
            if not entries:
                return
            lead = int(np.shape(example_batch["image"])[0])
            match = [e for e in entries if e["batch_images"] == lead]
            e = max(match or entries, key=lambda e: e["batch_images"])
            # cost_analysis() counts the PER-DEVICE partitioned program;
            # on a mesh the global batch spreads over `chips` devices,
            # so total flops per image is per-device flops x chips /
            # global batch (chips=1 single-chip: a plain ratio)
            chips = max(1, int(getattr(self, "chips", 1) or 1))
            self.flops_per_image = e["flops"] * chips / e["batch_images"]
            self.mfu_source = "cost-model"
            self._resolve_peak_flops()
        except Exception:  # pragma: no cover - accounting-only path
            logger.debug("cost-model flops adoption failed", exc_info=True)

    # -- ring ----------------------------------------------------------------

    @staticmethod
    def _is_done(arr) -> bool:
        """Non-blocking readiness poll (shared definition:
        :func:`blendjax.utils.device.transfer_done`)."""
        from blendjax.utils.device import transfer_done

        return transfer_done(arr)

    def _retire(self, entry) -> None:
        """Account one completed ring entry: the dispatch->retirement
        device-timeline histogram, the live MFU gauge, and the terminal
        stamp of any frame trace riding the entry. Host bookkeeping
        only — the loss value itself is NOT fetched here."""
        _loss, t0, images, traces = entry
        now = time.monotonic()
        if self._t_first_retire is None:
            self._t_first_retire = now
        metrics.observe("train.step_device_ms", (now - t0) * 1e3)
        self.images_retired += images
        if self.flops_per_image and self.peak_flops:
            if self._mfu_mark is None:
                self._mfu_mark = (now, self.images_retired)
            else:
                t_mark, img_mark = self._mfu_mark
                dt = now - t_mark
                if dt >= 1.0:
                    rate = (self.images_retired - img_mark) / dt
                    metrics.gauge(
                        "train.mfu",
                        round(
                            rate * self.flops_per_image / self.peak_flops,
                            6,
                        ),
                    )
                    self._mfu_mark = (now, self.images_retired)
        if traces:
            for tr in traces:
                trace_stage(tr, TERMINAL_STAGE)
                tracer.complete(tr)

    def _block_oldest(self) -> None:
        """Retire the oldest in-flight entry, blocking if needed. A
        block is counted only when genuine (the entry wasn't already
        done): with overlap working, the entry ``inflight`` steps back
        has finished and this is a free pop."""
        import jax

        entry = self._pending.popleft()
        if not self._is_done(entry[0]):
            self.host_blocks += 1
            # Registry mirror of the instance stat: the stall doctor
            # (blendjax.obs.doctor) reads plain metrics snapshots, and
            # a genuine ring-full block is its strongest step-bound
            # signal.
            metrics.count("train.host_blocks")
            with metrics.span("driver.ring_wait"):
                jax.block_until_ready(entry[0])
        self._retire(entry)

    def _sync_oldest(self) -> None:
        """Periodic loss fetch (the designed host-sync point): the
        OLDEST in-flight loss — a real training signal that blocks the
        least, because everything newer stays dispatched."""
        if not self._pending:
            return
        entry = self._pending.popleft()
        with metrics.span("driver.loss_sync"):
            self.losses.append(
                float(np.asarray(entry[0]).reshape(-1)[-1])
            )
        self._retire(entry)

    # -- dispatch ------------------------------------------------------------

    @staticmethod
    def _batch_images(batch) -> int:
        """Images this batch trains on — for the MFU gauge. Packed
        chunk groups count K' rows x the per-batch lead from `_spec`;
        decoded (K, B, H, W, C) superbatches count K*B; plain batches
        their leading dim. Shape reads only — no device values."""
        idx = batch.get("_echo_idx")
        if idx is None:
            idx = batch.get("_rl_idx")
        if idx is not None:
            # fused draw token (echo or RL replay): the host index
            # vector names every sample the step trains on (the gather
            # runs inside the jit)
            return int(len(idx))
        packed = batch.get("_packed")
        if packed is not None:
            spec = batch.get("_spec") or ()
            lead = next(
                (s[0] for n, _d, s, *_r in spec if n == "xy"), None
            )
            if lead is None:
                lead = max(
                    (s[0] for _n, _d, s, *_r in spec if s), default=1
                )
            return int(packed.shape[0]) * int(lead)
        img = batch.get("image")
        if img is not None and getattr(img, "ndim", 0) >= 4:
            shp = img.shape
            return int(shp[0] * shp[1]) if img.ndim >= 5 else int(shp[0])
        lead = next(
            (
                v.shape[0] for k, v in batch.items()
                if not k.startswith("_") and getattr(v, "ndim", 0) >= 1
            ),
            0,
        )
        return int(lead)

    def ensure_ring_slot(self) -> None:
        """Retire finished in-flight entries (non-blocking completion
        poll) and, when the ring is genuinely full, block on the
        oldest until a slot frees. ``submit`` runs this before every
        dispatch; callers that must not hold a lock across a device
        wait (the RL learner holds the reservoir lock across its
        dispatch) call it themselves FIRST, so the locked section
        contains only the async dispatch enqueue."""
        pending = self._pending
        while pending and self._is_done(pending[0][0]):
            self._retire(pending.popleft())  # completion tracking
        while len(pending) >= self.inflight:
            self._block_oldest()

    def submit(self, batch, post: bool = True) -> None:
        """Dispatch one step without waiting on its result. ``post``
        controls whether the cadenced step-boundary work
        (:meth:`post_dispatch`) runs before returning — callers that
        dispatch inside a critical section pass ``post=False`` and run
        it themselves after releasing the lock."""
        if self.preempt is not None and self.preempt.requested:
            self._preempt_flush()
        if (
            self.pad_partial and batch.get("_partial")
            and "_mask" not in batch
        ):
            from blendjax.data.batcher import pad_to_bucket

            batch = pad_to_bucket(batch, buckets=self.buckets)
        if self.place is not None:
            # Free a ring slot FIRST so at most `inflight` transfer+step
            # pairs are outstanding, then commit the grouped async
            # placement — it overlaps every older in-flight dispatch.
            # Runs before the trace pop below so the "place" stamp
            # precedes "step_dispatch" like it does on the feeder path.
            self.ensure_ring_slot()
            batch = self.place(batch)
        # Frame traces must come OFF the batch before the step call:
        # a trace dict is host-side metadata no jit can consume (the
        # same contract as `_meta`, which the step builders filter).
        traces = trace_pop(batch)
        if traces:
            for tr in traces:
                trace_stage(tr, "step_dispatch")
        # Scenario stamps (blendjax.scenario) are the same kind of
        # host-side sidecar: string/None leaves a jit flattens and
        # rejects. The eager echo path attaches per-row stamps to
        # SAMPLE batches (the fused token path filters keys itself),
        # so pop them here — accounting reads them BEFORE submit.
        if "_scenario_rows" in batch or "_scenario" in batch:
            batch = {
                k: v for k, v in batch.items()
                if k not in ("_scenario_rows", "_scenario")
            }
        images = self._batch_images(batch)
        if self._t_first_dispatch is None:
            self._t_first_dispatch = time.monotonic()
        self.ensure_ring_slot()
        with metrics.span("train.dispatch"):
            self.state, m = self.step(self.state, batch)
        metrics.count("train.dispatches")
        if self.retrace_audit is not None:
            # cache-size delta AFTER the dispatch: growth past warm-up
            # counts device.retraces and attributes this batch signature
            self.retrace_audit.observe(batch)
        self.dispatches += 1
        self.steps += 1
        pending = self._pending
        pending.append([m["loss"], time.monotonic(), images, traces])
        if len(pending) > self.inflight_hwm:
            self.inflight_hwm = len(pending)
        # Registry mirror runs UNCONDITIONALLY (gauge_max is already a
        # no-op when not a new high): gating it on instance-hwm growth
        # meant a metrics.reset() mid-run (bench's measured-window
        # reset) silently lost the gauge forever — the instance hwm,
        # pinned during warmup, never grew again.
        metrics.gauge_max("train.inflight_hwm", len(pending))
        if post:
            self.post_dispatch()

    def post_dispatch(self) -> None:
        """The cadenced step-boundary work ``submit`` runs after each
        dispatch: the periodic loss fetch (a BLOCKING d2h of the
        oldest in-flight value) and the checkpoint hand-off (a
        session-state collection + device clones). Factored out so
        callers that dispatch under a lock (the RL learner holds the
        reservoir lock across its dispatch enqueue) can run this part
        OUTSIDE it — neither belongs in a critical section another
        thread waits on."""
        if self.sync_every and self.steps % self.sync_every == 0:
            self._sync_oldest()
        if self.checkpoint is not None and (
            self._ckpt_request.is_set()
            or (
                self.checkpoint_every
                and self.steps % self.checkpoint_every == 0
            )
        ):
            self._ckpt_request.clear()
            self._dispatch_checkpoint()

    # -- checkpointing ---------------------------------------------------------

    def request_checkpoint(self) -> None:
        """Thread-safe: snapshot at the NEXT step boundary (the SLO
        watchdog's checkpoint-on-breach arm calls this from the
        reporter thread — the save itself still happens at
        retirement, never mid-flight)."""
        self._ckpt_request.set()

    def _dispatch_checkpoint(self) -> None:
        """Hand the current state + session to the SnapshotManager.
        Async by design: device leaves are cloned before this returns
        (a handful of non-train dispatches), the d2h + file writes run
        on the manager's writer thread."""
        session = {}
        if callable(self.session_state):
            session = dict(self.session_state() or {})
        session.setdefault("driver", self.state_dict())
        self.checkpoint.save_async(
            self.steps, self.state, session=session
        )
        self.checkpoints += 1

    def _preempt_flush(self) -> None:
        """The SIGTERM path: drain the ring (every in-flight dispatch
        retires — donated buffers settle), snapshot the final state,
        block until it commits, then raise for the run loop to exit.
        See blendjax.checkpoint.preempt."""
        from blendjax.checkpoint.preempt import PreemptionRequested

        self.drain()
        outcome = "no checkpoint manager attached"
        if self.checkpoint is not None:
            self._dispatch_checkpoint()
            # The one sanctioned synchronous checkpoint wait on the hot
            # path: the process is exiting on a preemption deadline —
            # an un-flushed async write would race interpreter teardown.
            # bjx: ignore[BJX114]
            self.checkpoint.wait()
            # the writer never raises into the train loop, so silence
            # is not evidence: report what actually landed — a
            # scheduler that believes a failed flush committed loses
            # every step since the last cadence save
            err = getattr(self.checkpoint, "last_error", None)
            outcome = (
                f"snapshot FAILED ({err!r}) — resuming from the last "
                "committed step" if err is not None
                else "snapshot committed"
            )
        metrics.count("ckpt.preemptions")
        raise PreemptionRequested(
            f"preemption honored at step {self.steps}: {outcome}"
        )

    def checkpoint_now(self, wait: bool = True) -> None:
        """Synchronous out-of-band snapshot (teardown / eval
        boundaries): drain the ring, snapshot, optionally block until
        committed. NOT for the hot loop — cadence saves go through
        ``checkpoint_every``/``request_checkpoint`` and stay async."""
        if self.checkpoint is None:
            raise RuntimeError("no checkpoint manager attached")
        self.drain()
        self._dispatch_checkpoint()
        if wait:
            # teardown flush, same justification as _preempt_flush
            # bjx: ignore[BJX114]
            self.checkpoint.wait()
            err = getattr(self.checkpoint, "last_error", None)
            if err is not None:
                raise RuntimeError(
                    f"checkpoint_now: snapshot write failed: {err!r}"
                ) from err

    #: Loss-history tail kept in the session snapshot: continuity only
    #: needs the step counters (cadence alignment), so bounding the
    #: tail keeps per-snapshot work and session size O(1) over a long
    #: run instead of re-serializing an ever-growing list every save.
    LOSS_TAIL = 4096

    def state_dict(self) -> dict:
        """Driver counters for the session snapshot: a resumed driver
        continues the same step numbering, so sync/checkpoint cadence
        and the augment key folds (keyed by ``state.step``) line up
        with the uninterrupted run."""
        tail = self.losses[-self.LOSS_TAIL:]
        return {
            "steps": self.steps,
            "dispatches": self.dispatches,
            "images_retired": self.images_retired,
            "checkpoints": self.checkpoints,
            "losses": [float(v) for v in tail],
            "losses_total": len(self.losses),
        }

    def load_state_dict(self, d: dict) -> None:
        self.steps = int(d["steps"])
        self.dispatches = int(d.get("dispatches", d["steps"]))
        self.images_retired = int(d.get("images_retired", 0))
        self.checkpoints = int(d.get("checkpoints", 0))
        self.losses = [float(v) for v in d.get("losses", [])]

    def drain(self):
        """Block until every dispatched step completed and return the
        newest loss value (the d2h fetch transitively syncs the whole
        donated-state chain — the one sync honest on every backend;
        see docs/performance.md measurement hygiene)."""
        if not self._pending:
            return self.losses[-1] if self.losses else None
        newest = self._pending[-1]
        val = float(np.asarray(newest[0]).reshape(-1)[-1])
        # the fetch transitively completed every older entry: retire
        # them all (device-timeline accounting + trace terminal stamps)
        while self._pending:
            self._retire(self._pending.popleft())
        # Whole-run MFU at the drain barrier: the windowed gauge in
        # _retire needs >=1 s between retirements, so a short run (or
        # a drain landing mid-window) would otherwise end without one.
        if (
            self.flops_per_image and self.peak_flops
            and self.images_retired and self._t_first_dispatch is not None
        ):
            dt = max(time.monotonic() - self._t_first_dispatch, 1e-9)
            metrics.gauge(
                "train.mfu",
                round(
                    (self.images_retired / dt) * self.flops_per_image
                    / self.peak_flops,
                    6,
                ),
            )
        self.losses.append(val)
        return val

    def finish(self):
        """Drain and return ``(state, final_loss)``."""
        return self.state, self.drain()

    def run(self, batches, max_steps: int | None = None):
        """Drive a batch iterable end to end; returns
        ``(state, final_loss)``."""
        for batch in batches:
            self.submit(batch)
            if max_steps is not None and self.steps >= max_steps:
                break
        return self.finish()

    @property
    def time_to_first_step_ms(self) -> float | None:
        """Wall time from driver construction to the first retired step
        (``None`` until one retires) — the end-to-end cold-start number
        the ``live_start`` bench row gates warm-vs-cold."""
        if self._t_first_retire is None:
            return None
        return (self._t_first_retire - self._t_created) * 1e3

    @property
    def stats(self) -> dict:
        return {
            "steps": self.steps,
            "dispatches": self.dispatches,
            "inflight": self.inflight,
            "inflight_hwm": self.inflight_hwm,
            "host_blocks": self.host_blocks,
            "syncs": len(self.losses),
            "images_retired": self.images_retired,
            "checkpoints": self.checkpoints,
            "startup_ms": self.startup_ms,
            "time_to_first_step_ms": self.time_to_first_step_ms,
            "mfu_source": self.mfu_source,
        }
