"""MeshTrainDriver: the live pipeline as one data-parallel program on a
named mesh.

``dryrun_multichip`` has long validated dp/fsdp/tp meshes to f32-exact
equivalence on 8 devices, but the *live* path — ShardedHostIngest ->
DeviceFeeder -> TrainDriver -> echo reservoir — ran on exactly one chip.
This module promotes the dryrun into the first-class driver (ROADMAP
item 1: "the structural refactor that makes every other item scale"):

- the :class:`~blendjax.data.pipeline.StreamDataPipeline` takes
  ``mesh=`` and places every ingest batch as a global ``jax.Array``
  sharded over ``data`` (one grouped placement per batch single-host,
  one ``make_array_from_process_local_data`` per field multihost — no
  per-device host loops, bjx-lint BJX111);
- :func:`make_mesh_supervised_step` / :func:`make_mesh_fused_step`
  build the SAME jitted steps the single-chip path runs, with explicit
  ``in_shardings``/``out_shardings`` pinned from the concrete train
  state — donation requires matching in/out layouts, and pinning them
  means a jit upgrade or a stray resharded input can never silently
  move the optimizer state mid-run;
- :class:`MeshTrainDriver` keeps the completion-tracked dispatch ring,
  device-timeline metrics, and live MFU gauge working unchanged on
  sharded outputs: the readiness poll (``transfer_done``) reads the
  GLOBAL array's ready bit, and MFU scales ``peak_flops_per_chip`` by
  the participating chip count;
- the :class:`~blendjax.data.echo.SampleReservoir` ring shards over
  ``data`` too (``EchoingPipeline(mesh=...)``), so echo capacity grows
  with the mesh and drawn batches leave pre-sharded in the feeder's
  batch layout.

Training semantics are layout-free: the same recorded stream through a
1-device and an 8-device mesh produces f32-identical losses
(tests/test_mesh_driver.py pins it), and throughput scales with chips —
the ``multichip_live`` bench row measures img/s at mesh sizes 1/2/4/8
with a scaling-efficiency figure.
"""

from __future__ import annotations

# bjx: driver-hot-path (BJX106/BJX108 hold here exactly as in driver.py)
# bjx: mesh-hot-path (BJX111: no per-device placement loops, no host
# materialization of global arrays in the dispatch loop)

from blendjax.train.driver import TrainDriver


def _require_jax():
    import jax

    return jax


def _state_jit_shardings(state, mesh):
    """The sharding pytree pinning a concrete state's layout through a
    ``step(state, *rest) -> (state, metrics)`` jit — the public helper
    normalized onto the driver's mesh (see
    :func:`blendjax.parallel.state_shardings` for the rules)."""
    from blendjax.parallel.sharding import state_shardings

    return state_shardings(state, mesh=mesh)


def make_mesh_supervised_step(
    state,
    mesh,
    loss_fn=None,
    donate: bool = True,
    augment=None,
    augment_rng=None,
):
    """:func:`blendjax.train.make_supervised_step` with the layout made
    explicit: ``in_shardings``/``out_shardings`` are pinned from the
    concrete ``state`` (params/optimizer leaves keep the mesh rules
    they were created with), so the donated update reuses the sharded
    buffers in place and can never drift layouts across a run. The
    batch side stays unspecified — it arrives committed to the batch
    sharding by the feeder (or the echo reservoir), and jit infers it.

    ONE step body: this delegates to the plain builder with the state
    sharding threaded through, so single-chip and mesh runs can never
    train different math.
    """
    from blendjax.train.steps import make_supervised_step

    return make_supervised_step(
        loss_fn=loss_fn, donate=donate, augment=augment,
        augment_rng=augment_rng,
        state_sharding=_state_jit_shardings(state, mesh),
    )


def make_mesh_fused_step(
    state,
    mesh,
    loss_fn=None,
    donate: bool = True,
    augment=None,
    augment_rng=None,
    data_axis: str = "data",
):
    """:func:`blendjax.train.make_fused_tile_step` with pinned state
    shardings: the still-encoded packed group decodes INSIDE the train
    jit (one device dispatch per step, zero standalone decode calls —
    the invariants the single-chip driver established) while the state
    layout is held by explicit ``in_shardings``/``out_shardings``.

    ONE step body: this delegates to the plain builder, adding only
    the mesh-specific pieces — the pinned state sharding tree, and an
    in-jit constraint that re-shards the just-decoded (K, B, ...)
    fields onto the batch axis (the packed wire buffer arrives
    replicated because bytes can't shard, and without the constraint
    GSPMD is free to keep the whole scan replicated per chip — data
    parallelism in name only)."""
    jax = _require_jax()

    from blendjax.train.steps import make_fused_tile_step

    if data_axis not in mesh.axis_names:
        # fail at build time: a typo'd/missing batch axis would
        # otherwise silently constrain the scan to REPLICATED — 1x
        # throughput at N chips, no error
        raise ValueError(
            f"data_axis {data_axis!r} is not an axis of mesh "
            f"{dict(mesh.shape)}"
        )

    def _pin_batch_axis(superbatch):
        from jax.sharding import NamedSharding, PartitionSpec

        from blendjax.parallel.sharding import batch_sharding

        bs = batch_sharding(mesh, axis=data_axis)
        sb = NamedSharding(mesh, PartitionSpec(None, *(bs.spec or ())))
        return {
            k: (
                jax.lax.with_sharding_constraint(v, sb)
                if getattr(v, "ndim", 0) >= 2 else v
            )
            for k, v in superbatch.items()
        }

    return make_fused_tile_step(
        loss_fn=loss_fn, donate=donate, augment=augment,
        augment_rng=augment_rng,
        state_sharding=_state_jit_shardings(state, mesh),
        superbatch_constraint=_pin_batch_axis,
    )


def make_mesh_echo_fused_step(
    state,
    mesh,
    reservoir,
    loss_fn=None,
    donate: bool = True,
    precision=None,
    data_axis: str = "data",
):
    """:func:`blendjax.train.make_echo_fused_step` with the mesh
    layouts made explicit: the state's ``in_shardings``/
    ``out_shardings`` pinned from the concrete ``state`` (the donated
    update can never drift layouts), the reservoir RING's
    ``data``-axis sharding pinned into the jit's buffer argument (a
    drifted ring placement fails loudly at dispatch instead of
    silently resharding the multi-GB ring every step), and an in-jit
    constraint re-sharding the just-gathered batch over the batch
    axis — the same hook trio ``make_mesh_fused_step`` uses for
    packed groups.

    ``reservoir`` is the :class:`blendjax.data.echo.SampleReservoir`
    backing the ``EchoingPipeline(mesh=..., emit_draws=True)`` this
    step trains from; its ring sharding must cover ``data_axis``
    (construct the pipeline with ``mesh=``). ONE step body: delegates
    to the plain builder, so single-chip and mesh echo runs train
    identical math."""
    jax = _require_jax()

    from blendjax.train.steps import make_echo_fused_step

    if data_axis not in mesh.axis_names:
        # same build-time failure as make_mesh_fused_step: a typo'd
        # batch axis would silently train replicated
        raise ValueError(
            f"data_axis {data_axis!r} is not an axis of mesh "
            f"{dict(mesh.shape)}"
        )
    ring_sharding = getattr(reservoir, "sharding", None)
    if ring_sharding is None:
        raise ValueError(
            "the reservoir ring is not mesh-sharded — construct the "
            "EchoingPipeline (or SampleReservoir) with mesh=/sharding= "
            "so echo capacity shards over the data axis"
        )

    def _pin_drawn_batch(batch):
        from blendjax.parallel.sharding import batch_sharding

        bs = batch_sharding(mesh, axis=data_axis)
        return {
            k: (
                jax.lax.with_sharding_constraint(v, bs)
                if getattr(v, "ndim", 0) >= 1 else v
            )
            for k, v in batch.items()
        }

    return make_echo_fused_step(
        reservoir_draw=reservoir.draw,
        loss_fn=loss_fn, donate=donate, precision=precision,
        state_sharding=_state_jit_shardings(state, mesh),
        buffer_sharding=ring_sharding,
        draw_constraint=_pin_drawn_batch,
    )


class MeshTrainDriver(TrainDriver):
    """:class:`~blendjax.train.driver.TrainDriver` running the live
    loop on a named mesh.

    Everything the single-chip driver proved carries over unchanged —
    the completion-tracked dispatch ring polls readiness on the GLOBAL
    array (one bit covering every shard), device-timeline histograms
    time dispatch->retirement of the sharded program, and exactly one
    device dispatch per step — while throughput and MFU account for
    the whole mesh:

    - ``peak_flops_per_chip`` (or a pre-scaled ``peak_flops``) is
      multiplied by the participating chip count — ALL processes'
      chips, since the jitted step is one SPMD program over the global
      batch — so the live ``train.mfu`` gauge reads the same whether
      one chip or 64 run the step;
    - ``stats`` carries ``chips``/``processes`` beside the ring
      numbers, and per-chip throughput is ``images/s / chips``;
    - :meth:`fleet_snapshots`/:meth:`fleet_report` aggregate each
      process's doctor/lineage/trace view into one fleet report
      (:mod:`blendjax.obs.fleetview`), process index tagged.

    Build the step with :func:`make_mesh_supervised_step` (decoded
    batches, echo path) or :func:`make_mesh_fused_step` (packed tile/pal
    groups), pair with ``StreamDataPipeline(mesh=mesh, ...)``, and the
    entire ingest->train loop is mesh-resident.
    """

    def __init__(self, step, state, mesh, *, data_axis: str = "data",
                 inflight: int = 4, sync_every: int = 32,
                 pad_partial: bool = True, buckets=None,
                 flops_per_image: float | None = None,
                 peak_flops_per_chip: float | None = None,
                 peak_flops: float | None = None,
                 checkpoint=None, checkpoint_every: int = 0,
                 session_state=None, place=None):
        from blendjax.parallel.sharding import mesh_chip_count

        self.mesh = mesh
        self.data_axis = data_axis
        self.chips = mesh_chip_count(mesh)
        if peak_flops is None and peak_flops_per_chip:
            peak_flops = float(peak_flops_per_chip) * self.chips
        super().__init__(
            step, state, inflight=inflight, sync_every=sync_every,
            pad_partial=pad_partial, buckets=buckets,
            flops_per_image=flops_per_image, peak_flops=peak_flops,
            checkpoint=checkpoint, checkpoint_every=checkpoint_every,
            session_state=session_state, place=place,
        )

    @classmethod
    def build(cls, model, mesh=None, example_batch=None, loss_fn=None,
              fused: bool = False, optimizer=None,
              learning_rate: float = 1e-3, rng=None, augment=None,
              augment_rng=None, aot: bool = False,
              aot_cache_dir: str | None = None, aot_batch=None,
              layout=None, rules=None,
              **driver_kwargs):
        """One call from model to mesh-resident driver: init the train
        state sharded by the layout's partition rules (params over
        ``fsdp``/``tp`` where the axes exist, replicated otherwise —
        see ``param_sharding_rules``/``resolve_rules``), build the
        pinned-sharding step (``fused=True`` for packed tile/pal
        streams), and wrap the driver. ``example_batch`` is one host
        batch of the stream's image field (shapes only; values never
        train).

        ``layout`` (a :class:`blendjax.parallel.Layout`, a name like
        ``"data×fsdp"``/``"data2xfsdp4"``, or an axis dict) selects
        the mesh composition AND the partition rules in one spelling;
        with ``mesh=None`` the mesh is created from it. ``rules``
        overrides the rule set explicitly (a tuple of
        :class:`~blendjax.parallel.PartitionRule`); without either the
        model's own ``partition_rules()`` applies when it defines one.

        ``aot=True`` with ``aot_batch`` (a full example batch dict —
        image + the loss's fields) AOT-compiles the step for every
        bucket-ladder shape before step 0, behind the persistent
        compilation cache when ``aot_cache_dir`` is set (docs/
        performance.md "Instant start"). The fused tile step is a host
        dispatcher over inner jits and is not lowerable as one unit, so
        AOT applies to the supervised step only."""
        import time as _time

        from blendjax.parallel.sharding import (
            resolve_layout,
            resolve_rules,
            validate_batch_sharding,
        )
        from blendjax.train.steps import make_train_state

        t0 = _time.monotonic()
        data_axis = driver_kwargs.get("data_axis", "data")
        if mesh is None:
            if layout is None:
                raise ValueError(
                    "MeshTrainDriver.build needs a mesh or a layout — "
                    "pass mesh=create_mesh(...) or layout='data×fsdp'"
                )
            mesh = resolve_layout(layout).create_mesh()
        if example_batch is None:
            raise ValueError("example_batch is required (shapes only)")
        rules = resolve_rules(rules=rules, layout=layout, model=model)
        if aot_batch is not None:
            # build-time gate: a model-axis-sharded *batch* compiles a
            # wrong program (satellite of the layout system; see
            # validate_batch_sharding)
            for k, v in aot_batch.items():
                sh = getattr(v, "sharding", None)
                if sh is not None:
                    validate_batch_sharding(
                        sh, data_axis=data_axis, what=f"aot_batch[{k!r}]"
                    )
        state = make_train_state(
            model, example_batch, optimizer=optimizer,
            learning_rate=learning_rate, rng=rng, mesh=mesh,
            rules=rules,
        )
        if fused:
            step = make_mesh_fused_step(
                state, mesh, loss_fn=loss_fn, augment=augment,
                augment_rng=augment_rng,
                # the fused step re-shards decoded fields over the SAME
                # axis the driver/pipeline use
                data_axis=driver_kwargs.get("data_axis", "data"),
            )
        else:
            step = make_mesh_supervised_step(
                state, mesh, loss_fn=loss_fn, augment=augment,
                augment_rng=augment_rng,
            )
        ledger_entry = None
        if aot and not fused and aot_batch is not None:
            from blendjax.train.aot import build_aot_step, cache_key

            buckets = driver_kwargs.get("buckets")
            step = build_aot_step(
                step, state, aot_batch, buckets=buckets,
                cache_dir=aot_cache_dir,
                key=cache_key(
                    model=model, mesh=mesh, buckets=buckets,
                    layout=layout, rules=rules,
                ) if aot_cache_dir else None,
                mesh=mesh, data_axis=data_axis,
                ledger_name=f"{type(model).__name__}.mesh_supervised_step",
            )
        elif aot_batch is not None and not fused:
            # Accounting-only registration for the non-AOT path: one
            # extra lower+compile (served from the persistent cache on
            # the first real dispatch) buys the mesh's per-collective
            # byte breakdown + cost-model FLOPs at build time. Opt-in
            # by passing aot_batch; guarded inside register_step.
            from blendjax.obs.devledger import ledger

            ledger_entry = ledger.register_step(
                f"{type(model).__name__}.mesh_supervised_step",
                step, state, aot_batch, mesh=mesh,
            )
        drv = cls(step, state, mesh, **driver_kwargs)
        # the committed layout, by name — bench rows and fleet reports
        # tag throughput/collective figures with it
        drv.layout = (
            resolve_layout(layout).name if layout is not None
            else "×".join(mesh.axis_names)
        )
        drv._adopt_cost_model_flops(
            step, {"image": example_batch},
            entries=[ledger_entry] if ledger_entry else None,
        )
        drv._t_created = t0
        drv.startup_ms = (_time.monotonic() - t0) * 1e3
        return drv

    def batch_sharding(self):
        """The layout live batches must arrive in (what
        ``StreamDataPipeline(mesh=...)`` produces)."""
        from blendjax.parallel.sharding import batch_sharding

        return batch_sharding(self.mesh, axis=self.data_axis)

    # -- fleet observability --------------------------------------------------

    def fleet_snapshots(self, prefetch: int | None = None) -> list:
        """Every participating process's observability snapshot
        (metrics/lineage/trace/doctor verdict), process-index tagged;
        single-process runs return just the local one."""
        from blendjax.obs.fleetview import gather_fleet_snapshots

        return gather_fleet_snapshots(driver=self.stats, prefetch=prefetch)

    def fleet_report(self, prefetch: int | None = None) -> dict:
        """One aggregated fleet view over :meth:`fleet_snapshots`
        (:func:`blendjax.obs.fleetview.fleet_report`)."""
        from blendjax.obs.fleetview import fleet_report

        return fleet_report(self.fleet_snapshots(prefetch=prefetch))

    @property
    def stats(self) -> dict:
        s = TrainDriver.stats.fget(self)
        s["chips"] = self.chips
        if getattr(self, "layout", None):
            s["layout"] = self.layout
        try:
            s["processes"] = _require_jax().process_count()
        except Exception:
            s["processes"] = 1
        return s


__all__ = [
    "MeshTrainDriver",
    "make_mesh_echo_fused_step",
    "make_mesh_fused_step",
    "make_mesh_supervised_step",
]
