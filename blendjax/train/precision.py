"""Precision policies, re-exported at their train-layer name.

The substance lives in :mod:`blendjax.precision` — a jax-only module
OUTSIDE the train package — because the model constructors resolve
their compute dtype from it at import time, and importing anything
under ``blendjax.train`` executes the package init (optax, flax
training state, checkpointing, the driver stack): a process that only
builds a model must not pay for — or depend on — the whole train
layer. Step-builder callers keep importing from here; both names are
the same module contents.
"""

from blendjax.precision import (  # noqa: F401
    BF16_COMPUTE,
    BF16_GRADS,
    DEFAULT_POLICY,
    F32,
    POLICIES,
    PrecisionPolicy,
    cast_floating,
    default_compute_dtype,
    policy_value_and_grad,
    resolve_policy,
)

from blendjax.precision import __all__  # noqa: F401
