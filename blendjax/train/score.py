"""Score-function (REINFORCE) gradients through non-differentiable
simulators.

The core trick of the reference's densityopt example
(``examples/densityopt/densityopt.py:285-309``): simulation parameters are
sampled from a Gaussian, rendered by the (non-differentiable) producer,
scored by a loss on the consumer, and the sampling distribution is updated
with ``grad log p(theta) * (loss - baseline)``. blendjax packages the
distribution + update as a reusable component; the association of rendered
frames back to their parameter samples rides on ``shape_id``
(``densityopt.py:99-103,119``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class GaussianSimParams:
    """Diagonal-Gaussian distribution over simulator parameters with a
    REINFORCE update and a running-mean baseline."""

    def __init__(self, mu, log_sigma, learning_rate: float = 5e-2,
                 baseline_decay: float = 0.9):
        self.mu = jnp.asarray(mu, jnp.float32)
        self.log_sigma = jnp.asarray(log_sigma, jnp.float32)
        self.lr = learning_rate
        self.baseline = None
        self.baseline_decay = baseline_decay

    def sample(self, key, n: int):
        """Draw n parameter vectors; returns (samples (n,D))."""
        eps = jax.random.normal(key, (n, *self.mu.shape))
        return self.mu + jnp.exp(self.log_sigma) * eps

    def log_prob(self, theta, mu=None, log_sigma=None):
        """Diagonal-Gaussian log density (also the differentiated core of
        :meth:`update`, so the math lives in exactly one place)."""
        mu = self.mu if mu is None else mu
        log_sigma = self.log_sigma if log_sigma is None else log_sigma
        var = jnp.exp(2 * log_sigma)
        return -0.5 * (
            (theta - mu) ** 2 / var + 2 * log_sigma + jnp.log(2 * jnp.pi)
        ).sum(-1)

    def update(self, theta, losses):
        """REINFORCE step: lower expected loss (``densityopt.py:290-309``).

        theta: (n, D) sampled params; losses: (n,) per-sample losses.
        Returns the plain mean loss (pre-baseline) for logging.
        """
        theta = jnp.asarray(theta, jnp.float32)
        losses = jnp.asarray(losses, jnp.float32)
        mean_loss = losses.mean()
        if self.baseline is None:
            self.baseline = mean_loss
        adv = losses - self.baseline

        def objective(mu, log_sigma):
            lp = self.log_prob(theta, mu, log_sigma)
            return (lp * jax.lax.stop_gradient(adv)).mean()

        gmu, gsig = jax.grad(objective, argnums=(0, 1))(
            self.mu, self.log_sigma
        )
        self.mu = self.mu - self.lr * gmu
        self.log_sigma = self.log_sigma - self.lr * gsig
        self.baseline = (
            self.baseline_decay * self.baseline
            + (1 - self.baseline_decay) * mean_loss
        )
        return float(mean_loss)


def chunk_across(items, n_chunks: int):
    """Split a list into n contiguous chunks (last may be short) — the
    reference's param fan-out across producer instances
    (``densityopt.py:95-107``)."""
    k, m = divmod(len(items), n_chunks)
    out = []
    i = 0
    for c in range(n_chunks):
        size = k + (1 if c < m else 0)
        out.append(items[i : i + size])
        i += size
    return out
