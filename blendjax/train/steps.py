"""Jitted training steps over mesh-sharded streamed batches.

These builders leave layouts to propagate from the arrays (jit infers;
GSPMD partitions). For the multi-chip LIVE loop use their
pinned-sharding twins in :mod:`blendjax.train.mesh_driver`
(``make_mesh_supervised_step`` / ``make_mesh_fused_step``): identical
training math, with ``in_shardings``/``out_shardings`` pinned from the
concrete state so the donated update can never drift layouts mid-run.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from flax.training.train_state import TrainState

from blendjax.parallel.sharding import param_sharding_rules
from blendjax.train.precision import policy_value_and_grad, resolve_policy


def make_train_state(
    model,
    example_input,
    optimizer=None,
    learning_rate: float = 1e-3,
    rng=None,
    mesh=None,
    rules=None,
    layout=None,
) -> TrainState:
    """Init params (sharded onto ``mesh`` per the partition rules) and
    wrap them with an optax optimizer in a flax TrainState.

    ``rules``/``layout`` select the parameter layout
    (:func:`blendjax.parallel.resolve_rules`: explicit rules win, then
    the layout's, then the model's own ``partition_rules()``, then the
    generic fsdp/tp defaults). Optimizer moments inherit the params'
    shardings through ``optax``'s ``zeros_like`` init, so one
    device_put here commits the WHOLE state to the layout."""
    from blendjax.parallel.sharding import resolve_rules

    rng = rng if rng is not None else jax.random.key(0)
    optimizer = optimizer or optax.adamw(learning_rate)
    params = model.init(rng, example_input)["params"]
    if mesh is not None:
        resolved = resolve_rules(rules=rules, layout=layout, model=model)
        params = jax.tree_util.tree_map_with_path(
            lambda p, v: jax.device_put(
                v, param_sharding_rules(mesh, p, v, rules=resolved)
            ),
            params,
        )
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optimizer
    )
    if mesh is not None:
        # moments inherit the params' shardings via optax zeros_like,
        # but optimizer scalars created fresh (adam's count) land on
        # the default device — commit them replicated so the WHOLE
        # state lives on the mesh and pinned jit shardings stay
        # mesh-uniform
        rep = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()
        )
        state = jax.tree_util.tree_map(
            lambda v: (
                v
                if not hasattr(v, "sharding")
                or isinstance(v.sharding, jax.sharding.NamedSharding)
                else jax.device_put(v, rep)
            ),
            state,
        )
    return state


def corner_loss(pred, xy, image_shape=None, mask=None):
    """MSE over predicted corner pixels, normalized to [0,1] image coords
    so the loss is resolution-independent.

    ``mask`` (lead,) marks valid rows of a bucket-padded partial batch
    (``blendjax.data.batcher.pad_to_bucket``): padded rows contribute
    nothing and the mean divides by the true row count, so a padded
    batch scores — and backpropagates — identically to its exact-shape
    form (up to float associativity). ``mask=None`` is bit-for-bit the
    old unmasked loss."""
    if image_shape is not None:
        h, w = image_shape
        scale = jnp.asarray([w, h], jnp.float32)
        pred = pred / scale
        xy = xy / scale
    err = (pred - xy.astype(jnp.float32)) ** 2
    if mask is None:
        return jnp.mean(err)
    per = err.reshape(err.shape[0], -1).mean(axis=1)
    m = mask.astype(jnp.float32)
    return (per * m).sum() / jnp.maximum(m.sum(), 1.0)


def _default_loss(state, params, batch):
    """ONE default loss for all step builders (per-batch, chunked, and
    fused runs must score identically): corner regression with the
    bucket-padding ``_mask`` honored when present, so mask-padded tail
    batches train without recompiles or loss skew."""
    return corner_loss(
        state.apply_fn({"params": params}, batch["image"]),
        batch["xy"],
        image_shape=batch["image"].shape[1:3],
        mask=batch.get("_mask"),
    )


def _sharding_jit_kwargs(state_sharding, n_data_args: int = 1,
                         data_shardings: dict | None = None) -> dict:
    """jit kwargs pinning a state's layout: ``in_shardings``/
    ``out_shardings`` with the state tree explicit and every data arg
    (and the metrics output) left unspecified for jit to infer. The
    mesh builders (:mod:`blendjax.train.mesh_driver`) pass the
    concrete state's sharding tree here; ``None`` for both keeps the
    plain propagate-from-arrays jit. ``data_shardings`` pins specific
    data args too (``{arg_index: sharding}``, 0 = the state): the echo
    path pins the reservoir ring's ``data``-axis layout so a drifted
    buffer placement fails loudly at dispatch instead of silently
    resharding the (potentially multi-GB) ring every step — honored
    with or without a state pin (a buffer-only caller must not lose
    the guarantee silently)."""
    if state_sharding is None and not data_shardings:
        return {}
    in_sh = [state_sharding] + [None] * n_data_args
    for i, sh in (data_shardings or {}).items():
        in_sh[i] = sh
    out: dict = {"in_shardings": tuple(in_sh)}
    if state_sharding is not None:
        out["out_shardings"] = (state_sharding, None)
    return out


def make_supervised_step(
    mesh=None,
    batch_sharding=None,
    loss_fn=None,
    donate: bool = True,
    accum_steps: int = 1,
    augment=None,
    augment_rng=None,
    state_sharding=None,
    precision=None,
):
    """Build ``step(state, batch) -> (state, metrics)``.

    - ``batch`` is the dict the ingest pipeline yields (tensor fields
      only); the uint8->compute-dtype cast happens inside the jitted step.
    - sharding is carried by the arrays themselves: the feeder places the
      batch under ``batch_sharding`` and params under the mesh rules; jit
      infers and GSPMD propagates, so no explicit in_shardings needed.
    - donation reuses the state's device buffers step-over-step.
    - ``accum_steps=N`` splits the batch's leading axis into N
      microbatches and accumulates gradients over a ``lax.scan`` before
      the single optimizer update — activation memory scales with the
      microbatch while the optimizer sees the full batch (gradients are
      identical to the unaccumulated step up to float associativity).
    - ``augment`` is an optional ``fn(rng, images) -> images``
      (:mod:`blendjax.ops.augment`) applied to ``batch['image']`` INSIDE
      the jitted step — on device, sharded with the batch, fused into
      the input cast. The per-step key folds ``augment_rng`` (default
      key 0) with the training step counter, so runs are deterministic
      and checkpoint-resume replays the same augmentation sequence.
      ONLY ``batch['image']`` is transformed: with spatial labels
      (pixel coordinates, masks), geometric ops like flip/crop would
      desynchronize image and label — use photometric ops there, or
      apply a paired transform in ``loss_fn`` instead.
    - ``state_sharding`` (a pytree of shardings matching the concrete
      train state) pins the jit's ``in_shardings``/``out_shardings``
      for the state argument — the mesh path's layout-stability
      guarantee (``blendjax.train.mesh_driver`` supplies it; plain
      single-chip callers leave it ``None``).
    - ``precision`` names a :mod:`blendjax.train.precision` policy (or
      passes one). ``None``/``"bf16-compute"`` keeps today's numerics;
      ``"bf16-grads"`` differentiates w.r.t. the bf16-cast params so
      gradients — and the cross-chip gradient all-reduce of a
      ``data``-sharded batch — cross the mesh in bf16 (half the
      bytes), cast back to f32 before the optimizer.
    """
    del mesh, batch_sharding  # layouts ride on the arrays (see above)
    base_rng = _resolve_augment_rng(augment, augment_rng)
    loss_fn = loss_fn or _default_loss
    policy = resolve_policy(precision)
    accum_steps = max(1, int(accum_steps))

    def step(state, batch):
        if augment is not None:
            rng = jax.random.fold_in(base_rng, state.step)
            batch = {**batch, "image": augment(rng, batch["image"])}

        def scalar_loss(params, b):
            return loss_fn(state, params, b)

        if accum_steps == 1:
            loss, grads = policy_value_and_grad(
                lambda p: scalar_loss(p, batch), state.params, policy
            )
        else:
            # Split only the real batch tensors; scalar sidecar fields
            # the pipeline attaches (producer btid stamps, '_meta', ...)
            # ride alongside every microbatch unchanged.
            lead = next(
                (
                    v.shape[0]
                    for v in batch.values()
                    if hasattr(v, "ndim") and getattr(v, "ndim", 0) >= 1
                ),
                0,
            )
            if lead % accum_steps:
                raise ValueError(
                    f"batch leading dim {lead} not divisible by "
                    f"accum_steps={accum_steps}"
                )

            def splittable(v):
                return (
                    hasattr(v, "ndim")
                    and getattr(v, "ndim", 0) >= 1
                    and v.shape[0] == lead
                )

            micro = {
                k: v.reshape(accum_steps, lead // accum_steps, *v.shape[1:])
                for k, v in batch.items()
                if splittable(v)
            }
            side = {k: v for k, v in batch.items() if k not in micro}

            def body(carry, mb):
                loss_sum, grad_sum = carry
                # policy_value_and_grad hands back grads already cast
                # to the master params' dtype (f32), so the zeros_like
                # accumulator below IS the policy's f32 accum_dtype
                loss, grads = policy_value_and_grad(
                    lambda p: scalar_loss(p, {**side, **mb}),
                    state.params, policy,
                )
                return (
                    loss_sum + loss,
                    jax.tree.map(jnp.add, grad_sum, grads),
                ), None

            zeros = jax.tree.map(jnp.zeros_like, state.params)
            (loss_sum, grad_sum), _ = jax.lax.scan(
                body, (jnp.zeros(()), zeros), micro
            )
            loss = loss_sum / accum_steps
            grads = jax.tree.map(lambda g: g / accum_steps, grad_sum)
        state = state.apply_gradients(grads=grads)
        metrics = {"loss": loss}
        return state, metrics

    return jax.jit(
        step,
        donate_argnums=(0,) if donate else (),
        **_sharding_jit_kwargs(state_sharding),
    )


def _resolve_augment_rng(augment, augment_rng):
    """ONE default-key rule for all three step builders: per-batch,
    chunked, and fused runs must resolve the same base key or their
    augmentation sequences silently diverge."""
    if augment is None:
        return None
    return augment_rng if augment_rng is not None else jax.random.key(0)


def _chunk_scan_body(loss_fn, augment, base_rng, policy=None):
    """Shared scan body for the chunked/fused steps: one optimizer
    update per slice, with the optional augment keyed by ``st.step`` —
    the SAME fold the per-batch step uses (``make_supervised_step``),
    so K scanned updates replay the exact augmentation sequence K
    sequential per-batch calls would. ``policy`` routes the grad
    computation through :func:`policy_value_and_grad` (same rule as
    the per-batch step: chunked runs must not train different math)."""
    policy = resolve_policy(policy)

    def body(st, batch):
        if augment is not None:
            rng = jax.random.fold_in(base_rng, st.step)
            batch = {**batch, "image": augment(rng, batch["image"])}

        def scalar_loss(params):
            return loss_fn(st, params, batch)

        loss, grads = policy_value_and_grad(scalar_loss, st.params, policy)
        return st.apply_gradients(grads=grads), loss

    return body


def make_chunked_supervised_step(
    loss_fn=None,
    donate: bool = True,
    augment=None,
    augment_rng=None,
    state_sharding=None,
    precision=None,
):
    """Build ``step(state, superbatch) -> (state, metrics)`` where
    ``superbatch`` fields carry a leading chunk axis: (K, B, ...).

    Runs K sequential optimizer updates (bit-identical training
    semantics to K calls of the per-batch step) inside ONE jitted
    ``lax.scan`` — one device round trip per K batches instead of per
    batch, which is the difference between working and crawling on
    high-latency device links (see docs/performance.md). Pairs with
    ``StreamDataPipeline(chunk=K)``. ``metrics['loss']`` is the K-vector
    of per-update losses.

    ``augment``/``augment_rng`` mirror :func:`make_supervised_step`:
    the per-update key folds ``augment_rng`` with the state's step
    counter INSIDE the scan, so a chunked run augments identically to
    the same stream trained one batch at a time (and to a
    checkpoint-resumed run).
    """
    loss_fn = loss_fn or _default_loss
    base_rng = _resolve_augment_rng(augment, augment_rng)

    def step(state, superbatch):
        state, losses = jax.lax.scan(
            _chunk_scan_body(loss_fn, augment, base_rng, precision),
            state, superbatch,
        )
        return state, {"loss": losses}

    return jax.jit(
        step,
        donate_argnums=(0,) if donate else (),
        **_sharding_jit_kwargs(state_sharding),
    )


def make_fused_tile_step(
    loss_fn=None,
    donate: bool = True,
    augment=None,
    augment_rng=None,
    state_sharding=None,
    superbatch_constraint=None,
    precision=None,
):
    """Build ``step(state, packed_batch) -> (state, metrics)`` where
    ``packed_batch`` is what ``StreamDataPipeline(emit_packed=True)``
    yields: the still-encoded chunk group plus its decode plan — a tile
    group (``_refs``/``_names``/``_geoms``) or a full-frame palette
    group (``_pal``).

    Fuses the on-device reconstruction INTO the train jit: one device
    call per K batches where the decode-then-step pipeline costs two,
    and ZERO standalone ``decode.dispatch`` calls — decoded frames live
    only as fused-step intermediates, never round-tripping as
    standalone ``jax.Array``s. On serialized tunnel/remote runtimes
    every dispatched call pays a queue turnaround (measured ~40ms on an
    axon link), so halving the call count is worth more than any
    kernel-level win. Training semantics are bit-identical to
    ``make_chunked_supervised_step`` over the decoded fields.

    A batch without ``"_packed"`` (the mixed-stream K'=1 degradation
    path, including mask-padded partial tails) falls back to the
    scan-only chunked step on its decoded fields — still one device
    call. Pairs with :class:`blendjax.train.TrainDriver` to keep
    several of these single-dispatch steps in flight.

    ``state_sharding`` pins the jits' in/out state layout (see
    :func:`make_supervised_step`); ``superbatch_constraint`` is an
    optional in-jit hook applied to the just-decoded superbatch before
    the scan — the mesh path re-shards the decoded fields over the
    batch axis there (``blendjax.train.mesh_driver``). Both default
    off with zero behavior change.
    """
    loss_fn = loss_fn or _default_loss
    chunked = make_chunked_supervised_step(
        loss_fn=loss_fn, donate=donate,
        augment=augment, augment_rng=augment_rng,
        state_sharding=state_sharding, precision=precision,
    )
    base_rng = _resolve_augment_rng(augment, augment_rng)
    pin = superbatch_constraint or (lambda sb: sb)

    def _fused(state, packed, refs, spec, names, geoms, rle):
        from blendjax.ops.tiles import decode_packed_superbatch

        superbatch = decode_packed_superbatch(
            packed, refs, spec, names, geoms, rle_groups=rle
        )
        state, losses = jax.lax.scan(
            _chunk_scan_body(loss_fn, augment, base_rng, precision), state,
            pin(superbatch),
        )
        return state, {"loss": losses}

    fused = jax.jit(
        _fused,
        static_argnames=("spec", "names", "geoms", "rle"),
        donate_argnums=(0,) if donate else (),
        **_sharding_jit_kwargs(state_sharding, n_data_args=2),
    )

    def _fused_pal(state, packed, spec, pal_groups, rle):
        from blendjax.ops.tiles import decode_packed_pal_superbatch

        superbatch = decode_packed_pal_superbatch(
            packed, spec, pal_groups, rle
        )
        state, losses = jax.lax.scan(
            _chunk_scan_body(loss_fn, augment, base_rng, precision), state,
            pin(superbatch),
        )
        return state, {"loss": losses}

    fused_pal = jax.jit(
        _fused_pal,
        static_argnames=("spec", "pal_groups", "rle"),
        donate_argnums=(0,) if donate else (),
        **_sharding_jit_kwargs(state_sharding),
    )

    def step(state, batch):
        # static decode-plan args go POSITIONALLY: jit rejects keyword
        # arguments once in_shardings is pinned (the mesh path), and
        # the plain path resolves them identically either way. `_rle`
        # is the deferred run-length expansion plan ("ndr" wire frames
        # decompressed INSIDE this dispatch — docs/wire-protocol.md).
        if "_pal" in batch:
            return fused_pal(
                state, batch["_packed"], batch["_spec"], batch["_pal"],
                batch.get("_rle", ()),
            )
        if "_packed" in batch:
            return fused(
                state, batch["_packed"], batch["_refs"],
                batch["_spec"], batch["_names"], batch["_geoms"],
                batch.get("_rle", ()),
            )
        fields = {
            k: v for k, v in batch.items()
            if k != "_meta" and getattr(v, "ndim", 0) >= 1
        }
        return chunked(state, fields)

    return step


def make_echo_fused_step(
    reservoir_draw,
    loss_fn=None,
    donate: bool = True,
    precision=None,
    state_sharding=None,
    buffer_sharding=None,
    draw_constraint=None,
):
    """Build the one-dispatch echo step: gather + re-augmentation +
    loss + donated update in ONE jit.

    ``reservoir_draw`` is the traceable gather+augment body a
    :class:`blendjax.data.echo.SampleReservoir` exposes as
    :meth:`~blendjax.data.echo.SampleReservoir.draw` —
    ``fn(buffers, idx, counter) -> batch`` — the same hook pattern as
    ``state_sharding``/``superbatch_constraint``. Before this builder
    the echo path cost TWO device dispatches per step (reservoir
    gather+augment in one jit, train update in another), the only
    place the ``dispatch_per_step == 1.0`` contract from PR 3 didn't
    hold; here the draw happens INSIDE the train jit, so the echoed
    batch exists only as a fused-step intermediate — it never
    round-trips as a standalone ``jax.Array``, and the per-step device
    call count is exactly one.

    The returned ``step(state, batch)`` composes with
    :class:`blendjax.train.TrainDriver` unchanged: ``batch`` is the
    draw token ``EchoingPipeline(emit_draws=True)`` yields —
    ``{"_echo_buffers": ring pytree, "_echo_idx": host (B,) indices,
    "_echo_counter": host draw counter}``. The ring buffers pass as
    ORDINARY (non-donated) arguments: the reservoir still owns them,
    the gather only reads, and the runtime donation audit
    (:mod:`blendjax.testing.donation`) pins that their pointers stay
    stable across fused steps. A batch without ``_echo_idx`` (e.g. a
    mixed stream's fresh decoded batch) falls back to the plain
    per-batch supervised step — still one dispatch.

    ``buffer_sharding`` (mesh path) pins the ring's ``data``-axis
    layout into the jit's ``in_shardings`` (a single sharding applies
    as a pytree prefix over every ring field), and ``draw_constraint``
    re-shards the just-gathered batch over the batch axis inside the
    jit — the same two mesh hooks ``make_mesh_fused_step`` uses for
    packed groups. ``precision`` follows
    :func:`make_supervised_step`.
    """
    loss_fn = loss_fn or _default_loss
    policy = resolve_policy(precision)
    pin = draw_constraint or (lambda b: b)
    fallback = make_supervised_step(
        loss_fn=loss_fn, donate=donate, precision=precision,
        state_sharding=state_sharding,
    )

    def _fused(state, buffers, idx, counter):
        batch = pin(reservoir_draw(buffers, idx, counter))

        def scalar_loss(params):
            return loss_fn(state, params, batch)

        loss, grads = policy_value_and_grad(
            scalar_loss, state.params, policy
        )
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss}

    jit_kwargs = _sharding_jit_kwargs(
        state_sharding, n_data_args=3,
        data_shardings=(
            {1: buffer_sharding} if buffer_sharding is not None else None
        ),
    )
    fused = jax.jit(
        _fused,
        donate_argnums=(0,) if donate else (),
        **jit_kwargs,
    )

    def step(state, batch):
        idx = batch.get("_echo_idx")
        if idx is None:
            fields = {
                k: v for k, v in batch.items()
                if not k.startswith("_") or k == "_mask"
            }
            return fallback(state, fields)
        return fused(
            state, batch["_echo_buffers"], idx, batch["_echo_counter"]
        )

    return step


def make_eval_step():
    def evaluate(state, batch):
        pred = state.apply_fn({"params": state.params}, batch["image"])
        mask = batch.get("_mask")
        err = jnp.linalg.norm(
            pred - batch["xy"].astype(jnp.float32), axis=-1
        )
        if mask is None:
            px_err = jnp.mean(err)
        else:
            # mask-padded tail batch: padded rows must not dilute the
            # eval metrics (an eval pass sees every real example once)
            m = mask.astype(jnp.float32)
            px_err = (
                err.reshape(err.shape[0], -1).mean(axis=1) * m
            ).sum() / jnp.maximum(m.sum(), 1.0)
        return {
            "loss": corner_loss(
                pred, batch["xy"], image_shape=batch["image"].shape[1:3],
                mask=mask,
            ),
            "px_err": px_err,
        }

    # pure read of the state (no update returned): donating it would
    # free params the caller still trains with
    # bjx: ignore[BJX112]
    return jax.jit(evaluate)
