"""Transport layer: wire codecs + socket pattern wrappers.

The reference inlines raw ZMQ use at each component (``publisher.py:22-27``,
``dataset.py:73-78``, ``duplex.py:12-18``, ``env.py:36-42``); blendjax
factors it into one layer so the ingest pipeline, control channels, and RL
RPC all share codec, backpressure, and failure semantics.
"""

from blendjax.transport.wire import (
    TensorCodec,
    PickleCodec,
    encode_message,
    decode_message,
    sizeof_frames,
)
from blendjax.transport.channels import (
    DataPublisherSocket,
    DataReceiverSocket,
    PairChannel,
    RpcClient,
    RpcServer,
    ReceiveTimeoutError,
    term_context,
)
from blendjax.transport.shm import (
    ShmCapacityError,
    ShmRing,
    attach_ring,
    detach_all,
)

__all__ = [
    "ShmRing",
    "ShmCapacityError",
    "attach_ring",
    "detach_all",
    "TensorCodec",
    "PickleCodec",
    "encode_message",
    "decode_message",
    "sizeof_frames",
    "DataPublisherSocket",
    "DataReceiverSocket",
    "PairChannel",
    "RpcClient",
    "RpcServer",
    "ReceiveTimeoutError",
    "term_context",
]
