"""Socket pattern wrappers with reference-equivalent semantics.

Patterns and options mirror the reference's "backend API" (SURVEY.md §5):

- PUSH(bind, SNDHWM, IMMEDIATE) -> PULL(connect-to-all, RCVHWM) for the data
  stream: backpressure via small HWMs, fair fan-in, at-most-once delivery,
  no ordering guarantee (``publisher.py:22-27`` <-> ``dataset.py:73-78``).
- PAIR(bind) <-> PAIR(connect) duplex control with HWM 10, linger and
  send/recv timeouts (``btb/duplex.py:12-18`` <-> ``btt/duplex.py:12-18``),
  message ids ``btmid`` + instance ids ``btid`` stamped on send
  (``btt/duplex.py:44-67``).
- REQ(RELAXED, CORRELATE) <-> REP for environment RPC
  (``btt/env.py:36-42`` <-> ``btb/env.py:212-216``).

Failure semantics are fail-fast: a poll timeout raises
``ReceiveTimeoutError`` (the reference asserts/raises on ``zmq.error.Again``,
``dataset.py:98-99``, ``btt/env.py:116-124``).
"""

from __future__ import annotations

# bjx: hot-path (recv/decode sits on the ingest critical path: BJX102
# flags any blocking device sync added to this module)

import os
import threading
import time

import zmq

from blendjax import constants
from blendjax.transport.shm import (
    REGISTRY_ENV,
    ShmCapacityError,
    ShmRing,
    resolve_message,
)
from blendjax.transport.wire import (
    DEFAULT_COMPRESS_MIN_BYTES,
    WireCompressState,
    decode_message,
    encode_message,
)


class ReceiveTimeoutError(TimeoutError):
    """No message arrived within the timeout — treat the peer as failed/hung."""


_context_lock = threading.Lock()
_context = None
_context_pid = None


def zmq_context() -> zmq.Context:
    """Process-wide ZMQ context (re-created after fork for DataLoader-style
    worker processes, matching the reference's lazy per-worker socket
    construction in ``dataset.py:64-78``)."""
    global _context, _context_pid
    with _context_lock:
        if _context is None or _context_pid != os.getpid():
            _context = zmq.Context()
            _context_pid = os.getpid()
        return _context


def term_context() -> None:
    """Terminate the process-wide context, BLOCKING until every closed
    socket's pending messages are flushed or its LINGER expires.

    This is the only operation that actually guarantees delivery of a
    finite stream's tail: ``socket.close()`` returns immediately and
    leaves flushing to the IO thread, which dies with the interpreter —
    a producer that publishes its last message and exits loses it
    sporadically unless something waits, and pyzmq deliberately skips
    context termination during interpreter shutdown. Call it at the END
    of a producer process, after closing all sockets (a fresh context
    is created transparently if sockets are opened afterwards).
    """
    global _context
    with _context_lock:
        ctx = _context
        _context = None
    if ctx is not None and _context_pid == os.getpid():
        # (a context inherited across fork is never terminated here —
        # its IO thread did not survive the fork)
        ctx.term()


def _as_frames(raw) -> list:
    return raw if isinstance(raw, list) else [raw]



class _Channel:
    """Shared socket plumbing: context-managed close + poll/recv/decode."""

    sock: zmq.Socket
    allow_pickle: bool = True
    # Only the bulk data stream accounts its frames into the
    # wire.raw_bytes/wire.compressed_bytes pair (DataReceiverSocket
    # flips this True): control/RPC arrays through the same codec would
    # pollute the published compression ratio.
    wire_metrics: bool = False

    def _register_poller(self) -> None:
        self.poller = zmq.Poller()
        self.poller.register(self.sock, zmq.POLLIN)

    # Deferred run-length decode: class-level default so every channel
    # decodes identically unless its owner (DataReceiverSocket) opts in.
    defer_rle: bool = False

    def _poll_frames(self, timeoutms: int):
        """Receive one raw multipart message within ``timeoutms``;
        returns the frame buffers or ``None`` on timeout. Decode is
        separate (:meth:`decode_frames`) so callers owning an inflate
        pool can pipeline receive against decode."""
        socks = dict(self.poller.poll(timeoutms))
        if self.sock not in socks:
            return None
        frames = _as_frames(self.sock.recv_multipart(copy=False))
        return [f.buffer for f in frames]

    def decode_frames(self, buffers, copy_arrays: bool = False):
        """Decode raw frame buffers with this channel's configured
        semantics (pickle policy, wire metrics, deferred rle) — the ONE
        decode call both the inline and the decode-ahead receive paths
        share. Intra-message parallel inflate stays a direct
        ``decode_message(inflate_pool=)`` surface: the stream path's
        whole-message decode-ahead subsumes it and must not re-enter
        the same executor from inside a decode job."""
        msg = decode_message(
            buffers, copy_arrays=copy_arrays,
            allow_pickle=self.allow_pickle,
            count_metrics=self.wire_metrics,
            defer_rle=self.defer_rle,
        )
        if isinstance(msg, dict) and "_shm" in msg:
            # Co-located producer: the wire carried only a descriptor;
            # the tensor bytes come straight out of the shared-memory
            # ring (blendjax.transport.shm). A torn generation leaves a
            # `_shm_torn` marker for the stream layer to account + skip.
            msg = resolve_message(msg)
        return msg

    def _poll_recv(self, timeoutms: int, copy_arrays: bool):
        """Receive+decode one message within ``timeoutms``; returns
        ``(message, raw_buffers)`` or ``None`` on timeout."""
        buffers = self._poll_frames(timeoutms)
        if buffers is None:
            return None
        return self.decode_frames(buffers, copy_arrays), buffers

    def close(self):
        # No linger override: close() keeps queued messages alive for
        # the IO thread to flush, bounded by the socket's configured
        # LINGER (``close(0)`` here silently DISCARDED them). Note the
        # flush is only GUARANTEED if the process lives long enough —
        # finite-stream producers must call
        # :func:`blendjax.transport.term_context` before exiting, which
        # blocks until the flush completes or LINGER expires.
        self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _SentTracker:
    """`zmq.MessageTracker` stand-in for shm publishes: the payload was
    copied into the ring before send, so it is 'done' immediately."""

    done = True

    def wait(self, timeout=None):
        return None


_DONE_TRACKER = _SentTracker()


class DataPublisherSocket(_Channel):
    """Producer end of the data stream: PUSH, bind side.

    Reference: ``pkg_blender/blendtorch/btb/publisher.py:4-43``. The small
    send HWM blocks the renderer when consumers fall behind, which is the
    framework's backpressure mechanism (``examples/datagen/Readme.md:168-175``).

    Zero-copy hazard: with ``copy=False`` (the default) ndarray payloads are
    handed to the socket by reference and transmitted asynchronously after
    ``publish`` returns. A producer that mutates or reuses its buffer (e.g.
    an offscreen render target) must pass ``copy=True`` — the reference
    always copied implicitly by pickling at send time (``publisher.py:43``).
    """

    def __init__(
        self,
        bind_addr: str,
        btid: int | None = None,
        send_hwm: int = constants.DEFAULT_SEND_HWM,
        codec: str = "tensor",
        lingerms: int = 0,
        copy: bool = False,
        compress_level: int = 0,
        compress_min_bytes: int = DEFAULT_COMPRESS_MIN_BYTES,
        compress_rle: bool = False,
        rle_cap: int | None = None,
        quantize_f16=(),
        lineage: bool = True,
        telemetry_every: int = 64,
        trace_every: int = 64,
        shm=None,
        shm_timeout_s: float = 5.0,
    ):
        self.codec = codec
        self.btid = btid
        self.copy = copy
        # Zero-copy local transport (docs/wire-protocol.md "Shared-memory
        # descriptors"): with ``shm`` set, ndarray payloads are written
        # into a shared-memory ring and only a tiny descriptor rides the
        # socket — same-host consumers attach and read the slot with no
        # pickle/inflate. Pass an ``ShmRing`` to share one, ``True``/an
        # int slot count to lazily create a ring sized from the first
        # payload. Messages without arrays (or that outgrow the slot)
        # fall back to the wire codecs transparently, so remote-capable
        # code needs no changes.
        self._shm_timeout_s = float(shm_timeout_s)
        self._shm_owned = False
        if isinstance(shm, ShmRing):
            self._shm_ring = shm
            self._shm_slots = shm.slots
        elif shm:
            self._shm_ring = None
            self._shm_slots = 4 if shm is True else int(shm)
            self._shm_owned = True
        else:
            self._shm_ring = None
            self._shm_slots = 0
        # Per-publisher wire compression (tensor codec only): level > 0
        # ships large array frames as zlib "ndz" entries. Trades producer
        # CPU for wire bytes — the right trade on tunneled/cross-host
        # links, the wrong one on ipc/loopback (docs/performance.md).
        self.compress_level = int(compress_level)
        self.compress_min_bytes = int(compress_min_bytes)
        # Run-length "ndr" wire frames (docs/wire-protocol.md): cheap
        # host encode, near-free consumer inflate, and — on the fused
        # tile path — expansion deferred INTO the consumer's train jit.
        # rle_cap pins the packed per-row capacity fleet-wide (the
        # TileBatchPublisher capacity contract); quantize_f16 names
        # float sidecar fields to ship half-width (lossy; exact for
        # integer pixel coordinates up to 2048).
        self.compress_rle = bool(compress_rle)
        self.rle_cap = int(rle_cap) if rle_cap else None
        self.quantize_f16 = tuple(quantize_f16)
        # Reusable per-publisher compression state: compressobj
        # templates, the incompressible-key skip memo, sticky rle caps.
        self._wire_state = (
            WireCompressState()
            if (self.compress_level > 0 or self.compress_rle) else None
        )
        # Frame lineage (docs/observability.md): every message carries a
        # wall + monotonic publish time and a per-publisher monotonic
        # sequence number, and every `telemetry_every`-th message
        # piggybacks a snapshot of this process's metrics registry —
        # the consumer side (blendjax.obs.lineage) turns these into
        # per-producer staleness histograms, exact drop/reorder counts,
        # and a fleet telemetry view, all without a second socket.
        # lineage=False restores the pre-telemetry wire shape.
        self.lineage = bool(lineage)
        self.telemetry_every = int(telemetry_every) if lineage else 0
        # Distributed frame tracing (blendjax.obs.trace): every
        # trace_every-th message additionally carries a `_trace` context
        # — trace id, producer btid/pid, and a growing list of
        # [stage, t_mono, t_wall] stamps each downstream stage appends
        # in place. Off the sampled path the cost is one modulo check;
        # trace_every=0 disables stamping entirely (and lineage=False
        # implies it, like telemetry).
        self.trace_every = int(trace_every) if lineage else 0
        self._pid = os.getpid()
        self._seq = 0
        self._created_wall = time.time()
        self._tel_mark = (0, self._created_wall)  # (seq, wall) at last snapshot
        self.sock = zmq_context().socket(zmq.PUSH)
        self.sock.setsockopt(zmq.SNDHWM, send_hwm)
        self.sock.setsockopt(zmq.IMMEDIATE, 1)
        self.sock.setsockopt(zmq.LINGER, lingerms)
        self.sock.bind(bind_addr)
        # Wildcard ports ("tcp://host:*") resolve at bind time; expose the
        # effective address so launchers/tests can hand it to consumers.
        self.addr = self.sock.getsockopt_string(zmq.LAST_ENDPOINT)

    def publish(self, **kwargs):
        """Publish a message dict; stamps ``btid`` for provenance
        (reference stamps every payload, ``publisher.py:42``) plus the
        lineage stamps (seq + publish times; see ``__init__``)."""
        data = self._stamp({"btid": self.btid, **kwargs})
        if self._shm_slots:
            frames = self._encode_shm(data)
            if frames is not None:
                # descriptor frames are tiny: copy-send, nothing to track
                self.sock.send_multipart(frames, copy=True)
                return
        self.sock.send_multipart(
            self._encode(data), copy=self.copy
        )

    def _encode_shm(self, data: dict) -> list | None:
        """Write the message's arrays into the shm ring and encode the
        descriptor message; ``None`` means "use the wire codecs" (no
        array payload, or the payload outgrew the slot)."""
        import numpy as np

        arrs = {
            k: v for k, v in data.items()
            if isinstance(v, np.ndarray) and v.ndim >= 1
        }
        if not arrs:
            return None
        ring = self._shm_ring
        if ring is None:
            # size the ring from the first payload (stable shapes are the
            # co-located steady state), with headroom for stamp jitter
            slot_bytes = sum(v.nbytes + 64 for v in arrs.values()) * 2
            ring = ShmRing(
                slots=self._shm_slots, slot_bytes=slot_bytes,
                btid=self.btid,
            )
            self._shm_ring = ring
        try:
            desc = ring.write(arrs, timeout_s=self._shm_timeout_s)
        except ShmCapacityError:
            from blendjax.utils.metrics import metrics

            metrics.count("wire.shm_fallbacks")
            return None
        small = {k: v for k, v in data.items() if k not in arrs}
        small["_shm"] = desc
        return self._encode(small)

    def _stamp(self, data: dict) -> dict:
        if not self.lineage:
            return data
        data["_seq"] = self._seq
        data["_pub_wall"] = time.time()
        data["_pub_mono"] = time.monotonic()
        if self.telemetry_every and self._seq % self.telemetry_every == 0:
            data["_telemetry"] = self._telemetry_snapshot()
        if self.trace_every and self._seq % self.trace_every == 0:
            # Sampled end-to-end frame trace (blendjax.obs.trace): the
            # shape is inlined (not imported) so producer processes —
            # Blender's Python — need nothing beyond this module. The
            # trace id is globally unique per (producer pid, seq).
            data["_trace"] = {
                "id": f"{self.btid}-{self._pid}-{self._seq}",
                "btid": self.btid,
                "pid": self._pid,
                "stages": [["publish", time.monotonic(), time.time()]],
            }
        self._seq += 1
        return data

    def _telemetry_snapshot(self) -> dict:
        """Compact, msgpack-native snapshot of this process's metrics
        (producer render spans, publish rate, frame counter) — the
        piggyback payload the consumer's fleet view aggregates."""
        from blendjax.utils.metrics import metrics

        now = time.time()
        last_seq, last_wall = self._tel_mark
        dt = max(now - last_wall, 1e-9)
        self._tel_mark = (self._seq, now)
        report = metrics.report()
        return {
            "seq": int(self._seq),
            "uptime_s": round(now - self._created_wall, 3),
            # messages/s since the previous snapshot (0.0 on the first)
            "mps": round((self._seq - last_seq) / dt, 3),
            "counters": {k: int(v) for k, v in report["counters"].items()},
            "spans": {
                k: {
                    "count": int(v["count"]),
                    "mean_ms": round(float(v["mean_ms"]), 3),
                    "p95_ms": round(float(v.get("p95_ms", 0.0)), 3),
                }
                for k, v in report["spans"].items()
            },
        }

    def _encode(self, data: dict) -> list:
        return encode_message(
            data, codec=self.codec,
            compress_level=self.compress_level,
            compress_min_bytes=self.compress_min_bytes,
            compress_rle=self.compress_rle,
            rle_cap=self.rle_cap,
            quantize_f16=self.quantize_f16,
            state=self._wire_state,
        )

    def publish_tracked(self, **kwargs):
        """Zero-copy publish returning a ``zmq.MessageTracker``.

        ``tracker.done`` flips True once the IO thread no longer references
        the payload buffers, so a producer rotating a fixed buffer pool can
        ``tracker.wait()`` before rendering into a slot again. Unlike
        HWM-based pool sizing this bounds buffer reuse for *any* number of
        connected consumers: PUSH keeps one queue per pipe, so per-pipe HWM
        alone does not cap the total number of in-flight messages."""
        data = self._stamp({"btid": self.btid, **kwargs})
        if self._shm_slots:
            frames = self._encode_shm(data)
            if frames is not None:
                # the ring copied the arrays already: the caller's buffers
                # are free the moment we return, so the tracker is a
                # pre-completed stand-in (the ring's ack counters — not
                # MessageTracker — now bound slot reuse)
                self.sock.send_multipart(frames, copy=True)
                return _DONE_TRACKER
        return self.sock.send_multipart(
            self._encode(data), copy=False, track=True
        )

    def close(self):
        super().close()
        ring = self._shm_ring
        if ring is not None and self._shm_owned:
            ring.close()
            # Under a fleet launcher the registry owns the unlink (after
            # the consumer drains); standalone producers unlink on clean
            # close so nothing leaks in /dev/shm. ShmRing.unlink() is
            # idempotent, so racing the launcher is harmless.
            if not os.environ.get(REGISTRY_ENV):
                ring.unlink()



class DataReceiverSocket(_Channel):
    """Consumer end: PULL, connects to *all* producer addresses.

    Reference: ``pkg_pytorch/blendtorch/btt/dataset.py:68-111``. Fair-queued
    fan-in across producers; at-most-once per consumer; raises on timeout.
    ``recv`` returns ``(message, raw_frames)`` so a recorder can tee the
    exact wire bytes without re-encoding (reference tees raw pickles in the
    hot loop, ``dataset.py:100-103``).
    """

    wire_metrics = True  # the data stream IS the wire.* counter pair

    def __init__(
        self,
        addresses,
        queue_size: int = constants.DEFAULT_QUEUE_SIZE,
        timeoutms: int = constants.DEFAULT_TIMEOUTMS,
        allow_pickle: bool = True,
        defer_rle: bool = False,
    ):
        if isinstance(addresses, str):
            addresses = [addresses]
        self.addresses = list(addresses)
        self.timeoutms = timeoutms
        self.allow_pickle = allow_pickle
        # defer_rle: leave "ndr" frames of prebatched messages packed
        # for a device-side expansion plan (the fused tile path) —
        # see blendjax.transport.wire.TensorCodec.decode.
        self.defer_rle = bool(defer_rle)
        self.sock = zmq_context().socket(zmq.PULL)
        self.sock.setsockopt(zmq.RCVHWM, queue_size)
        self.sock.setsockopt(zmq.LINGER, 0)
        for addr in self.addresses:
            self.sock.connect(addr)
        self._register_poller()

    def recv(self, timeoutms: int | None = None, copy_arrays: bool = False):
        t = self.timeoutms if timeoutms is None else timeoutms
        out = self._poll_recv(t, copy_arrays)
        if out is None:
            raise ReceiveTimeoutError(
                f"no message within {t} ms from {self.addresses}"
            )
        return out

    def recv_frames(self, timeoutms: int | None = None):
        """Receive one message's RAW frame buffers (no decode) — the
        receive half of the decode-ahead pipeline (RemoteStream hands
        the buffers to a shared inflate executor and yields decoded
        messages in receive order)."""
        t = self.timeoutms if timeoutms is None else timeoutms
        buffers = self._poll_frames(t)
        if buffers is None:
            raise ReceiveTimeoutError(
                f"no message within {t} ms from {self.addresses}"
            )
        return buffers

    # -- elastic membership (fleet controller substrate) ---------------------
    # ZMQ sockets are single-thread: both calls below must run on the
    # thread that owns this socket (RemoteStream queues membership ops
    # and applies them from its iterating thread — see
    # ``blendjax.data.stream``).

    def connect(self, addr: str) -> None:
        """Admit one more producer endpoint into the fan-in (idempotent
        at the bookkeeping level; duplicate connects are skipped)."""
        if addr in self.addresses:
            return
        self.sock.connect(addr)
        self.addresses.append(addr)

    def disconnect(self, addr: str) -> None:
        """Retire one producer endpoint. NOTE: zmq drops messages still
        queued on that endpoint's pipe — drain first (retire the
        producer, keep receiving through a grace window) or the tail is
        lost."""
        try:
            self.sock.disconnect(addr)
        except zmq.ZMQError:
            pass  # already gone (e.g. peer closed the transport)
        if addr in self.addresses:
            self.addresses.remove(addr)



class PairChannel(_Channel):
    """Duplex control channel (PAIR<->PAIR), producer binds / consumer connects.

    Reference: ``btt/duplex.py:8-67`` and ``btb/duplex.py:8-66``. ``send``
    stamps ``btid`` plus a fresh random message id ``btmid``; ``recv``
    returns ``None`` on timeout (densityopt polls with ``timeoutms=0`` each
    frame, ``supershape.blend.py:26-37``).
    """

    def __init__(
        self,
        addr: str,
        btid: int | None = None,
        bind: bool = False,
        hwm: int = constants.DEFAULT_SEND_HWM,
        lingerms: int = 0,
        codec: str = "tensor",
        default_timeoutms: int = constants.DEFAULT_TIMEOUTMS,
        allow_pickle: bool = True,
    ):
        self.btid = btid
        self.codec = codec
        self.default_timeoutms = default_timeoutms
        self.allow_pickle = allow_pickle
        self.sock = zmq_context().socket(zmq.PAIR)
        self.sock.setsockopt(zmq.SNDHWM, hwm)
        self.sock.setsockopt(zmq.RCVHWM, hwm)
        self.sock.setsockopt(zmq.LINGER, lingerms)
        if bind:
            self.sock.bind(addr)
            self.addr = self.sock.getsockopt_string(zmq.LAST_ENDPOINT)
        else:
            self.sock.connect(addr)
            self.addr = addr
        self._register_poller()

    def send(self, **kwargs) -> bytes:
        """Send a message; returns the generated ``btmid`` message id.

        Control messages are small, so payloads are copied at send time
        (no buffer-reuse hazard, unlike the bulk data stream).
        """
        btmid = os.urandom(4)
        data = {"btid": self.btid, "btmid": btmid, **kwargs}
        self.sock.send_multipart(encode_message(data, codec=self.codec), copy=True)
        return btmid

    def recv(self, timeoutms: int | None = None):
        """Receive one message or ``None`` if nothing arrives in time."""
        t = self.default_timeoutms if timeoutms is None else timeoutms
        out = self._poll_recv(t, copy_arrays=True)
        return None if out is None else out[0]



class RpcClient(_Channel):
    """Blocking request/reply client (REQ with RELAXED+CORRELATE).

    Reference: ``btt/env.py:36-42,111-124``. RELAXED+CORRELATE let the REQ
    socket recover from a lost reply instead of wedging, and timeouts raise
    so a dead environment fails fast.
    """

    def __init__(self, addr: str, timeoutms: int = constants.DEFAULT_TIMEOUTMS,
                 codec: str = "tensor", allow_pickle: bool = True):
        self.codec = codec
        self.timeoutms = timeoutms
        self.addr = addr
        self.allow_pickle = allow_pickle
        self.sock = zmq_context().socket(zmq.REQ)
        self.sock.setsockopt(zmq.REQ_RELAXED, 1)
        self.sock.setsockopt(zmq.REQ_CORRELATE, 1)
        self.sock.setsockopt(zmq.SNDTIMEO, timeoutms)
        self.sock.setsockopt(zmq.RCVTIMEO, timeoutms)
        self.sock.setsockopt(zmq.LINGER, 0)
        self.sock.connect(addr)

    def call(self, **kwargs) -> dict:
        try:
            self.sock.send_multipart(
                encode_message(kwargs, codec=self.codec), copy=True
            )
            frames = _as_frames(self.sock.recv_multipart(copy=False))
        except zmq.error.Again as e:
            raise ReceiveTimeoutError(f"rpc to {self.addr} timed out") from e
        return decode_message(
            [f.buffer for f in frames],
            copy_arrays=True,
            allow_pickle=self.allow_pickle,
        )



class RpcServer(_Channel):
    """Reply side of the RPC pattern (REP, bind).

    Reference: ``btb/env.py:212-216``. ``recv``/``reply`` are split so the
    producer's STATE_REQ/STATE_REP machine (``btb/env.py:206-252``) can
    interleave them with frame callbacks; ``recv`` supports non-blocking
    polls for the ``real_time`` degradation mode (``btb/env.py:222-233``).
    """

    def __init__(self, bind_addr: str, codec: str = "tensor",
                 default_timeoutms: int = constants.DEFAULT_TIMEOUTMS,
                 allow_pickle: bool = True):
        self.codec = codec
        self.default_timeoutms = default_timeoutms
        self.allow_pickle = allow_pickle
        self.sock = zmq_context().socket(zmq.REP)
        self.sock.setsockopt(zmq.LINGER, 0)
        self.sock.bind(bind_addr)
        self.addr = self.sock.getsockopt_string(zmq.LAST_ENDPOINT)
        self._register_poller()

    def recv(self, timeoutms: int | None = None):
        """Receive one request, or ``None`` on timeout (``timeoutms=0`` polls)."""
        t = self.default_timeoutms if timeoutms is None else timeoutms
        out = self._poll_recv(t, copy_arrays=True)
        return None if out is None else out[0]

    def reply(self, **kwargs):
        self.sock.send_multipart(encode_message(kwargs, codec=self.codec), copy=True)

