"""Shared-memory ring transport for co-located producers.

The synthetic tier's 4-slot publish-by-reference pool showed that same-host
producers pay the wire codecs for nothing: the arrays are already in RAM on
the right machine.  ``ShmRing`` generalizes that pool into a
``multiprocessing.shared_memory`` segment that a producer process writes and
a consumer process reads directly — the ZMQ message shrinks to a tiny
*descriptor* (segment name, slot index, generation, field layout) while the
tensor bytes never touch pickle, zlib, or the socket.

Cross-process discipline is a seqlock per slot (the same single-writer
contract the in-process threadguard/BJX117 pass polices):

* the writer bumps the slot's generation counter to an **odd** value before
  touching the payload and to the next **even** value after — a reader that
  observes an odd generation, or a generation that changed across its copy,
  discards the slot as *torn* (``wire.shm_torn``);
* the reader acknowledges consumption by storing the generation it consumed
  into the slot's ``ack`` counter; the writer reuses a slot only once
  ``ack == gen`` — bounded by a timeout, after which the slot is *reclaimed*
  (``wire.shm_reclaims``) so a kill -9'd reader never wedges the writer.

Both counters are 8-byte-aligned u64 stores, which are atomic on every
platform JAX runs on; no cross-process locks exist anywhere in the protocol.

Segment lifecycle: creators register their segment in the directory named by
``$BLENDJAX_SHM_REGISTRY`` (one marker file per segment, ``<btid>__<name>``)
when the env var is set — the fleet launcher exports it and then *owns* the
unlink in ``retire_instance``/teardown, so segments are unlinked exactly once
even when the producer dies abnormally.  Without a registry (standalone
producers) the creator unlinks on clean close.  Attach-side handles are
cached per process (``attach_ring``); an attached mapping survives the
segment's unlink, so in-flight descriptors keep resolving during scale-down.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from multiprocessing import shared_memory

import numpy as np

from blendjax.utils.metrics import metrics

logger = logging.getLogger(__name__)

REGISTRY_ENV = "BLENDJAX_SHM_REGISTRY"

_MAGIC = b"BJXSHM1\0"
_HDR_BYTES = 24  # magic(8) + slots(u64) + slot_bytes(u64)
_ALIGN = 64

__all__ = [
    "ShmRing",
    "ShmCapacityError",
    "attach_ring",
    "detach_all",
    "resolve_message",
    "reap_registry",
    "unlink_segment",
    "REGISTRY_ENV",
]


class ShmCapacityError(ValueError):
    """Payload does not fit a slot; callers fall back to the wire codecs."""


def _align(n: int, a: int = _ALIGN) -> int:
    return (int(n) + a - 1) // a * a


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """Detach this handle from the resource_tracker.

    Cleanup ownership is explicit (registry + launcher, or creator close):
    leaving the tracker registered means a second, racing unlink attempt at
    interpreter exit plus a leaked-resource warning for segments that were
    already reclaimed.  Attach-side handles must never be tracked at all.
    """
    try:  # pragma: no cover - depends on stdlib internals staying put
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


def _unlink_quietly(shm: shared_memory.SharedMemory) -> None:
    """Unlink without resource_tracker noise.

    Stdlib ``unlink()`` unregisters the name from the tracker — but every
    handle here was untracked at creation/attach (cleanup ownership is
    explicit), so the tracker process would log a ``KeyError`` removing a
    name it never had.  Re-registering immediately before the unlink keeps
    the pair balanced; the two messages are ordered on the tracker pipe.
    """
    try:  # pragma: no cover - depends on stdlib internals staying put
        from multiprocessing import resource_tracker

        resource_tracker.register(shm._name, "shared_memory")
    except Exception:
        pass
    shm.unlink()


def _sanitize(btid: object) -> str:
    return re.sub(r"[^A-Za-z0-9_-]", "-", str(btid))


def _register(name: str, btid: object) -> None:
    reg = os.environ.get(REGISTRY_ENV)
    if not reg:
        return
    try:
        os.makedirs(reg, exist_ok=True)
        path = os.path.join(reg, f"{_sanitize(btid)}__{name}")
        with open(path, "w"):
            pass
    except OSError:  # registry dir raced away: cleanup falls to the creator
        logger.warning("could not register shm segment %s in %s", name, reg)


def _deregister(name: str) -> None:
    reg = os.environ.get(REGISTRY_ENV)
    if not reg:
        return
    try:
        for fn in os.listdir(reg):
            if fn.partition("__")[2] == name:
                try:
                    os.remove(os.path.join(reg, fn))
                except FileNotFoundError:
                    pass
    except OSError:
        pass


class ShmRing:
    """Fixed-slot shared-memory ring with per-slot seqlock generations.

    One process creates (and writes) the ring; any number of processes may
    attach, but the protocol is single-writer / single-reader-per-slot —
    exactly the shape ``DataPublisherSocket(shm=...)`` + PUSH/PULL gives.

    Layout (offsets in bytes)::

        0                magic  "BJXSHM1\\0"
        8                u64    slots
        16               u64    slot_bytes (aligned payload capacity)
        24               u64[slots]  gen   (odd = write in progress)
        24 + 8*slots     u64[slots]  ack   (last generation consumed)
        align64(...)     slots * slot_bytes payload
    """

    def __init__(
        self,
        slots: int = 4,
        slot_bytes: int = 0,
        *,
        name: str | None = None,
        create: bool = True,
        btid: object = None,
    ) -> None:
        self._closed = False
        self._unlinked = False
        self._cursor = 0
        self.reclaims = 0
        self._owner = bool(create)
        if create:
            slots = int(slots)
            if slots < 1:
                raise ValueError("ShmRing needs at least one slot")
            slot_bytes = _align(max(int(slot_bytes), _ALIGN))
            payload_off = _align(_HDR_BYTES + 16 * slots)
            total = payload_off + slots * slot_bytes
            self._shm = shared_memory.SharedMemory(
                create=True, size=total, name=name,
            )
            buf = self._shm.buf
            buf[:8] = _MAGIC
            hdr = np.ndarray((2,), dtype=np.uint64, buffer=buf, offset=8)
            hdr[0] = slots
            hdr[1] = slot_bytes
            _register(self._shm.name, btid if btid is not None else os.getpid())
        else:
            if not name:
                raise ValueError("attach requires a segment name")
            self._shm = shared_memory.SharedMemory(name=name)
            buf = self._shm.buf
            if bytes(buf[:8]) != _MAGIC:
                self._shm.close()
                raise ValueError(f"segment {name!r} is not a blendjax shm ring")
            hdr = np.ndarray((2,), dtype=np.uint64, buffer=buf, offset=8)
            slots = int(hdr[0])
            slot_bytes = int(hdr[1])
        _untrack(self._shm)
        self.slots = slots
        self.slot_bytes = slot_bytes
        self._payload_off = _align(_HDR_BYTES + 16 * slots)
        self._gen = np.ndarray(
            (slots,), dtype=np.uint64, buffer=self._shm.buf, offset=_HDR_BYTES,
        )
        self._ack = np.ndarray(
            (slots,), dtype=np.uint64, buffer=self._shm.buf,
            offset=_HDR_BYTES + 8 * slots,
        )

    # -- identity ------------------------------------------------------------

    @property
    def name(self) -> str:
        return self._shm.name

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        return cls(name=name, create=False)

    # -- writer side ---------------------------------------------------------

    def _slot_view(self, slot: int, shape, dtype, off: int) -> np.ndarray:
        base = self._payload_off + slot * self.slot_bytes + off
        return np.ndarray(shape, dtype=dtype, buffer=self._shm.buf, offset=base)

    def write(
        self,
        fields: dict[str, np.ndarray],
        *,
        timeout_s: float = 5.0,
    ) -> dict:
        """Copy ``fields`` into the next slot; return the wire descriptor.

        Raises :class:`ShmCapacityError` *before* touching the slot when the
        payload cannot fit, so an oversized message never tears a
        generation.  Blocks (bounded by ``timeout_s``) while the slot's last
        generation is unacknowledged, then reclaims it.
        """
        layout: list[tuple[str, np.ndarray, int]] = []
        off = 0
        for key, arr in fields.items():
            arr = np.ascontiguousarray(arr)
            layout.append((key, arr, off))
            off = _align(off + arr.nbytes, 16)
        if off > self.slot_bytes:
            raise ShmCapacityError(
                f"payload needs {off} bytes, slot holds {self.slot_bytes}"
            )
        slot = self._cursor
        self._cursor = (slot + 1) % self.slots
        gen = int(self._gen[slot])
        if gen and int(self._ack[slot]) != gen:
            deadline = time.monotonic() + timeout_s
            while int(self._ack[slot]) != gen:
                if time.monotonic() >= deadline:
                    # Reader gone (kill -9) or hopelessly behind: reclaim.
                    # The stale descriptor, if ever consumed, fails its
                    # generation check and is counted wire.shm_torn there.
                    self.reclaims += 1
                    metrics.count("wire.shm_reclaims")
                    break
                time.sleep(0.0005)
        self._gen[slot] = gen + 1  # odd: write in progress
        desc_fields: list[list] = []
        for key, arr, f_off in layout:
            np.copyto(self._slot_view(slot, arr.shape, arr.dtype, f_off), arr)
            desc_fields.append([key, arr.dtype.str, list(arr.shape), f_off])
        self._gen[slot] = gen + 2  # even: stable
        return {
            "n": self.name,
            "s": slot,
            "g": gen + 2,
            "f": desc_fields,
        }

    def begin_write(self, slot: int) -> None:
        """Test hook: mark ``slot`` write-in-progress (odd generation).

        Simulates a writer killed mid-copy — ``read`` of any descriptor for
        this slot reports torn until :meth:`end_write` runs.
        """
        self._gen[slot] = int(self._gen[slot]) + 1

    def end_write(self, slot: int) -> int:
        self._gen[slot] = int(self._gen[slot]) + 1
        return int(self._gen[slot])

    # -- reader side ---------------------------------------------------------

    def read(self, desc: dict) -> dict[str, np.ndarray] | None:
        """Copy the descriptor's fields out of the ring; ``None`` when torn.

        Torn covers every unsafe case: generation odd (write in progress or
        writer died mid-copy), generation behind/ahead of the descriptor
        (slot reclaimed), or a concurrent overwrite detected by the re-check
        after the copy.  A successful read acknowledges the generation so
        the writer may reuse the slot.
        """
        slot = int(desc["s"])
        gen = int(desc["g"])
        if slot < 0 or slot >= self.slots:
            return None
        if int(self._gen[slot]) != gen or gen % 2:
            return None
        out: dict[str, np.ndarray] = {}
        for key, dtype_str, shape, off in desc["f"]:
            src = self._slot_view(slot, tuple(shape), np.dtype(dtype_str), off)
            out[key] = src.copy()
        if int(self._gen[slot]) != gen:
            return None  # overwritten mid-copy
        self._ack[slot] = gen
        return out

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # numpy views pin the mmap's exported buffer; drop them first
        self._gen = None
        self._ack = None
        try:
            self._shm.close()
        except BufferError:  # pragma: no cover - stray external view
            pass

    def unlink(self) -> None:
        """Remove the segment name; idempotent (safe to race the launcher)."""
        if self._unlinked:
            return
        self._unlinked = True
        _deregister(self._shm.name)
        try:
            _unlink_quietly(self._shm)
        except FileNotFoundError:
            pass

    def __enter__(self) -> "ShmRing":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
        if self._owner:
            self.unlink()


# -- attach cache (consumer side) -------------------------------------------

_attach_lock = threading.Lock()
_attached: dict[str, ShmRing] = {}
_attach_failed: set[str] = set()


def attach_ring(name: str) -> ShmRing | None:
    """Attach to ``name``, caching the handle per process.

    Returns ``None`` (once-logged) when the segment no longer exists — the
    producer died and the launcher reaped it before we ever attached; the
    caller treats the message as torn.
    """
    with _attach_lock:
        ring = _attached.get(name)
        if ring is not None:
            return ring
        if name in _attach_failed:
            return None
        try:
            ring = ShmRing.attach(name)
        except (FileNotFoundError, ValueError, OSError) as e:
            _attach_failed.add(name)
            logger.warning("cannot attach shm segment %s: %s", name, e)
            return None
        _attached[name] = ring
        return ring


def detach_all() -> None:
    """Close every cached attach handle (tests / consumer teardown)."""
    with _attach_lock:
        rings = list(_attached.values())
        _attached.clear()
        _attach_failed.clear()
    for ring in rings:
        ring.close()


def resolve_message(msg: dict) -> dict:
    """Resolve a ``_shm`` descriptor in a decoded message, in place.

    On success the slot's arrays are copied out and merged into ``msg``.  A
    torn generation (or a vanished segment) counts ``wire.shm_torn`` and
    returns the message with a ``_shm_torn`` marker instead — the lineage
    stamps rode the descriptor and arrived intact, so the caller still
    ingests them (no phantom seq gaps) before dropping the payload.
    """
    desc = msg.pop("_shm", None)
    if desc is None:
        return msg
    out = None
    ring = attach_ring(desc["n"])
    if ring is not None:
        try:
            out = ring.read(desc)
        except (IndexError, ValueError, TypeError):
            out = None
    if out is None:
        metrics.count("wire.shm_torn")
        msg["_shm_torn"] = True
        return msg
    nbytes = 0
    for key, arr in out.items():
        msg[key] = arr
        nbytes += arr.nbytes
    metrics.count("wire.shm_reads")
    metrics.count("wire.shm_bytes", nbytes)
    return msg


# -- registry reaping (launcher side) ---------------------------------------

def unlink_segment(name: str) -> bool:
    """Unlink a segment by name; ``True`` if it existed."""
    try:
        seg = shared_memory.SharedMemory(name=name)
    except FileNotFoundError:
        return False
    except OSError:
        return False
    _untrack(seg)
    seg.close()
    try:
        _unlink_quietly(seg)
    except FileNotFoundError:
        return False
    return True


def reap_registry(registry_dir: str, btid: object = None) -> int:
    """Unlink every segment registered under ``registry_dir``.

    With ``btid`` given, only that producer's segments are reaped (the
    ``retire_instance`` path); otherwise everything goes (teardown).  Marker
    files are removed either way, so a second pass is a no-op — this is what
    makes "unlinked exactly once" hold across retire + teardown + atexit.
    """
    reaped = 0
    try:
        entries = os.listdir(registry_dir)
    except OSError:
        return 0
    prefix = None if btid is None else f"{_sanitize(btid)}__"
    for fn in entries:
        if "__" not in fn:
            continue
        if prefix is not None and not fn.startswith(prefix):
            continue
        if unlink_segment(fn.partition("__")[2]):
            reaped += 1
        try:
            os.remove(os.path.join(registry_dir, fn))
        except FileNotFoundError:
            pass
        except OSError:
            pass
    return reaped
