"""Wire formats.

Two codecs share one decode entry point:

- ``TensorCodec`` ("bjx1"): a multipart message — one msgpack header frame
  prefixed with magic ``BJX1``, followed by one raw frame per ndarray.
  Arrays travel as raw bytes and are reconstructed with ``np.frombuffer``
  on receive, so a 640x480 RGBA image crosses the stack with zero copies
  and zero pickling. This is the blendjax-native format and the reason the
  ingest path can feed ``jax.device_put`` without a Python-object hop
  (SURVEY.md §5 "distributed communication backend").

  A publisher may opt into per-frame compression (``compress_level > 0``):
  array frames at least ``compress_min_bytes`` long whose zlib stream is
  actually smaller ship as ``"ndz"`` entries instead of ``"nd"``. The
  entry kind rides in the header, so decode needs no configuration —
  ``"nd"`` and ``"ndz"`` frames interleave freely in one stream and old
  ``"nd"``-only producers keep working unmodified. Compression is a
  per-publisher negotiation in the same sense the codec itself is: the
  consumer accepts everything, the producer chooses what to send.

- ``PickleCodec``: single-frame pickled dict, byte-compatible with the
  reference producers (``pkg_blender/blendtorch/btb/publisher.py:43`` uses
  ``send_pyobj``; consumer ``dataset.py:105`` uses ``recv_pyobj``), so
  unmodified ``btb`` Blender scripts can publish into a blendjax consumer.

Decode autodetects: pickled frames begin with the pickle PROTO opcode
``b"\\x80"`` while tensor-codec headers begin with ``BJX1``, and the two can
never collide.

Semantics and safety notes:

- msgpack has no tuple type, so non-array tuples arrive as lists under the
  tensor codec (``(640, 480)`` -> ``[640, 480]``); use ndarrays or lists on
  the wire if the distinction matters. The pickle codec preserves tuples.
- Unpickling is remote code execution by design. Receivers accept pickled
  payloads by default for compatibility with unmodified reference producers
  (``send_pyobj``); on untrusted networks pass ``allow_pickle=False`` to
  reject both legacy pickle frames and embedded ``pkl`` fallback entries.
"""

from __future__ import annotations

import pickle
import zlib

import numpy as np

try:  # msgpack ships in the image; guard anyway so producers degrade to pickle.
    import msgpack
except ImportError:  # pragma: no cover
    msgpack = None

from blendjax.constants import WIRE_MAGIC
from blendjax.utils.metrics import metrics

# Pickle protocol 4: readable by every Python >= 3.4 (the reference pins 3
# for Blender 2.8's py3.7, ``file.py:58-63``; any modern Blender reads 4).
PICKLE_PROTOCOL = 4

# Arrays below this size aren't worth a zlib round trip: the per-call
# overhead beats the byte savings, and tiny sidecar arrays (tile indices,
# corner coordinates) dominate frame COUNT while contributing almost no
# frame BYTES.
DEFAULT_COMPRESS_MIN_BYTES = 16_384


def _np_scalar_to_py(value):
    if isinstance(value, np.generic):
        return value.item()
    return value


class TensorCodec:
    """Zero-copy multipart codec: msgpack header + raw ndarray frames."""

    name = "tensor"

    @staticmethod
    def encode(message: dict, compress_level: int = 0,
               compress_min_bytes: int = DEFAULT_COMPRESS_MIN_BYTES) -> list:
        """Encode ``message`` into a list of frames (bytes / memoryview).

        ndarray values (non-object dtype) are shipped as raw frames;
        msgpack-native values ride in the header; anything else falls back
        to an embedded pickle so arbitrary metadata still round-trips.

        With ``compress_level > 0``, array frames of at least
        ``compress_min_bytes`` ship zlib-compressed (``"ndz"``) — but only
        when the compressed stream actually shrinks; incompressible data
        (already-palettized tiles, encrypted blobs) stays raw so the
        decoder never pays an inflate for nothing.
        """
        if msgpack is None:  # pragma: no cover
            return PickleCodec.encode(message)
        entries = []
        buffers = []
        for key, value in message.items():
            if isinstance(value, np.ndarray) and value.dtype != object:
                arr = np.ascontiguousarray(value)
                raw = arr.data if arr.size else b""
                if compress_level > 0 and arr.nbytes >= compress_min_bytes:
                    # zlib takes the contiguous view directly — no copy
                    packed = zlib.compress(raw, compress_level)
                    if len(packed) < arr.nbytes:
                        entries.append(
                            ["ndz", key, list(arr.shape), arr.dtype.str,
                             len(buffers)]
                        )
                        buffers.append(packed)
                        continue
                entries.append(
                    ["nd", key, list(arr.shape), arr.dtype.str, len(buffers)]
                )
                buffers.append(raw)
            else:
                value = _np_scalar_to_py(value)
                try:
                    packed = msgpack.packb(value, use_bin_type=True)
                    entries.append(["obj", key, packed])
                except (TypeError, ValueError, OverflowError):
                    entries.append(
                        ["pkl", key, pickle.dumps(value, protocol=PICKLE_PROTOCOL)]
                    )
        header = WIRE_MAGIC + msgpack.packb([1, entries], use_bin_type=True)
        return [header, *buffers]

    @staticmethod
    def decode(frames: list, copy_arrays: bool = False,
               allow_pickle: bool = True,
               count_metrics: bool = False) -> dict:
        header = bytes(frames[0][: len(WIRE_MAGIC)])
        if header != WIRE_MAGIC:
            raise ValueError("not a tensor-codec message")
        version, entries = msgpack.unpackb(
            bytes(frames[0])[len(WIRE_MAGIC):], raw=False, strict_map_key=False
        )
        if version != 1:
            raise ValueError(f"unsupported wire version {version}")
        out = {}
        # wire.raw_bytes / wire.compressed_bytes: decoded array bytes vs
        # what actually crossed the wire for them — the pair the bench
        # publishes so compression wins are evidenced, not asserted. Raw
        # frames count into both sides (ratio 1 when nothing compresses).
        # Accumulated locally, ONE locked pair of counts per message:
        # sidecar arrays dominate frame count and this is the hot path.
        raw_bytes = wire_bytes = 0
        for entry in entries:
            kind, key = entry[0], entry[1]
            if kind == "nd":
                _, _, shape, dtype, idx = entry
                buf = frames[1 + idx]
                arr = np.frombuffer(buf, dtype=np.dtype(dtype)).reshape(shape)
                raw_bytes += arr.nbytes
                wire_bytes += arr.nbytes
                out[key] = arr.copy() if copy_arrays else arr
            elif kind == "ndz":
                _, _, shape, dtype, idx = entry
                wire_buf = frames[1 + idx]
                dt = np.dtype(dtype)
                expected = dt.itemsize
                for dim in shape:
                    expected *= int(dim)
                if expected <= 0:
                    raise ValueError(
                        f"ndz frame for {key!r} declares zero bytes "
                        "(empty arrays never ship compressed)"
                    )
                # Bounded inflate: allocation is capped at the DECLARED
                # array size — no more than an honest raw "nd" frame of
                # the same header could make us hold — so a small
                # malicious stream can't balloon memory (decompression
                # bomb; this path is advertised safe for untrusted
                # networks under allow_pickle=False).
                dec = zlib.decompressobj()
                buf = dec.decompress(wire_buf, expected)
                if not dec.eof or dec.unconsumed_tail:
                    raise ValueError(
                        f"ndz frame for {key!r} does not decompress to "
                        f"the declared {expected} bytes"
                    )
                arr = np.frombuffer(buf, dtype=dt).reshape(shape)
                raw_bytes += arr.nbytes
                wire_bytes += (
                    wire_buf.nbytes if isinstance(wire_buf, memoryview)
                    else len(wire_buf)
                )
                # frombuffer over bytes is read-only; honor the nd-path
                # contract (torch consumers need writable arrays)
                out[key] = arr.copy() if copy_arrays else arr
            elif kind == "obj":
                out[key] = msgpack.unpackb(entry[2], raw=False, strict_map_key=False)
            elif kind == "pkl":
                if not allow_pickle:
                    raise ValueError(
                        f"refusing embedded pickle for key {key!r} "
                        "(allow_pickle=False)"
                    )
                out[key] = pickle.loads(entry[2])
            else:
                raise ValueError(f"unknown wire entry kind {kind!r}")
        if count_metrics and raw_bytes:
            # Only the DATA stream counts (DataReceiverSocket sets the
            # flag): control/RPC messages through the same codec would
            # pollute the compression-ratio pair the bench publishes.
            metrics.count("wire.raw_bytes", raw_bytes)
            metrics.count("wire.compressed_bytes", wire_bytes)
        return out


class PickleCodec:
    """Reference-compatible single-frame pickle codec."""

    name = "pickle"

    @staticmethod
    def encode(message: dict) -> list:
        return [pickle.dumps(message, protocol=PICKLE_PROTOCOL)]

    @staticmethod
    def decode(frames: list, copy_arrays: bool = False,
               allow_pickle: bool = True) -> dict:
        del copy_arrays  # pickle always materializes copies
        if not allow_pickle:
            raise ValueError("refusing pickled message (allow_pickle=False)")
        return pickle.loads(bytes(frames[0]))


CODECS = {TensorCodec.name: TensorCodec, PickleCodec.name: PickleCodec}


def encode_message(message: dict, codec: str = "tensor",
                   compress_level: int = 0,
                   compress_min_bytes: int = DEFAULT_COMPRESS_MIN_BYTES) -> list:
    if codec == TensorCodec.name:
        return TensorCodec.encode(
            message, compress_level=compress_level,
            compress_min_bytes=compress_min_bytes,
        )
    return CODECS[codec].encode(message)


def decode_message(frames: list, copy_arrays: bool = False,
                   allow_pickle: bool = True,
                   count_metrics: bool = False) -> dict:
    """Decode frames from either codec (autodetected by leading bytes).

    ``count_metrics=True`` accounts the array frames into the
    ``wire.raw_bytes``/``wire.compressed_bytes`` pair — set only by
    data-stream receivers so control/RPC traffic stays out of the
    published compression ratio."""
    head = bytes(frames[0][: len(WIRE_MAGIC)])
    if head == WIRE_MAGIC:
        return TensorCodec.decode(
            frames, copy_arrays=copy_arrays, allow_pickle=allow_pickle,
            count_metrics=count_metrics,
        )
    return PickleCodec.decode(
        frames, copy_arrays=copy_arrays, allow_pickle=allow_pickle
    )


def sizeof_frames(frames: list) -> int:
    """Total payload bytes of an encoded message (for metrics/recording)."""
    total = 0
    for f in frames:
        if isinstance(f, (bytes, bytearray)):
            total += len(f)
        elif isinstance(f, memoryview):
            # len() of a multi-dimensional or non-byte view counts
            # elements, not bytes — nbytes is the wire size either way.
            total += f.nbytes
        else:
            total += len(bytes(f))
    return total
