"""Wire formats.

Two codecs share one decode entry point:

- ``TensorCodec`` ("bjx1"): a multipart message — one msgpack header frame
  prefixed with magic ``BJX1``, followed by one raw frame per ndarray.
  Arrays travel as raw bytes and are reconstructed with ``np.frombuffer``
  on receive, so a 640x480 RGBA image crosses the stack with zero copies
  and zero pickling. This is the blendjax-native format and the reason the
  ingest path can feed ``jax.device_put`` without a Python-object hop
  (SURVEY.md §5 "distributed communication backend").

  A publisher may opt into per-frame compression (``compress_level > 0``):
  array frames at least ``compress_min_bytes`` long whose zlib stream is
  actually smaller ship as ``"ndz"`` entries instead of ``"nd"``. The
  entry kind rides in the header, so decode needs no configuration —
  ``"nd"`` and ``"ndz"`` frames interleave freely in one stream and old
  ``"nd"``-only producers keep working unmodified. Compression is a
  per-publisher negotiation in the same sense the codec itself is: the
  consumer accepts everything, the producer chooses what to send.

- ``PickleCodec``: single-frame pickled dict, byte-compatible with the
  reference producers (``pkg_blender/blendtorch/btb/publisher.py:43`` uses
  ``send_pyobj``; consumer ``dataset.py:105`` uses ``recv_pyobj``), so
  unmodified ``btb`` Blender scripts can publish into a blendjax consumer.

Decode autodetects: pickled frames begin with the pickle PROTO opcode
``b"\\x80"`` while tensor-codec headers begin with ``BJX1``, and the two can
never collide.

Semantics and safety notes:

- msgpack has no tuple type, so non-array tuples arrive as lists under the
  tensor codec (``(640, 480)`` -> ``[640, 480]``); use ndarrays or lists on
  the wire if the distinction matters. The pickle codec preserves tuples.
- Unpickling is remote code execution by design. Receivers accept pickled
  payloads by default for compatibility with unmodified reference producers
  (``send_pyobj``); on untrusted networks pass ``allow_pickle=False`` to
  reject both legacy pickle frames and embedded ``pkl`` fallback entries.
"""

from __future__ import annotations

import pickle
import time
import zlib

import numpy as np

try:  # msgpack ships in the image; guard anyway so producers degrade to pickle.
    import msgpack
except ImportError:  # pragma: no cover
    msgpack = None

from blendjax.constants import WIRE_MAGIC
from blendjax.utils.metrics import metrics

# Pickle protocol 4: readable by every Python >= 3.4 (the reference pins 3
# for Blender 2.8's py3.7, ``file.py:58-63``; any modern Blender reads 4).
PICKLE_PROTOCOL = 4

# Arrays below this size aren't worth a zlib round trip: the per-call
# overhead beats the byte savings, and tiny sidecar arrays (tile indices,
# corner coordinates) dominate frame COUNT while contributing almost no
# frame BYTES.
DEFAULT_COMPRESS_MIN_BYTES = 16_384


class WireCompressState:
    """Per-publisher compression working state (one instance per
    :class:`~blendjax.transport.channels.DataPublisherSocket`).

    Three jobs, all bounded:

    - a reusable ``zlib.compressobj`` template per level: every message
      compresses through ``template.copy()`` instead of re-building the
      deflate state from scratch per frame;
    - a bounded skip memo for keys/kinds that recently LOST the size
      check (incompressible render noise, already-palettized tiles):
      those fields skip the trial compression for ``SKIP_FRAMES``
      encodes before re-trying, so a loser stops paying the round trip
      every frame while a stream that turns compressible recovers;
    - sticky per-key run-length capacities (the ``pack_batch`` capacity
      idiom applied to the wire): the "ndr" packed shape ratchets up on
      overflow and never shrinks, keeping a consumer's decode-plan jit
      cache stable across frames.
    """

    SKIP_FRAMES = 64   # trials skipped after a size-check loss
    MEMO_LIMIT = 128   # bounded: stream content can't grow the dicts

    def __init__(self):
        self._templates: dict = {}
        self._skip: dict = {}
        self._caps: dict = {}

    def compress(self, raw, level: int) -> bytes:
        template = self._templates.get(level)
        if template is None:
            template = self._templates[level] = zlib.compressobj(level)
        c = template.copy()
        return c.compress(raw) + c.flush()

    def should_try(self, kind: str, key) -> bool:
        left = self._skip.get((kind, key), 0)
        if left > 0:
            self._skip[(kind, key)] = left - 1
            metrics.count("wire.compress_skips")
            return False
        return True

    def lost(self, kind: str, key) -> None:
        if len(self._skip) >= self.MEMO_LIMIT:
            self._skip.clear()
        self._skip[(kind, key)] = self.SKIP_FRAMES

    def won(self, kind: str, key) -> None:
        self._skip.pop((kind, key), None)

    def rle_cap(self, key):
        return self._caps.get(key)

    def set_rle_cap(self, key, cap: int) -> None:
        if len(self._caps) >= self.MEMO_LIMIT:
            self._caps.clear()
        prev = self._caps.get(key, 0)
        if cap > prev:
            self._caps[key] = int(cap)


def _np_scalar_to_py(value):
    if isinstance(value, np.generic):
        return value.item()
    return value


class TensorCodec:
    """Zero-copy multipart codec: msgpack header + raw ndarray frames."""

    name = "tensor"

    @staticmethod
    def encode(message: dict, compress_level: int = 0,
               compress_min_bytes: int = DEFAULT_COMPRESS_MIN_BYTES,
               compress_rle: bool = False, rle_cap: int | None = None,
               quantize_f16=(), state: WireCompressState | None = None,
               ) -> list:
        """Encode ``message`` into a list of frames (bytes / memoryview).

        ndarray values (non-object dtype) are shipped as raw frames;
        msgpack-native values ride in the header; anything else falls back
        to an embedded pickle so arbitrary metadata still round-trips.

        With ``compress_level > 0``, array frames of at least
        ``compress_min_bytes`` ship zlib-compressed (``"ndz"``) — but only
        when the compressed stream actually shrinks; incompressible data
        (already-palettized tiles, encrypted blobs) stays raw so the
        decoder never pays an inflate for nothing.

        ``compress_rle=True`` tries the run-length "ndr" kind FIRST for
        uint8 arrays at least ``compress_min_bytes`` long (the tile-group
        codec of :mod:`blendjax.ops.tiles`): run-heavy payloads — palette
        index planes, flat-shaded frames — keep ~the zlib wire ratio
        while the consumer inflates with one vectorized ``np.repeat``, or
        defers the expansion into its train jit entirely (zero host
        inflate). ``rle_cap`` pins the per-row pair capacity (fleet-wide
        shape stability, the ``TileBatchPublisher(capacity=...)``
        contract); without it the capacity is sticky per key via
        ``state``. A frame whose runs don't fit a pinned cap, or that RLE
        fails to shrink, falls back to ndz/nd for that message — "ndr"
        interleaves freely with both.

        ``quantize_f16`` names float32/float64 fields to cast to float16
        before encoding (lossy by design — point labels whose integer
        pixel coordinates are exact up to 2048; consumers dequantize
        in-jit via their existing f32 input casts). ``state`` is the
        per-publisher :class:`WireCompressState` (compressobj reuse +
        loss-memo + sticky caps); ``None`` keeps the stateless behavior.
        """
        if msgpack is None:  # pragma: no cover
            return PickleCodec.encode(message)
        entries = []
        buffers = []
        for key, value in message.items():
            if isinstance(value, np.ndarray) and value.dtype != object:
                arr = np.ascontiguousarray(value)
                if key in quantize_f16 and arr.dtype in (
                    np.float32, np.float64
                ):
                    arr = arr.astype(np.float16)
                raw = arr.data if arr.size else b""
                if (
                    compress_rle
                    and arr.dtype == np.uint8
                    and arr.nbytes >= compress_min_bytes
                    and (state is None or state.should_try("r", key))
                ):
                    from blendjax.ops.tiles import rle_encode_rows

                    cap = rle_cap if rle_cap else (
                        state.rle_cap(key) if state is not None else None
                    )
                    out = rle_encode_rows(arr, cap=cap)
                    if out is None and cap is not None and not rle_cap:
                        # sticky cap overflowed: re-derive (ratchets up)
                        out = rle_encode_rows(arr)
                    if out is not None and out[0].nbytes < arr.nbytes:
                        buf, cap_eff, isz = out
                        if state is not None:
                            state.won("r", key)
                            if not rle_cap:
                                state.set_rle_cap(key, cap_eff)
                        entries.append(
                            ["ndr", key, list(arr.shape), arr.dtype.str,
                             len(buffers), int(cap_eff), int(isz)]
                        )
                        buffers.append(buf)
                        continue
                    if state is not None:
                        state.lost("r", key)
                if (
                    compress_level > 0
                    and arr.nbytes >= compress_min_bytes
                    and (state is None or state.should_try("z", key))
                ):
                    # zlib takes the contiguous view directly — no copy
                    packed = (
                        state.compress(raw, compress_level)
                        if state is not None
                        else zlib.compress(raw, compress_level)
                    )
                    if len(packed) < arr.nbytes:
                        if state is not None:
                            state.won("z", key)
                        entries.append(
                            ["ndz", key, list(arr.shape), arr.dtype.str,
                             len(buffers)]
                        )
                        buffers.append(packed)
                        continue
                    if state is not None:
                        state.lost("z", key)
                entries.append(
                    ["nd", key, list(arr.shape), arr.dtype.str, len(buffers)]
                )
                buffers.append(raw)
            else:
                value = _np_scalar_to_py(value)
                try:
                    packed = msgpack.packb(value, use_bin_type=True)
                    entries.append(["obj", key, packed])
                except (TypeError, ValueError, OverflowError):
                    entries.append(
                        ["pkl", key, pickle.dumps(value, protocol=PICKLE_PROTOCOL)]
                    )
        header = WIRE_MAGIC + msgpack.packb([1, entries], use_bin_type=True)
        return [header, *buffers]

    @staticmethod
    def _declared_bytes(key, shape, dt: np.dtype) -> int:
        expected = dt.itemsize
        for dim in shape:
            expected *= int(dim)
        if expected <= 0:
            raise ValueError(
                f"compressed frame for {key!r} declares zero bytes "
                "(empty arrays never ship compressed)"
            )
        return expected

    @staticmethod
    def _inflate_bounded(key, wire_buf, expected: int) -> bytes:
        """Bounded inflate: allocation is capped at the DECLARED array
        size — no more than an honest raw "nd" frame of the same header
        could make us hold — so a small malicious stream can't balloon
        memory (decompression bomb; this path is advertised safe for
        untrusted networks under allow_pickle=False). The ONE sanctioned
        host-inflate site (bjx-lint BJX116 flags zlib inflates added to
        hot-path modules outside this codec/pool)."""
        dec = zlib.decompressobj()
        buf = dec.decompress(wire_buf, expected)
        if not dec.eof or dec.unconsumed_tail:
            raise ValueError(
                f"ndz frame for {key!r} does not decompress to "
                f"the declared {expected} bytes"
            )
        return buf

    @staticmethod
    def decode(frames: list, copy_arrays: bool = False,
               allow_pickle: bool = True,
               count_metrics: bool = False,
               defer_rle: bool = False,
               inflate_pool=None) -> dict:
        """Decode one multipart message.

        ``defer_rle=True`` leaves "ndr" entries of PREBATCHED messages
        (``_prebatched=True`` riding the header — the opaque tile-stream
        pass-through, whose batch shapes never enter schema assembly)
        still run-packed: the decoded dict carries ``<key>__ndr`` (the
        packed buffer) + ``<key>__ndrspec`` (shape/item/cap plan) instead
        of the expanded array, for a downstream device plan to expand
        inside its decode/train jit. Non-prebatched messages always
        expand on host so schema-assembled streams keep stable shapes.

        ``inflate_pool`` (a ``concurrent.futures`` executor) inflates a
        message's "ndz" entries in parallel — zlib releases the GIL, so
        a multi-field frame's inflates overlap on real cores. A DIRECT-
        consumer surface: the stream path instead pipelines whole-
        message decode-ahead (``RemoteStream.set_inflate_pool``), whose
        decode jobs deliberately run with this parameter unset —
        re-submitting into the same small executor from inside a decode
        job could deadlock it."""
        header = bytes(frames[0][: len(WIRE_MAGIC)])
        if header != WIRE_MAGIC:
            raise ValueError("not a tensor-codec message")
        version, entries = msgpack.unpackb(
            bytes(frames[0])[len(WIRE_MAGIC):], raw=False, strict_map_key=False
        )
        if version != 1:
            raise ValueError(f"unsupported wire version {version}")
        out = {}
        # wire.raw_bytes / wire.compressed_bytes: decoded array bytes vs
        # what actually crossed the wire for them — the pair the bench
        # publishes so compression wins are evidenced, not asserted. Raw
        # frames count into both sides (ratio 1 when nothing compresses).
        # Accumulated locally, ONE locked pair of counts per message:
        # sidecar arrays dominate frame count and this is the hot path.
        raw_bytes = wire_bytes = 0
        inflate_ms = 0.0
        if defer_rle:
            # Deferral is per MESSAGE, decided before any array entry is
            # touched: only opaque prebatched messages may change shape
            # under the consumer's feet (their batches bypass schema
            # assembly like tile batches do).
            defer_rle = any(
                e[0] == "obj" and e[1] == "_prebatched"
                and bool(msgpack.unpackb(e[2], raw=False))
                for e in entries
            )
        inflated: dict = {}
        if inflate_pool is not None:
            jobs = []
            for i, entry in enumerate(entries):
                if entry[0] != "ndz":
                    continue
                _, key, shape, dtype, idx = entry
                expected = TensorCodec._declared_bytes(
                    key, shape, np.dtype(dtype)
                )
                jobs.append((i, inflate_pool.submit(
                    TensorCodec._inflate_bounded, key, frames[1 + idx],
                    expected,
                )))
            if len(jobs) >= 2:
                t0 = time.perf_counter()
                for i, fut in jobs:
                    inflated[i] = fut.result()
                inflate_ms += (time.perf_counter() - t0) * 1e3
            elif jobs:
                # one job gains nothing from the pool hop's latency —
                # but it was already submitted; harvest it inline
                t0 = time.perf_counter()
                inflated[jobs[0][0]] = jobs[0][1].result()
                inflate_ms += (time.perf_counter() - t0) * 1e3
        for i, entry in enumerate(entries):
            kind, key = entry[0], entry[1]
            if kind == "nd":
                _, _, shape, dtype, idx = entry
                buf = frames[1 + idx]
                arr = np.frombuffer(buf, dtype=np.dtype(dtype)).reshape(shape)
                raw_bytes += arr.nbytes
                wire_bytes += arr.nbytes
                out[key] = arr.copy() if copy_arrays else arr
            elif kind == "ndz":
                _, _, shape, dtype, idx = entry
                wire_buf = frames[1 + idx]
                dt = np.dtype(dtype)
                expected = TensorCodec._declared_bytes(key, shape, dt)
                buf = inflated.get(i)
                if buf is None:
                    t0 = time.perf_counter()
                    buf = TensorCodec._inflate_bounded(
                        key, wire_buf, expected
                    )
                    inflate_ms += (time.perf_counter() - t0) * 1e3
                arr = np.frombuffer(buf, dtype=dt).reshape(shape)
                raw_bytes += arr.nbytes
                wire_bytes += (
                    wire_buf.nbytes if isinstance(wire_buf, memoryview)
                    else len(wire_buf)
                )
                # frombuffer over bytes is read-only; honor the nd-path
                # contract (torch consumers need writable arrays)
                out[key] = arr.copy() if copy_arrays else arr
            elif kind == "ndr":
                _, _, shape, dtype, idx, cap, isz = entry
                from blendjax.ops.tiles import (
                    NDR_SUFFIX,
                    NDRSPEC_SUFFIX,
                    rle_expand_packed_np,
                    rle_packed_stride,
                )

                wire_buf = frames[1 + idx]
                dt = np.dtype(dtype)
                if dt != np.uint8:
                    raise ValueError(
                        f"ndr frame for {key!r} declares dtype {dtype!r} "
                        "(run-length frames are uint8-only)"
                    )
                expected = TensorCodec._declared_bytes(key, shape, dt)
                rows = int(shape[0]) if len(shape) >= 2 else 1
                # the frame may be a memoryview (socket), bytes, or the
                # publisher's 2-D staging array (in-process replay) —
                # nbytes is the wire size for all buffer-protocol forms
                nb = getattr(wire_buf, "nbytes", None)
                if nb is None:
                    nb = len(wire_buf)
                stride = rle_packed_stride(int(cap), int(isz))
                if rows <= 0 or nb != rows * stride:
                    raise ValueError(
                        f"ndr frame for {key!r} carries {nb} bytes, "
                        f"declared {rows} rows x {stride} (truncated or "
                        "padded stream)"
                    )
                buf = np.frombuffer(wire_buf, np.uint8).reshape(rows, stride)
                raw_bytes += expected
                wire_bytes += nb
                if defer_rle:
                    # Deferred device expansion: the packed buffer +
                    # its plan ride the batch; the consumer's decode
                    # plan re-validates (rle_validate_packed) before
                    # any jit sees the buffer.
                    out[key + NDR_SUFFIX] = (
                        buf.copy() if copy_arrays else buf
                    )
                    out[key + NDRSPEC_SUFFIX] = [
                        [int(s) for s in shape], int(isz), int(cap),
                    ]
                else:
                    # validates (declared-size + run-sum guards) then
                    # expands via one vectorized repeat per row; the
                    # expansion allocates fresh, so the result is
                    # always writable (copy_arrays moot)
                    out[key] = rle_expand_packed_np(
                        buf, shape, int(isz), int(cap)
                    )
            elif kind == "obj":
                out[key] = msgpack.unpackb(entry[2], raw=False, strict_map_key=False)
            elif kind == "pkl":
                if not allow_pickle:
                    raise ValueError(
                        f"refusing embedded pickle for key {key!r} "
                        "(allow_pickle=False)"
                    )
                out[key] = pickle.loads(entry[2])
            else:
                raise ValueError(f"unknown wire entry kind {kind!r}")
        if count_metrics and raw_bytes:
            # Only the DATA stream counts (DataReceiverSocket sets the
            # flag): control/RPC messages through the same codec would
            # pollute the compression-ratio pair the bench publishes.
            metrics.count("wire.raw_bytes", raw_bytes)
            metrics.count("wire.compressed_bytes", wire_bytes)
            if inflate_ms:
                # per-message host inflate cost — the histogram the
                # ndz-vs-ndr bench legs compare (ndr legs observe ~0)
                metrics.observe("wire.inflate_ms", inflate_ms)
        return out


class PickleCodec:
    """Reference-compatible single-frame pickle codec."""

    name = "pickle"

    @staticmethod
    def encode(message: dict) -> list:
        return [pickle.dumps(message, protocol=PICKLE_PROTOCOL)]

    @staticmethod
    def decode(frames: list, copy_arrays: bool = False,
               allow_pickle: bool = True) -> dict:
        del copy_arrays  # pickle always materializes copies
        if not allow_pickle:
            raise ValueError("refusing pickled message (allow_pickle=False)")
        return pickle.loads(bytes(frames[0]))


CODECS = {TensorCodec.name: TensorCodec, PickleCodec.name: PickleCodec}


def encode_message(message: dict, codec: str = "tensor",
                   compress_level: int = 0,
                   compress_min_bytes: int = DEFAULT_COMPRESS_MIN_BYTES,
                   compress_rle: bool = False, rle_cap: int | None = None,
                   quantize_f16=(),
                   state: WireCompressState | None = None) -> list:
    if codec == TensorCodec.name:
        return TensorCodec.encode(
            message, compress_level=compress_level,
            compress_min_bytes=compress_min_bytes,
            compress_rle=compress_rle, rle_cap=rle_cap,
            quantize_f16=quantize_f16, state=state,
        )
    return CODECS[codec].encode(message)


def decode_message(frames: list, copy_arrays: bool = False,
                   allow_pickle: bool = True,
                   count_metrics: bool = False,
                   defer_rle: bool = False,
                   inflate_pool=None) -> dict:
    """Decode frames from either codec (autodetected by leading bytes).

    ``count_metrics=True`` accounts the array frames into the
    ``wire.raw_bytes``/``wire.compressed_bytes`` pair — set only by
    data-stream receivers so control/RPC traffic stays out of the
    published compression ratio. ``defer_rle``/``inflate_pool`` apply to
    tensor-codec messages only (see :meth:`TensorCodec.decode`)."""
    head = bytes(frames[0][: len(WIRE_MAGIC)])
    if head == WIRE_MAGIC:
        return TensorCodec.decode(
            frames, copy_arrays=copy_arrays, allow_pickle=allow_pickle,
            count_metrics=count_metrics, defer_rle=defer_rle,
            inflate_pool=inflate_pool,
        )
    return PickleCodec.decode(
        frames, copy_arrays=copy_arrays, allow_pickle=allow_pickle
    )


def sizeof_frames(frames: list) -> int:
    """Total payload bytes of an encoded message (for metrics/recording)."""
    total = 0
    for f in frames:
        if isinstance(f, (bytes, bytearray)):
            total += len(f)
        elif isinstance(f, memoryview):
            # len() of a multi-dimensional or non-byte view counts
            # elements, not bytes — nbytes is the wire size either way.
            total += f.nbytes
        else:
            total += len(bytes(f))
    return total
