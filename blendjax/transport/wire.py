"""Wire formats.

Two codecs share one decode entry point:

- ``TensorCodec`` ("bjx1"): a multipart message — one msgpack header frame
  prefixed with magic ``BJX1``, followed by one raw frame per ndarray.
  Arrays travel as raw bytes and are reconstructed with ``np.frombuffer``
  on receive, so a 640x480 RGBA image crosses the stack with zero copies
  and zero pickling. This is the blendjax-native format and the reason the
  ingest path can feed ``jax.device_put`` without a Python-object hop
  (SURVEY.md §5 "distributed communication backend").

- ``PickleCodec``: single-frame pickled dict, byte-compatible with the
  reference producers (``pkg_blender/blendtorch/btb/publisher.py:43`` uses
  ``send_pyobj``; consumer ``dataset.py:105`` uses ``recv_pyobj``), so
  unmodified ``btb`` Blender scripts can publish into a blendjax consumer.

Decode autodetects: pickled frames begin with the pickle PROTO opcode
``b"\\x80"`` while tensor-codec headers begin with ``BJX1``, and the two can
never collide.

Semantics and safety notes:

- msgpack has no tuple type, so non-array tuples arrive as lists under the
  tensor codec (``(640, 480)`` -> ``[640, 480]``); use ndarrays or lists on
  the wire if the distinction matters. The pickle codec preserves tuples.
- Unpickling is remote code execution by design. Receivers accept pickled
  payloads by default for compatibility with unmodified reference producers
  (``send_pyobj``); on untrusted networks pass ``allow_pickle=False`` to
  reject both legacy pickle frames and embedded ``pkl`` fallback entries.
"""

from __future__ import annotations

import pickle

import numpy as np

try:  # msgpack ships in the image; guard anyway so producers degrade to pickle.
    import msgpack
except ImportError:  # pragma: no cover
    msgpack = None

from blendjax.constants import WIRE_MAGIC

# Pickle protocol 4: readable by every Python >= 3.4 (the reference pins 3
# for Blender 2.8's py3.7, ``file.py:58-63``; any modern Blender reads 4).
PICKLE_PROTOCOL = 4


def _np_scalar_to_py(value):
    if isinstance(value, np.generic):
        return value.item()
    return value


class TensorCodec:
    """Zero-copy multipart codec: msgpack header + raw ndarray frames."""

    name = "tensor"

    @staticmethod
    def encode(message: dict) -> list:
        """Encode ``message`` into a list of frames (bytes / memoryview).

        ndarray values (non-object dtype) are shipped as raw frames;
        msgpack-native values ride in the header; anything else falls back
        to an embedded pickle so arbitrary metadata still round-trips.
        """
        if msgpack is None:  # pragma: no cover
            return PickleCodec.encode(message)
        entries = []
        buffers = []
        for key, value in message.items():
            if isinstance(value, np.ndarray) and value.dtype != object:
                arr = np.ascontiguousarray(value)
                entries.append(
                    ["nd", key, list(arr.shape), arr.dtype.str, len(buffers)]
                )
                buffers.append(arr.data if arr.size else b"")
            else:
                value = _np_scalar_to_py(value)
                try:
                    packed = msgpack.packb(value, use_bin_type=True)
                    entries.append(["obj", key, packed])
                except (TypeError, ValueError, OverflowError):
                    entries.append(
                        ["pkl", key, pickle.dumps(value, protocol=PICKLE_PROTOCOL)]
                    )
        header = WIRE_MAGIC + msgpack.packb([1, entries], use_bin_type=True)
        return [header, *buffers]

    @staticmethod
    def decode(frames: list, copy_arrays: bool = False,
               allow_pickle: bool = True) -> dict:
        header = bytes(frames[0][: len(WIRE_MAGIC)])
        if header != WIRE_MAGIC:
            raise ValueError("not a tensor-codec message")
        version, entries = msgpack.unpackb(
            bytes(frames[0])[len(WIRE_MAGIC):], raw=False, strict_map_key=False
        )
        if version != 1:
            raise ValueError(f"unsupported wire version {version}")
        out = {}
        for entry in entries:
            kind, key = entry[0], entry[1]
            if kind == "nd":
                _, _, shape, dtype, idx = entry
                buf = frames[1 + idx]
                arr = np.frombuffer(buf, dtype=np.dtype(dtype)).reshape(shape)
                out[key] = arr.copy() if copy_arrays else arr
            elif kind == "obj":
                out[key] = msgpack.unpackb(entry[2], raw=False, strict_map_key=False)
            elif kind == "pkl":
                if not allow_pickle:
                    raise ValueError(
                        f"refusing embedded pickle for key {key!r} "
                        "(allow_pickle=False)"
                    )
                out[key] = pickle.loads(entry[2])
            else:
                raise ValueError(f"unknown wire entry kind {kind!r}")
        return out


class PickleCodec:
    """Reference-compatible single-frame pickle codec."""

    name = "pickle"

    @staticmethod
    def encode(message: dict) -> list:
        return [pickle.dumps(message, protocol=PICKLE_PROTOCOL)]

    @staticmethod
    def decode(frames: list, copy_arrays: bool = False,
               allow_pickle: bool = True) -> dict:
        del copy_arrays  # pickle always materializes copies
        if not allow_pickle:
            raise ValueError("refusing pickled message (allow_pickle=False)")
        return pickle.loads(bytes(frames[0]))


CODECS = {TensorCodec.name: TensorCodec, PickleCodec.name: PickleCodec}


def encode_message(message: dict, codec: str = "tensor") -> list:
    return CODECS[codec].encode(message)


def decode_message(frames: list, copy_arrays: bool = False,
                   allow_pickle: bool = True) -> dict:
    """Decode frames from either codec (autodetected by leading bytes)."""
    head = bytes(frames[0][: len(WIRE_MAGIC)])
    if head == WIRE_MAGIC:
        return TensorCodec.decode(
            frames, copy_arrays=copy_arrays, allow_pickle=allow_pickle
        )
    return PickleCodec.decode(
        frames, copy_arrays=copy_arrays, allow_pickle=allow_pickle
    )


def sizeof_frames(frames: list) -> int:
    """Total payload bytes of an encoded message (for metrics/recording)."""
    return sum(len(f) if isinstance(f, (bytes, bytearray)) else f.nbytes if isinstance(f, memoryview) else len(bytes(f)) for f in frames)
