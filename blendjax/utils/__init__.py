from blendjax.utils.ipaddr import get_primary_ip
from blendjax.utils.logging import get_logger

__all__ = ["get_primary_ip", "get_logger"]
