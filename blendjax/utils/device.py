"""Small device-array helpers shared across the streaming/train layers."""

from __future__ import annotations


def transfer_done(arr) -> bool:
    """Non-blocking readiness poll for an in-flight device array; False
    when the backend can't say (lazy-flushing remote runtimes may never
    locally report ready — callers keep a bounded blocking wait as the
    honest fallback). ONE definition for the feeder's throttle window
    and the TrainDriver's dispatch ring, so their retirement semantics
    cannot diverge."""
    try:
        return bool(arr.is_ready())
    except Exception:
        return False
