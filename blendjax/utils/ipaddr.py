"""Network helpers.

Reference: ``pkg_pytorch/blendtorch/btt/utils.py:2-16`` — the UDP-connect
trick to find the primary (default-route) interface IP, used by the
launcher's ``bind_addr='primaryip'`` mode for two-machine setups
(``launcher.py:187-188``).
"""

from __future__ import annotations

import socket


def get_primary_ip() -> str:
    """IP of the default-route interface; falls back to loopback offline."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        # The address does not need to be reachable; no packet is sent.
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()
