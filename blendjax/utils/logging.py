"""Logging under one logger name (reference uses ``'blendtorch'``,
``launcher.py:12``, ``file.py:8``, ``finder.py:9``)."""

from __future__ import annotations

import logging

from blendjax.constants import LOGGER_NAME


def get_logger(suffix: str | None = None) -> logging.Logger:
    name = LOGGER_NAME if not suffix else f"{LOGGER_NAME}.{suffix}"
    return logging.getLogger(name)
