"""Lightweight metrics: counters, gauges, exact histograms, timing spans.

The reference has no metrics system (SURVEY.md §5 — only wall-clock in its
benchmark harness); blendjax instruments the whole producer → wire →
ingest → train pipeline so feed stalls are diagnosable: per-stage spans
feed lock-exact log-bucketed histograms (p50/p95/p99, not just means —
the mean hides exactly the tail a stall doctor needs), queue-depth
gauges, and a one-line report. ``blendjax.obs`` builds the cross-process
layer on top: frame lineage, the stall doctor, and the Prometheus /
JSONL / Chrome-trace exporters. For deep device-side dives, ``trace``
wraps ``jax.profiler.trace`` so the same code path emits a
TensorBoard-loadable profile.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
from collections import defaultdict, deque

from blendjax.utils.tg import guard

# 8 buckets per octave: bucket bounds grow by 2**(1/8) ≈ 9.05%, so a
# quantile read from the bucket midpoint is within ~4.4% of the true
# value — tight enough to tell a 2x tail regression apart, cheap enough
# (one log + one dict bump) for the ingest hot path.
_GAMMA = 2.0 ** 0.125
_LOG_GAMMA = math.log(_GAMMA)


class Histogram:
    """Exact-count log-bucketed histogram.

    COUNTS are exact (every ``observe`` lands in exactly one bucket;
    bucket counts always sum to ``count`` — the property the bench's
    "histogram counts sum exactly to span counts" acceptance check
    rides on); VALUES are bucketed at ~9% geometric resolution, with
    exact ``min``/``max``/``sum`` kept alongside so p0/p100 and the
    mean never suffer bucketing error. Not self-locking: the owning
    :class:`Metrics` registry serializes access under its one lock.
    """

    __slots__ = (
        "count", "total", "min", "max", "zeros", "nonfinite", "buckets",
    )

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        # Non-positive observations (e.g. cross-host staleness under
        # clock skew) can't take a log: they get their own bucket below
        # every log bucket, so ordering — and therefore quantiles —
        # stays correct.
        self.zeros = 0
        # NaN/inf observations (a producer with a corrupted clock can
        # put one on the wire as a staleness input) are counted here
        # and otherwise ignored: math.log would raise and kill the
        # observing thread — the ingest loop, for lineage — over one
        # bad telemetry stamp.
        self.nonfinite = 0
        self.buckets: dict = {}

    def observe(self, value) -> None:
        v = float(value)
        if not math.isfinite(v):
            self.nonfinite += 1
            return
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if v <= 0.0:
            self.zeros += 1
            return
        idx = math.floor(math.log(v) / _LOG_GAMMA)
        self.buckets[idx] = self.buckets.get(idx, 0) + 1

    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1] (bucket-midpoint estimate,
        clamped to the exact observed [min, max])."""
        if self.count == 0:
            return 0.0
        if q <= 0.0:
            return self.min
        if q >= 1.0:
            return self.max
        rank = q * (self.count - 1)
        seen = self.zeros
        if rank < seen:
            return min(self.min, 0.0)
        for idx in sorted(self.buckets):
            seen += self.buckets[idx]
            if rank < seen:
                mid = _GAMMA ** (idx + 0.5)
                return min(max(mid, self.min), self.max)
        return self.max

    def summary(self) -> dict:
        if self.count == 0:
            out = {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                   "p50": 0.0, "p95": 0.0, "p99": 0.0}
            if self.nonfinite:
                out["nonfinite"] = self.nonfinite
            return out
        out = {
            "count": self.count,
            "sum": self.total,
            "min": self.min,
            "max": self.max,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }
        if self.nonfinite:
            out["nonfinite"] = self.nonfinite
        return out

    def state_dict(self) -> dict:
        """Snapshot for the session store (blendjax.checkpoint):
        exact counts + bucket map; min/max only when observed (±inf
        sentinels don't belong in a wire document)."""
        d = {
            "count": self.count,
            "sum": self.total,
            "zeros": self.zeros,
            "nonfinite": self.nonfinite,
            "buckets": dict(self.buckets),
        }
        if self.count:
            d["min"] = self.min
            d["max"] = self.max
        return d

    def load_state_dict(self, d: dict) -> None:
        self.count = int(d["count"])
        self.total = float(d["sum"])
        self.zeros = int(d.get("zeros", 0))
        self.nonfinite = int(d.get("nonfinite", 0))
        self.buckets = {int(k): int(v) for k, v in d["buckets"].items()}
        self.min = float(d["min"]) if "min" in d else math.inf
        self.max = float(d["max"]) if "max" in d else -math.inf

    def cumulative_buckets(self) -> list:
        """``(upper_bound, cumulative_count)`` pairs in ascending bound
        order — the Prometheus histogram exposition shape (the exporter
        appends the implicit ``+Inf`` bucket itself)."""
        out = []
        cum = self.zeros
        if self.zeros:
            out.append((0.0, cum))
        for idx in sorted(self.buckets):
            cum += self.buckets[idx]
            out.append((_GAMMA ** (idx + 1), cum))
        return out


# bjx: thread-shared (every thread in the process reports here; one
# `_lock` makes each snapshot/update consistent — BJX117)
class Metrics:
    """Process-local registry. Thread-safe AND snapshot-exact: every
    mutation — counters, gauges, spans, histograms — runs under one
    lock (uncontended CPython lock acquire is ~100 ns — noise next to
    the per-batch work being counted, and the sharded ingest pool's
    ``wire.*``/``ingest.*`` pairs must sum EXACTLY, not approximately,
    for the bench's compression/throughput evidence), and ``report()``
    reads a consistent snapshot under the same lock (a lock-free read
    raced worker mutation: torn gauge snapshots and a possible
    ``RuntimeError: dictionary changed size during iteration``).
    """

    def __init__(self):
        self._lock = threading.Lock()
        # threadguard wiring (blendjax.utils.tg): under
        # BLENDJAX_THREADGUARD=1 any MUTATION of these tables without
        # `_lock` held raises at the access site; disabled, guard() is
        # identity and the registry is exactly as before. The read-only
        # dict surface of the two public tables stays exempt: tests and
        # debug code read counters after quiescing, and the consistent-
        # snapshot path is report(), not the raw dict.
        reads = (
            "get", "keys", "items", "values", "copy",
            "__getitem__", "__iter__", "__len__", "__contains__",
        )
        self.counters: dict = guard(
            defaultdict(int), name="metrics.counters", lock=self._lock,
            exempt=reads,
        )
        self.gauges: dict = guard(
            {}, name="metrics.gauges", lock=self._lock, exempt=reads,
        )
        self._spans: dict = guard(  # count, total_s
            defaultdict(lambda: [0, 0.0]), name="metrics.spans",
            lock=self._lock,
        )
        self._hists: dict = guard(
            defaultdict(Histogram), name="metrics.hists", lock=self._lock
        )
        # Optional per-span event ring for Chrome-trace export
        # (blendjax.obs.exporters.write_chrome_trace): disabled by
        # default — aggregates are always on, events are opt-in.
        self._events: deque | None = None

    def count(self, name: str, n: int = 1) -> None:
        # `dict[k] += n` is load/add/store bytecode — two workers
        # interleaving it lose increments. The lock makes the pair of
        # counters the bench ratios (compressed vs raw) exact.
        with self._lock:
            self.counters[name] += n

    def counter_value(self, name: str) -> int:
        """Locked read of one counter's current value — for writers
        that derive a gauge from counters they also emit (the value
        then stays consistent with the counters in the same snapshot,
        across any ``reset()``)."""
        with self._lock:
            return self.counters.get(name, 0)

    def gauge(self, name: str, value) -> None:
        # Locked like everything else: a bare dict store is GIL-atomic,
        # but report()'s consistent snapshot needs writers excluded.
        with self._lock:
            self.gauges[name] = value

    def gauge_max(self, name: str, value) -> None:
        # High-water-mark gauge: read-max-store is a lost-update race
        # for concurrent writers (the sharded ingest pool), so the pair
        # runs under the counter lock.
        with self._lock:
            if value > self.gauges.get(name, value - 1):
                self.gauges[name] = value

    def observe(self, name: str, value) -> None:
        """Record one sample into the named histogram (lock-exact:
        concurrent observers never lose a count)."""
        with self._lock:
            self._hists[name].observe(value)

    def observe_many(self, name: str, values) -> None:
        """Record a batch of samples into one histogram under a SINGLE
        lock acquisition — for hot loops that produce a vector of
        observations per iteration (e.g. the echo reservoir's per-draw
        sample ages): one lock round trip instead of len(values)."""
        with self._lock:
            h = self._hists[name]
            for v in values:
                h.observe(v)

    @contextlib.contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                s = self._spans[name]
                s[0] += 1
                s[1] += dt
                # Spans FEED the histogram of the same name, under the
                # same lock acquisition: histogram counts sum exactly
                # to span counts, by construction, at any concurrency.
                self._hists[name].observe(dt)
                if self._events is not None:
                    self._events.append(
                        (name, t0, dt, threading.get_ident())
                    )

    # -- span events (Chrome-trace source) -----------------------------------

    def enable_span_events(self, capacity: int = 200_000) -> None:
        """Start recording one ``(name, t0, dur_s, tid)`` event per span
        into a bounded ring (oldest dropped past ``capacity``).
        Timestamps are ``perf_counter`` seconds — the same clock the
        span aggregates use, so the exported trace lines up with spans
        taken anywhere in the process."""
        with self._lock:
            self._events = deque(self._events or (), maxlen=int(capacity))

    def disable_span_events(self) -> None:
        with self._lock:
            self._events = None

    def span_events(self) -> list:
        with self._lock:
            return list(self._events or ())

    # -- snapshots ------------------------------------------------------------

    def _spans_locked(self) -> dict:
        out = {}
        for k, (c, t) in self._spans.items():
            d = {
                "count": c,
                "total_s": t,
                "mean_ms": (t / c * 1e3) if c else 0.0,
            }
            h = self._hists.get(k)
            if h is not None and h.count:
                d["p50_ms"] = h.quantile(0.50) * 1e3
                d["p95_ms"] = h.quantile(0.95) * 1e3
                d["p99_ms"] = h.quantile(0.99) * 1e3
            out[k] = d
        return out

    def spans(self) -> dict:
        with self._lock:
            return self._spans_locked()

    def histograms(self) -> dict:
        with self._lock:
            return {k: h.summary() for k, h in self._hists.items()}

    def histogram_buckets(self) -> dict:
        """``name -> (cumulative_buckets, count, sum)`` snapshot — the
        raw-bucket view the Prometheus exporter renders."""
        with self._lock:
            return {
                k: (h.cumulative_buckets(), h.count, h.total)
                for k, h in self._hists.items()
            }

    def report(self, include_buckets: bool = False) -> dict:
        # One lock acquisition for the WHOLE snapshot: counters, gauges,
        # spans, and histograms are mutually consistent (no worker can
        # bump a counter between the copies). ``include_buckets`` adds
        # the raw cumulative-bucket view under the SAME lock, so an
        # exporter can render native histograms from the same snapshot
        # as the counters beside them (a separate histogram_buckets()
        # call races spans recorded in between).
        with self._lock:
            out = {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "spans": self._spans_locked(),
                "histograms": {
                    k: h.summary() for k, h in self._hists.items()
                },
            }
            if include_buckets:
                out["histogram_buckets"] = {
                    k: (h.cumulative_buckets(), h.count, h.total)
                    for k, h in self._hists.items()
                }
            return out

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self._spans.clear()
            self._hists.clear()
            if self._events is not None:
                self._events.clear()


# Default process-wide registry (imports stay cheap; no jax dependency).
metrics = Metrics()


# jax.profiler supports exactly ONE active trace per process;
# start_trace raises on a second. The SLO watchdog's flight recorder
# may fire a capture at any moment — possibly inside a user's own open
# trace — so activation is tracked under a module lock and a nested
# trace degrades to a logged no-op instead of killing the run.
_trace_lock = threading.Lock()
_trace_active = False


@contextlib.contextmanager
def trace(logdir: str):
    """JAX profiler trace around a code block; view in TensorBoard/XProf.

    Reentrancy-safe: if a trace is already active in this process (the
    profiler allows only one), the nested call logs a warning and runs
    the block untraced instead of raising out of
    ``jax.profiler.start_trace`` — so a watchdog-triggered capture can
    never take down a run that was already being profiled.

    >>> with trace("/tmp/profile"):
    ...     for batch in pipeline: step(state, batch)
    """
    global _trace_active
    import jax

    with _trace_lock:
        already = _trace_active
        if not already:
            _trace_active = True
    if already:
        from blendjax.utils.logging import get_logger

        get_logger("metrics").warning(
            "jax profiler trace already active: nested trace(%r) "
            "degrades to a no-op", logdir,
        )
        yield
        return
    try:
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
    finally:
        with _trace_lock:
            _trace_active = False
