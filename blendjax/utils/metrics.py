"""Lightweight metrics: counters, gauges, and timing spans.

The reference has no metrics system (SURVEY.md §5 — only wall-clock in its
benchmark harness); blendjax instruments the ingest pipeline so feed
stalls are diagnosable: per-stage spans, queue-depth gauges, and a
one-line report. For deep dives, ``trace`` wraps ``jax.profiler.trace``
so the same code path emits a TensorBoard-loadable profile.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict


class Metrics:
    """Process-local registry. Thread-safe: counters increment under a
    lock (uncontended CPython lock acquire is ~100 ns — noise next to
    the per-batch work they count, and the sharded ingest pool's
    ``wire.*``/``ingest.*`` pairs must sum EXACTLY, not approximately,
    for the bench's compression/throughput evidence); report() reads a
    consistent snapshot of spans but only an approximate one of gauges.
    """

    def __init__(self):
        self.counters: dict = defaultdict(int)
        self.gauges: dict = {}
        self._spans: dict = defaultdict(lambda: [0, 0.0])  # count, total_s
        self._lock = threading.Lock()

    def count(self, name: str, n: int = 1) -> None:
        # `dict[k] += n` is load/add/store bytecode — two workers
        # interleaving it lose increments. The lock makes the pair of
        # counters the bench ratios (compressed vs raw) exact.
        with self._lock:
            self.counters[name] += n

    def gauge(self, name: str, value) -> None:
        self.gauges[name] = value

    def gauge_max(self, name: str, value) -> None:
        # High-water-mark gauge: read-max-store is a lost-update race
        # for concurrent writers (the sharded ingest pool), so the pair
        # runs under the counter lock.
        with self._lock:
            if value > self.gauges.get(name, value - 1):
                self.gauges[name] = value

    @contextlib.contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                s = self._spans[name]
                s[0] += 1
                s[1] += dt

    def spans(self) -> dict:
        with self._lock:
            return {
                k: {"count": c, "total_s": t, "mean_ms": (t / c * 1e3) if c else 0.0}
                for k, (c, t) in self._spans.items()
            }

    def report(self) -> dict:
        return {
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "spans": self.spans(),
        }

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self._spans.clear()


# Default process-wide registry (imports stay cheap; no jax dependency).
metrics = Metrics()


@contextlib.contextmanager
def trace(logdir: str):
    """JAX profiler trace around a code block; view in TensorBoard/XProf.

    >>> with trace("/tmp/profile"):
    ...     for batch in pipeline: step(state, batch)
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
