"""Zero-overhead indirection to the threadguard sanitizer.

Production modules declare their concurrency contracts by wrapping the
objects the contracts are about::

    from blendjax.utils.tg import guard
    ...
    self._counters = guard({}, name="metrics.counters", lock=self._lock)

With ``BLENDJAX_THREADGUARD`` unset (the default, and every hot path's
contract) ``guard`` is the identity function: no proxy, no per-access
cost, and :mod:`blendjax.testing.threadguard` is never even imported.
With ``BLENDJAX_THREADGUARD=1`` (the threadguard CI job, soak runs)
the real sanitizer wraps the object and raises
:class:`~blendjax.testing.threadguard.ThreadGuardError` on any
affinity or lock-discipline violation.

The switch is read ONCE at import (process start): the sanitizer
changes what attribute access *means* on wired objects, which is not
something to toggle mid-run. Tests that need the real ``guard``
regardless of the environment import it from
``blendjax.testing.threadguard`` directly.
"""

from __future__ import annotations

import os

if os.environ.get("BLENDJAX_THREADGUARD", "0") not in ("", "0", "false"):
    from blendjax.testing.threadguard import guard
else:

    def guard(obj, **kwargs):  # noqa: ARG001 - mirror the real signature
        """Disabled sanitizer: identity (see module docstring)."""
        return obj


__all__ = ["guard"]
