"""Blender scene script: physics cartpole served over the GYM RPC.

blendjax port of the reference's ``examples/control/cartpole_gym/envs/
cartpole.blend.py:7-61``: a cart driven by a rigid-body motor constraint
with a hinged pole on top; actions are motor forces, observations are
(cart x, pole x, pole angle), done when the pole tips or the cart runs
off. The reference relies on a prepared ``cartpole.blend``; this script
BUILDS the rig programmatically (ground, cart on a slider+motor
constraint, pole on a hinge) so no binary asset ships.

Launch via ``blendjax.env.launch_env`` or the Gymnasium adapter
(``blendjax.env.gymnasium_adapter``); pair with
``examples/control/cartpole.py``.
"""

import sys

import bpy
import numpy as np

from blendjax.producer import BaseEnv, RemoteControlledAgent, parse_launch_args
from blendjax.producer.bpy_engine import BpyEngine


def _rigid(obj, kind="ACTIVE", mass=1.0):
    bpy.context.view_layer.objects.active = obj
    bpy.ops.rigidbody.object_add(type=kind)
    if kind == "ACTIVE":
        obj.rigid_body.mass = mass
    return obj


def _empty(name, location):
    e = bpy.data.objects.new(name, None)
    e.location = location
    bpy.context.collection.objects.link(e)
    return e


def build_rig():
    """Ground + cart (slider/motor constraint) + pole (hinge)."""
    bpy.ops.rigidbody.world_add()
    bpy.context.scene.rigidbody_world.enabled = True

    bpy.ops.mesh.primitive_plane_add(size=40)
    _rigid(bpy.context.active_object, "PASSIVE")

    bpy.ops.mesh.primitive_cube_add(size=1.0, location=(0, 0, 1.2))
    cart = bpy.context.active_object
    cart.name = "Cart"
    cart.scale = (0.8, 0.5, 0.2)
    _rigid(cart, mass=1.0)

    bpy.ops.mesh.primitive_cube_add(size=1.0, location=(0, 0, 2.2))
    pole = bpy.context.active_object
    pole.name = "Pole"
    pole.scale = (0.05, 0.05, 0.8)
    _rigid(pole, mass=0.1)

    # Slider+motor: constrains the cart to the x axis and drives it.
    motor = _empty("Motor", (0, 0, 1.2))
    bpy.context.view_layer.objects.active = motor
    bpy.ops.rigidbody.constraint_add(type="SLIDER")
    rc = motor.rigid_body_constraint
    rc.object1 = None  # world
    rc.object2 = cart
    rc.use_motor_lin = True
    rc.motor_lin_max_impulse = 50.0

    # Hinge: pole pivots about y at the cart's top.
    hinge = _empty("Hinge", (0, 0, 1.4))
    bpy.context.view_layer.objects.active = hinge
    bpy.ops.rigidbody.constraint_add(type="HINGE")
    hc = hinge.rigid_body_constraint
    hc.object1 = cart
    hc.object2 = pole
    return cart, pole, motor


class CartpoleEnv(BaseEnv):
    def __init__(self, agent):
        super().__init__(agent)
        self.cart, self.pole, motor = build_rig()
        self.motor = motor.rigid_body_constraint
        self.fps = bpy.context.scene.render.fps
        self.total_mass = (
            self.cart.rigid_body.mass + self.pole.rigid_body.mass
        )
        self.rng = np.random.default_rng()

    def _env_reset(self):
        self.motor.motor_lin_target_velocity = 0.0
        self.cart.location = (0.0, 0, 1.2)
        self.pole.rotation_euler[1] = self.rng.uniform(-0.6, 0.6)

    def _env_prepare_step(self, action):
        # v_(t+1) = v(t) + (f/m)*dt (constant acceleration between steps)
        self.motor.motor_lin_target_velocity += (
            float(action) / self.total_mass / self.fps
        )

    def _env_post_step(self):
        c = float(self.cart.matrix_world.translation[0])
        p = float(self.pole.matrix_world.translation[0])
        a = float(self.pole.matrix_world.to_euler("XYZ")[1])
        return dict(
            obs=(c, p, a),
            reward=0.0,
            done=bool(abs(a) > 0.6 or abs(c) > 4.0),
        )


def main():
    args, remainder = parse_launch_args(sys.argv)
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--render-every", default=None, type=int)
    ap.add_argument("--real-time", dest="realtime", action="store_true")
    ap.add_argument("--no-real-time", dest="realtime", action="store_false")
    ap.set_defaults(realtime=False)
    opts = ap.parse_args(remainder)

    agent = RemoteControlledAgent(
        args.btsockets["GYM"], real_time=opts.realtime
    )
    env = CartpoleEnv(agent)
    if not bpy.app.background and opts.render_every:
        env.attach_default_renderer(every_nth=opts.render_every)
    try:
        env.run(BpyEngine(), frame_range=(1, 10000))
    finally:
        agent.close()


main()
