"""Balance the remote cartpole with a hand-tuned controller.

Mirrors the reference example (``examples/control/cartpole.py:19-39``: a
proportional controller on pole angle driving the motor velocity through
the gym API), against the headless producer here.

Run: ``python examples/control/cartpole.py``
"""

from __future__ import annotations

import os

import numpy as np

from blendjax.env import launch_env

SCRIPT = os.path.join(os.path.dirname(__file__), "cartpole_producer.py")


def control(obs) -> float:
    """P(D)-controller: push the cart under the falling pole
    (reference ``cartpole.py:19-21``)."""
    x, x_dot, theta, theta_dot = np.asarray(obs, np.float32)
    return float(8.0 * theta + 1.0 * theta_dot + 0.2 * x)


def main() -> None:
    with launch_env(script=SCRIPT, seed=3) as env:
        obs, _ = env.reset()
        total, steps = 0.0, 0
        for _ in range(300):
            obs, reward, done, info = env.step(control(obs))
            total += reward
            steps += 1
            if done:
                print(f"episode end after {steps} steps, return {total}")
                obs, _ = env.reset()
                total, steps = 0.0, 0
        print(f"final: {steps} steps balanced, return {total}")


if __name__ == "__main__":
    main()
