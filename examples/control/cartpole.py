"""Balance the remote cartpole with a hand-tuned controller.

Mirrors the reference example (``examples/control/cartpole.py:19-39``: a
proportional controller on pole angle driving the motor velocity through
``gym.make('blendtorch-cartpole-v0')``), against the registered headless
env here — ``import blendjax.env`` registers ``blendjax/Cartpole-v0``
(and the legacy reference-shaped alias) with Gymnasium.

Run: ``python examples/control/cartpole.py``
"""

from __future__ import annotations

import gymnasium
import numpy as np

import blendjax.env  # noqa: F401  (registers blendjax/Cartpole-v0)


def control(obs) -> float:
    """P(D)-controller: push the cart under the falling pole
    (reference ``cartpole.py:19-21``)."""
    x, x_dot, theta, theta_dot = np.asarray(obs, np.float32)
    return float(8.0 * theta + 1.0 * theta_dot + 0.2 * x)


def main(steps_total: int = 300) -> None:
    env = gymnasium.make("blendjax/Cartpole-v0", seed=3, proto="ipc")
    try:
        obs, _ = env.reset()
        total, steps = 0.0, 0
        for _ in range(steps_total):
            obs, reward, terminated, truncated, info = env.step(
                np.array([control(obs)], np.float32)
            )
            total += reward
            steps += 1
            if terminated or truncated:
                print(f"episode end after {steps} steps, return {total}")
                obs, _ = env.reset()
                total, steps = 0.0, 0
        print(f"final: {steps} steps balanced, return {total}")
    finally:
        env.close()


if __name__ == "__main__":
    main()
