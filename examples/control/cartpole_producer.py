"""Producer script: a remote-controlled cartpole environment.

Kept as a stable examples-path entry point; the implementation moved
into the package (:mod:`blendjax.producer.scripts.cartpole`) so the
Gymnasium registry can launch it from any install. See that module for
the physics/RPC wiring and flags. (Deliberately imports nothing from
:mod:`blendjax.env`: producer processes must not pay the env package's
gymnasium import.)
"""

from blendjax.producer.scripts.cartpole import CartpoleEnv, main  # noqa: F401

if __name__ == "__main__":
    main()
