"""DQN on cartpole through the full blendjax.rl stack (docs/rl.md).

Where ``train_reinforce.py`` collects synchronous rollouts by hand,
this example runs the production actor-learner shape: background
actors drive a fleet of remote cartpole producers against a host-side
policy snapshot, transitions land in the device-resident
``TrajectoryReservoir`` (prioritized by default), and the learner
trains at full step rate with ONE fused device dispatch per step —
gather + TD loss + donated update + in-jit priority write-back.
``--checkpoint DIR`` arms the session store so a killed run resumes
mid-curve (``--resume``).

Run: ``python examples/control/train_dqn.py --steps 400``
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--envs", type=int, default=2)
    ap.add_argument("--steps", type=int, default=400,
                    help="learner steps")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--capacity", type=int, default=512)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--gamma", type=float, default=0.98)
    ap.add_argument("--uniform", action="store_true",
                    help="uniform instead of prioritized replay")
    ap.add_argument("--sync-every", type=int, default=8,
                    help="learner steps between actor policy syncs")
    ap.add_argument("--checkpoint", default=None,
                    help="session-store directory (docs/rl.md)")
    ap.add_argument("--ckpt-every", type=int, default=100)
    ap.add_argument("--resume", action="store_true")
    args = ap.parse_args()

    if args.resume and not args.checkpoint:
        ap.error("--resume requires --checkpoint DIR")

    from blendjax.env import BatchedRemoteEnv
    from blendjax.models import QNetwork
    from blendjax.rl import (
        ActorPool,
        HostQPolicy,
        RLTrainDriver,
        TrajectoryReservoir,
        make_dqn_step,
        make_rl_train_state,
    )

    script = os.path.join(os.path.dirname(__file__),
                          "cartpole_producer.py")
    reservoir = TrajectoryReservoir(
        args.capacity, rng=0, prioritized=not args.uniform,
    )
    model = QNetwork(hidden=(32, 32), n_actions=3)
    state = make_rl_train_state(
        model, np.zeros((1, 4), np.float32), learning_rate=args.lr,
    )
    step = make_dqn_step(reservoir, model.apply, gamma=args.gamma)
    mgr = None
    if args.checkpoint:
        from blendjax.checkpoint import SnapshotManager

        mgr = SnapshotManager(args.checkpoint)
    try:
        with BatchedRemoteEnv(script=script, num_envs=args.envs,
                              seed=0) as venv:
            pool = ActorPool(
                venv, reservoir,
                HostQPolicy(3, eps_steps=1500, seed=0),
                # discrete action index -> motor velocity
                action_map=np.array([-2.0, 0.0, 2.0], np.float32),
            )
            driver = RLTrainDriver(
                step, state, reservoir, actors=pool,
                batch_size=args.batch, min_fill=2 * args.batch,
                sync_every=args.sync_every, inflight=2,
                checkpoint=mgr,
                checkpoint_every=args.ckpt_every if mgr else 0,
            )
            if args.resume:
                restored = mgr.restore(state)
                if restored is None:
                    # a fresh/empty checkpoint dir has nothing to
                    # resume — start from scratch instead of crashing
                    print(f"no committed snapshot in "
                          f"{args.checkpoint!r} — starting fresh")
                else:
                    driver.state = restored.state
                    names = driver.restore_session(restored.session)
                    print(f"resumed at step {driver.steps} "
                          f"(restored: {', '.join(names)})")
            with pool:
                report_every = max(args.steps // 8, 1)
                while driver.steps < args.steps:
                    driver.train_step()
                    if driver.steps % report_every == 0:
                        driver.drain()
                        s = driver.stats
                        print(
                            f"step {s['steps']}: "
                            f"loss={driver.losses[-1]:.4f} "
                            f"mean_return={s['actor']['mean_return']} "
                            f"episodes={s['actor']['episodes']} "
                            f"replay_ratio="
                            f"{s['reservoir']['replay_ratio']}"
                        )
                loss = driver.drain()
            print(
                f"final: loss={loss:.4f} "
                f"mean_return={pool.stats['mean_return']} "
                f"env_steps={pool.env_steps} "
                f"transitions={reservoir.inserts}"
            )
    finally:
        if mgr is not None:
            mgr.close()


if __name__ == "__main__":
    main()
