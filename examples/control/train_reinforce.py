"""REINFORCE on TPU against a fleet of remote cartpole producers.

The learned-control counterpart the reference leaves as an exercise
(its agent is hand-tuned, ``examples/control/cartpole.py``): batched envs
collect rollouts over the RPC plane while policy/value updates run as a
jitted step on the accelerator.

Run: ``python examples/control/train_reinforce.py --iters 20``
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--envs", type=int, default=2)
    ap.add_argument("--horizon", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--gamma", type=float, default=0.98)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from blendjax.env import BatchedRemoteEnv
    from blendjax.models import PolicyValueNet

    script = os.path.join(os.path.dirname(__file__), "cartpole_producer.py")
    model = PolicyValueNet(action_dim=1)
    params = model.init(jax.random.key(0), jnp.zeros((1, 4)))["params"]
    tx = optax.adam(args.lr)
    opt_state = tx.init(params)

    def log_prob(mean, log_std, a):
        var = jnp.exp(2 * log_std)
        return -0.5 * (
            ((a - mean) ** 2) / var + 2 * log_std + jnp.log(2 * jnp.pi)
        ).sum(-1)

    @jax.jit
    def update(params, opt_state, obs, actions, returns):
        def loss_fn(p):
            mean, log_std, value = model.apply({"params": p}, obs)
            adv = returns - value
            pg = -(log_prob(mean, log_std, actions) * jax.lax.stop_gradient(adv)).mean()
            vloss = (adv**2).mean()
            return pg + 0.5 * vloss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def act(params, key, obs):
        mean, log_std, _ = model.apply({"params": params}, obs)
        return mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)

    key = jax.random.key(1)
    with BatchedRemoteEnv(script=script, num_envs=args.envs) as venv:
        obs, _ = venv.reset()
        for it in range(args.iters):
            O, A, R, D = [], [], [], []
            for _ in range(args.horizon):
                key, sub = jax.random.split(key)
                a = np.asarray(act(params, sub, jnp.asarray(obs)))
                nobs, reward, done, _ = venv.step(a[:, 0])
                O.append(obs); A.append(a); R.append(reward); D.append(done)
                obs = nobs
            # discounted returns (zeroed across episode boundaries)
            ret = np.zeros(args.envs, np.float32)
            returns = np.zeros((args.horizon, args.envs), np.float32)
            for t in reversed(range(args.horizon)):
                ret = R[t] + args.gamma * ret * (~D[t])
                returns[t] = ret
            params, opt_state, loss = update(
                params,
                opt_state,
                jnp.asarray(np.concatenate(O)),
                jnp.asarray(np.concatenate(A)),
                jnp.asarray(returns.reshape(-1)),
            )
            print(
                f"iter {it}: mean_reward={np.mean(R):.3f} "
                f"loss={float(loss):.4f}"
            )


if __name__ == "__main__":
    main()
