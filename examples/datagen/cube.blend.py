"""Blender scene script: rotating-cube datagen (real Blender).

blendjax port of the reference's ``examples/datagen/cube.blend.py:6-39``:
randomize the cube in ``pre_frame``, publish image + projected-corner
annotations in ``post_frame``. Runs against the stock startup scene (the
default Cube/Camera/Light) — no .blend asset required.

Launch from the consumer side:

    from blendjax.launcher import BlenderLauncher
    BlenderLauncher(script="examples/datagen/cube.blend.py",
                    num_instances=2, named_sockets=["DATA"])

Offscreen (Eevee) rendering needs the Blender UI (reference
``offscreen.py:16-19``); under ``--background`` this script streams
annotations + frame ids only, which still exercises the full transport/
ingest path. The headless counterpart with images everywhere is
``examples/datagen/cube_producer.py`` (the sim engine).
"""

import sys

import bpy
import numpy as np

from blendjax.producer import AnimationController, DataPublisher, parse_launch_args
from blendjax.producer.bpy_engine import (
    BpyAnimationDriver,
    BpyEngine,
    camera_from_bpy,
    world_coordinates,
)
from blendjax.producer.camera import Camera


def main():
    args, _ = parse_launch_args(sys.argv)
    rng = np.random.default_rng(args.btseed)
    cube = bpy.data.objects["Cube"]

    pub = DataPublisher(args.btsockets["DATA"], btid=args.btid)
    ctrl = AnimationController(BpyEngine())

    off = None
    if not bpy.app.background:
        from blendjax.producer.offscreen import OffScreenRenderer

        off = OffScreenRenderer(mode="rgb")
        off.set_render_style(shading="RENDERED", overlays=False)

    def pre_frame(_frame):
        cube.rotation_euler = rng.uniform(0, np.pi, size=3)

    def post_frame(frame):
        cam = camera_from_bpy(Camera)  # re-read pose each frame
        payload = dict(
            xy=cam.world_to_pixel(world_coordinates(cube)).astype(
                np.float32
            ),
            frameid=frame,
        )
        if off is not None:
            payload["image"] = off.render()
        pub.publish(**payload)

    ctrl.pre_frame.add(pre_frame)
    ctrl.post_frame.add(post_frame)
    if bpy.app.background:
        ctrl.play(frame_range=(0, 100), num_episodes=-1)
    else:
        BpyAnimationDriver(ctrl).play(frame_range=(0, 100))


main()
