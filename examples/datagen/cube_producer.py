"""Producer script: streams rotating-cube images + corner annotations.

The headless counterpart of the reference's ``examples/datagen/
cube.blend.py:6-39`` (randomize in pre_frame, publish in post_frame) and
the producer used by ``bench.py``. Launch it with
:class:`blendjax.launcher.PythonProducerLauncher`; it reads the handshake
(btid/seed/sockets) exactly like a Blender scene script would.

Usage flags (passed via ``instance_args``):
  --shape H W      image size (default 480 640)
  --frames N       stop after N frames (default: run forever)
  --batch B        publish one (B, H, W, 4) message per B frames instead of
                   B per-frame messages (renders straight into the batch
                   buffer; the consumer's ingest passes full batches
                   through without re-assembly)
  --encoding E     'raw' (default) ships full frames; 'tile' ships only
                   the 32x32 tiles that changed vs the scene background
                   (lossless; decoded on-device by the consumer — see
                   blendjax.ops.tiles). Requires --batch > 1.
  --tile T [TW]    tile dims for --encoding tile (default 16 32); two
                   values give rectangular (rows, cols) tiles — (16, 32)
                   at C=4 unlocks the consumer's direct-spatial decode
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from blendjax.transport import term_context
from blendjax.producer import AnimationController, DataPublisher, parse_launch_args
from blendjax.producer.sim import CubeScene, SimEngine


def main() -> None:
    args, remainder = parse_launch_args(sys.argv)
    parser = argparse.ArgumentParser()
    parser.add_argument("--shape", nargs=2, type=int, default=[480, 640])
    parser.add_argument("--frames", type=int, default=-1)
    parser.add_argument("--batch", type=int, default=1)
    parser.add_argument(
        "--encoding", choices=["raw", "tile", "pal"], default="raw"
    )
    # one value = square tiles; two = (rows, cols). Default (16, 32):
    # finer granularity than 32x32 (fewer wasted pixels per changed
    # tile) and, at C=4, rows span 128 lanes — the consumer's
    # direct-spatial Pallas decode engages (docs/performance.md).
    parser.add_argument("--tile", nargs="+", type=int, default=[16, 32])
    parser.add_argument(
        "--tile-rgba", action="store_true",
        help="ship full RGBA tiles (Pallas-decodable) even when alpha is "
        "static, instead of slicing to RGB",
    )
    parser.add_argument(
        "--ref-interval", type=int, default=64,
        help="re-send the tile reference every N batches (keyframes; lets "
        "multiple consumers/workers join a stream). 0 = send once.",
    )
    parser.add_argument(
        "--tile-capacity", type=int, default=0,
        help="pin the per-frame changed-tile capacity (stable shapes "
        "across a producer fleet => one consumer decode compilation and "
        "unbroken chunk groups). 0 = per-stream high-water mark.",
    )
    parser.add_argument(
        "--trace-every", type=int, default=64,
        help="stamp every Nth published message with a sampled "
        "distributed-trace context (blendjax.obs.trace; "
        "docs/observability.md 'Tracing a frame'). 0 disables.",
    )
    opts = parser.parse_args(remainder)

    scene = CubeScene(shape=tuple(opts.shape), seed=args.btseed)
    ctrl = AnimationController(SimEngine(scene))
    flush = None

    if opts.encoding == "tile":
        # Sparse streaming: per frame, render into a reused framebuffer,
        # scan for tiles that differ from the background, and ship only
        # those (plus the one-time reference). Wire bytes scale with scene
        # activity instead of resolution; the consumer reconstructs exact
        # frames on device (blendjax.ops.tiles <-> data.TileStreamDecoder).
        from blendjax.producer import TileBatchPublisher

        if opts.batch < 2:
            parser.error("--encoding tile requires --batch > 1")
        h, w = opts.shape
        pub = DataPublisher(
            args.btsockets["DATA"], btid=args.btid, lingerms=10000,
            send_hwm=2, trace_every=opts.trace_every,
        )
        if len(opts.tile) > 2:
            parser.error("--tile takes one side or two (rows cols) values")
        tile = opts.tile[0] if len(opts.tile) == 1 else tuple(opts.tile)
        tiles = TileBatchPublisher(
            pub, scene.background_image(), opts.batch, tile=tile,
            alpha_slice=not opts.tile_rgba, ref_interval=opts.ref_interval,
            capacity=opts.tile_capacity or None,
        )
        framebuf = np.empty((h, w, 4), np.uint8)
        flush = tiles.flush  # ship trailing frames of a partial batch

        def publish(frame: int) -> None:
            scene.render(out=framebuf)
            tiles.add(
                framebuf,
                # Everything outside the rect the rasterizer just drew is
                # untouched background == the reference: bound the scan.
                hint=scene.raster.last_drawn,
                xy=scene.camera.world_to_pixel(scene.corners_world()).astype(
                    np.float32
                ),
                frameid=np.int64(frame),
            )
            if 0 < opts.frames <= frame:
                ctrl.cancel()

    elif opts.encoding == "pal":
        # Non-sparse lossless codec: palette-compress FULL frames (no
        # reference, no temporal assumption — only "synthetic frames
        # carry few colors"). Per-frame palettes: 16x/8x/4x fewer bytes
        # (2/4/8-bit indices by the widest frame) across the socket AND
        # the host->device link; the consumer decodes with one fused
        # gather on device (blendjax.ops.tiles.palettize_frames).
        # Falls back to a raw batch whenever ANY frame exceeds 256
        # colors.
        from blendjax.ops.tiles import (
            FRAMEPAL_SUFFIXES,
            FRAMESHAPE_SUFFIX,
            PALETTE_SUFFIX,
            palettize_frames,
        )

        if opts.batch < 2:
            parser.error("--encoding pal requires --batch > 1")
        pub = DataPublisher(
            args.btsockets["DATA"], btid=args.btid, lingerms=10000,
            send_hwm=2, trace_every=opts.trace_every,
        )
        b, (h, w) = opts.batch, opts.shape
        buf = {
            "image": np.empty((b, h, w, 4), np.uint8),
            "xy": np.empty((b, 8, 2), np.float32),
            "frameid": np.empty((b,), np.int64),
        }
        cursor = {"i": 0}

        def _ship(filled: dict) -> None:
            # publish() hands ndarrays to the zmq IO thread by REFERENCE
            # (DataPublisher zero-copy contract): anything reused across
            # batches must be copied here, or the next frame's render
            # rewrites bytes of a still-queued message (silent label
            # corruption). packed/pal are fresh allocations per batch;
            # xy/frameid (and the whole buf on palette overflow) are the
            # reused render targets.
            out = palettize_frames(filled["image"])
            if out is None:  # scene outgrew the palette: stay lossless
                pub.publish(
                    _batched=True, **{k: v.copy() for k, v in filled.items()}
                )
                return
            packed, pal, bits = out
            suffix = FRAMEPAL_SUFFIXES[bits]
            pub.publish(
                _prebatched=True,
                **{
                    "image" + suffix: packed,
                    "xy": filled["xy"].copy(),
                    "frameid": filled["frameid"].copy(),
                    "image" + PALETTE_SUFFIX: pal,
                    "image" + FRAMESHAPE_SUFFIX: np.array(
                        [h, w, 4, bits], np.int32
                    ),
                },
            )

        def publish(frame: int) -> None:
            scene.observation_into(frame, buf, cursor["i"])
            cursor["i"] += 1
            if cursor["i"] == b:
                _ship(buf)
                cursor["i"] = 0
            if 0 < opts.frames <= frame:
                ctrl.cancel()

        def flush() -> None:
            i = cursor["i"]
            if i > 0:
                _ship({k: v[:i] for k, v in buf.items()})

    elif opts.batch > 1:
        # Zero-copy batch pool: publish_tracked hands buffers to the socket
        # by reference and returns a zmq MessageTracker; a slot is rendered
        # into again only after its tracker reports the IO thread is done
        # with it. This bounds buffer reuse for any number of connected
        # consumers (per-pipe SNDHWM alone would not: PUSH queues per pipe).
        # A small HWM still provides backpressure (batch messages are
        # ~10MB; 2 batches of queue ≈ the reference's 10-item HWM at
        # batch 8); pool size HWM+2 = queued + in flight + being rendered.
        send_hwm = 2
        pub = DataPublisher(
            args.btsockets["DATA"], btid=args.btid, lingerms=10000,
            send_hwm=send_hwm, trace_every=opts.trace_every,
        )
        b, (h, w) = opts.batch, opts.shape
        pool = [
            {
                "image": np.empty((b, h, w, 4), np.uint8),
                "xy": np.empty((b, 8, 2), np.float32),
                "frameid": np.empty((b,), np.int64),
            }
            for _ in range(send_hwm + 2)
        ]
        trackers = [None] * len(pool)
        cursor = {"slot": 0, "i": 0}

        def publish(frame: int) -> None:
            slot = cursor["slot"]
            if cursor["i"] == 0 and trackers[slot] is not None:
                trackers[slot].wait()  # backpressure: slot still in flight
                trackers[slot] = None
            buf = pool[slot]
            scene.observation_into(frame, buf, cursor["i"])
            cursor["i"] += 1
            if cursor["i"] == b:
                trackers[slot] = pub.publish_tracked(_batched=True, **buf)
                cursor["i"] = 0
                cursor["slot"] = (slot + 1) % len(pool)
            if 0 < opts.frames <= frame:
                ctrl.cancel()

        def flush() -> None:
            # Tail frames of a partial batch (--frames not a multiple of
            # --batch): ship the filled prefix; the consumer's ingest
            # re-batches mismatched sizes.
            i = cursor["i"]
            if i > 0:
                buf = pool[cursor["slot"]]
                pub.publish(_batched=True, **{k: v[:i] for k, v in buf.items()})

    else:
        pub = DataPublisher(
            args.btsockets["DATA"], btid=args.btid, lingerms=10000,
            trace_every=opts.trace_every,
        )

        def publish(frame: int) -> None:
            pub.publish(**scene.observation(frame))
            if 0 < opts.frames <= frame:
                ctrl.cancel()

    ctrl.post_frame.add(publish)
    end = opts.frames if opts.frames > 0 else 2_147_483_647
    try:
        ctrl.play(frame_range=(1, end), num_episodes=-1)
        if flush is not None:
            flush()
    finally:
        pub.close()
        term_context()  # block until the tail is flushed (bounded by linger)


if __name__ == "__main__":
    main()
