"""Producer script: streams rotating-cube images + corner annotations.

The headless counterpart of the reference's ``examples/datagen/
cube.blend.py:6-39`` (randomize in pre_frame, publish in post_frame) and
the producer used by ``bench.py``. Launch it with
:class:`blendjax.launcher.PythonProducerLauncher`; it reads the handshake
(btid/seed/sockets) exactly like a Blender scene script would.

Usage flags (passed via ``instance_args``):
  --shape H W      image size (default 480 640)
  --frames N       stop after N frames (default: run forever)
"""

from __future__ import annotations

import argparse
import sys

from blendjax.producer import AnimationController, DataPublisher, parse_launch_args
from blendjax.producer.sim import CubeScene, SimEngine


def main() -> None:
    args, remainder = parse_launch_args(sys.argv)
    parser = argparse.ArgumentParser()
    parser.add_argument("--shape", nargs=2, type=int, default=[480, 640])
    parser.add_argument("--frames", type=int, default=-1)
    opts = parser.parse_args(remainder)

    pub = DataPublisher(args.btsockets["DATA"], btid=args.btid, lingerms=2000)
    scene = CubeScene(shape=tuple(opts.shape), seed=args.btseed)
    ctrl = AnimationController(SimEngine(scene))

    def publish(frame: int) -> None:
        pub.publish(**scene.observation(frame))
        if 0 < opts.frames <= frame:
            ctrl.cancel()

    ctrl.post_frame.add(publish)
    end = opts.frames if opts.frames > 0 else 2_147_483_647
    try:
        ctrl.play(frame_range=(1, end), num_episodes=-1)
    finally:
        pub.close()


if __name__ == "__main__":
    main()
