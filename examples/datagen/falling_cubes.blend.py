"""Blender scene script: falling rigid-body cubes (real Blender).

blendjax port of the reference's ``examples/datagen/falling_cubes.blend.
py`` (random drop poses per episode, publish image + per-cube pixel
positions per frame). The reference relies on a prepared ``falling_cubes.
blend`` scene with a ``Cubes`` collection; this script BUILDS that scene
(N rigid-body cubes + a passive ground plane) so no binary asset ships.

Under ``--background`` annotations stream without images (offscreen
rendering needs the UI, reference ``offscreen.py:16-19``).
"""

import sys

import bpy
import numpy as np

from blendjax.producer import AnimationController, DataPublisher, parse_launch_args
from blendjax.producer.bpy_engine import (
    BpyAnimationDriver,
    BpyEngine,
    camera_from_bpy,
    world_coordinates,
)
from blendjax.producer.camera import Camera

NUM_CUBES = 8


def build_scene(rng):
    bpy.ops.rigidbody.world_add()
    bpy.ops.mesh.primitive_plane_add(size=40)
    bpy.ops.rigidbody.object_add(type="PASSIVE")
    cubes = []
    for i in range(NUM_CUBES):
        bpy.ops.mesh.primitive_cube_add(size=1.0)
        c = bpy.context.active_object
        c.name = f"Cube{i:02d}"
        bpy.ops.rigidbody.object_add(type="ACTIVE")
        mat = bpy.data.materials.new(name=f"random{i}")
        mat.diffuse_color = (*rng.random(3), 1.0)
        c.data.materials.append(mat)
        c.active_material = mat
        cubes.append(c)
    return cubes


def main():
    args, _ = parse_launch_args(sys.argv)
    rng = np.random.default_rng(args.btseed)
    cubes = build_scene(rng)

    pub = DataPublisher(args.btsockets["DATA"], btid=args.btid)
    ctrl = AnimationController(BpyEngine())

    off = None
    if not bpy.app.background:
        from blendjax.producer.offscreen import OffScreenRenderer

        off = OffScreenRenderer(mode="rgb")
        off.set_render_style(shading="RENDERED", overlays=False)

    def pre_animation():
        # New drop poses each episode (reference pre_anim).
        xyz = rng.uniform((-3, -3, 6), (3, 3, 12.0), size=(len(cubes), 3))
        rot = rng.uniform(-np.pi, np.pi, size=(len(cubes), 3))
        for c, p, r in zip(cubes, xyz, rot):
            c.location = p
            c.rotation_euler = r

    def post_frame(frame):
        cam = camera_from_bpy(Camera)
        payload = dict(
            xy=cam.world_to_pixel(world_coordinates(*cubes)).astype(
                np.float32
            ),
            frameid=frame,
        )
        if off is not None:
            payload["image"] = off.render()
        pub.publish(**payload)

    ctrl.pre_animation.add(pre_animation)
    ctrl.post_frame.add(post_frame)
    if bpy.app.background:
        ctrl.play(frame_range=(0, 100), num_episodes=-1)
    else:
        BpyAnimationDriver(ctrl).play(frame_range=(0, 100))


main()
