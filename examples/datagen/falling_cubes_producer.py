"""Producer: physics scene with N cubes falling under gravity.

Counterpart of the reference's ``examples/datagen/falling_cubes.blend.py``
(rigid-body cubes dropped per episode, per-instance seeds randomize the
drop). Episodes replay automatically: each episode re-randomizes positions
from the instance seed stream.
"""

from __future__ import annotations

import argparse
import sys

from blendjax.transport import term_context
from blendjax.producer import AnimationController, DataPublisher, parse_launch_args
from blendjax.producer.sim import FallingCubesScene, SimEngine


def main() -> None:
    args, remainder = parse_launch_args(sys.argv)
    parser = argparse.ArgumentParser()
    parser.add_argument("--shape", nargs=2, type=int, default=[480, 640])
    parser.add_argument("--num-cubes", type=int, default=8)
    parser.add_argument("--episode-frames", type=int, default=100)
    parser.add_argument("--encoding", choices=["raw", "tile"], default="raw")
    parser.add_argument("--batch", type=int, default=8)
    opts = parser.parse_args(remainder)

    pub = DataPublisher(args.btsockets["DATA"], btid=args.btid, lingerms=10000)
    scene = FallingCubesScene(
        shape=tuple(opts.shape), seed=args.btseed, num_cubes=opts.num_cubes
    )
    ctrl = AnimationController(SimEngine(scene))
    if opts.encoding == "tile":
        # Sparse streaming (blendjax.producer.TileBatchPublisher): only
        # tiles the cubes touch cross the wire; exact frames rebuild on
        # the consumer's device.
        from blendjax.producer import TileBatchPublisher

        tiles = TileBatchPublisher(
            pub, scene.background_image(), opts.batch, tile=(16, 32),
            ref_interval=64,
        )

        def publish(f: int) -> None:
            obs = scene.observation(f)
            tiles.add(obs.pop("image"), **obs)

        ctrl.post_frame.add(publish)
    else:
        ctrl.post_frame.add(lambda f: pub.publish(**scene.observation(f)))
    try:
        ctrl.play(frame_range=(1, opts.episode_frames), num_episodes=-1)
    finally:
        pub.close()
        term_context()  # block until the tail is flushed (bounded by linger)


if __name__ == "__main__":
    main()
