"""Smallest end-to-end blendjax program.

Counterpart of the reference's ``examples/datagen/minimal.py:6-29``:
launch producers, iterate batches, print shapes — in blendjax the batches
arrive as device arrays already sharded over the mesh.
"""

import os

from blendjax.data import StreamDataPipeline
from blendjax.launcher import PythonProducerLauncher
from blendjax.parallel import batch_sharding, create_mesh


def main():
    producer = os.path.join(os.path.dirname(__file__), "cube_producer.py")
    mesh = create_mesh({"data": -1})
    with PythonProducerLauncher(
        script=producer, num_instances=2, named_sockets=["DATA"], seed=10
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"],
            # batch 8 = the reference benchmark's batch; batches shard
            # over the data axis, so batch_size must be a multiple of
            # the mesh size (1/2/4/8-device meshes all divide 8).
            batch_size=8,
            sharding=batch_sharding(mesh),
            launcher=launcher,
        ) as pipe:
            for i, batch in enumerate(pipe):
                print(
                    f"batch {i}: image{tuple(batch['image'].shape)} "
                    f"xy{tuple(batch['xy'].shape)} on "
                    f"{batch['image'].sharding}"
                )
                if i == 4:
                    break


if __name__ == "__main__":
    main()
