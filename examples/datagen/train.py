"""Supervised training on a live cube stream — the blendjax counterpart of
the reference's ``examples/datagen/generate.py`` + a real train loop.

Launches N headless producers (swap in BlenderLauncher + a ``.blend.py``
scene for real Blender), streams image+corner batches onto the device
mesh, and trains :class:`CubeRegressor` with a donated jitted step.

Run: ``python examples/datagen/train.py --steps 50`` (add ``--record
PREFIX`` / ``--replay PREFIX`` for the reference's record/replay flows,
``generate.py:48-81``).
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--instances", type=int, default=2)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--shape", nargs=2, type=int, default=[128, 128])
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--record", default=None, help="record stream to PREFIX")
    ap.add_argument("--replay", default=None, help="replay from PREFIX (no producers)")
    ap.add_argument(
        "--allow-pickle", action="store_true",
        help="trust pickle-bearing recordings (legacy .btr) on --replay",
    )
    ap.add_argument(
        "--encoding", choices=["raw", "tile", "pal"], default="raw",
        help="'tile' streams only changed tiles (decoded on device); "
        "'pal' palette-compresses whole frames (the lossless non-sparse "
        "codec — no reference frame)",
    )
    ap.add_argument(
        "--chunk", type=int, default=1,
        help="coalesce K tile/pal batches into one transfer + one "
        "jitted scan of K updates (needs --encoding tile or pal)",
    )
    ap.add_argument(
        "--inflight", type=int, default=0,
        help="async overlap driver: fuse the decode into the train jit "
        "(one device dispatch per step) and keep up to N dispatches in "
        "flight via blendjax.train.TrainDriver (needs --encoding tile "
        "or pal; see docs/performance.md 'Closing the live-MFU gap'). "
        "0 = classic decode-then-step loop",
    )
    ap.add_argument(
        "--sync-every", type=int, default=16,
        help="driver loss-fetch cadence (steps) when --inflight > 0",
    )
    ap.add_argument(
        "--echo", type=int, default=0, metavar="FACTOR",
        help="data echoing for producer-bound runs (docs/performance.md "
        "'Echoing past a producer-bound pipeline'): hold decoded "
        "samples in a device-resident reservoir and draw train batches "
        "at the STEP rate, re-augmented per draw, each sample reused "
        "at most FACTOR times (0 = off). Incompatible with --chunk > 1 "
        "(the reservoir echoes per-batch decoded samples); photometric "
        "re-augmentation only, since this task's labels are pixel "
        "coordinates",
    )
    ap.add_argument(
        "--echo-capacity", type=int, default=256,
        help="reservoir size in samples when --echo > 0",
    )
    ap.add_argument(
        "--echo-warm-start", default=None, metavar="PATH",
        help="pre-fill the reservoir from a .bjr recording before live "
        "frames arrive (step 0 never blocks on the first render)",
    )
    ap.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve Prometheus text at http://127.0.0.1:PORT/metrics "
        "while training (0 picks a free port; blendjax.obs.exporters) "
        "and log a stall-doctor verdict every 10s (StatsReporter)",
    )
    ap.add_argument(
        "--trace-export", default=None, metavar="PATH",
        help="record pipeline span events and write a Chrome/Perfetto "
        "trace JSON to PATH at exit (load in ui.perfetto.dev beside a "
        "jax.profiler trace); completed distributed frame traces are "
        "merged in as producer/consumer lanes with flow arrows",
    )
    ap.add_argument(
        "--trace-every", type=int, default=64, metavar="N",
        help="producers stamp every Nth message with a sampled "
        "distributed-trace context; each pipeline stage appends its "
        "timestamp and the driver completes the record at step "
        "retirement (docs/observability.md 'Tracing a frame'). "
        "0 disables stamping",
    )
    ap.add_argument(
        "--slo", action="append", default=None, metavar="RULE",
        help="declarative SLO rule, repeatable — e.g. "
        "'rate(ingest.items) >= 50', 'p95(wire.e2e_staleness_s) <= 0.5 "
        "@ 30', 'rate(wire.seq_gaps) == 0', 'doctor != wire-bound' — "
        "evaluated every reporter tick (10s); breaches log, flip "
        "/healthz to 503 (with --metrics-port), and trigger the flight "
        "recorder (with --flight-dir). See docs/observability.md "
        "'SLOs and the flight recorder'",
    )
    ap.add_argument(
        "--flight-dir", default=None, metavar="DIR",
        help="on a sustained SLO breach, dump a bounded diagnostic "
        "bundle here: recent metrics snapshots + doctor verdicts, the "
        "lineage report, span events + frame traces as one Chrome "
        "trace, and the breaching rule states (needs --slo)",
    )
    ap.add_argument(
        "--checkpoint", default=None, metavar="DIR",
        help="async sharded snapshots (blendjax.checkpoint, docs/"
        "checkpointing.md): every --checkpoint-every steps the driver "
        "hands the train state + session (driver counters, lineage "
        "positions, echo/scenario state when active) to a background "
        "writer — a kill -9 resumes from the last committed step. "
        "SIGTERM drains the ring and snapshots before exit; with "
        "--slo, a breach also requests a snapshot at the next step",
    )
    ap.add_argument(
        "--checkpoint-every", type=int, default=50, metavar="STEPS",
        help="snapshot cadence in train steps (0 = only the exit/"
        "preemption snapshot)",
    )
    ap.add_argument(
        "--resume", action="store_true",
        help="restore the latest committed snapshot from --checkpoint "
        "before training (elastic: the state re-places under THIS "
        "run's mesh, so a job preempted on 8 chips resumes on fewer)",
    )
    ap.add_argument(
        "--fleet", default=None, metavar="MIN:MAX",
        help="elastic producer autoscaling (blendjax.fleet, docs/"
        "fleet.md): start MIN producers and let a FleetController "
        "grow/shrink the fleet between MIN and MAX on live stall-"
        "doctor verdicts — up on producer-bound/echo-saturated, down "
        "on step-bound/idle, crashed instances respawned in place; "
        "with --slo, a breaching watchdog blocks scale-down. The "
        "scale-event log prints beside the doctor verdict at exit",
    )
    ap.add_argument(
        "--synthetic-producers", type=int, default=0, metavar="N",
        help="replace the cube producers with N Blender-free synthetic "
        "producers (blendjax.fleet.synthetic: the native rasterizer at "
        "~1,100 frames/s each, raw frames only) — the high-rate tier "
        "that reaches step-bound and scale-down regimes Blender "
        "cannot. Composes with --fleet (MIN wins as the start count)",
    )
    ap.add_argument(
        "--scenarios", default=None, metavar="SPEC",
        help="closed-loop domain randomization (blendjax.scenario, "
        "docs/scenarios.md): publish a scenario space over a per-"
        "producer duplex channel and account every train row to the "
        "scenario that rendered it. SPEC uses the space grammar, e.g. "
        "'easy:half_extent=u(0.8,1.2) / "
        "hard:half_extent=u(0.8,1.2),xy_jitter=g(6,0.5)'. Needs "
        "--synthetic-producers (the synthetic tier consumes the "
        "duplex channel; Blender scenes wire their own "
        "ScenarioApplicator)",
    )
    ap.add_argument(
        "--curriculum", action="store_true",
        help="adapt the scenario space from per-scenario training "
        "loss: mixture weights move toward high-loss scenarios "
        "(bandit) and gaussian params update by REINFORCE, "
        "re-published on a cadence (needs --scenarios; incompatible "
        "with --inflight — the curriculum reads the loss every step)",
    )
    ap.add_argument(
        "--curriculum-every", type=int, default=50, metavar="STEPS",
        help="curriculum update cadence in train steps",
    )
    ap.add_argument(
        "--augment", action="store_true",
        help="on-device color jitter inside the jitted step "
        "(blendjax.ops.augment; per-step deterministic keys). Only "
        "photometric ops: this task supervises pixel-space corner "
        "coordinates, which geometric ops (flip/crop) would invalidate "
        "without a matching label transform.",
    )
    args = ap.parse_args()

    fleet_bounds = None
    if args.fleet:
        try:
            lo, hi = (int(v) for v in args.fleet.split(":"))
        except ValueError:
            ap.error("--fleet expects MIN:MAX, e.g. --fleet 1:4")
        if not 1 <= lo <= hi:
            ap.error("--fleet needs 1 <= MIN <= MAX")
        if args.replay:
            ap.error("--fleet scales live producers; drop --replay")
        fleet_bounds = (lo, hi)
    if args.synthetic_producers and args.encoding != "raw":
        ap.error(
            "--synthetic-producers publishes raw frames: use "
            "--encoding raw"
        )
    if args.scenarios and not args.synthetic_producers:
        ap.error(
            "--scenarios needs --synthetic-producers (the synthetic "
            "tier consumes the scenario duplex channel; Blender scenes "
            "wire a ScenarioApplicator in their producer script)"
        )
    if args.scenarios and args.replay:
        ap.error("--scenarios publishes to live producers; drop --replay")
    if args.curriculum and not args.scenarios:
        ap.error("--curriculum needs a --scenarios space to adapt")
    if args.curriculum and args.inflight > 0:
        ap.error(
            "--curriculum reads the loss every step: drop --inflight"
        )
    if args.resume and not args.checkpoint:
        ap.error("--resume needs a --checkpoint directory")

    import jax

    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.models import CubeRegressor
    from blendjax.parallel import batch_sharding, create_mesh
    from blendjax.train import (
        make_chunked_supervised_step,
        make_supervised_step,
        make_train_state,
    )

    # Observability (docs/observability.md): a live Prometheus scrape
    # target + periodic doctor verdicts, SLO watchdog + flight
    # recorder, and/or a Chrome-trace of the pipeline spans — torn
    # down in the finally below.
    exporter = reporter = None
    # Checkpoint plumbing shared across modes: ckpt_refs carries the
    # live driver (for the breach arm) or the direct loop's breach
    # flag; ckpt_session is the restored session, applied per
    # component as each one is constructed.
    ckpt_refs: dict = {}
    scenario_ctx: dict = {}

    def _ckpt_on_breach():
        drv = ckpt_refs.get("driver")
        if drv is not None:
            drv.request_checkpoint()
        else:
            ckpt_refs["breach"] = True
        return {"mode": "driver" if drv is not None else "direct"}

    if args.flight_dir and not args.slo:
        ap.error("--flight-dir needs at least one --slo rule to breach")
    if args.metrics_port is not None or args.slo:
        from blendjax.obs import StatsReporter, start_http_exporter

        reporter = StatsReporter(
            interval_s=10.0, slos=args.slo, flight_dir=args.flight_dir,
            checkpoint_on_breach=(
                _ckpt_on_breach if args.checkpoint else None
            ),
        ).start()
        if args.metrics_port is not None:
            # /healthz serves 200/503 from the reporter's SLO state —
            # the machine-readable health bit beside /metrics.
            exporter = start_http_exporter(
                port=args.metrics_port, health=reporter.health
            )
            print(
                f"metrics: http://127.0.0.1:{exporter.port}/metrics  "
                f"health: http://127.0.0.1:{exporter.port}/healthz"
            )
    if args.trace_export:
        from blendjax.utils.metrics import metrics as _metrics

        _metrics.enable_span_events()

    mesh = create_mesh({"data": -1})
    sharding = batch_sharding(mesh)
    h, w = args.shape

    model = CubeRegressor()
    state = make_train_state(
        model, np.zeros((args.batch, h, w, 4), np.uint8), mesh=mesh
    )
    ckpt_mgr = None
    ckpt_session: dict = {}
    if args.checkpoint:
        from blendjax.checkpoint import SnapshotManager, restore_session
        from blendjax.parallel.sharding import state_shardings

        ckpt_mgr = SnapshotManager(args.checkpoint)
        if args.resume:
            restored = ckpt_mgr.restore(
                state, shardings=state_shardings(state, mesh=mesh)
            )
            if restored is None:
                print(f"no snapshot in {args.checkpoint}: starting fresh")
            else:
                state = restored.state
                ckpt_session = restored.session
                from blendjax.obs.lineage import lineage

                restore_session(ckpt_session, lineage=lineage)
                print(
                    f"resumed from snapshot step {restored.step}"
                    + (" (resharded onto this mesh)"
                       if restored.resharded else "")
                )

    def _session_state() -> dict:
        from blendjax.checkpoint import collect_session
        from blendjax.obs.lineage import lineage

        comps = {"lineage": lineage}
        if "accounting" in scenario_ctx:
            comps["scenario"] = scenario_ctx["accounting"]
        if "curriculum" in scenario_ctx:
            comps["curriculum"] = scenario_ctx["curriculum"]
        if ckpt_refs.get("echo") is not None:
            comps["echo"] = ckpt_refs["echo"]
        if ckpt_refs.get("fleet") is not None:
            comps["fleet"] = ckpt_refs["fleet"]
        return collect_session(**comps)

    def _direct_session(steps_done: int) -> dict:
        # the direct loop has no TrainDriver to stamp its counters:
        # record the step position itself, so a resumed run continues
        # the same numbering (snapshot names, cadence) the driver
        # mode gets for free
        session = _session_state()
        session["driver"] = {"steps": int(steps_done)}
        return session

    augment = None
    if args.augment:
        # Label-safe augmentation only: the corner labels live in pixel
        # space, so flips/crops would need the xy labels co-transformed.
        from blendjax.ops.augment import color_jitter, make_augment

        augment = make_augment(color_jitter)
    chunk = args.chunk if args.encoding in ("tile", "pal") else 1
    echo_mode = args.echo > 0
    if echo_mode and chunk > 1:
        ap.error("--echo needs a per-batch decoded pipeline: drop --chunk")
    use_fused = (
        args.inflight > 0 and args.encoding in ("tile", "pal")
        and not echo_mode
    )
    driver = None
    if echo_mode:
        # Data echoing: the reservoir feeds a plain supervised step on
        # decoded batches (the per-draw re-augmentation lives INSIDE
        # the reservoir's gather jit, so --augment's in-step chain is
        # not also applied); --inflight > 0 additionally keeps step
        # dispatches in flight — still one train dispatch per step.
        step = make_supervised_step(mesh=mesh, batch_sharding=sharding)
        if args.inflight > 0:
            from blendjax.train import TrainDriver

            driver = TrainDriver(
                step, state, inflight=args.inflight,
                sync_every=args.sync_every,
                checkpoint=ckpt_mgr,
                checkpoint_every=args.checkpoint_every,
                session_state=_session_state,
            )
    elif use_fused:
        # Fused decode + async overlap: exactly one device dispatch per
        # step, up to --inflight of them outstanding, loss fetched every
        # --sync-every steps (docs/performance.md).
        from blendjax.train import TrainDriver, make_fused_tile_step

        step = make_fused_tile_step(augment=augment)
        driver = TrainDriver(
            step, state, inflight=args.inflight,
            sync_every=args.sync_every,
            checkpoint=ckpt_mgr, checkpoint_every=args.checkpoint_every,
            session_state=_session_state,
        )
    elif chunk > 1:
        # K sequential updates per device call (see docs/performance.md);
        # augmentation keys fold the in-scan step counter, so this
        # trains identically to chunk=1 with --augment.
        step = make_chunked_supervised_step(augment=augment)
    else:
        step = make_supervised_step(
            mesh=mesh, batch_sharding=sharding, augment=augment
        )

    def batch_count(batch):
        if "_packed" in batch:
            # packed chunk group: K' rows x the per-batch xy lead
            lead = next(
                s[0] for nm, d, s, o, b in batch["_spec"] if nm == "xy"
            )
            return batch["_packed"].shape[0] * lead
        # superbatches are (K', B, ...) and K' can run short on a
        # group flush; count what actually arrived
        shp = batch["image"].shape
        return shp[0] * shp[1] if chunk > 1 or use_fused else shp[0]

    if driver is not None:
        ckpt_refs["driver"] = driver
        if ckpt_session.get("driver"):
            driver.load_state_dict(ckpt_session["driver"])
    guard = None
    if ckpt_mgr is not None:
        from blendjax.checkpoint import PreemptionGuard

        # SIGTERM -> drain + snapshot + clean exit (docs/
        # checkpointing.md); with no driver the direct loop polls the
        # flag itself
        guard = PreemptionGuard(driver) if driver is not None else (
            PreemptionGuard()
        )

    def wrap_echo(pipe):
        if not echo_mode:
            return pipe
        from blendjax.data import EchoingPipeline

        echo = EchoingPipeline(
            pipe, capacity=args.echo_capacity,
            max_echo_factor=args.echo,
            warm_start=args.echo_warm_start,
            warm_start_allow_pickle=args.allow_pickle,
        )
        ckpt_refs["echo"] = echo
        if ckpt_session.get("echo"):
            echo.load_state_dict(ckpt_session["echo"])
            print("resumed echo reservoir "
                  f"(fill={echo.stats['reservoir_fill']})")
        return echo

    def run_steps(batches):
        nonlocal state
        from blendjax.checkpoint import PreemptionRequested

        t0, n = time.perf_counter(), 0
        preempted = False
        start_step = (ckpt_session.get("driver") or {}).get("steps", 0)
        try:
            n = _run_steps_inner(batches, start_step)
        except PreemptionRequested as e:
            preempted = True
            print(f"preempted cleanly: {e}")
        if driver is not None and not preempted:
            state, final = driver.finish()
            if final is not None:  # None = zero batches submitted
                print(f"final loss={final:.5f}  driver={driver.stats}")
        if ckpt_mgr is not None and not preempted:
            # exit snapshot: the run's last word (close() in the outer
            # finally flushes it)
            if driver is not None:
                steps_done = driver.steps
                session = _session_state()
                session["driver"] = driver.state_dict()
            else:
                steps_done = start_step + ckpt_refs.get("steps", 0)
                session = _direct_session(steps_done)
            ckpt_mgr.save_async(steps_done, state, session)
        dt = time.perf_counter() - t0
        print(f"{n / dt:.1f} images/sec ({n} images in {dt:.1f}s)")

    def _run_steps_inner(batches, start_step):
        nonlocal state
        from blendjax.checkpoint import PreemptionRequested

        n = 0
        for i, batch in enumerate(batches):
            if i >= args.steps:
                break
            if driver is not None:
                if scenario_ctx:
                    # rows only (no per-step loss fetch in driver
                    # mode), accounted BEFORE submit — the driver
                    # strips the host-side scenario sidecar off the
                    # batch it hands to the jit
                    scenario_ctx["accounting"].account_batch(batch)
                driver.submit(batch)
            else:
                fields = {"image": batch["image"], "xy": batch["xy"]}
                if "_mask" in batch:  # bucket-padded tail: loss-masked
                    fields["_mask"] = batch["_mask"]
                state, metrics = step(state, fields)
                if scenario_ctx:
                    loss_val = None
                    cur = scenario_ctx.get("curriculum")
                    if cur is not None:
                        loss = metrics["loss"]
                        loss = (
                            loss[-1] if getattr(loss, "ndim", 0) else loss
                        )
                        loss_val = float(loss)  # the curriculum's evidence
                    scenario_ctx["accounting"].account_batch(
                        batch, loss=loss_val
                    )
                    if cur is not None:
                        report = cur.step(1)
                        if report:
                            print(
                                f"curriculum v{report['version']}: "
                                f"weights={report['weights']}"
                            )
                if i % 10 == 0:
                    loss = metrics["loss"]
                    loss = loss[-1] if getattr(loss, "ndim", 0) else loss
                    print(f"step {i}: loss={float(loss):.5f}")
                if ckpt_mgr is not None:
                    # direct-loop twin of the driver cadence: async
                    # snapshot every N steps, on breach request, and a
                    # drain-free SIGTERM flush (no ring to drain here)
                    ckpt_refs["steps"] = i + 1
                    done = start_step + i + 1
                    if (
                        ckpt_refs.pop("breach", None)
                        or (args.checkpoint_every
                            and (i + 1) % args.checkpoint_every == 0)
                    ):
                        ckpt_mgr.save_async(
                            done, state, _direct_session(done)
                        )
                    if guard is not None and guard.requested:
                        ckpt_mgr.save_async(
                            done, state, _direct_session(done)
                        )
                        ckpt_mgr.wait()
                        err = ckpt_mgr.last_error
                        raise PreemptionRequested(
                            f"snapshot FAILED at step {done} "
                            f"({err!r}) — resuming from the last "
                            "committed step" if err is not None
                            else f"snapshot committed at step {done}"
                        )
            n += batch_count(batch)
        return n

    del jax  # device work happens inside the pipeline/step

    try:
        if args.replay:
            # Replays through the identical ingest -> decode path as
            # live traffic (tile-delta recordings included), looping
            # like epochs.
            pipe = StreamDataPipeline.from_recording(
                args.replay, batch_size=args.batch, sharding=sharding,
                loop=True, chunk=chunk, emit_packed=use_fused,
                allow_pickle=args.allow_pickle,
            )
            with wrap_echo(pipe) as source:
                run_steps(iter(source))
            return

        if args.synthetic_producers:
            from blendjax.fleet import SYNTHETIC_PRODUCER

            script = SYNTHETIC_PRODUCER
            producer_args = [
                "--shape", str(h), str(w), "--batch", str(args.batch),
                "--trace-every", str(args.trace_every),
            ]
            start_n = args.synthetic_producers
        else:
            script = __file__.replace("train.py", "cube_producer.py")
            producer_args = ["--shape", str(h), str(w),
                             "--trace-every", str(args.trace_every)]
            if args.encoding in ("tile", "pal"):
                producer_args += [
                    "--batch", str(args.batch),
                    "--encoding", args.encoding,
                ]
            start_n = args.instances
        if fleet_bounds:
            start_n = fleet_bounds[0]
        named_sockets = ["DATA"]
        if args.scenarios:
            named_sockets = ["DATA", "CTRL"]
            producer_args = producer_args + ["--scenario-wait", "15"]
        with PythonProducerLauncher(
            script=script,
            num_instances=start_n,
            named_sockets=named_sockets,
            seed=0,
            instance_args=[producer_args] * start_n,
        ) as launcher:
            pipe = StreamDataPipeline(
                launcher.addresses["DATA"],
                batch_size=args.batch,
                sharding=sharding,
                chunk=chunk,
                emit_packed=use_fused,
                record_path_prefix=args.record,
            )
            svc = None
            if args.scenarios:
                from blendjax.scenario import (
                    ScenarioCurriculum,
                    ScenarioService,
                    ScenarioSpace,
                    accounting,
                )

                space = ScenarioSpace.parse(args.scenarios)
                svc = ScenarioService(space)
                for i, addr in enumerate(launcher.addresses["CTRL"]):
                    svc.attach(i, addr)
                if not svc.wait_acked(timeout=15):
                    print(
                        "warning: not every producer acked the scenario "
                        f"space yet: {svc.state()}"
                    )
                scenario_ctx["accounting"] = accounting
                scenario_ctx["service"] = svc
                if args.curriculum:
                    scenario_ctx["curriculum"] = ScenarioCurriculum(
                        space, service=svc,
                        every_steps=args.curriculum_every,
                    )
                if ckpt_session:
                    from blendjax.checkpoint import restore_session

                    # restored curriculum re-publishes its space (and
                    # version) through the freshly-attached service
                    restore_session(
                        ckpt_session, scenario=accounting,
                        curriculum=scenario_ctx.get("curriculum"),
                    )
            ctrl = None
            if fleet_bounds:
                from blendjax.fleet import FleetController, FleetPolicy

                # the controller's own daemon thread runs the blocking
                # launcher lifecycle (BJX110); the pipeline applies the
                # connect/disconnect ops from its socket-owning thread
                ctrl = FleetController(
                    launcher, connector=pipe,
                    policy=FleetPolicy(
                        min_instances=fleet_bounds[0],
                        max_instances=fleet_bounds[1],
                    ),
                    diagnose=lambda: pipe.doctor(driver),
                    health=(
                        (lambda: reporter.healthy)
                        if reporter is not None else None
                    ),
                    instance_args=producer_args,
                    # elastic scenario membership: a scaled-up producer
                    # receives the current space before its data
                    # address joins the fan-in
                    scenario_service=svc,
                ).start()
                ckpt_refs["fleet"] = ctrl
                if ckpt_session.get("fleet"):
                    # reconnect the snapshot's fleet: grow back to the
                    # saved count, re-admit remote members
                    ctrl.load_state_dict(ckpt_session["fleet"])
                if reporter is not None:
                    # fleet state rides the JSONL archive per tick
                    reporter.fleet = ctrl
            try:
                with wrap_echo(pipe) as source:
                    run_steps(iter(source))
                    if echo_mode:
                        print(f"echo={source.stats}")
                    if scenario_ctx:
                        rep = scenario_ctx["accounting"].report()
                        print(
                            f"scenario space v{rep['space_version']}: "
                            + ", ".join(
                                f"{sid}: {s['rows']} rows "
                                f"({s['fresh']} fresh/{s['echoed']} "
                                f"echoed, loss p50 "
                                f"{s['loss']['p50']:.4f})"
                                for sid, s in rep["scenarios"].items()
                            )
                        )
                        if "curriculum" in scenario_ctx:
                            w = scenario_ctx["curriculum"].space.weights()
                            print(
                                "curriculum weights: "
                                + ", ".join(
                                    f"{k}={v:.3f}" for k, v in w.items()
                                )
                            )
                    print(source.doctor(driver).render())
                    if ctrl is not None:
                        st = ctrl.state()
                        print(
                            f"fleet: instances={st['instances']} "
                            f"(bounds {st['min']}:{st['max']}), "
                            f"ticks={st['ticks']}, "
                            f"last verdict={st['verdict']}"
                        )
                        for ev in ctrl.scale_events():
                            detail = {
                                k: v for k, v in ev.items()
                                if k not in ("t", "action")
                            }
                            print(f"  fleet {ev['action']}: {detail}")
            finally:
                if ctrl is not None:
                    ctrl.stop()
                if svc is not None:
                    svc.stop()
    finally:
        if guard is not None:
            guard.uninstall()
        if ckpt_mgr is not None:
            ckpt_mgr.close()  # flushes the exit snapshot
            print(
                f"checkpoints in {args.checkpoint}: "
                f"steps {ckpt_mgr.steps()}"
            )
        if reporter is not None:
            reporter.stop()  # final tick logs the closing verdict
        if exporter is not None:
            exporter.close()
        if args.trace_export:
            from blendjax.obs import write_chrome_trace

            n = write_chrome_trace(args.trace_export)
            print(f"wrote {n} span events to {args.trace_export}")


if __name__ == "__main__":
    main()
