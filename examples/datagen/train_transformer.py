"""Transformer training on a live producer stream.

The long-context layer meeting the data plane: `StreamFormer` (a
patch-embedding transformer regressing the same cube corners as
`CubeRegressor`) trains on streamed frames, sharded over whatever mesh
the host offers — batch over `data`, dense kernels over `tensor`
(Megatron-style), and, with a `seq` axis, exact ring attention rotating
K/V blocks around the ICI ring (`blendjax.parallel.ring`; Ulysses via
``--sp-mode ulysses``). No reference counterpart exists (the reference
has no sequence models, SURVEY.md §2.4); this composes blendjax's
net-new ICI plane with the reference-shaped streaming pipeline.

Run on one chip (mesh collapses to data=1):

    python examples/datagen/train_transformer.py --steps 20

Multi-chip shapes compile + execute on the virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/datagen/train_transformer.py \
        --steps 4 --mesh data=2,tensor=2,seq=2 --shape 64 64
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def parse_mesh(spec: str) -> dict:
    """'data=2,tensor=2,seq=2' -> {'data': 2, 'tensor': 2, 'seq': 2}
    ('data=-1' fills with the remaining devices)."""
    out = {}
    for part in spec.split(","):
        name, _, n = part.partition("=")
        out[name.strip()] = int(n)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--instances", type=int, default=2)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--shape", nargs=2, type=int, default=[128, 128])
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--mesh", default="data=-1",
                    help="mesh axes, e.g. data=2,tensor=2,seq=2")
    ap.add_argument("--patch", type=int, default=16)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--sp-mode", choices=["ring", "ulysses"],
                    default="ring")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize blocks (HBM for FLOPs)")
    args = ap.parse_args()

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # some images pre-import jax pinning a device plugin via
        # sitecustomize; the config update (before the first device
        # query) is what actually selects CPU (same workaround as
        # tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")

    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.models import StreamFormer
    from blendjax.parallel import batch_sharding, create_mesh
    from blendjax.train import (
        corner_loss,
        make_supervised_step,
        make_train_state,
    )

    axes = parse_mesh(args.mesh)
    mesh = create_mesh(axes)
    sharding = batch_sharding(mesh)
    h, w = args.shape
    model = StreamFormer(
        patch=args.patch, dim=args.dim, depth=args.depth,
        num_heads=args.heads, num_outputs=16,
        use_ring=mesh.shape.get("seq", 1) > 1,
        mesh=mesh, sp_mode=args.sp_mode, remat=args.remat,
    )
    state = make_train_state(
        model, np.zeros((args.batch, h, w, 4), np.uint8), mesh=mesh
    )

    def loss_fn(state, params, b):
        pred = state.apply_fn({"params": params}, b["image"])
        return corner_loss(pred.reshape(-1, 8, 2), b["xy"], image_shape=(h, w))

    step = make_supervised_step(
        mesh=mesh, batch_sharding=sharding, loss_fn=loss_fn
    )

    with PythonProducerLauncher(
        script=__file__.replace("train_transformer.py", "cube_producer.py"),
        num_instances=args.instances,
        named_sockets=["DATA"],
        seed=0,
        instance_args=[["--shape", str(h), str(w)]] * args.instances,
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"],
            batch_size=args.batch,
            sharding=sharding,
        ) as pipe:
            t0, n = time.perf_counter(), 0
            for i, batch in enumerate(pipe):
                if i >= args.steps:
                    break
                state, metrics = step(
                    state, {"image": batch["image"], "xy": batch["xy"]}
                )
                n += batch["image"].shape[0]
                if i % 5 == 0:
                    print(f"step {i}: loss={float(metrics['loss']):.5f}")
            dt = time.perf_counter() - t0
            print(
                f"{n / dt:.1f} images/sec over mesh "
                f"{dict(mesh.shape)} ({n} images in {dt:.1f}s)"
            )


if __name__ == "__main__":
    main()
