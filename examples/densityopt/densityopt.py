"""Optimize simulation parameters from a TPU loss — adversarial style.

blendjax counterpart of the reference's flagship bidirectional example
(``examples/densityopt/densityopt.py``): a fleet of supershape producers
renders parameter samples fanned out over duplex CTRL channels; a
discriminator on the accelerator scores rendered vs. target images; the
sampling distribution over shape parameters updates by score-function
gradient (the renderer is non-differentiable). ``shape_id`` round-trips
through the producers to re-associate images with their samples
(``densityopt.py:99-103,119``).

Run: ``python examples/densityopt/densityopt.py --iters 10``
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--instances", type=int, default=2)
    ap.add_argument("--samples", type=int, default=8, help="per iteration")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--target-m", type=float, default=3.0)
    ap.add_argument("--init-m", type=float, default=7.0)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from blendjax.data import RemoteStream
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.models import Discriminator
    from blendjax.producer.sim import SupershapeScene
    from blendjax.train.score import GaussianSimParams, chunk_across
    from blendjax.transport import PairChannel

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "supershape_producer.py"
    )

    # Target distribution: "real" images rendered locally at target params
    # (the reference draws its target set the same way, from known params).
    target_scene = SupershapeScene(seed=123)
    rng = np.random.default_rng(0)

    def target_batch(n):
        imgs = []
        for _ in range(n):
            m = args.target_m + rng.normal() * 0.1
            target_scene.set_params([m, 1.0, 1.0, 1.0], shape_id=0)
            imgs.append(target_scene.render())
        return np.stack(imgs)

    disc = Discriminator(features=(16, 32))
    dummy = np.zeros((2, 256, 256, 4), np.uint8)
    dparams = disc.init(jax.random.key(0), dummy)["params"]
    tx = optax.adam(1e-3)
    opt_state = tx.init(dparams)

    @jax.jit
    def disc_step(dparams, opt_state, real, fake):
        def loss_fn(p):
            lr = disc.apply({"params": p}, real)
            lf = disc.apply({"params": p}, fake)
            return (
                optax.sigmoid_binary_cross_entropy(lr, jnp.ones_like(lr)).mean()
                + optax.sigmoid_binary_cross_entropy(
                    lf, jnp.zeros_like(lf)
                ).mean()
            )

        loss, grads = jax.value_and_grad(loss_fn)(dparams)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(dparams, updates), opt_state, loss

    @jax.jit
    def fake_scores(dparams, fake):
        # Simulator wants fakes classified REAL: per-sample BCE vs 1.
        logits = disc.apply({"params": dparams}, fake)
        return optax.sigmoid_binary_cross_entropy(
            logits, jnp.ones_like(logits)
        )

    sim = GaussianSimParams(
        mu=[args.init_m], log_sigma=[np.log(0.5)], learning_rate=0.15
    )

    with PythonProducerLauncher(
        script=script,
        num_instances=args.instances,
        named_sockets=["DATA", "CTRL"],
        seed=0,
    ) as launcher:
        remotes = [
            PairChannel(a, bind=False)
            for a in launcher.addresses["CTRL"]
        ]
        stream = RemoteStream(
            launcher.addresses["DATA"], timeoutms=30_000, copy_arrays=True
        )
        images_iter = iter(stream)
        key = jax.random.key(0)
        next_id = 0
        for it in range(args.iters):
            key, sub = jax.random.split(key)
            theta = np.asarray(sim.sample(sub, args.samples))
            ids = list(range(next_id, next_id + args.samples))
            next_id += args.samples
            # Fan samples out across instances (reference
            # ``update_simulations``, ``densityopt.py:95-107``).
            for remote, th_chunk, id_chunk in zip(
                remotes,
                chunk_across(list(theta), args.instances),
                chunk_across(ids, args.instances),
            ):
                for th, sid in zip(th_chunk, id_chunk):
                    remote.send(
                        shape_params=np.array(
                            [th[0], 1.0, 1.0, 1.0], np.float32
                        ),
                        shape_id=sid,
                    )
            # Collect one render per sample, re-associated by shape_id.
            by_id = {}
            while len(by_id) < args.samples:
                item = next(images_iter)
                if item["shape_id"] in ids:
                    by_id[item["shape_id"]] = item["image"]
            fake = np.stack([by_id[i] for i in ids])
            real = target_batch(args.samples)
            dparams, opt_state, dloss = disc_step(
                dparams, opt_state, real, fake
            )
            losses = np.asarray(fake_scores(dparams, fake))
            mean_loss = sim.update(theta, losses)
            print(
                f"iter {it}: mu={float(sim.mu[0]):.3f} "
                f"(target {args.target_m}) d_loss={float(dloss):.4f} "
                f"sim_loss={mean_loss:.4f}"
            )
        for r in remotes:
            r.close()


if __name__ == "__main__":
    main()
