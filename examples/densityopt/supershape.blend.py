"""Blender scene script: duplex-driven supershape mesh regeneration.

blendjax port of the reference's ``examples/densityopt/supershape.blend.
py:26-44``: the consumer pushes batches of supershape parameters over the
CTRL duplex channel; each frame the producer regenerates the mesh from
the next parameter sample and publishes a render tagged with the
``shape_id`` that produced it — the id round-trip that lets the
optimizer re-associate images with parameter samples
(``densityopt.py:99-103``).

The reference imports the external ``supershape`` package inside
Blender; the (public, Gielis 2003) superformula is small, so it is
implemented inline here instead — no extra install into Blender's
Python.
"""

import sys

import bpy
import numpy as np

from blendjax.producer import (
    AnimationController,
    DataPublisher,
    DuplexChannel,
    parse_launch_args,
)
from blendjax.producer.bpy_engine import BpyAnimationDriver, BpyEngine

UV = (50, 50)


def supercoords(params, shape=UV):
    """Superformula surface coordinates (m, a, b, n1, n2, n3) x2."""

    def sf(m, a, b, n1, n2, n3, theta):
        t = np.abs(np.cos(m * theta / 4) / a) ** n2
        t = t + np.abs(np.sin(m * theta / 4) / b) ** n3
        return t ** (-1.0 / n1)

    p = np.asarray(params, np.float64).reshape(2, 6)
    nu, nv = shape
    theta = np.linspace(-np.pi, np.pi, nu)
    phi = np.linspace(-np.pi / 2, np.pi / 2, nv)
    r1 = sf(*p[0], theta)[:, None]
    r2 = sf(*p[1], phi)[None, :]
    x = r1 * np.cos(theta)[:, None] * r2 * np.cos(phi)[None, :]
    y = r1 * np.sin(theta)[:, None] * r2 * np.cos(phi)[None, :]
    # z varies only with phi; broadcast to the full grid so the three
    # coordinate arrays stack (first caught by the fake-Blender tier:
    # this script had never executed before it).
    z = np.broadcast_to(r2 * np.sin(phi)[None, :], x.shape)
    return x, y, z


def make_mesh(shape=UV):
    nu, nv = shape
    mesh = bpy.data.meshes.new("supershape")
    verts = [(0.0, 0.0, 0.0)] * (nu * nv)
    faces = [
        (i * nv + j, i * nv + j + 1, (i + 1) * nv + j + 1, (i + 1) * nv + j)
        for i in range(nu - 1)
        for j in range(nv - 1)
    ]
    mesh.from_pydata(verts, [], faces)
    obj = bpy.data.objects.new("supershape", mesh)
    bpy.context.collection.objects.link(obj)
    return obj


def update_mesh(obj, x, y, z):
    co = np.stack([x, y, z], axis=-1).reshape(-1, 3)
    obj.data.vertices.foreach_set("co", co.reshape(-1))
    obj.data.update()


def main():
    args, _ = parse_launch_args(sys.argv)
    obj = make_mesh()
    pub = DataPublisher(args.btsockets["DATA"], btid=args.btid)
    duplex = DuplexChannel(args.btsockets["CTRL"], btid=args.btid)
    ctrl = AnimationController(BpyEngine())

    pending = []  # (params, shape_id) queue fed by the duplex channel
    current = {"shape_id": None}

    off = None
    if not bpy.app.background:
        from blendjax.producer.offscreen import OffScreenRenderer

        off = OffScreenRenderer(mode="rgb")
        off.set_render_style(shading="SOLID", overlays=False)

    def pre_frame(_frame):
        msg = duplex.recv(timeoutms=0)  # non-blocking poll each frame
        if msg is not None:
            pending.extend(
                zip(list(msg["shape_params"]), list(msg["shape_ids"]))
            )
        if pending:
            params, sid = pending.pop(0)
            update_mesh(obj, *supercoords(params))
            current["shape_id"] = sid
        else:
            current["shape_id"] = None

    def post_frame(_frame):
        if current["shape_id"] is None:
            return  # nothing new to report this frame
        payload = dict(shape_id=current["shape_id"])
        if off is not None:
            payload["image"] = off.render()
        pub.publish(**payload)

    ctrl.pre_frame.add(pre_frame)
    ctrl.post_frame.add(post_frame)
    if bpy.app.background:
        ctrl.play(frame_range=(0, 10000), num_episodes=-1)
    else:
        BpyAnimationDriver(ctrl).play(frame_range=(0, 10000))


main()
