"""Producer: renders supershapes whose parameters arrive over the duplex
control channel.

Headless counterpart of ``examples/densityopt/supershape.blend.py``:
``pre_frame`` polls CTRL non-blocking for new ``(shape_params, shape_id)``
(``supershape.blend.py:26-37``), ``post_frame`` publishes
``(image, shape_id)`` so the consumer can re-associate renders with the
parameter samples that produced them (``supershape.blend.py:39-44``).
"""

from __future__ import annotations

import sys
import time
from collections import deque

import numpy as np

from blendjax.transport import term_context
from blendjax.producer import (
    AnimationController,
    DataPublisher,
    DuplexChannel,
    parse_launch_args,
)
from blendjax.producer.sim import SimEngine, SupershapeScene


def main() -> None:
    args, _ = parse_launch_args(sys.argv)
    pub = DataPublisher(args.btsockets["DATA"], btid=args.btid, lingerms=10000)
    ctrl = DuplexChannel(args.btsockets["CTRL"], btid=args.btid)
    scene = SupershapeScene(seed=args.btseed)
    pending: deque = deque()
    fresh = False

    def pre_frame(frame: int) -> None:
        nonlocal fresh
        # Drain all queued param updates, keep them in arrival order.
        while True:
            msg = ctrl.recv(timeoutms=0)
            if msg is None:
                break
            pending.append(
                (np.asarray(msg["shape_params"]), int(msg["shape_id"]))
            )
        if pending:
            params, sid = pending.popleft()
            scene.set_params(params, sid)
            fresh = True
        else:
            fresh = False
            time.sleep(0.001)  # idle: don't spin the frame loop hot

    def post_frame(frame: int) -> None:
        # One published render per parameter sample, so the consumer's
        # image count matches the samples it fanned out.
        if fresh and scene.shape_id >= 0:
            pub.publish(**scene.observation(frame))

    ctrl_engine = SimEngine(scene)
    ctl = AnimationController(ctrl_engine)
    ctl.pre_frame.add(pre_frame)
    ctl.post_frame.add(post_frame)
    try:
        ctl.play(frame_range=(1, 2_147_483_647), num_episodes=-1)
    finally:
        pub.close()
        ctrl.close()
        term_context()  # block until the tail is flushed (bounded by linger)


if __name__ == "__main__":
    main()
