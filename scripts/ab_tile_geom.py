"""Interleaved A/B of the tile geometries: square 16x16 (slot-scatter
decode) vs rectangular 16x32 (direct-spatial decode, r4).

Gates on the weather preflight first (pass ``--force`` to run anyway —
in degraded windows the absolute numbers are meaningless, though the
within-run ranking is still weakly informative). Alternates geometries
pass-by-pass so tunnel drift affects both arms alike, then prints one
JSON verdict line. If 16x32 wins in fit weather, flip bench.py's
TILE_GEOM default and record the numbers in PARITY.md.

Run: ``PYTHONPATH=.:$PYTHONPATH python scripts/ab_tile_geom.py
[--reps 2] [--force]``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=2,
                    help="measurement passes per geometry (interleaved)")
    ap.add_argument("--items", type=int, default=512)
    ap.add_argument("--force", action="store_true",
                    help="run even when the weather preflight fails")
    args = ap.parse_args()

    probe = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "weather.py")]
    )
    fit = probe.returncode == 0
    if not fit and not args.force:
        print("weather not fit for measurement; skipping A/B "
              "(--force to override)")
        return 3

    sys.path.insert(0, REPO_ROOT)
    import bench

    arms = ("16", "16x32")
    results: dict = {g: [] for g in arms}
    for rep in range(args.reps):
        for geom in arms:
            tile_args = geom.split("x")
            th, tw = int(tile_args[0]), int(tile_args[-1])
            r = bench.measure(
                bench.ENCODING, bench.CHUNK, args.items,
                bench.TIME_CAP_S, with_stages=False,
                tile_args=tile_args,
                tile_capacity=bench.tile_capacity_default(th, tw),
            )
            results[geom].append(round(r["value"], 1))
            print(f"pass {rep} tile={geom}: {r['value']:.1f} img/s "
                  f"({r['seconds']:.1f} s)", flush=True)
    best = {g: max(v) for g, v in results.items()}
    print(json.dumps({
        "weather_fit": fit,
        "passes": results,
        "best": best,
        "winner": max(best, key=best.get),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
