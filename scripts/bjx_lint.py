#!/usr/bin/env python
"""Shim so CI and pre-commit hooks can run bjx-lint without installing
the package: ``python scripts/bjx_lint.py [args...]`` == ``python -m
blendjax.analysis [args...]`` run from the repo root (relative path
arguments are resolved against the INVOKER's cwd first, so the shim
really is runnable from anywhere)."""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VALUE_OPTS = {"--select", "--baseline", "--format", "--max-seconds"}

if __name__ == "__main__":
    # Pin positional path args to the invoker's cwd before we chdir to
    # the repo root (where the default targets and baseline live).
    # Option VALUES (--format json, --select BJX101) are never
    # rewritten, even if a same-named file happens to exist here.
    argv = []
    expect_value = False
    for a in sys.argv[1:]:
        if expect_value or a.startswith("-"):
            argv.append(a)
            expect_value = not expect_value and a in VALUE_OPTS
        else:
            argv.append(os.path.abspath(a) if os.path.exists(a) else a)
    sys.path.insert(0, REPO_ROOT)
    os.chdir(REPO_ROOT)
    from blendjax.analysis.__main__ import main

    sys.exit(main(argv))
