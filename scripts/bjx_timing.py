"""Chained-reps device timing shared by the perf scripts.

The only honest method on tunneled backends (docs/performance.md
"Measurement hygiene"): chain ``reps`` calls between two d2h fetches
and subtract a measured bare fetch, so the ~0.1 s sync constant
divides out.
"""

from __future__ import annotations

import time

import numpy as np


def sync(x) -> None:
    """Block on (and fetch one element of) the result's last leaf."""
    import jax

    np.asarray(jax.tree_util.tree_leaves(x)[-1]).reshape(-1)[-1]


def timed(fn, args, reps: int, sync=sync) -> float:
    """Seconds per call of ``fn(*args)`` over ``reps`` chained calls
    (first call untimed: compile/warm)."""
    out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    sync(out)
    total = time.perf_counter() - t0
    t1 = time.perf_counter()
    sync(out)
    bare = time.perf_counter() - t1
    return max(total - bare, 1e-9) / reps
