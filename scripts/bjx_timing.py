"""Chained-reps device timing shared by the perf scripts.

The only honest method on tunneled backends (docs/performance.md
"Measurement hygiene"): chain ``reps`` calls between two d2h fetches
and subtract a measured bare fetch, so the ~0.1 s sync constant
divides out.
"""

from __future__ import annotations

import time

import numpy as np


def sync(x) -> None:
    """Block on (and fetch one element of) the result's last leaf."""
    import jax

    np.asarray(jax.tree_util.tree_leaves(x)[-1]).reshape(-1)[-1]


def timed(fn, args, reps: int, sync=sync) -> float:
    """Seconds per call of ``fn(*args)`` over ``reps`` chained calls
    (first call untimed: compile/warm).

    Caveat: each rep is a separate host dispatch. In the tunnel's
    stall modes a dispatch costs 40-250+ ms, so rankings from this
    method reflect dispatch count, not device compute — use
    :func:`timed_one_dispatch` there."""
    out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    sync(out)
    total = time.perf_counter() - t0
    t1 = time.perf_counter()
    sync(out)
    bare = time.perf_counter() - t1
    return max(total - bare, 1e-9) / reps


def timed_one_dispatch(make_stage, reps: int) -> float:
    """Seconds per iteration of ``make_stage(c)`` with ALL reps inside
    one jitted ``fori_loop`` — a single host dispatch and a scalar
    fetch, so per-dispatch tunnel stalls cannot pollute the figure:
    this measures pure device compute even in collapsed windows.

    ``make_stage`` takes an int32 carry scalar and must fold it into
    its input (e.g. ``buf ^ c.astype(uint8)``): the loop carries one
    output element back as ``c``, making the body loop-VARIANT — with
    constant inputs XLA would hoist the whole stage out of the loop
    and the timing would measure nothing. The xor pass over the input
    is the method's overhead; keep the perturbed input small relative
    to the stage's real traffic.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(c0):
        def body(_, c):
            out = make_stage(c)
            # Reduce over EVERY element: a single-element carry lets
            # XLA dead-code-eliminate the rest of the stage (observed:
            # a broadcast+concat stage "measured" 0.0 ms). The fused
            # convert+reduce pass over the output is the remaining
            # method overhead, alongside the input xor.
            return out.astype(jnp.int32).sum() & 1

        return jax.lax.fori_loop(0, reps, body, c0)

    np.asarray(run(jnp.int32(0)))  # compile + warm
    t0 = time.perf_counter()
    np.asarray(run(jnp.int32(0)))
    total = time.perf_counter() - t0
    # Sync constant: a fresh trivial dispatch + scalar fetch (a CACHED
    # re-fetch would measure ~0 and under-correct; jax caches
    # np.asarray results on the Array).
    tiny = jax.jit(lambda c: c * 0)
    np.asarray(tiny(jnp.int32(0)))  # compile
    t1 = time.perf_counter()
    np.asarray(tiny(jnp.int32(1)))
    bare = time.perf_counter() - t1
    return max(total - bare, 1e-9) / reps
