#!/usr/bin/env bash
# One-command local run of the opt-in real-Blender CI job
# (.github/workflows/ci.yml `blender-tests`), degrading gracefully when
# no Blender binary can exist (this dev image has no Blender and no
# egress): every step that does not require the binary executes for
# real, and the Blender-dependent steps run only if `blender` is
# found on PATH (or after `BLENDER_INSTALL=1` fetches one via
# scripts/install_blender.sh on a networked machine).
#
# Usage:
#   scripts/blender_ci_dryrun.sh                 # validate; run tier if blender exists
#   BLENDER_INSTALL=1 scripts/blender_ci_dryrun.sh   # download Blender 3.6 LTS first
#
# Exit 0 = everything runnable here passed ("dry-run green minus the
# Blender step"); the summary names what was skipped.
set -uo pipefail
cd "$(dirname "$0")/.."

fail=0
skipped=()

step() { echo; echo "== $1"; }

step "install_blender.sh syntax"
bash -n scripts/install_blender.sh || fail=1

step "install_producer.py compiles"
python -m py_compile scripts/install_producer.py || fail=1

step "blender-marked tests collect"
# The tier's test selection must resolve (imports, fixtures, marker
# registration) even without the binary.
python -m pytest tests -m blender -q --collect-only >/tmp/bjx_blender_collect.txt 2>&1
rc=$?
if [ $rc -eq 5 ]; then
    # pytest rc 5 = collection succeeded but ZERO tests matched the
    # marker — a legitimate tree state (e.g. the blender tier pruned),
    # not a collection failure; name it and move on
    echo "no blender-marked tests in the tree (pytest rc 5)"
    skipped+=("blender-marked tests (none collected)")
elif [ $rc -ne 0 ]; then
    tail -5 /tmp/bjx_blender_collect.txt
    fail=1
else
    grep -E "test[s]? (selected|collected)|selected" /tmp/bjx_blender_collect.txt | tail -1
fi

step "producer fixtures execute against the fake runtime"
# The same fixtures the real tier runs, driven through the production
# launcher+finder against blendjax.testing's blender CLI emulator —
# the strongest no-binary proxy for the real job.
python -m pytest tests/test_fake_blender.py -q || fail=1

if [ "${BLENDER_INSTALL:-0}" = "1" ] && ! command -v blender >/dev/null; then
    step "install Blender 3.6 LTS"
    scripts/install_blender.sh && source .envs || fail=1
fi

if command -v blender >/dev/null; then
    step "blender: install producer package into Blender's Python"
    blender --background --python scripts/install_producer.py || fail=1
    blender --background --python-use-system-env \
        --python-expr "import blendjax.producer; print('producer OK')" \
        || fail=1
    step "blender-marked tests (ground truth)"
    python -m pytest tests -m blender -q || fail=1
else
    skipped+=("blender binary steps (no blender on PATH; BLENDER_INSTALL=1 to fetch)")
fi

echo
if [ $fail -ne 0 ]; then
    echo "DRYRUN FAILED"
    exit 1
fi
# ${skipped[*]-} (with the `-` default): expanding an EMPTY array under
# `set -u` is an "unbound variable" error on bash < 4.4, and macOS
# ships 3.2
if [ -n "${skipped[*]-}" ]; then
    printf 'DRYRUN GREEN (skipped: %s)\n' "${skipped[*]-}"
else
    echo "FULL TIER GREEN"
fi
