#!/usr/bin/env python
"""CI gate: a bench-exported Chrome trace must show at least one
sampled frame as CONNECTED flow events across distinct producer and
consumer process lanes (docs/observability.md "Tracing a frame").

Checks, on ``traceEvents``:

- non-empty and JSON-parseable (the load itself);
- at least one flow pair — an ``s`` (start) and ``f`` (finish) event
  sharing an ``id`` on DIFFERENT pids: the producer → consumer arrow;
- ``frame_trace`` stage-transition slices (``ph: "X"``) exist, each
  with a non-negative duration;
- every pid appearing in a frame-trace event has a ``process_name``
  metadata record, so the lanes are labeled in the viewer.

Usage: ``python scripts/check_frame_trace.py TRACE.json``
"""

from __future__ import annotations

import json
import sys
from collections import defaultdict


def main(path: str) -> None:
    with open(path, encoding="utf-8") as f:
        trace = json.load(f)
    events = trace.get("traceEvents") or []
    assert events, f"{path}: empty traceEvents"

    flows: dict = defaultdict(lambda: {"s": set(), "f": set()})
    slices = []
    named_pids = set()
    frame_pids = set()
    for e in events:
        ph = e.get("ph")
        if ph in ("s", "f"):
            flows[e["id"]][ph].add(e["pid"])
            frame_pids.add(e["pid"])
        elif ph == "X" and e.get("cat") == "frame_trace":
            slices.append(e)
            frame_pids.add(e["pid"])
        elif ph == "M" and e.get("name") == "process_name":
            named_pids.add(e["pid"])

    connected = [
        fid for fid, v in flows.items()
        if v["s"] and v["f"] and v["s"] != v["f"]
    ]
    assert connected, (
        f"{path}: no flow pair crosses process lanes "
        f"(flows: {dict(flows)})"
    )
    assert slices, f"{path}: no frame_trace stage slices"
    bad = [e for e in slices if e.get("dur", 0) < 0]
    assert not bad, f"{path}: negative-duration slices: {bad[:3]}"
    unnamed = frame_pids - named_pids
    assert not unnamed, f"{path}: unlabeled process lanes: {unnamed}"
    print(
        f"{path}: OK — {len(connected)} cross-lane frame flow(s), "
        f"{len(slices)} stage slices, {len(frame_pids)} lanes"
    )


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "bench-frame-trace.json")
