"""Real-TPU check of the direct-spatial tile decode: bit-exactness vs
the XLA scatter at flagship geometry, plus a chained-slope timing of the
full decode (palette expand + kernel) for the spatial (16, 32) kernel
against the slot (16, 16) kernel chain it replaces.

Run: ``PYTHONPATH=.:$PYTHONPATH python scripts/check_spatial_decode.py``.
Timing uses the chained-reps method of docs/performance.md "Measurement
hygiene" (the only honest method on tunneled backends).
"""

from __future__ import annotations

import argparse

import numpy as np

from bjx_timing import sync, timed


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=128)
    args = ap.parse_args()

    import jax

    import blendjax.ops.tiles as T
    from blendjax.producer.sim import CubeScene

    H, W, C = 480, 640, 4
    B = args.batch
    scene = CubeScene(shape=(H, W), seed=0)
    ref = scene.background_image()

    # Real flagship-scene frames (flat-shaded -> palettizable), so the
    # two tile geometries compare on the actual workload.
    frames = []
    for f in range(1, 5):
        scene.step(f)
        frames.append(scene.render().copy())

    results = {}
    for tag, tile, kcap in (("slot 16x16", 16, 288),
                            ("spatial 16x32", (16, 32), 160)):
        enc = T.TileDeltaEncoder(ref, tile=tile)
        deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
        idx, tiles = T.pack_batch(deltas, enc.num_tiles, capacity=kcap)
        idx = np.tile(idx, (B // len(frames), 1))
        tiles = np.tile(tiles, (B // len(frames), 1, 1, 1, 1))
        rt = jax.device_put(np.asarray(T.tile_ref(ref, tile)))
        pal = T.palettize_tiles(tiles)
        assert pal is not None, "synthetic tiles should palettize"
        packed, palette, bits = pal
        packed_d = jax.device_put(packed)
        pal_d = jax.device_put(palette)
        idx_d = jax.device_put(idx)
        th, tw = T.tile_hw(tile)

        def full(p, q, i, r, _bits=bits, _tile=tile, _th=th, _tw=tw):
            tl = T.expand_palette_tiles(p, q, _bits, _tile, C)
            return T.decode_tile_delta(r, i, tl, (H, W, C))

        jfull = jax.jit(full)
        out = np.asarray(jfull(packed_d, pal_d, idx_d, rt))
        want = T.decode_tile_delta_np(
            ref, idx, T.expand_palette_tiles_np(packed, palette, bits,
                                                tile, C))
        np.testing.assert_array_equal(out, want)
        print(f"{tag}: bit-exact ok (K={idx.shape[1]}, "
              f"{packed.nbytes / B / 1024:.1f} KB/img packed)")
        results[tag] = timed(
            jfull, (packed_d, pal_d, idx_d, rt), args.reps, sync
        )

    for tag, dt in results.items():
        print(f"{tag}: {dt * 1000:8.1f} ms/group ({B / dt:7.0f} img/s)")


if __name__ == "__main__":
    main()
