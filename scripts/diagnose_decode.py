"""Per-stage slopes of the tile-decode chain on a real TPU.

Quantifies where a chunk group's device time goes — palette expand,
ref-broadcast base init, Pallas slot scatter (incl. transpose to
frames), the one-pass direct-spatial (16, 32) decode that replaces all
three, and the train step — using the ONLY timing method that is honest on
tunneled backends (docs/performance.md "Measurement hygiene"): chain
``--reps`` iterations of each stage between two d2h fetches and report
the slope, so the ~0.1s sync constant divides out.

Run: ``python scripts/diagnose_decode.py [--reps 8]``. Prints one line
per stage. Feeds the r4->r5 lever ranking in PARITY.md.

``--one-dispatch`` re-times every device stage with ALL reps inside one
jitted ``fori_loop`` (``bjx_timing.timed_one_dispatch``): a single host
dispatch, so the figures are pure device compute and remain honest in
the tunnel's stall modes, where the default per-rep dispatching
measures the stall, not the op (observed: the same chain ranked 1.85x
FASTER in a fit window and ~2x SLOWER in a collapsed one under per-rep
dispatch). The loop perturbs each stage's input with the carried
output bit, so XLA cannot hoist the loop-invariant stage; the xor pass
over the input is the method's (small) overhead. The host->device
transfer row is inherently per-dispatch and is skipped in this mode.
"""

from __future__ import annotations

import argparse

import numpy as np

from bjx_timing import sync, timed, timed_one_dispatch


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=128,
                    help="frames per chunk group (chunk*B)")
    ap.add_argument("--one-dispatch", action="store_true",
                    help="reps inside one fori_loop: pure device "
                    "compute, honest in tunnel stall modes")
    args = ap.parse_args()
    if args.batch % 8:
        ap.error("--batch must be a multiple of 8 (the step's B)")

    import jax
    import jax.numpy as jnp

    import blendjax.ops.tiles as T
    from blendjax.models import CubeRegressor
    from blendjax.parallel import create_mesh
    from blendjax.train import make_chunked_supervised_step, make_train_state

    B, K, t, C, N = args.batch, 288, 16, 4, 1200
    H, W = 480, 640
    tt, lanes = t * t, t * t * C // 8
    rng = np.random.default_rng(0)
    palidx = rng.integers(0, 4, (B, K, tt), np.uint8)
    packed2 = jax.device_put(T.pack_palette_indices(palidx, 2))
    pal_d = jax.device_put(
        rng.integers(0, 255, (B, 4, C)).astype(np.uint8)
    )
    idx_d = jax.device_put(
        np.sort(rng.choice(N, (B, K), replace=True)).astype(np.int32)
    )
    ref = rng.integers(0, 255, (H, W, C), np.uint8)
    ref_tiles = jax.device_put(np.asarray(T.tile_ref(ref, t)))
    raw_tiles = jax.device_put(
        rng.integers(0, 255, (B, K, t, t, C), np.uint8)
    )

    expand = jax.jit(
        lambda p, q: T.expand_palette_tiles(p, q, 2, t, C)
    )
    base_init = jax.jit(
        lambda r: jnp.concatenate([
            jnp.broadcast_to(r.reshape(1, N, 8, lanes), (B, N, 8, lanes)),
            jnp.zeros((B, 1, 8, lanes), jnp.uint8),
        ], axis=1)
    )
    scatter = jax.jit(
        lambda i, tl, r: T.decode_tile_delta(r, i, tl, (H, W, C))
    )
    full_decode = jax.jit(
        lambda p, q, i, r: T.decode_tile_delta(
            r, i, T.expand_palette_tiles(p, q, 2, t, C), (H, W, C)
        )
    )

    mesh = create_mesh({"data": -1})
    state = make_train_state(
        CubeRegressor(), np.zeros((8, H, W, 4), np.uint8), mesh=mesh
    )
    step = make_chunked_supervised_step()
    frames = jax.device_put(
        rng.integers(0, 255, (B // 8, 8, H, W, 4), np.uint8)
    )
    xy = jax.device_put(
        (rng.random((B // 8, 8, 8, 2)) * W).astype(np.float32)
    )

    host_buf = np.ascontiguousarray(
        rng.integers(0, 255, (B * 19 * 1024,), np.uint8)
    )  # ~19KB/img: the pal2-era wire size

    # Rectangular (16, 32) twin of the same workload: tile count halves
    # (same pixel activity), tt doubles, and decode_tile_delta takes the
    # direct-spatial kernel (no base init, no transpose) — the r4 lever.
    Kr, ttr = K // 2, (16, 32)
    ghr, gwr = T.tile_grid((H, W, C), ttr)
    Nr = ghr * gwr
    palidx_r = rng.integers(0, 4, (B, Kr, ttr[0] * ttr[1]), np.uint8)
    packed2_r = jax.device_put(T.pack_palette_indices(palidx_r, 2))
    idx_r = jax.device_put(
        np.sort(rng.choice(Nr, (B, Kr), replace=True)).astype(np.int32)
    )
    ref_tiles_r = jax.device_put(np.asarray(T.tile_ref(ref, ttr)))
    full_decode_r = jax.jit(
        lambda p, q, i, r: T.decode_tile_delta(
            r, i, T.expand_palette_tiles(p, q, 2, ttr, C), (H, W, C)
        )
    )

    if args.one_dispatch:
        def xor8(buf, c):
            return buf ^ c.astype(jnp.uint8)

        def dec(geom, packed, idx, ref_t, c):
            """The ONE definition of the pal2 expand+decode chain; both
            geometries and both (standalone / step-fed) timings use it."""
            return T.decode_tile_delta(
                ref_t, idx,
                T.expand_palette_tiles(xor8(packed, c), pal_d, 2, geom,
                                       C),
                (H, W, C),
            )

        results = {
            "palette expand (pal2)": timed_one_dispatch(
                lambda c: T.expand_palette_tiles(
                    xor8(packed2, c), pal_d, 2, t, C
                ), args.reps,
            ),
            "base init (ref broadcast+concat)": timed_one_dispatch(
                lambda c: base_init(xor8(ref_tiles, c)), args.reps,
            ),
            "scatter+transpose (raw tiles)": timed_one_dispatch(
                lambda c: T.decode_tile_delta(
                    ref_tiles, idx_d, xor8(raw_tiles, c), (H, W, C)
                ), args.reps,
            ),
            "full decode (expand+scatter)": timed_one_dispatch(
                lambda c: dec(t, packed2, idx_d, ref_tiles, c),
                args.reps,
            ),
            "full decode (expand+spatial 16x32)": timed_one_dispatch(
                lambda c: dec(ttr, packed2_r, idx_r, ref_tiles_r, c),
                args.reps,
            ),
        }

        # No donation for the loop-wrapped step: every iteration reuses
        # the same captured state, so its buffers must survive.
        step_nodonate = make_chunked_supervised_step(donate=False)

        def step_stage(c):
            _, m = step_nodonate(
                state, {"image": xor8(frames, c), "xy": xy}
            )
            return m["loss"]

        step_reps = max(2, args.reps // 4)
        step_dt = timed_one_dispatch(step_stage, step_reps)
        results["train step (chunked)"] = step_dt

        # Decode feeding its REAL consumer: the sum-carry rows above
        # under-measure XLA stages whose tails the reducer can
        # algebraically skip (sum(transpose(x)) drops the transpose;
        # sum(broadcast(x)) folds to a scalar multiply — the 0.0 ms
        # base-init row). The train step consumes every decoded pixel
        # through convs, so decode+step MINUS the step row is the
        # honest marginal device cost of each variant (slightly
        # optimistic vs production's separate jits: here XLA may fuse
        # across the decode/step boundary).
        def dstep(geom, packed, idx, ref_t):
            def stage(c):
                fr = dec(geom, packed, idx, ref_t, c).reshape(
                    B // 8, 8, H, W, C
                )
                _, m = step_nodonate(state, {"image": fr, "xy": xy})
                return m["loss"]

            return timed_one_dispatch(stage, step_reps)

        results["decode 16x16 marginal (via step consumer)"] = max(
            dstep(t, packed2, idx_d, ref_tiles) - step_dt, 1e-9
        )
        results["decode 16x32 marginal (via step consumer)"] = max(
            dstep(ttr, packed2_r, idx_r, ref_tiles_r) - step_dt, 1e-9
        )
    else:
        results = {
            "transfer (pal2-sized buffer)": timed(
                jax.device_put, (host_buf,), args.reps, sync
            ),
            "palette expand (pal2)": timed(
                expand, (packed2, pal_d), args.reps, sync
            ),
            "base init (ref broadcast+concat)": timed(
                base_init, (ref_tiles,), args.reps, sync
            ),
            "scatter+transpose (raw tiles)": timed(
                scatter, (idx_d, raw_tiles, ref_tiles), args.reps, sync
            ),
            "full decode (expand+scatter)": timed(
                full_decode, (packed2, pal_d, idx_d, ref_tiles),
                args.reps, sync,
            ),
            "full decode (expand+spatial 16x32)": timed(
                full_decode_r, (packed2_r, pal_d, idx_r, ref_tiles_r),
                args.reps, sync,
            ),
        }

        cell = {"state": state}  # the step donates its state buffers

        def run_step(fr, xy_):
            cell["state"], m = step(
                cell["state"], {"image": fr, "xy": xy_}
            )
            return m["loss"]

        results["train step (chunked)"] = timed(
            run_step, (frames, xy), args.reps, sync
        )

    for name, dt in results.items():
        print(f"{name}: {dt * 1000:8.1f} ms/group  "
              f"({args.batch / dt:7.0f} img/s)")


if __name__ == "__main__":
    main()
