"""Experiment: byte-LUT palette expand vs the unpack+gather chain.

For bits=2 the current expand unpacks each packed byte into four 2-bit
indices (shifts + stack + reshape) then gathers the palette per pixel.
A per-frame 256-entry LUT (byte value -> 4 pixels x C bytes, built on
device from the (cap, C) palette) collapses that to ONE gather per
packed byte. The LUT form IS the library path since r4
(``blendjax.ops.tiles._lut_expand``); this script reproduces the
decision by ranking it against the inlined pre-r4 chain on the real
chip (chained-reps timing; relative ranking is meaningful even in
degraded tunnel weather — measured 1.23-1.33x across windows).

Run: ``PYTHONPATH=.:$PYTHONPATH python scripts/exp_lut_expand.py``.
"""

from __future__ import annotations

import argparse

import numpy as np

from bjx_timing import sync, timed


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=128)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import blendjax.ops.tiles as T

    B, K, th, tw, C = args.batch, 160, 16, 32, 4
    tt = th * tw
    rng = np.random.default_rng(0)
    palidx = rng.integers(0, 4, (B, K, tt), np.uint8)
    packed = jax.device_put(T.pack_palette_indices(palidx, 2))
    pal = jax.device_put(rng.integers(0, 255, (B, 4, C)).astype(np.uint8))

    # Baseline inlines the PRE-r4 unpack+gather chain (the library's
    # expand_palette_tiles now dispatches to the LUT itself, so calling
    # it here would compare LUT vs LUT).
    def unpack_gather(p, q):
        def one(pk, qq):
            idx = T.unpack_palette_indices(pk, 2, jnp)
            return qq[idx].reshape(K, th, tw, C)

        return jax.vmap(one)(p, q)

    current = jax.jit(unpack_gather)
    jlut = jax.jit(
        lambda p, q: jax.vmap(
            lambda pk, qq: T._lut_expand(pk, qq, 2)
        )(p, q).reshape(B, K, th, tw, C)
    )
    a = np.asarray(current(packed, pal))
    b = np.asarray(jlut(packed, pal))
    np.testing.assert_array_equal(a, b)
    print("bit-exact ok")
    t_cur = timed(current, (packed, pal), args.reps, sync)
    t_lut = timed(jlut, (packed, pal), args.reps, sync)
    print(f"unpack+gather: {t_cur * 1000:8.1f} ms/group")
    print(f"byte-LUT     : {t_lut * 1000:8.1f} ms/group "
          f"({t_cur / t_lut:.2f}x)")


if __name__ == "__main__":
    main()
