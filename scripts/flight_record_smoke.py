#!/usr/bin/env python
"""CI smoke: drive the real reporter → watchdog → flight-recorder
chain through a synthetic sustained breach and verify the bundle
parses (docs/observability.md "SLOs and the flight recorder").

No producers, no jax: a private registry is fed healthy counters for a
few manual reporter ticks, then starved so ``rate(ingest.items) >= 50``
breaches; the dump must contain parseable ``breach.json``,
``snapshots.jsonl`` (with doctor verdicts), ``lineage.json``, and
``trace.json`` (a loadable Chrome trace). The hermetic pytest suite
covers the live producer-kill version; this script exists so the CI
artifact upload always has a real bundle to ship.

Usage: ``python scripts/flight_record_smoke.py [OUT_DIR]``
"""

from __future__ import annotations

import json
import os
import sys

from blendjax.obs.reporter import StatsReporter
from blendjax.utils.metrics import Metrics


def main(out_dir: str) -> None:
    reg = Metrics()
    reg.enable_span_events()
    rep = StatsReporter(
        interval_s=3600.0,  # ticked manually below, never by thread
        registry=reg,
        slos=["rate(ingest.items) >= 50"],
        flight_dir=out_dir,
    )
    # healthy ticks: ~100 items/s between evaluations
    reg.count("ingest.items", 100)
    with reg.span("ingest.recv"):
        pass
    rep.tick()
    reg.count("ingest.items", 100)
    rep.tick()
    # starvation: no new items -> rate 0 < 50 -> breach + dump
    rep.tick()
    assert rep.healthy is False, rep.health()
    assert rep.watchdog.state()["breached"], rep.watchdog.state()

    bundles = sorted(
        d for d in os.listdir(out_dir) if d.startswith("flight-")
    )
    assert bundles, f"no bundle written under {out_dir}"
    bundle = os.path.join(out_dir, bundles[-1])
    breach = json.load(open(os.path.join(bundle, "breach.json")))
    assert breach["slo"], breach
    snaps = [
        json.loads(line)
        for line in open(os.path.join(bundle, "snapshots.jsonl"))
    ]
    assert snaps and all("doctor" in s for s in snaps), snaps[:1]
    trace = json.load(open(os.path.join(bundle, "trace.json")))
    assert "traceEvents" in trace, sorted(trace)
    print(f"{bundle}: OK — {len(snaps)} snapshots, breach parsed")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "flight-records")
