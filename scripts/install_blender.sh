#!/usr/bin/env bash
# Headless Blender bootstrap for the opt-in real-Blender test tier.
#
# Counterpart of the reference's scripts/install_blender.sh (download a
# pinned tarball, cache it, extract, emit a PATH export) updated to a
# Blender LTS whose bundled Python (3.10) can import blendjax — the
# package uses 3.10+ syntax.
#
# Usage:
#   scripts/install_blender.sh          # download + extract + write .envs
#   source .envs                        # put blender on PATH
#   blender --background --python scripts/install_producer.py
#   BLENDJAX_TEST_BLENDER=1 pytest tests -m blender
set -euo pipefail

VERSION="${BLENDER_VERSION:-3.6.5}"
SERIES="${VERSION%.*}"
NAME="blender-${VERSION}-linux-x64"
NAMETAR="${NAME}.tar.xz"
CACHE="${BLENDER_CACHE:-${HOME}/.blender-cache}"
TAR="${CACHE}/${NAMETAR}"
DEST="${BLENDER_DEST:-${HOME}}"
URL="https://download.blender.org/release/Blender${SERIES}/${NAMETAR}"

echo "Installing Blender ${NAME} -> ${DEST}/${NAME}"
mkdir -p "${CACHE}"
if [ ! -f "${TAR}" ]; then
    # Download to a temp name and mv on success: an interrupted transfer
    # must not leave a truncated tarball at the cached path (CI caches
    # the directory under an immutable key and would never self-heal).
    wget -q --show-progress -O "${TAR}.part" "${URL}"
    mv "${TAR}.part" "${TAR}"
fi
tar -xf "${TAR}" -C "${DEST}"

# Consumed by CI (`source .envs`) like the reference's .travis.yml:15-17.
echo "export PATH=\"\${PATH}:${DEST}/${NAME}\"" > .envs
echo "wrote .envs; run: source .envs"
