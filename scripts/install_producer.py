"""Install the blendjax producer package into Blender's bundled Python.

Counterpart of the reference's ``scripts/install_btb.py:23-41`` (which
pip-installs ``blendtorch.btb`` into Blender via the interpreter path
Blender reports about itself). Run it THROUGH Blender so the right
interpreter self-reports:

    blender --background --python scripts/install_producer.py -- [--user]

Installs blendjax plus the producer-side deps (pyzmq, msgpack, numpy);
the JAX stack is intentionally NOT installed into Blender.
"""

from __future__ import annotations

import os
import subprocess
import sys


def blender_python() -> str:
    import bpy  # only importable when run through Blender

    # Blender >= 2.91 exposes the interpreter via sys.executable; older
    # builds report it as bpy.app.binary_path_python.
    return getattr(bpy.app, "binary_path_python", None) or sys.executable


def main() -> None:
    py = blender_python()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    args = sys.argv[sys.argv.index("--") + 1:] if "--" in sys.argv else []
    cmd = [py, "-m", "pip", "install", *args, repo, "pyzmq", "msgpack"]
    print("running:", " ".join(cmd))
    subprocess.run(cmd, check=True)
    out = subprocess.run(
        [py, "-c", "import blendjax.producer, zmq; print('producer OK')"],
        capture_output=True, text=True,
    )
    print(out.stdout or out.stderr)


if __name__ == "__main__":
    main()
