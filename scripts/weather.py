"""Tunnel-weather thermometer for the dev box.

The TPU sits behind a tunnel with three observed modes (memory +
docs/performance.md "Caveat on recorded numbers"):

- good: d2h RTT ~0.1 s, end-to-end ~500-600 img/s;
- bandwidth-collapsed: RTT still ~0.1 s but passes at ~20-100 img/s;
- hard-stall/outage: RTT 3-58 s, or single device calls blocking for
  10+ minutes.

Run before any perf work: ``python scripts/weather.py [--pass]``.
The default run probes RTT and h2d bandwidth (an 8 MB incompressible
transfer — catches the bandwidth-collapsed mode in seconds);
``--pass`` adds one real end-to-end measurement pass (~10-45 s in any
completing weather) as the definitive check. Exits nonzero when the
window is not fit for measurement.
"""

from __future__ import annotations

import os
import signal
import sys
import time

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    # Hard-stall guard: the mode this script exists to detect can block
    # a single device call for 10+ minutes — a thermometer must answer.
    def on_alarm(*_):
        print("probe stalled: HARD-STALL/OUTAGE mode")
        os._exit(4)

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(int(os.environ.get("BLENDJAX_WEATHER_DEADLINE_S", "300")))

    import jax

    try:
        np.asarray(jax.device_put(np.zeros(8, np.uint8)))  # untimed init
        t0 = time.perf_counter()
        np.asarray(jax.device_put(np.zeros(8, np.uint8)))
        rtt = time.perf_counter() - t0
    except Exception as e:
        print(f"probe failed: {e!r}")
        return 5
    print(f"d2h rtt: {rtt * 1000:.0f} ms "
          f"({'ok' if rtt < 0.5 else 'DEGRADED'})")
    if rtt >= 0.5:
        return 2
    # Bandwidth probe: the collapsed mode keeps a healthy RTT, so only
    # a sized transfer exposes it (~43 MB/s good-weather h2d measured
    # in BENCH_r03; collapsed windows sit at ~5-15 MB/s). Same probe
    # the bench stamps into its record as link_h2d_MB_s.
    sys.path.insert(0, REPO_ROOT)
    from bench import FIT_H2D_MBS, probe_link_bandwidth

    mbs = probe_link_bandwidth(rtt)
    if mbs is None:
        print("h2d bandwidth: probe failed")
        return 5
    # FIT_H2D_MBS bar (bench.py owns it — the bench's in-record per-pass
    # gate and this preflight must agree): good windows measure ~43; a
    # 27-29 MB/s window passed a 25 bar once and still ran end-to-end
    # passes at ~22 img/s (the tunnel flapped right after the probe), so
    # the bar sits close to the good-weather figure. --pass remains the
    # definitive check.
    print(f"h2d bandwidth: {mbs:.0f} MB/s "
          f"({'ok' if mbs >= FIT_H2D_MBS else 'BANDWIDTH-COLLAPSED'})")
    if mbs < FIT_H2D_MBS:
        return 3
    if "--pass" not in sys.argv:
        return 0

    import bench

    # Same config + floor the bench itself gates retries on, so the
    # preflight verdict can't drift from the run it predicts.
    floor = float(
        os.environ.get(
            "BLENDJAX_BENCH_RETRY_FLOOR", bench.RETRY_FLOOR_DEFAULT
        )
    )
    r = bench.measure(bench.ENCODING, bench.CHUNK, 512, 45.0,
                      with_stages=False)
    good = r["value"] > floor
    print(f"measurement pass: {r['value']} img/s in {r['seconds']} s "
          f"({'ok' if good else 'BANDWIDTH-COLLAPSED'})")
    return 0 if good else 3


if __name__ == "__main__":
    sys.exit(main())
