"""Blender fixture: record the animation lifecycle signal order.

Paired with tests/test_blender.py::test_blender_animation_lifecycle
(reference pairing: ``tests/test_animation.py:7-26`` with
``tests/blender/anim.blend.py:8-39`` — two episodes of frames 1..3 must
produce pre_play -> [pre_animation -> (pre_frame -> post_frame) x N ->
post_animation] x 2 -> post_play).
"""

import sys

from blendjax.transport import term_context
from blendjax.producer import AnimationController, DataPublisher, parse_launch_args
from blendjax.producer.bpy_engine import BpyEngine


def main():
    args, _ = parse_launch_args(sys.argv)
    pub = DataPublisher(args.btsockets["DATA"], btid=args.btid, lingerms=5000)
    ctrl = AnimationController(BpyEngine())
    seq = []

    ctrl.pre_play.add(lambda: seq.append("pre_play"))
    ctrl.pre_animation.add(lambda: seq.append("pre_animation"))
    ctrl.pre_frame.add(lambda f: seq.append(f"pre_frame:{f}"))
    ctrl.post_frame.add(lambda f: seq.append(f"post_frame:{f}"))
    ctrl.post_animation.add(lambda: seq.append("post_animation"))

    def post_play():
        seq.append("post_play")
        pub.publish(seq=seq)

    ctrl.post_play.add(post_play)
    ctrl.play(frame_range=(1, 3), num_episodes=2)
    pub.close()
    term_context()  # flush the tail before Blender exits


main()
