"""Blender fixture: project known geometry through bpy-derived cameras.

Paired with tests/test_blender.py::test_blender_camera_projection
(reference pairing: ``tests/test_camera.py:10-49`` with
``tests/blender/cam.blend.py`` + the prepared ``cam.blend`` scene holding
an ortho and a perspective camera).

Instead of a binary .blend asset, this script CONSTRUCTS the scene:
a unit cube at a known offset plus one ortho and one perspective camera
with pinned poses/intrinsics — so the consumer test can compute the
expected pixels analytically with blendjax's standalone Camera and
assert the bpy-derived projection matches.
"""

import math
import sys

import bpy

from blendjax.transport import term_context
from blendjax.producer import DataPublisher, parse_launch_args
from blendjax.producer.bpy_engine import (
    camera_from_bpy,
    world_coordinates,
)
from blendjax.producer.camera import Camera


def _scene():
    bpy.ops.mesh.primitive_cube_add(size=2.0, location=(0.5, -0.25, 0.75))
    cube = bpy.context.active_object
    cube.name = "TestCube"

    def add_cam(name, kind, **props):
        cam_data = bpy.data.cameras.new(name)
        cam_data.type = kind
        for k, v in props.items():
            setattr(cam_data, k, v)
        cam = bpy.data.objects.new(name, cam_data)
        bpy.context.collection.objects.link(cam)
        return cam

    proj = add_cam("CamProj", "PERSP", lens=50.0, sensor_width=36.0,
                   clip_start=0.1, clip_end=100.0)
    proj.location = (8.0, -8.0, 6.0)
    proj.rotation_euler = (math.radians(60.0), 0.0, math.radians(45.0))

    ortho = add_cam("CamOrtho", "ORTHO", ortho_scale=12.0,
                    clip_start=0.1, clip_end=100.0)
    ortho.location = (0.0, 0.0, 10.0)
    ortho.rotation_euler = (0.0, 0.0, 0.0)

    render = bpy.context.scene.render
    render.resolution_x, render.resolution_y = 640, 480
    render.resolution_percentage = 100
    bpy.context.view_layer.update()
    return cube, proj, ortho


def main():
    args, _ = parse_launch_args(sys.argv)
    cube, proj, ortho = _scene()
    xyz = world_coordinates(cube)

    cam_p = camera_from_bpy(Camera, proj)
    cam_o = camera_from_bpy(Camera, ortho)
    pix_p, z_p = cam_p.world_to_pixel(xyz, return_depth=True)
    pix_o, z_o = cam_o.world_to_pixel(xyz, return_depth=True)

    pub = DataPublisher(args.btsockets["DATA"], btid=args.btid, lingerms=5000)
    pub.publish(
        xyz=xyz,
        proj_xy=pix_p, proj_z=z_p,
        ortho_xy=pix_o, ortho_z=z_o,
        # raw camera params so the consumer can rebuild the SAME analytic
        # camera and assert bit-level agreement
        proj_pose=[list(r) for r in proj.matrix_world],
        ortho_pose=[list(r) for r in ortho.matrix_world],
    )
    pub.close()
    term_context()  # flush the tail before Blender exits


main()
