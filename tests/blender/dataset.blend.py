"""Blender fixture: stream fixed-size frames through the animation loop.

Paired with tests/test_blender.py::test_blender_stream_ingest (reference
pairing: ``tests/test_dataset.py:11-33`` with
``tests/blender/dataset.blend.py:5-17`` — 16 items of (64, 64) through
DataLoader workers).
"""

import sys

import numpy as np

from blendjax.transport import term_context
from blendjax.producer import AnimationController, DataPublisher, parse_launch_args
from blendjax.producer.bpy_engine import BpyEngine


def main():
    args, _ = parse_launch_args(sys.argv)
    pub = DataPublisher(args.btsockets["DATA"], btid=args.btid, lingerms=5000)
    ctrl = AnimationController(BpyEngine())

    def post_frame(frame):
        pub.publish(
            frameid=frame,
            img=np.full((64, 64), frame % 251, dtype=np.uint8),
        )

    ctrl.post_frame.add(post_frame)
    # 4 episodes x frames 1..4 = 16 messages, then exit.
    ctrl.play(frame_range=(1, 4), num_episodes=4)
    pub.close()
    term_context()  # flush the tail before Blender exits


main()
