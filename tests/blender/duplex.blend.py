"""Blender fixture: echo one duplex message, then signal end.

Paired with tests/test_blender.py::test_blender_duplex_echo (reference
pairing: ``tests/test_duplex.py:9-47`` with
``tests/blender/duplex.blend.py:3-11`` — asserts btid/btmid stamping).
"""

import sys

from blendjax.transport import term_context
from blendjax.producer import DuplexChannel, parse_launch_args


def main():
    args, _ = parse_launch_args(sys.argv)
    duplex = DuplexChannel(
        args.btsockets["CTRL"], btid=args.btid, lingerms=5000
    )
    msg = duplex.recv(timeoutms=10000)
    duplex.send(echo=msg)
    duplex.send(msg="end")
    duplex.close()
    term_context()  # flush the tail before Blender exits


main()
