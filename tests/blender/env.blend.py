"""Blender fixture: minimal rotate-the-cube env served over the GYM RPC.

Paired with tests/test_blender.py::test_blender_remote_env (reference
pairing: ``tests/test_env.py:12-43`` with ``tests/blender/env.blend.py:
7-47`` — reset/step/reward/done semantics across two episodes).

Builds its own scene (a default cube) so no .blend asset is needed.
"""

import sys

import bpy

from blendjax.producer import BaseEnv, RemoteControlledAgent, parse_launch_args
from blendjax.producer.bpy_engine import BpyEngine


def _ensure_cube():
    if "Cube" not in bpy.data.objects:
        bpy.ops.mesh.primitive_cube_add()
        bpy.context.active_object.name = "Cube"
    return bpy.data.objects["Cube"]


class RotateEnv(BaseEnv):
    def __init__(self, agent, done_after=10):
        super().__init__(agent)
        self.cube = _ensure_cube()
        self.count = 0
        self.done_after = done_after

    def _env_reset(self):
        self.cube.rotation_euler[2] = 0.0
        self.count = 0

    def _env_prepare_step(self, action):
        self.cube.rotation_euler[2] = float(action)

    def _env_post_step(self):
        self.count += 1
        angle = float(self.cube.rotation_euler[2])
        return dict(
            obs=angle,
            reward=1.0 if abs(angle) > 0.5 else 0.0,
            done=self.events.frameid > self.done_after,
            count=self.count,
        )


def main():
    args, remainder = parse_launch_args(sys.argv)
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--done-after", default=10, type=int)
    opts = ap.parse_args(remainder)

    agent = RemoteControlledAgent(args.btsockets["GYM"])
    env = RotateEnv(agent, done_after=opts.done_after)
    try:
        env.run(BpyEngine(), frame_range=(1, 10000))
    finally:
        agent.close()


main()
