"""Blender fixture: publish the parsed launch handshake back to the test.

Paired with tests/test_blender.py::test_blender_launcher_handshake
(reference pairing: ``tests/test_launcher.py:20-44`` with
``tests/blender/launcher.blend.py:3-9`` — the producer echoes its argv so
the torch side can assert btid/seed/socket plumbing).
"""

import sys

from blendjax.transport import term_context
from blendjax.producer import DataPublisher, parse_launch_args


def main():
    args, remainder = parse_launch_args(sys.argv)
    # Linger so the single message is flushed before Blender exits.
    pub = DataPublisher(
        args.btsockets["DATA"], btid=args.btid, lingerms=10000
    )
    pub.publish(
        btid=args.btid,
        btseed=args.btseed,
        btsockets=list(args.btsockets),
        remainder=list(remainder),
    )
    pub.close()
    term_context()  # flush the tail before Blender exits


main()
