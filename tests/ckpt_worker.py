"""Subprocess worker for the kill -9 resume-equality tests.

Trains a tiny CubeRegressor on a DETERMINISTIC seeded stream through
the real mesh pipeline (StreamDataPipeline -> MeshTrainDriver) with
async checkpointing enabled, and writes its per-step f32 loss vector
to ``--out`` at the end. The parent test runs it three ways:

- uninterrupted (the reference trajectory),
- to-be-killed (``--pace`` slows the loop so the parent can observe a
  committed snapshot and SIGKILL mid-run),
- ``--resume`` (restores the latest snapshot — onto ``--mesh``, which
  may DIFFER from the snapshot's mesh: elastic resume — fast-forwards
  the deterministic stream by the restored step count, and continues
  to ``--steps``).

Equality of the resumed and uninterrupted loss vectors is the
acceptance contract: a restart is invisible to the training math.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

B = 8
HW = 16
SEED = 7


def _messages(n, skip=0):
    """The same deterministic message sequence every call (the
    recorded-stream stand-in): resuming = regenerating and skipping
    the consumed prefix, exactly like fast-forwarding a replay."""
    import numpy as np

    rng = np.random.default_rng(SEED)
    for i in range(n):
        msg = {
            "_prebatched": True,
            "btid": 0,
            "image": rng.integers(0, 255, (B, HW, HW, 4), np.uint8),
            "xy": (rng.random((B, 8, 2)) * HW).astype(np.float32),
        }
        if i >= skip:
            yield msg


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("directory")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--mesh", type=int, default=1)
    ap.add_argument("--ckpt-every", type=int, default=3)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--pace", type=float, default=0.0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from blendjax.checkpoint import SnapshotManager
    from blendjax.data import StreamDataPipeline
    from blendjax.models import CubeRegressor
    from blendjax.parallel import create_mesh
    from blendjax.parallel.sharding import state_shardings
    from blendjax.train import MeshTrainDriver, make_train_state
    from blendjax.train.mesh_driver import make_mesh_supervised_step

    mesh = create_mesh(
        {"data": args.mesh}, devices=jax.devices()[: args.mesh]
    )
    model = CubeRegressor(features=(8,))
    example = np.zeros((B, HW, HW, 4), np.uint8)
    mgr = SnapshotManager(args.directory, keep=3)
    state = make_train_state(model, example, mesh=mesh)
    start = 0
    restored_driver = None
    if args.resume:
        restored = mgr.restore(
            state, shardings=state_shardings(state, mesh=mesh)
        )
        assert restored is not None, "resume requested but no snapshot"
        state = restored.state
        restored_driver = restored.session["driver"]
        start = int(restored_driver["steps"])
    step = make_mesh_supervised_step(state, mesh)
    drv = MeshTrainDriver(
        step, state, mesh, inflight=2, sync_every=1,
        checkpoint=mgr, checkpoint_every=args.ckpt_every,
    )
    if restored_driver is not None:
        drv.load_state_dict(restored_driver)
    with StreamDataPipeline(
        _messages(args.steps, skip=start), batch_size=B, mesh=mesh
    ) as pipe:
        for sb in pipe:
            drv.submit(sb)
            if args.pace:
                time.sleep(args.pace)
    drv.finish()
    mgr.close()
    result = {
        "losses": [float(v) for v in drv.losses],
        "start": start,
        "steps": drv.steps,
        "checkpoints": drv.checkpoints,
        "mesh": args.mesh,
    }
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(result, f)
    print("ckpt_worker done", json.dumps({k: result[k] for k in (
        "start", "steps", "checkpoints", "mesh")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
