"""Test configuration.

Tests run on a virtual 8-device CPU mesh (no TPU needed in CI) by forcing
the host platform before JAX is first imported. This mirrors the
multi-chip sharding environment the driver validates via
``__graft_entry__.dryrun_multichip``.
"""

import os

# Child processes (producers, the blendjax-launch CLI) must import
# blendjax from this source checkout even when spawned with a foreign
# cwd; export the repo root so the whole process tree inherits it.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_pp = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p]
if _repo_root not in _pp:
    os.environ["PYTHONPATH"] = os.pathsep.join([_repo_root] + _pp)

# The CURRENT interpreter also needs the repo root importable (tests
# import repo-root modules like `bench`): the bare `pytest` entry point
# does not put the cwd on sys.path the way `python -m pytest` does.
import sys

if _repo_root not in sys.path:
    sys.path.insert(0, _repo_root)

# Opt-in real-device runs: `BLENDJAX_TEST_TPU=1 pytest -m tpu` skips the
# CPU-mesh override so tpu-marked tests really touch the device.
if os.environ.get("BLENDJAX_TEST_TPU") != "1":
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    # The machine image pre-imports jax and pins the TPU plugin via
    # sitecustomize, so the env var alone is read too late; the config
    # update is what actually selects the CPU backend (must run before the
    # first backend/device query).
    import jax

    jax.config.update("jax_platforms", "cpu")
