"""Test configuration.

Tests run on a virtual 8-device CPU mesh (no TPU needed in CI) by forcing
the host platform before JAX is first imported. This mirrors the
multi-chip sharding environment the driver validates via
``__graft_entry__.dryrun_multichip``.
"""

import os

# Opt-in real-device runs: `BLENDJAX_TEST_TPU=1 pytest -m tpu` skips the
# CPU-mesh override so tpu-marked tests really touch the device.
if os.environ.get("BLENDJAX_TEST_TPU") != "1":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
