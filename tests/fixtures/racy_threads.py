"""Deliberately-racy fixture for the whole-program pass (BJX117/118/119).

NOT production code and NOT importable by tests as logic — this module
exists so ``tests/test_analysis.py`` can assert the project pass flags
a known-bad file end-to-end through ``analyze_paths(project=True)``.
It lives under ``tests/fixtures/`` precisely so the repo self-run
(which scans ``blendjax/``) never sees it.

Expected findings:

- BJX117 on ``Racy.counter`` — written from the spawned drain thread
  and read from the public API with no common lock.
- BJX118 on ``(Racy.lock_a, Racy.lock_b)`` — acquired a->b in
  ``both_ab`` but b->a in ``both_ba``.
- BJX119 on ``Racy.wedge`` — an untimed queue get while holding
  ``lock_a``.
"""

import queue
import threading


class Racy:
    def __init__(self):
        self.lock_a = threading.Lock()
        self.lock_b = threading.Lock()
        self.counter = 0
        self._q = queue.Queue()

    def start(self):
        threading.Thread(target=self._drain, daemon=True).start()

    def _drain(self):
        while True:
            self._q.get(timeout=0.25)
            self.counter += 1  # raced write: no lock, two contexts

    def snapshot(self) -> int:
        return self.counter  # raced read from the public API

    def both_ab(self):
        with self.lock_a:
            with self.lock_b:
                pass

    def both_ba(self):
        with self.lock_b:
            with self.lock_a:
                pass

    def wedge(self):
        with self.lock_a:
            return self._q.get()  # blocking, untimed, under a lock
