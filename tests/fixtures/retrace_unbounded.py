"""Deliberately-retracing fixture for BJX122: an unbounded static
argument fed straight from per-message data, next to the sanctioned
bucket-ladder path that must stay quiet.

NOT production code — lives under ``tests/fixtures/`` so the repo
self-run never sees it; ``tests/test_analysis.py`` asserts the
dataflow pass flags exactly the unbounded call site end-to-end.

``jax.jit`` compiles once per distinct static-argument value: feeding
``n=batch["count"]`` recompiles per distinct count (silent, seconds
per compile, unbounded cache). The decode-plan contract bounds it by
quantizing through the bucket ladder first.

Expected finding: BJX122 in ``feed`` at the ``decode`` call, static
argument ``n``; ``feed_bucketed`` stays clean.
"""

import jax


def _decode(tiles, n):
    del n
    return tiles


decode = jax.jit(_decode, static_argnames=("n",))


def pad_to_bucket(n):
    return max(64, 1 << int(n).bit_length())


def feed(batch):
    # BJX122: the static arg derives from the message itself
    return decode(batch["tiles"], n=int(batch["count"]))


def feed_bucketed(batch):
    # sanctioned: quantized through the bucket ladder first
    n = pad_to_bucket(int(batch["count"]))
    return decode(batch["tiles"], n=n)
