"""Deliberately-leaky fixture for BJX120: the PR-10 (review round 4)
`_scenario_rows`-to-jit regression, reproduced shape-for-shape.

NOT production code — lives under ``tests/fixtures/`` so the repo
self-run never sees it; ``tests/test_analysis.py`` asserts the
dataflow pass flags it end-to-end.

The historical shape: the echo sampler stamps the per-scenario
accounting sidecar (``batch["_scenario_rows"] = rows``) directly onto
the draw it is about to dispatch, and the stamped dict goes straight
into the reservoir's jitted gather+augment — a direct (zero-hop)
leak, the complement of the collate shape in
``stamp_leak_trace.py``.

Expected finding: BJX120 in ``EchoSampler.draw`` at the
``self._draw_fn`` call, keys ``_scenario_rows``.
"""

import jax


def _gather_augment(batch):
    return batch


class EchoSampler:
    def __init__(self):
        self._draw_fn = jax.jit(_gather_augment)

    def draw(self, batch, rows):
        # per-scenario accounting sidecar, stamped on the live draw
        batch["_scenario_rows"] = rows
        return self._draw_fn(batch)  # BJX120: sidecar crosses the jit
