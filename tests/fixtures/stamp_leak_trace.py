"""Deliberately-leaky fixture for BJX120: the PR-6 `_trace`-to-collate
regression, reproduced shape-for-shape.

NOT production code — this module exists so ``tests/test_analysis.py``
can assert the jit-boundary dataflow pass flags the historical bug
end-to-end through ``analyze_paths(project=True)`` and the CLI. It
lives under ``tests/fixtures/`` so the repo self-run (which scans
``blendjax/``) never sees it.

The historical shape: a producer stamps the sampled frame-trace
context onto a message (``msg["_trace"] = ...``); the collate helper
merges fields into a batch but forgets the sidecar; the stamped batch
reaches the donating train-step jit and crashes with "not a valid JAX
type" — only when a *sampled* frame happens to arrive, i.e. rarely.

Expected finding: BJX120 in ``feed`` at the ``train_step`` call,
keys ``_trace`` — anchored where the tainted dict crosses the jit
boundary, two call hops after the stamp.
"""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def train_step(state, batch):
    del batch
    return state


def stamp(msg):
    """Producer side: the sampled-trace context rides the message."""
    msg["_trace"] = {"start": 0.0, "spans": []}
    return msg


def collate(batch):
    """The collate path: rebuilds the dict but keeps every key —
    including the sidecar it should have popped."""
    return dict(batch)


def feed(state, raw):
    msg = stamp(raw)
    batch = collate(msg)
    return train_step(state, batch)  # BJX120: '_trace' reaches the jit
