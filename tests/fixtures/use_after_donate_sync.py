"""Deliberately-broken fixture for BJX121: the PR-12 policy-sync bug,
reproduced shape-for-shape.

NOT production code — lives under ``tests/fixtures/`` so the repo
self-run never sees it; ``tests/test_analysis.py`` asserts the
dataflow pass flags it end-to-end.

The historical shape: the learner hands the training state to a
donating fused step, then ships the SAME (now-donated) state object to
the actors — a zero-copy view of deallocated device memory once XLA
actually reuses the donation. The fix was to publish ``new_state``;
the sanctioned idiom ``state = step(state, batch)`` (see
``clean_update``) rebinds at the call statement and never flags.

Expected finding: BJX121 in ``Learner.update`` at the
``self.publish(state)`` read, variable ``state``.
"""

import jax


def _fused(state, batch):
    del batch
    return state


class Learner:
    def __init__(self):
        self._step = jax.jit(_fused, donate_argnums=(0,))

    def publish(self, state):
        del state

    def update(self, state, batch):
        new_state = self._step(state, batch)
        self.publish(state)  # BJX121: reads the donated buffer
        return new_state

    def clean_update(self, state, batch):
        # sanctioned: rebinds from the step's return at the donating call
        state = self._step(state, batch)
        self.publish(state)
        return state
