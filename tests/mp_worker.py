"""Multi-process JAX worker for tests/test_multiprocess.py.

Runs as one of N coordinated processes (``jax.distributed.initialize``
over a localhost coordinator, 4 virtual CPU devices per process — the
CPU stand-in for one TPU host of a multi-host pod, SURVEY.md §4
"multi-process CPU JAX tests mirroring the reference's mp.Process
trick"). Asserts, from every process:

- DeviceFeeder(multihost=True) assembles per-process local batches into
  ONE global array of the right shape, content, and sharding;
- a psum collective over the assembled batch sees every process's rows;
- a tile-delta stream decodes through the multihost pipeline path with
  each process's local shard rows bit-exact vs its own frames;
- chunk=4 tile streams flush in lockstep into ONE global (K, B, ...)
  superbatch per group, bit-exact per shard (VERDICT r2 item 4);
- mode "divergent-ref": processes send DIFFERENT reference content and
  the fleet-digest all-gather must fail loudly on every process
  (ADVICE r2 medium).

Usage: mp_worker.py PROCESS_ID NUM_PROCESSES COORD_PORT [MODE]
(env JAX_PLATFORMS/XLA_FLAGS are set by the parent test).
"""

import sys

import numpy as np


def main() -> int:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    mode = sys.argv[4] if len(sys.argv) > 4 else "full"
    import jax

    # The machine image pre-imports jax and pins a device plugin via
    # sitecustomize, so the env var alone is read too late (same
    # workaround as tests/conftest.py).
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        f"localhost:{port}", num_processes=nproc, process_id=pid
    )
    assert jax.process_count() == nproc
    local = jax.local_device_count()
    ndev = jax.device_count()
    assert ndev == local * nproc

    from jax.sharding import NamedSharding, PartitionSpec as P

    from blendjax.data.pipeline import DeviceFeeder, StreamDataPipeline
    from blendjax.parallel import create_mesh

    mesh = create_mesh({"data": -1})
    sharding = NamedSharding(mesh, P("data"))

    # -- raw multihost assembly ------------------------------------------
    b_local = local  # one row per local device
    rows = pid * b_local + np.arange(b_local)
    batch = {
        "image": (rows[:, None, None] * np.ones((1, 2, 2))).astype(np.uint8),
        "frameid": rows,
    }
    feeder = DeviceFeeder(sharding=sharding, multihost=True)
    (out,) = list(feeder([batch]))
    assert out["image"].shape == (ndev, 2, 2), out["image"].shape
    assert out["image"].sharding.is_equivalent_to(sharding, 3)
    # every process holds its own rows, in global order
    for shard in out["image"].addressable_shards:
        row = int(np.asarray(shard.data)[0, 0, 0])
        assert row == (shard.index[0].start or 0), (row, shard.index)

    # -- a collective sees all rows --------------------------------------
    total = jax.jit(
        lambda x: jax.numpy.sum(x.astype(jax.numpy.int32)),
        out_shardings=NamedSharding(mesh, P()),
    )(out["frameid"])
    # replicated output: fully addressable on every process
    got = int(np.asarray(total.addressable_shards[0].data))
    assert got == sum(range(ndev)), got

    # -- tile stream through the multihost pipeline path ------------------
    from blendjax.ops.tiles import (
        TILEIDX_SUFFIX,
        TILEREF_SUFFIX,
        TILES_SUFFIX,
        TILESHAPE_SUFFIX,
        TileDeltaEncoder,
        pack_batch,
    )

    if mode == "divergent-ref":
        # Each process ships DIFFERENT reference content: the pipeline's
        # fleet-digest all-gather must raise on every process instead of
        # silently decoding rows against the wrong background.
        bad_ref = np.full((32, 32, 4), 10 + pid, np.uint8)
        enc = TileDeltaEncoder(bad_ref, tile=16)
        deltas = [tuple(a.copy() for a in enc.encode(bad_ref))]
        idx, tiles = pack_batch(deltas, enc.num_tiles, capacity=4)

        def bad_messages():
            yield {
                "_prebatched": True, "btid": pid,
                "image" + TILEIDX_SUFFIX: idx,
                "image" + TILES_SUFFIX: tiles,
                "image" + TILESHAPE_SUFFIX: [32, 32, 4, 16],
                "image" + TILEREF_SUFFIX: bad_ref,
            }

        try:
            with StreamDataPipeline(
                bad_messages(), batch_size=1, sharding=sharding,
                multihost=True,
            ) as pipe:
                list(pipe)
        except RuntimeError as e:
            assert "DIFFERENT fleet references" in str(e), e
            print(f"mp_worker {pid}/{nproc} divergence-detected")
            return 0
        print(f"mp_worker {pid}/{nproc} ERROR: divergence NOT detected")
        return 1

    rng = np.random.default_rng(7)  # SAME ref content on every process
    ref = rng.integers(0, 255, (32, 32, 4), np.uint8)
    # Rectangular (16, 32) tiles: the 5-element wire form and rect grid
    # math also hold through the true multi-process global-assembly path.
    enc = TileDeltaEncoder(ref, tile=(16, 32))
    frames = []
    for i in range(ndev):
        img = ref.copy()
        img[8:16, 8:16] = (i * 29) % 251
        frames.append(img)
    local_frames = frames[pid * b_local: (pid + 1) * b_local]
    deltas = [tuple(a.copy() for a in enc.encode(f)) for f in local_frames]
    idx, tiles = pack_batch(deltas, enc.num_tiles, capacity=4)

    def messages():
        yield {
            "_prebatched": True, "btid": pid,
            "image" + TILEIDX_SUFFIX: idx,
            "image" + TILES_SUFFIX: tiles,
            "image" + TILESHAPE_SUFFIX: [32, 32, 4, 16, 32],
            "image" + TILEREF_SUFFIX: ref,
            "frameid": np.asarray(rows),
        }

    with StreamDataPipeline(
        messages(), batch_size=b_local, sharding=sharding, multihost=True
    ) as pipe:
        (got_batch,) = list(pipe)
    img = got_batch["image"]
    assert img.shape == (ndev, 32, 32, 4), img.shape
    for shard in img.addressable_shards:
        g = shard.index[0].start or 0
        np.testing.assert_array_equal(np.asarray(shard.data)[0], frames[g])

    # -- chunk>1 tile stream: lockstep flush into (K, B, ...) -------------
    K = 4
    chunk_frames = []  # [k][global row] -> frame
    for k in range(K):
        row = []
        for g in range(ndev):
            img_ = ref.copy()
            img_[0:16, 16:32] = (17 + 31 * g + 7 * k) % 251
            row.append(img_)
        chunk_frames.append(row)

    def chunk_messages():
        for k in range(K):
            local = chunk_frames[k][pid * b_local: (pid + 1) * b_local]
            deltas = [
                tuple(a.copy() for a in enc.encode(f)) for f in local
            ]
            idx_, tiles_ = pack_batch(deltas, enc.num_tiles, capacity=4)
            msg = {
                "_prebatched": True, "btid": pid,
                "image" + TILEIDX_SUFFIX: idx_,
                "image" + TILES_SUFFIX: tiles_,
                "image" + TILESHAPE_SUFFIX: [32, 32, 4, 16, 32],
                "frameid": np.asarray(rows) + 100 * k,
            }
            if k == 0:
                msg["image" + TILEREF_SUFFIX] = ref
            yield msg

    with StreamDataPipeline(
        chunk_messages(), batch_size=b_local, sharding=sharding,
        multihost=True, chunk=K,
    ) as pipe:
        (sb,) = list(pipe)
    assert sb["image"].shape == (K, ndev, 32, 32, 4), sb["image"].shape
    assert sb["frameid"].shape == (K, ndev)
    # chunk axis replicated, batch axis sharded: every process holds its
    # own rows for ALL K updates of the scanned step
    for shard in sb["image"].addressable_shards:
        ks = shard.index[0]
        assert (ks.start or 0) == 0 and (
            ks.stop is None or ks.stop == K
        ), shard.index
        g = shard.index[1].start or 0
        data = np.asarray(shard.data)
        for k in range(K):
            np.testing.assert_array_equal(data[k, 0], chunk_frames[k][g])
    fid = np.asarray(
        jax.jit(
            lambda x: x, out_shardings=NamedSharding(mesh, P())
        )(sb["frameid"]).addressable_shards[0].data
    )
    np.testing.assert_array_equal(
        fid, np.arange(ndev)[None, :] + 100 * np.arange(K)[:, None]
    )

    # -- full-frame palette stream (non-sparse codec) ---------------------
    # multihost pal batches take the host-expand fallback, then the
    # standard global assembly; every process's shard rows must decode
    # bit-exact vs its own frames.
    from blendjax.ops.tiles import (
        FRAMEPAL_SUFFIXES,
        FRAMESHAPE_SUFFIX,
        PALETTE_SUFFIX,
        palettize_frames,
    )

    pal_frames = np.stack([
        np.repeat(
            ((np.arange(32 * 32).reshape(32, 32, 1) + g * 7) % 4
             ).astype(np.uint8) * 61,
            4, axis=-1,
        )
        for g in range(ndev)
    ])
    local_pal = pal_frames[pid * b_local: (pid + 1) * b_local]
    packed, palette, bits = palettize_frames(local_pal)

    def pal_messages():
        yield {
            "_prebatched": True, "btid": pid,
            "image" + FRAMEPAL_SUFFIXES[bits]: packed,
            "frameid": np.asarray(rows),
            "image" + PALETTE_SUFFIX: palette,
            "image" + FRAMESHAPE_SUFFIX: np.array(
                [32, 32, 4, bits], np.int32
            ),
        }

    with StreamDataPipeline(
        pal_messages(), batch_size=b_local, sharding=sharding,
        multihost=True,
    ) as pipe:
        (pb,) = list(pipe)
    assert pb["image"].shape == (ndev, 32, 32, 4), pb["image"].shape
    for shard in pb["image"].addressable_shards:
        g = shard.index[0].start or 0
        np.testing.assert_array_equal(
            np.asarray(shard.data)[0], pal_frames[g]
        )

    print(f"mp_worker {pid}/{nproc} ok: ndev={ndev}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
