"""Fake producer: publishes its parsed handshake back to the consumer.

Mirrors the reference test fixture ``tests/blender/launcher.blend.py:3-9``
(which publishes btid/seed/addresses/remainder for the launcher test to
assert on), but runs headless — no Blender.
"""

import sys
import time

from blendjax.launcher import parse_launch_args
from blendjax.transport import DataPublisherSocket, term_context


def main():
    args, remainder = parse_launch_args(sys.argv)
    pub = DataPublisherSocket(
        args.btsockets["DATA"], btid=args.btid, lingerms=5000
    )
    pub.publish(
        btseed=args.btseed,
        sockets=args.btsockets,
        remainder=remainder,
    )
    # Stay alive briefly so the consumer can connect and drain.
    time.sleep(10)
    pub.close()
    term_context()  # guarantee the flush before exiting


if __name__ == "__main__":
    main()
