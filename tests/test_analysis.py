"""bjx-lint (blendjax.analysis) tests: one true positive AND one true
negative per rule, inline-suppression and baseline mechanics, CLI exit
codes, and the self-gate (the repo itself stays clean)."""

import json
import os
import subprocess
import sys
import textwrap
import time

from blendjax.analysis import (
    analyze_paths,
    analyze_source,
    load_baseline,
    write_baseline,
)
from blendjax.analysis.core import all_rules, apply_baseline

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def findings(source, relpath="mod.py", select=None):
    return analyze_source(
        textwrap.dedent(source), relpath, select=set(select) if select else None
    )


def rule_ids(source, relpath="mod.py", select=None):
    return [f.rule for f in findings(source, relpath, select)]


# -- BJX101 jit-purity ------------------------------------------------------


def test_bjx101_flags_side_effects_in_jit_decorated_function():
    got = findings(
        """
        import time

        import jax
        import numpy as np

        @jax.jit
        def step(x):
            print("x =", x)
            t = time.time()
            noise = np.random.rand(4)
            return x + noise + t
        """
    )
    assert [f.rule for f in got] == ["BJX101"] * 3
    assert "print()" in got[0].message
    assert "time.time" in got[1].message
    assert "numpy.random" in got[2].message


def test_bjx101_reaches_through_call_graph_and_partial_and_lambda():
    got = findings(
        """
        import functools

        import jax

        def helper(x):
            print(x)
            return x

        @functools.partial(jax.jit, static_argnames=("k",))
        def outer(x, k=1):
            return helper(x) * k

        def wrap(x):
            return jax.jit(lambda y: print(y))(x)
        """
    )
    quals = {f.message.split("'")[1] for f in got}
    assert quals == {"helper", "<lambda>"}


def test_bjx101_negative_host_side_code_and_jax_random():
    assert (
        rule_ids(
            """
            import jax

            def host_loop(batches):
                for b in batches:
                    print("host logging is fine outside jit", b)

            @jax.jit
            def step(x, key):
                noise = jax.random.normal(key, x.shape)
                jax.debug.print("traced-safe {x}", x=x)
                return x + noise
            """
        )
        == []
    )


def test_bjx101_global_mutation_flagged_but_readonly_global_is_not():
    got = findings(
        """
        import jax

        _step_count = 0
        _config = {}

        @jax.jit
        def counted(x):
            global _step_count
            _step_count = _step_count + 1
            return x

        @jax.jit
        def reader(x):
            global _config
            return x * len(_config)
        """
    )
    assert [f.rule for f in got] == ["BJX101"]
    assert "_step_count" in got[0].message


# -- BJX102 host-sync-in-hot-path -------------------------------------------

HOT_SYNC = """
    import jax
    import numpy as np

    def feed(batches):
        for b in batches:
            db = jax.device_put(b)
            db.block_until_ready()
            x = float(np.asarray(db))
            yield x
"""


def test_bjx102_flags_sync_in_hot_module():
    got = findings(HOT_SYNC, relpath="blendjax/data/pipeline.py")
    assert [f.rule for f in got] == ["BJX102"] * 3


def test_bjx102_hot_marker_opts_a_module_in():
    marked = "# bjx: hot-path\n" + textwrap.dedent(HOT_SYNC)
    assert all(
        f.rule == "BJX102" for f in analyze_source(marked, "anywhere.py")
    )
    assert len(analyze_source(marked, "anywhere.py")) == 3


def test_bjx102_marker_in_docstring_does_not_opt_in():
    doc = '"""Module that merely DOCUMENTS the bjx: hot-path marker."""\n'
    assert analyze_source(doc + textwrap.dedent(HOT_SYNC), "anywhere.py") == []


def test_bjx102_negative_outside_hot_path_and_benign_hot_code():
    # same sync code in a non-hot module: silent
    assert rule_ids(HOT_SYNC, relpath="blendjax/train/bench_tool.py") == []
    # hot module doing async placement only: silent
    assert (
        rule_ids(
            """
            import jax

            def feed(batches):
                for b in batches:
                    yield jax.device_put(b)
            """,
            relpath="blendjax/data/pipeline.py",
        )
        == []
    )


# -- BJX106 sync-on-inflight-step -------------------------------------------

DRIVER_SYNC = """
    import jax
    import numpy as np

    def run(step, state, batches):
        for b in batches:
            state, m = step(state, b)
            jax.block_until_ready(m["loss"])
            v = float(np.asarray(m["loss"]))
        return state
"""


def test_bjx106_flags_same_iteration_sync_in_driver_module():
    got = findings(DRIVER_SYNC, relpath="blendjax/train/driver.py")
    assert [f.rule for f in got] == ["BJX106"] * 3
    assert "block_until_ready()" in got[0].message
    assert "'m'" in got[0].message


def test_bjx106_marker_opts_a_module_in():
    marked = "# bjx: driver-hot-path\n" + textwrap.dedent(DRIVER_SYNC)
    got = analyze_source(marked, "anywhere.py")
    assert [f.rule for f in got] == ["BJX106"] * 3


def test_bjx106_negatives_prior_iteration_and_non_driver_modules():
    # the sanctioned driver shapes: syncs on ring-popped values from
    # EARLIER iterations (helper methods, no same-iteration assign)
    clean = """
        import collections

        import jax
        import numpy as np

        def run(step, state, batches, inflight=4):
            pending = collections.deque()
            for b in batches:
                while len(pending) >= inflight:
                    _wait(pending)
                state, m = step(state, b)
                pending.append(m["loss"])
            return state, float(np.asarray(pending.pop()))

        def _wait(pending):
            oldest = pending.popleft()
            jax.block_until_ready(oldest)
    """
    assert rule_ids(clean, relpath="blendjax/train/driver.py") == []
    # identical per-iteration sync outside driver hot paths: silent
    assert rule_ids(DRIVER_SYNC, relpath="blendjax/train/loops.py") == []
    # sync placed BEFORE the dispatch reads the PREVIOUS iteration's
    # value — the sanctioned sync-one-behind shape, not flagged
    one_behind = """
        import numpy as np

        def run(step, state, batches):
            m = None
            for b in batches:
                if m is not None:
                    print(float(np.asarray(m["loss"])))
                state, m = step(state, b)
            return state
    """
    assert rule_ids(one_behind, relpath="blendjax/train/driver.py") == []


def test_bjx106_item_and_attribute_form():
    got = findings(
        """
        def run(step, state, batches):
            for b in batches:
                state, m = step(state, b)
                x = m["loss"].item()
            return state
        """,
        relpath="blendjax/train/driver.py",
    )
    assert [f.rule for f in got] == ["BJX106"]
    assert "item()" in got[0].message


# -- BJX107 metric-name-cardinality -----------------------------------------

METRIC_NAMES = """
    from blendjax.utils.metrics import metrics

    def consume(items):
        for i, item in enumerate(items):
            metrics.count(f"ingest.item{i}")
            key = "ingest." + item["kind"]
            metrics.count(key)
            with metrics.span("ingest.consume.{}".format(item["kind"])):
                pass
"""


def test_bjx107_flags_computed_names_in_hot_module():
    got = findings(METRIC_NAMES, relpath="blendjax/data/pipeline.py")
    assert [f.rule for f in got] == ["BJX107"] * 3
    assert "f-string" in got[0].message
    assert "variable 'key'" in got[1].message
    assert "str.format()" in got[2].message


def test_bjx107_marker_opts_a_module_in():
    marked = "# bjx: hot-path\n" + textwrap.dedent(METRIC_NAMES)
    got = analyze_source(marked, "anywhere.py")
    assert [f.rule for f in got] == ["BJX107"] * 3
    # the identical code outside a hot path is silent (cold-path
    # cardinality is still a smell, but not this rule's gate)
    assert rule_ids(METRIC_NAMES, relpath="blendjax/cold.py") == []


def test_bjx107_negatives_constant_names_aliases_and_non_registry():
    clean = """
        from blendjax.utils.metrics import metrics as reg

        def consume(items, results):
            for item in items:
                reg.count("ingest.items")
                reg.gauge("ingest.queue_depth", len(items))
                reg.observe(name="ingest.bytes", value=item["n"])
                with reg.span("ingest.consume"):
                    pass
                # not a registry: same method names on another object
                results.count(f"whatever.{item}")
    """
    assert rule_ids(clean, relpath="blendjax/data/pipeline.py") == []


def test_bjx107_alias_import_and_duck_typed_registry_are_covered():
    got = findings(
        """
        from blendjax.utils.metrics import metrics as reg

        class Ingest:
            def __init__(self, metrics):
                self.metrics = metrics

            def consume(self, key):
                reg.count(f"a.{key}")
                self.metrics.count("b." + key)
        """,
        relpath="blendjax/data/batcher.py",
    )
    assert [f.rule for f in got] == ["BJX107"] * 2


def test_bjx107_inline_suppression():
    src = """
        from blendjax.utils.metrics import metrics

        def per_shard(idx):
            name = f"ingest.recv.shard{idx}"
            with metrics.span(name):  # bjx: ignore[BJX107]
                pass
    """
    assert rule_ids(src, relpath="blendjax/data/pipeline.py") == []


# -- BJX108 reservoir-host-materialization -----------------------------------

RESERVOIR_FETCH = """
    # bjx: driver-hot-path
    import numpy as np

    def draw(reservoir, idx):
        batch = reservoir.sample(idx)
        imgs = np.asarray(batch["image"])
        loss = float(batch["xy"])
        return imgs, loss
"""


def test_bjx108_flags_host_fetch_of_sample_result():
    got = findings(RESERVOIR_FETCH, select=["BJX108"])
    assert [f.rule for f in got] == ["BJX108"] * 2
    assert "numpy.asarray()" in got[0].message
    assert "'batch'" in got[0].message


def test_bjx108_flags_direct_nesting_and_constructed_locals():
    src = """
        # bjx: driver-hot-path
        import numpy as np
        from blendjax.data.echo import SampleReservoir

        def insert_and_peek(batches, idx):
            res = SampleReservoir(64)
            for b in batches:
                res.insert(b)
            return np.asarray(res.sample(idx))

        def peek_item(self, idx):
            return self.reservoir.gather(idx)["image"].item()
    """
    got = findings(src, select=["BJX108"])
    assert [f.rule for f in got] == ["BJX108"] * 2
    assert {"insert_and_peek", "peek_item"} == {
        f.message.split("'")[1] for f in got
    }


def test_bjx108_negatives_host_indices_and_unmarked_modules():
    # the sanctioned shape: accounting on the HOST-chosen index vector,
    # device batch never materialized
    clean = """
        # bjx: driver-hot-path
        import numpy as np

        def draw(reservoir, use, rng, b):
            idx = rng.choice(np.flatnonzero(use < 8), size=b)
            batch = reservoir.sample(idx)
            fresh = int((use[idx] == 0).sum())
            np.add.at(use, idx, 1)
            return batch, fresh
    """
    assert rule_ids(clean, select=["BJX108"]) == []
    # a fetch BEFORE the sample assignment reads an unrelated value
    one_behind = """
        # bjx: driver-hot-path
        import numpy as np

        def draw(reservoir, idx, batch):
            host = np.asarray(batch)
            batch = reservoir.sample(idx)
            return host, batch
    """
    assert rule_ids(one_behind, select=["BJX108"]) == []
    # same fetch outside driver hot paths: silent (eval/test code may
    # materialize freely)
    assert rule_ids(
        RESERVOIR_FETCH.replace("# bjx: driver-hot-path", ""),
        select=["BJX108"],
    ) == []


def test_bjx108_inline_suppression():
    src = """
        # bjx: driver-hot-path
        import numpy as np

        def debug_draw(reservoir, idx):
            batch = reservoir.sample(idx)
            return np.asarray(batch["image"])  # bjx: ignore[BJX108]
    """
    assert rule_ids(src, select=["BJX108"]) == []


# -- BJX103 unsafe-deserialization ------------------------------------------


def test_bjx103_flags_ungated_pickle():
    got = findings(
        """
        import pickle

        def load(blob):
            return pickle.loads(blob)
        """
    )
    assert [f.rule for f in got] == ["BJX103"]


def test_bjx103_negatives_gated_and_trusted_and_dumps():
    assert (
        rule_ids(
            """
            import pickle

            def load(blob, allow_pickle=False):
                if not allow_pickle:
                    raise ValueError("untrusted")
                return pickle.loads(blob)

            class Reader:
                def __init__(self, path, allow_pickle=False):
                    self.allow_pickle = allow_pickle

                def _open(self, f):
                    return pickle.Unpickler(f)

            def save(obj):
                return pickle.dumps(obj)

            def load_cache(blob):
                # bjx: trusted-source (bytes we wrote ourselves above)
                return pickle.loads(blob)
            """
        )
        == []
    )


# -- BJX104 zmq-thread-affinity ---------------------------------------------


def test_bjx104_flags_socket_crossing_thread_boundary():
    got = findings(
        """
        import threading

        import zmq

        class Pump:
            def __init__(self, ctx):
                self.sock = ctx.socket(zmq.PULL)
                self._thread = threading.Thread(target=self._run)

            def _run(self):
                while True:
                    self._drain()

            def _drain(self):
                self.sock.recv()
        """
    )
    assert [f.rule for f in got] == ["BJX104"]
    assert "self.sock" in got[0].message and "_run" in got[0].message


def test_bjx104_flags_positional_thread_target():
    got = findings(
        """
        import threading

        import zmq

        class Pump:
            def __init__(self, ctx):
                self.sock = ctx.socket(zmq.PULL)
                self._thread = threading.Thread(None, self._run)

            def _run(self):
                self.sock.recv()
        """
    )
    assert [f.rule for f in got] == ["BJX104"]


def test_bjx104_negatives_same_thread_and_annotated():
    # socket created inside the thread target itself: correct affinity
    assert (
        rule_ids(
            """
            import threading

            import zmq

            class Pump:
                def __init__(self, ctx):
                    self.ctx = ctx
                    self._thread = threading.Thread(target=self._run)

                def _run(self):
                    self.sock = self.ctx.socket(zmq.PULL)
                    self.sock.recv()
            """
        )
        == []
    )
    # explicit ownership-transfer annotation
    assert (
        rule_ids(
            """
            import threading

            import zmq

            class Pump:
                def __init__(self, ctx):
                    self.sock = ctx.socket(zmq.PULL)  # bjx: thread-owner
                    self._thread = threading.Thread(target=self._run)

                def _run(self):
                    self.sock.recv()
            """
        )
        == []
    )


# -- BJX105 socket-leak -----------------------------------------------------


def test_bjx105_flags_leak_and_partial_close():
    got = findings(
        """
        import zmq

        def leaky(ctx):
            sock = ctx.socket(zmq.PUSH)
            sock.send(b"x")

        def conditional(ctx, flag):
            sock = ctx.socket(zmq.PULL)
            if flag:
                sock.close()
        """
    )
    assert [f.rule for f in got] == ["BJX105"] * 2
    assert "never closed" in got[0].message
    assert "some paths" in got[1].message


def test_bjx105_using_the_socket_is_not_an_ownership_transfer():
    got = findings(
        """
        import zmq

        def recv_leak(ctx):
            sock = ctx.socket(zmq.PULL)
            msg = sock.recv()
            return msg

        def print_leak(ctx):
            sock = ctx.socket(zmq.PULL)
            print(sock.recv())
        """
    )
    assert [f.rule for f in got] == ["BJX105"] * 2


def test_bjx105_container_store_is_a_transfer():
    assert (
        rule_ids(
            """
            import zmq

            def pooled(ctx, pool):
                sock = ctx.socket(zmq.PUSH)
                pool.append(sock)

            def listed(ctx):
                socks = [ctx.socket(zmq.PUSH) for _ in range(2)]
                extra = ctx.socket(zmq.PULL)
                bundle = (extra, socks)
                return bundle
            """
        )
        == []
    )


def test_bjx105_negatives_finally_with_transfer():
    assert (
        rule_ids(
            """
            import zmq

            def closed(ctx):
                sock = ctx.socket(zmq.PULL)
                try:
                    sock.recv()
                finally:
                    sock.close()

            def managed(ctx):
                with ctx.socket(zmq.PUB) as sock:
                    sock.send(b"x")

            def handed_off(ctx):
                sock = ctx.socket(zmq.PUSH)
                return sock

            class Holder:
                def __init__(self, ctx):
                    self.sock = ctx.socket(zmq.PAIR)
            """
        )
        == []
    )


def test_bjx105_negative_create_and_close_inside_branch_or_loop():
    assert (
        rule_ids(
            """
            import zmq

            def branch(ctx, flag):
                if flag:
                    sock = ctx.socket(zmq.PULL)
                    sock.recv()
                    sock.close()

            def loop(ctx, addrs):
                for a in addrs:
                    sock = ctx.socket(zmq.PUSH)
                    try:
                        sock.connect(a)
                    finally:
                        sock.close()
            """
        )
        == []
    )


def test_bjx102_lambda_body_is_scanned_in_hot_module():
    got = findings(
        """
        def make_waiter():
            return lambda arr: arr.block_until_ready()
        """,
        relpath="blendjax/data/pipeline.py",
    )
    assert [f.rule for f in got] == ["BJX102"]


# -- suppression / baseline / CLI -------------------------------------------

LEAKY = """
    import zmq

    def leaky(ctx):
        sock = ctx.socket(zmq.PUSH)
        sock.send(b"x")
"""


def test_inline_ignore_suppresses_by_rule_and_bare():
    src = """
        import zmq

        def leaky(ctx):
            sock = ctx.socket(zmq.PUSH)  # bjx: ignore[BJX105]
            sock.send(b"x")

        def leaky2(ctx):
            # bjx: ignore
            sock = ctx.socket(zmq.PUSH)
            sock.send(b"x")
    """
    assert rule_ids(src) == []
    # wrong rule id in the marker does NOT suppress
    assert (
        rule_ids(
            """
            import zmq

            def leaky(ctx):
                sock = ctx.socket(zmq.PUSH)  # bjx: ignore[BJX101]
                sock.send(b"x")
            """
        )
        == ["BJX105"]
    )


def test_baseline_roundtrip_suppresses_and_survives_line_shifts(tmp_path):
    mod = tmp_path / "leak.py"
    mod.write_text(textwrap.dedent(LEAKY))
    base = str(tmp_path / "baseline.json")
    got = analyze_paths([str(mod)], root=str(tmp_path))
    assert [f.rule for f in got] == ["BJX105"]
    assert write_baseline(base, got, str(tmp_path)) == 1
    # baselined: nothing reported
    assert apply_baseline(got, load_baseline(base), str(tmp_path)) == []
    # unrelated lines added above: fingerprint (line-content keyed) holds
    mod.write_text("# a new header comment\nX = 1\n" + textwrap.dedent(LEAKY))
    shifted = analyze_paths([str(mod)], root=str(tmp_path))
    assert [f.rule for f in shifted] == ["BJX105"]
    assert apply_baseline(shifted, load_baseline(base), str(tmp_path)) == []
    # a NEW finding is still reported alongside the baselined one
    mod.write_text(
        textwrap.dedent(LEAKY)
        + textwrap.dedent(
            """
            def leaky_b(ctx):
                s2 = ctx.socket(zmq.PULL)
                s2.recv()
            """
        )
    )
    both = analyze_paths([str(mod)], root=str(tmp_path))
    left = apply_baseline(both, load_baseline(base), str(tmp_path))
    assert len(both) == 2 and len(left) == 1
    assert "s2" in left[0].message


def test_baseline_does_not_alias_identical_line_in_new_function(tmp_path):
    """A brand-new violation textually identical to a grandfathered one
    (same source line, earlier in the file, different function) must NOT
    inherit the baselined fingerprint."""
    mod = tmp_path / "leak.py"
    mod.write_text(textwrap.dedent(LEAKY))
    base = str(tmp_path / "baseline.json")
    write_baseline(
        base, analyze_paths([str(mod)], root=str(tmp_path)), str(tmp_path)
    )
    mod.write_text(
        textwrap.dedent(
            """
            import zmq

            def newer(ctx):
                sock = ctx.socket(zmq.PUSH)
                sock.send(b"y")
            """
        )
        + textwrap.dedent(LEAKY)
    )
    left = apply_baseline(
        analyze_paths([str(mod)], root=str(tmp_path)),
        load_baseline(base),
        str(tmp_path),
    )
    assert [f.rule for f in left] == ["BJX105"]
    assert "'newer'" in left[0].message


def test_cli_exit_codes_and_json(tmp_path):
    mod = tmp_path / "fixture.py"
    mod.write_text(textwrap.dedent(LEAKY))
    env = {**os.environ, "PYTHONPATH": REPO_ROOT, "JAX_PLATFORMS": "cpu"}

    def run(*args):
        return subprocess.run(
            [sys.executable, "-m", "blendjax.analysis", *args],
            capture_output=True, text=True, cwd=str(tmp_path), env=env,
        )

    bad = run(str(mod), "--format", "json")
    assert bad.returncode == 1
    data = json.loads(bad.stdout)
    assert data[0]["rule"] == "BJX105"

    wrote = run(str(mod), "--write-baseline")
    assert wrote.returncode == 0
    clean = run(str(mod))
    assert clean.returncode == 0, clean.stdout + clean.stderr

    ok = run("--list-rules")
    assert ok.returncode == 0
    for rule_id in (
        "BJX101", "BJX102", "BJX103", "BJX104", "BJX105", "BJX106",
        "BJX107", "BJX108",
    ):
        assert rule_id in ok.stdout


def test_select_restricts_rules():
    src = """
        import pickle
        import zmq

        def both(ctx, blob):
            sock = ctx.socket(zmq.PUSH)
            return pickle.loads(blob)
    """
    assert sorted(rule_ids(src)) == ["BJX103", "BJX105"]
    assert rule_ids(src, select=["BJX103"]) == ["BJX103"]


def test_syntax_error_reports_bjx000():
    got = analyze_source("def broken(:\n", "bad.py")
    assert [f.rule for f in got] == ["BJX000"]


# -- BJX109 wall-clock-duration ----------------------------------------------


def test_bjx109_flags_wall_clock_duration_in_hot_path():
    src = """
        # bjx: hot-path
        import time

        def recv_loop(work):
            t0 = time.time()
            work()
            return time.time() - t0
    """
    got = findings(src, select=["BJX109"])
    assert [f.rule for f in got] == ["BJX109"]
    assert "time.monotonic" in got[0].message


def test_bjx109_checks_driver_modules_by_basename_and_marker():
    src = """
        import time

        def ring_wait():
            start = time.time()
            return time.time() - start
    """
    assert rule_ids(src, relpath="driver.py", select=["BJX109"]) == [
        "BJX109"
    ]
    marked = "# bjx: driver-hot-path\n" + textwrap.dedent(src)
    got = analyze_source(marked, "echo.py", select={"BJX109"})
    assert [f.rule for f in got] == ["BJX109"]


def test_bjx109_negatives_wire_stamps_mixed_clocks_and_unmarked():
    # cross-process staleness math: one side comes off the message,
    # not a local wall-clock read — the sanctioned pattern
    wire = """
        # bjx: hot-path
        import time

        def ingest(msg):
            now = time.time()
            return now - float(msg["_pub_wall"])
    """
    assert rule_ids(wire, select=["BJX109"]) == []
    # mixed clocks (the chrome-trace timebase offset) are not a
    # wall-wall duration
    mixed = """
        # bjx: hot-path
        import time

        def offset():
            return time.perf_counter() - time.time()
    """
    assert rule_ids(mixed, select=["BJX109"]) == []
    # unmarked modules are out of scope (eval/bench code times with
    # wall clocks freely)
    unmarked = """
        import time

        def f():
            t0 = time.time()
            return time.time() - t0
    """
    assert rule_ids(unmarked, select=["BJX109"]) == []


def test_bjx109_monotonic_durations_stay_clean():
    src = """
        # bjx: hot-path
        import time

        def recv_loop(work):
            t0 = time.monotonic()
            work()
            return time.monotonic() - t0
    """
    assert rule_ids(src, select=["BJX109"]) == []


def test_bjx109_inline_suppression():
    src = """
        # bjx: hot-path
        import time

        def f(work):
            t0 = time.time()
            work()
            return time.time() - t0  # bjx: ignore[BJX109]
    """
    assert rule_ids(src, select=["BJX109"]) == []


# -- BJX110 fleet-thread-affinity ---------------------------------------------


def test_bjx110_flags_launcher_lifecycle_in_hot_path():
    src = """
        # bjx: hot-path

        def on_timeout(self):
            self.launcher.assert_alive()
            return True

        def rebalance(launcher, n):
            launcher.scale_to(n)

        def drain(blender_launcher):
            blender_launcher.retire_instance(0, drain=True)
            blender_launcher.wait()
    """
    got = findings(src, select=["BJX110"])
    assert [f.rule for f in got] == ["BJX110"] * 4
    assert "assert_alive" in got[0].message
    assert "control thread" in got[0].message


def test_bjx110_negatives_non_launcher_receivers_and_unmarked():
    # generic wait()s — trackers, events, subprocesses — are out of
    # scope: the receiver gate requires a launcher-like name
    src = """
        # bjx: hot-path

        def publish(tracker, proc, event):
            tracker.wait()
            event.wait(1.0)
            proc.wait(timeout=5)
    """
    assert rule_ids(src, select=["BJX110"]) == []
    # unmarked modules may drive the launcher freely (the controller
    # module itself, bench code, tests)
    unmarked = """
        def control_tick(launcher):
            launcher.scale_to(3)
            launcher.wait()
    """
    assert rule_ids(unmarked, select=["BJX110"]) == []
    # non-lifecycle launcher calls stay clean
    reads = """
        # bjx: hot-path

        def fleet_size(launcher):
            return launcher.active_count()
    """
    assert rule_ids(reads, select=["BJX110"]) == []


def test_bjx110_hot_by_basename_and_inline_suppression():
    src = """
        def iterate(self):
            self.launcher.poll_processes()
    """
    assert rule_ids(src, relpath="pipeline.py", select=["BJX110"]) == [
        "BJX110"
    ]
    suppressed = """
        def iterate(self):
            self.launcher.poll_processes()  # bjx: ignore[BJX110]
    """
    assert rule_ids(
        suppressed, relpath="pipeline.py", select=["BJX110"]
    ) == []


# -- BJX111 mesh-placement ----------------------------------------------------


def test_bjx111_flags_per_device_device_put_loops():
    src = """
        # bjx: mesh-hot-path
        import jax

        def place_loop(mesh, batch):
            out = []
            for d in mesh.devices:
                out.append(jax.device_put(batch, d))
            return out

        def place_comp(batch):
            return [jax.device_put(batch, d) for d in jax.devices()]

        def place_local(batch):
            for d in jax.local_devices():
                jax.device_put(batch, d)
    """
    got = findings(src, select=["BJX111"])
    assert [f.rule for f in got] == ["BJX111"] * 3
    assert "per-device" in got[0].message
    assert "NamedSharding" in got[0].message


def test_bjx111_flags_global_array_host_materialization():
    src = """
        # bjx: mesh-hot-path
        import jax
        import numpy as np

        def assemble(s, v):
            g = jax.make_array_from_process_local_data(s, v)
            host = np.asarray(g)
            return host

        def direct(s, v):
            return np.asarray(
                jax.make_array_from_process_local_data(s, v)
            )

        def shard_walk(g):
            return [s.data for s in g.addressable_shards]
    """
    got = findings(src, select=["BJX111"])
    assert [f.rule for f in got] == ["BJX111"] * 3
    assert "'g'" in got[0].message
    assert "addressable_shards" in got[2].message


def test_bjx111_negatives_single_placement_and_unmarked():
    # the sanctioned pattern: one grouped placement, no device loop
    src = """
        # bjx: mesh-hot-path
        import jax

        def place(batch, sharding):
            return jax.device_put(batch, sharding)

        def over_fields(batch, sharding):
            # loops over FIELDS are fine; the loop var is not a device
            return {k: jax.device_put(v, sharding)
                    for k, v in batch.items()}
    """
    assert rule_ids(src, select=["BJX111"]) == []
    # a fetch of something never bound from a global assembly is fine
    host = """
        # bjx: mesh-hot-path
        import numpy as np

        def pack(rows):
            return np.asarray(rows)
    """
    assert rule_ids(host, select=["BJX111"]) == []
    # unmarked modules (tests, debug tooling) iterate shards freely
    unmarked = """
        def inspect(g):
            return [s.data for s in g.addressable_shards]
    """
    assert rule_ids(unmarked, select=["BJX111"]) == []


def test_bjx111_hot_by_basename_and_inline_suppression():
    src = """
        def inspect(g):
            for s in g.addressable_shards:
                print(s)
    """
    assert rule_ids(src, relpath="mesh_driver.py", select=["BJX111"]) == [
        "BJX111"
    ]
    suppressed = """
        def inspect(g):
            for s in g.addressable_shards:  # bjx: ignore[BJX111]
                print(s)
    """
    assert rule_ids(
        suppressed, relpath="mesh_driver.py", select=["BJX111"]
    ) == []


# -- BJX112 non-donated-train-jit --------------------------------------------


def test_bjx112_flags_undonated_step_jit_in_hot_module():
    src = """
        # bjx: driver-hot-path
        import jax

        def make_step():
            def step(state, batch):
                return state, {}
            return jax.jit(step)
    """
    assert rule_ids(src, select=["BJX112"]) == ["BJX112"]
    # state-named first param triggers even without a step-ish name
    src2 = """
        # bjx: driver-hot-path
        import jax

        def build():
            def evaluate(state, batch):
                return state.params
            return jax.jit(evaluate)
    """
    assert rule_ids(src2, select=["BJX112"]) == ["BJX112"]


def test_bjx112_donation_keyword_presence_satisfies():
    src = """
        # bjx: driver-hot-path
        import jax

        def make_step(donate=True):
            def step(state, batch):
                return state, {}
            return jax.jit(step, donate_argnums=(0,) if donate else ())
    """
    assert rule_ids(src, select=["BJX112"]) == []


def test_bjx112_decorator_form_and_step_module_scope():
    src = """
        import jax

        @jax.jit
        def train_step(state, batch):
            return state
    """
    # steps.py is in scope without a marker (the builders live there)
    assert rule_ids(src, relpath="steps.py", select=["BJX112"]) == [
        "BJX112"
    ]
    # ... an unmarked ordinary module is not
    assert rule_ids(src, relpath="mod.py", select=["BJX112"]) == []


def test_bjx112_non_step_jits_and_suppressions_pass():
    src = """
        # bjx: driver-hot-path
        import jax

        def build():
            draw = jax.jit(lambda bufs, i: bufs[i])
            gather = jax.jit(_gather)
            # segment-anchored name match: 'constrain' must not read
            # as train
            pin = jax.jit(apply_constraint)
            return draw, gather, pin

        def apply_constraint(sb):
            return sb
    """
    assert rule_ids(src, select=["BJX112"]) == []
    suppressed = """
        # bjx: driver-hot-path
        import jax

        def make_eval():
            def eval_step(state, batch):
                return state.params
            # bjx: ignore[BJX112]
            return jax.jit(eval_step)
    """
    assert rule_ids(suppressed, select=["BJX112"]) == []


# -- BJX113 scenario-id-cardinality ------------------------------------------


def test_bjx113_flags_scenario_id_fstring_anywhere():
    # NOT a hot-path module: BJX107 stays silent, BJX113 fires — the
    # scenario-id rule covers every module.
    src = """
        from blendjax.utils.metrics import metrics

        def account(sid, loss):
            metrics.count(f"scenario.{sid}.rows")
            metrics.observe("loss_" + sid, loss)
    """
    assert rule_ids(src, select=["BJX113"]) == ["BJX113", "BJX113"]
    assert rule_ids(src, select=["BJX107"]) == []


def test_bjx113_flags_format_and_bare_variable_forms():
    src = """
        from blendjax.utils.metrics import metrics

        def account(scenario_id, batch):
            metrics.gauge("scenario.{}.fill".format(scenario_id), 1)
            metrics.count(scenario_id)
    """
    assert rule_ids(src, select=["BJX113"]) == ["BJX113", "BJX113"]


def test_bjx113_ignores_constant_and_non_scenario_dynamic_names():
    src = """
        from blendjax.utils.metrics import metrics

        def account(shard, sids):
            metrics.count("scenario.rows", len(sids))
            metrics.gauge("scenario.space_version", 3)
            # dynamic but not scenario identity: BJX107's (hot-path)
            # business, not BJX113's
            metrics.count(f"ingest.shard{shard}.items")
    """
    assert rule_ids(src, select=["BJX113"]) == []


def test_bjx113_non_registry_receivers_untouched():
    src = """
        def f(ledger, sid):
            ledger.count(f"scenario.{sid}")
    """
    assert rule_ids(src, select=["BJX113"]) == []


def test_bjx113_suppressible_inline():
    src = """
        from blendjax.utils.metrics import metrics

        def account(sid):
            # bounded: test fixture with exactly two ids
            # bjx: ignore[BJX113]
            metrics.count(f"scenario.{sid}.rows")
    """
    assert rule_ids(src, select=["BJX113"]) == []


def test_every_rule_registered():
    assert set(all_rules()) == {
        "BJX101", "BJX102", "BJX103", "BJX104", "BJX105", "BJX106",
        "BJX107", "BJX108", "BJX109", "BJX110", "BJX111", "BJX112",
        "BJX113", "BJX114", "BJX115", "BJX116", "BJX117", "BJX118",
        "BJX119", "BJX120", "BJX121", "BJX122", "BJX125", "BJX126",
    }


def test_project_rules_marked_and_skipped_by_per_file_pass():
    rules = all_rules()
    project_ids = {
        "BJX117", "BJX118", "BJX119", "BJX120", "BJX121", "BJX122",
    }
    assert all(rules[r].project for r in project_ids)
    assert all(not rules[r].project for r in set(rules) - project_ids)
    # per-file analysis never runs a project rule (check() is a no-op)
    assert rules["BJX117"].check(None) == ()


# -- BJX114 checkpoint-in-hot-path -------------------------------------------


def test_bjx114_flags_sync_checkpoint_calls_in_driver_hot_path():
    src = """
        # bjx: driver-hot-path
        def loop(self, batches):
            for b in batches:
                self.state, m = self.step(self.state, b)
                self.checkpoint.save(self.steps, self.state)
                self.checkpoint.wait()
    """
    assert rule_ids(src, select=["BJX114"]) == ["BJX114", "BJX114"]


def test_bjx114_flags_dataflow_from_manager_construction():
    src = """
        # bjx: driver-hot-path
        from blendjax.checkpoint import SnapshotManager

        def run(step, state, batches):
            mgr = SnapshotManager("ckpt/")
            for b in batches:
                state, m = step(state, b)
                mgr.save(1, state)
            mgr.restore(state)
    """
    assert rule_ids(src, select=["BJX114"]) == ["BJX114", "BJX114"]


def test_bjx114_driver_basename_always_checked():
    src = """
        def drain_and_save(self):
            self.ckpt_manager.wait_until_finished()
    """
    assert rule_ids(src, relpath="driver.py", select=["BJX114"]) == [
        "BJX114"
    ]


def test_bjx114_async_and_non_checkpoint_receivers_untouched():
    src = """
        # bjx: driver-hot-path
        def loop(self, batches):
            for b in batches:
                self.state, m = self.step(self.state, b)
                self.checkpoint.save_async(self.steps, self.state)
                self.checkpoint.latest_step(wait=False)
                self.driver.request_checkpoint()
                self.queue.wait()       # not a checkpoint receiver
                self.recorder.save(b)   # not a checkpoint receiver
    """
    assert rule_ids(src, select=["BJX114"]) == []


def test_bjx114_silent_outside_hot_path_and_suppressible():
    src = """
        def teardown(self):
            self.checkpoint.save(self.steps, self.state)
    """
    assert rule_ids(src, select=["BJX114"]) == []
    suppressed = """
        # bjx: driver-hot-path
        def teardown(self):
            # the process is exiting: sanctioned sync flush
            # bjx: ignore[BJX114]
            self.checkpoint.wait()
    """
    assert rule_ids(suppressed, select=["BJX114"]) == []


# -- BJX115 host-materialization-in-actor-loop -------------------------------


def test_bjx115_flags_policy_and_reservoir_fetches_in_actor_module():
    src = """
        # bjx: actor-hot-path
        import numpy as np

        def loop(self, obs):
            while True:
                actions = self.policy(self._snapshot, obs)
                a = np.asarray(actions)
                drawn = self.reservoir.sample(idx)
                v = float(drawn)
    """
    assert rule_ids(src, select=["BJX115"]) == ["BJX115", "BJX115"]


def test_bjx115_flags_item_and_block_until_ready_anywhere_in_actor():
    src = """
        # bjx: actor-hot-path
        import jax

        def loop(self, q):
            x = q.item()
            jax.block_until_ready(q)
    """
    assert rule_ids(src, select=["BJX115"]) == ["BJX115", "BJX115"]


def test_bjx115_actor_basename_always_checked_and_nesting_flagged():
    src = """
        import numpy as np

        def loop(self, idx):
            a = np.asarray(self.policy(snap, obs))
    """
    assert rule_ids(src, "rl/actor.py", select=["BJX115"]) == ["BJX115"]


def test_bjx115_env_outputs_and_host_math_stay_clean():
    """Env step results and plain host accounting never lived on a
    device — the rule must not flag the sanctioned actor shape."""
    src = """
        # bjx: actor-hot-path
        import numpy as np

        def loop(self):
            while True:
                obs, reward, done, infos = self.env.step(a)
                o = np.asarray(obs)
                r = float(reward[0])
                ret = float(self._ep_ret[0])
    """
    assert rule_ids(src, select=["BJX115"]) == []


def test_bjx115_silent_outside_actor_modules_and_suppressible():
    src = """
        import numpy as np

        def learner_sync(self):
            snap = np.asarray(self.policy(s, o))
    """
    assert rule_ids(src, select=["BJX115"]) == []
    suppressed = """
        # bjx: actor-hot-path
        import numpy as np

        def probe(self):
            # one-off debugging probe, not the loop
            # bjx: ignore[BJX115]
            a = np.asarray(self.policy(s, o))
    """
    assert rule_ids(suppressed, select=["BJX115"]) == []


# -- self-gate ---------------------------------------------------------------


def test_repo_is_clean_under_baseline():
    """The CI contract: ``python -m blendjax.analysis blendjax/`` exits 0
    — per-file rules AND the whole-program pass."""
    baseline = load_baseline(os.path.join(REPO_ROOT, ".bjx-baseline.json"))
    got = analyze_paths(
        [os.path.join(REPO_ROOT, "blendjax")], root=REPO_ROOT, project=True
    )
    left = apply_baseline(got, baseline, REPO_ROOT)
    assert left == [], "\n".join(f.render() for f in left)


# -- BJX116 host-inflate-in-hot-path -----------------------------------------


def test_bjx116_flags_zlib_inflate_in_hot_path_module():
    src = """
        # bjx: hot-path
        import zlib

        def consume(self, frames):
            for buf in frames:
                data = zlib.decompress(buf)
                dec = zlib.decompressobj()
    """
    assert rule_ids(src, select=["BJX116"]) == ["BJX116", "BJX116"]


def test_bjx116_flags_aliased_import_and_driver_hot_path():
    src = """
        # bjx: driver-hot-path
        from zlib import decompress

        def submit(self, batch):
            raw = decompress(batch["z"])
    """
    assert rule_ids(src, select=["BJX116"]) == ["BJX116"]


def test_bjx116_streaming_basenames_always_checked():
    src = """
        import zlib

        def pump(self):
            return zlib.decompress(self._buf)
    """
    assert rule_ids(
        src, "blendjax/data/pipeline.py", select=["BJX116"]
    ) == ["BJX116"]


def test_bjx116_silent_outside_hot_modules_and_for_compress():
    """The codec implementation (wire.py, unmarked) and compress-side
    calls stay clean — only hot-path inflate is the hazard."""
    src = """
        import zlib

        def decode(buf):
            return zlib.decompress(buf)
    """
    assert rule_ids(src, select=["BJX116"]) == []
    hot_compress = """
        # bjx: hot-path
        import zlib

        def encode(self, raw):
            return zlib.compress(raw, 6)
    """
    assert rule_ids(hot_compress, select=["BJX116"]) == []


def test_bjx116_suppressible_inline():
    src = """
        # bjx: hot-path
        import zlib

        def consume(self, buf):
            # bjx: ignore[BJX116]
            return zlib.decompress(buf)
    """
    assert rule_ids(src, select=["BJX116"]) == []


# -- whole-program pass (ProjectContext + BJX117/118/119) ---------------------

from blendjax.analysis.core import (  # noqa: E402
    ModuleContext,
    analyze_project_modules,
    parse_paths,
)


def project_findings(*sources, select=None):
    """Project-pass findings over one or more dedented module sources
    (named ``pkg/m0.py``, ``pkg/m1.py``, ...)."""
    modules = [
        ModuleContext(textwrap.dedent(src), f"pkg/m{i}.py")
        for i, src in enumerate(sources)
    ]
    return analyze_project_modules(
        modules, select=set(select) if select else None
    )


RACY_WORKER = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def start(self):
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            while True:
                self.count += 1

        def snapshot(self):
            return self.count
"""


def test_bjx117_flags_unlocked_write_across_thread_contexts():
    got = project_findings(RACY_WORKER, select=["BJX117"])
    assert [f.rule for f in got] == ["BJX117"]
    assert got[0].identity == "pkg.m0.Worker.count"
    assert "self.count" in got[0].message
    assert "Worker._run" in got[0].message  # the spawned context is named


def test_bjx117_negative_common_lock_over_all_accesses():
    src = """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                while True:
                    with self._lock:
                        self.count += 1

            def snapshot(self):
                with self._lock:
                    return self.count
    """
    assert project_findings(src, select=["BJX117"]) == []


def test_bjx117_negative_init_only_config_and_safe_types():
    src = """
        import queue
        import threading

        class Worker:
            def __init__(self):
                self.size = 4            # config: written only here
                self._q = queue.Queue()  # thread-safe value type
                self._stop = threading.Event()

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                while not self._stop.is_set():
                    self._q.put(self.size)

            def snapshot(self):
                return self._q.qsize() + self.size
    """
    assert project_findings(src, select=["BJX117"]) == []


def test_bjx117_entry_lockset_covers_locked_helpers():
    """A private helper called ONLY under the lock inherits it (the
    ``tick`` -> ``_tick_locked`` shape): no finding."""
    src = """
        import threading

        class Controller:
            def __init__(self):
                self._lock = threading.Lock()
                self.streak = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                while True:
                    self.tick()

            def tick(self):
                with self._lock:
                    self._tick_locked()

            def _tick_locked(self):
                self.streak += 1

            def state(self):
                with self._lock:
                    return self.streak
    """
    assert project_findings(src, select=["BJX117"]) == []


def test_bjx117_thread_shared_marker_demands_locks_without_spawns():
    marked = """
        import threading

        # bjx: thread-shared
        class Reservoir:
            def __init__(self):
                self.lock = threading.RLock()
                self.draws = 0

            def draw(self):
                with self.lock:
                    self.draws += 1

            def stats(self):
                return self.draws
    """
    got = project_findings(marked, select=["BJX117"])
    assert [f.rule for f in got] == ["BJX117"]
    assert got[0].identity == "pkg.m0.Reservoir.draws"
    # same class, no marker: no spawns anywhere -> single context, clean
    unmarked = marked.replace("# bjx: thread-shared", "# (unmarked)")
    assert project_findings(unmarked, select=["BJX117"]) == []


def test_bjx117_cross_module_spawn_graph():
    """A thread spawned in module 0 reaches a class in module 1 through
    a resolvable constructor attribute — the whole-program part."""
    spawner = """
        import threading

        from pkg.m1 import Sink

        class Pump:
            def __init__(self):
                self.sink = Sink()

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                while True:
                    self.sink.push(1)
    """
    sink = """
        class Sink:
            def __init__(self):
                self.total = 0

            def push(self, n):
                self.total += n

            def read(self):
                return self.total
    """
    got = project_findings(spawner, sink, select=["BJX117"])
    assert [f.identity for f in got] == ["pkg.m1.Sink.total"]
    assert "Pump._run" in got[0].message


def test_bjx117_suppressible_inline():
    src = RACY_WORKER.replace(
        "                self.count += 1",
        "                # bjx: ignore[BJX117]\n"
        "                self.count += 1",
    )
    assert project_findings(src, select=["BJX117"]) == []


def test_bjx117_executor_submit_is_a_spawn_site():
    src = """
        from concurrent.futures import ThreadPoolExecutor

        class Pool:
            def __init__(self):
                self.done = 0
                self._pool = ThreadPoolExecutor(2)

            def kick(self):
                self._pool.submit(self._work)

            def _work(self):
                self.done += 1

            def read(self):
                return self.done
    """
    got = project_findings(src, select=["BJX117"])
    assert [f.identity for f in got] == ["pkg.m0.Pool.done"]


LOCK_ORDER = """
    import threading

    class Orders:
        def __init__(self):
            self.a = threading.Lock()
            self.b = threading.Lock()

        def one(self):
            with self.a:
                with self.b:
                    pass

        def two(self):
            with self.b:
                with self.a:
                    pass
"""


def test_bjx118_flags_inconsistent_nesting_once_per_pair():
    got = project_findings(LOCK_ORDER, select=["BJX118"])
    assert [f.rule for f in got] == ["BJX118"]
    assert got[0].identity == "pkg.m0.Orders.a<>pkg.m0.Orders.b"
    assert "Orders.two" in got[0].message or "Orders.one" in got[0].message


def test_bjx118_negative_consistent_order_and_same_lock():
    src = """
        import threading

        class Orders:
            def __init__(self):
                self.a = threading.RLock()
                self.b = threading.Lock()

            def one(self):
                with self.a:
                    with self.b:
                        pass

            def two(self):
                with self.a:
                    with self.a:  # reentrant re-acquire, not a pair
                        with self.b:
                            pass
    """
    assert project_findings(src, select=["BJX118"]) == []


def test_bjx118_transitive_through_the_call_graph():
    src = """
        import threading

        class Orders:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def outer_ab(self):
                with self.a:
                    self._take_b()

            def _take_b(self):
                with self.b:
                    pass

            def outer_ba(self):
                with self.b:
                    with self.a:
                        pass
    """
    got = project_findings(src, select=["BJX118"])
    assert [f.identity for f in got] == ["pkg.m0.Orders.a<>pkg.m0.Orders.b"]


BLOCKED = """
    import queue
    import threading

    class Service:
        def __init__(self):
            self._lock = threading.Lock()
            self._cmds = queue.Queue()

        def start(self):
            threading.Thread(target=self._serve, daemon=True).start()

        def _serve(self):
            while True:
                pass

        def wedge(self):
            with self._lock:
                return self._cmds.get()
"""


def test_bjx119_flags_untimed_queue_get_under_contended_lock():
    got = project_findings(BLOCKED, select=["BJX119"])
    assert [f.rule for f in got] == ["BJX119"]
    assert "queue get()" in got[0].message
    assert "Service.wedge" in got[0].message


def test_bjx119_negative_timeouts_nowait_and_unthreaded_classes():
    timed = BLOCKED.replace(
        "self._cmds.get()", "self._cmds.get(timeout=0.25)"
    )
    assert project_findings(timed, select=["BJX119"]) == []
    nonblock = BLOCKED.replace(
        "self._cmds.get()", "self._cmds.get(block=False)"
    )
    assert project_findings(nonblock, select=["BJX119"]) == []
    # positional timeout slot (the documented Queue.get signature)
    positional = BLOCKED.replace(
        "self._cmds.get()", "self._cmds.get(True, 0.25)"
    )
    assert project_findings(positional, select=["BJX119"]) == []
    # no thread ever contends the lock: the same shape is not flagged
    unthreaded = BLOCKED.replace(
        "            threading.Thread(target=self._serve, daemon=True).start()",
        "            pass",
    )
    assert project_findings(unthreaded, select=["BJX119"]) == []


def test_bjx119_flags_socket_send_join_and_wait_under_lock():
    src = """
        import threading

        class Service:
            def __init__(self):
                self._lock = threading.Lock()

            def start(self):
                threading.Thread(target=self._serve, daemon=True).start()

            def _serve(self):
                pass

            def publish(self, chan, t, ev):
                with self._lock:
                    chan.send(b"x")
                    t.join()
                    ev.wait()
    """
    got = project_findings(src, select=["BJX119"])
    assert sorted(f.message.split(" in ")[0] for f in got) == [
        "blocking join()",
        "blocking socket send()",
        "blocking wait()",
    ]


def test_bjx119_condition_wait_and_bounded_calls_are_sanctioned():
    src = """
        import threading

        class Service:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)

            def start(self):
                threading.Thread(target=self._serve, daemon=True).start()

            def _serve(self):
                pass

            def waiter(self, chan, t):
                with self._lock:
                    self._cv.wait()          # releases the lock by design
                    t.join(timeout=2.0)
                    chan.recv(timeoutms=0)
    """
    assert project_findings(src, select=["BJX119"]) == []


def test_bjx119_suppressible_inline():
    src = BLOCKED.replace(
        "                return self._cmds.get()",
        "                # bjx: ignore[BJX119]\n"
        "                return self._cmds.get()",
    )
    assert project_findings(src, select=["BJX119"]) == []


# -- project fingerprints + baseline migration --------------------------------


def test_project_fingerprints_survive_line_shifts_and_rewording(tmp_path):
    root = tmp_path
    mod = tmp_path / "pkg"
    mod.mkdir()
    path = mod / "w.py"
    path.write_text(textwrap.dedent(RACY_WORKER))
    got = analyze_paths([str(mod)], root=str(root), project=True)
    assert [f.rule for f in got] == ["BJX117"]
    baseline = tmp_path / "bl.json"
    write_baseline(str(baseline), got, str(root))
    data = json.load(open(baseline))
    assert data["version"] == 2
    assert data["entries"][0]["identity"] == "pkg.w.Worker.count"
    # shift every line AND change the anchor line's text: the identity
    # fingerprint still matches, so the finding stays grandfathered
    shifted = "# a new leading comment\nX = 1\n" + textwrap.dedent(
        RACY_WORKER
    ).replace("self.count += 1", "self.count = self.count + 2")
    path.write_text(shifted)
    again = analyze_paths([str(mod)], root=str(root), project=True)
    left = apply_baseline(again, load_baseline(str(baseline)), str(root))
    assert left == []


def test_baseline_version_1_files_stay_valid(tmp_path):
    bl = tmp_path / "old.json"
    bl.write_text(json.dumps({
        "version": 1,
        "entries": [{"fingerprint": "cafe", "rule": "BJX102",
                     "path": "x.py", "line": 1, "message": "m"}],
    }))
    assert load_baseline(str(bl)) == {"cafe"}


# -- shared AST cache ----------------------------------------------------------


def test_parse_paths_shares_one_module_context_per_file(tmp_path):
    p = tmp_path / "m.py"
    p.write_text("import threading\n\n\ndef f():\n    return 1\n")
    modules, errors = parse_paths([str(p)], root=str(tmp_path))
    assert errors == [] and len(modules) == 1
    m = modules[0]
    # the by-type index serves repeated queries without re-walking
    import ast as _ast

    assert m.nodes(_ast.Import) and m.nodes(_ast.FunctionDef)
    # the function table is computed once and cached
    assert list(m.iter_functions()) == list(m.iter_functions())
    assert m.modname == "m"


def test_parse_paths_reports_syntax_errors_as_findings(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text("def broken(:\n")
    modules, errors = parse_paths([str(p)], root=str(tmp_path))
    assert modules == []
    assert [f.rule for f in errors] == ["BJX000"]


# -- the racy fixture, end to end ---------------------------------------------


def test_project_pass_flags_the_racy_fixture():
    fixture = os.path.join(REPO_ROOT, "tests", "fixtures", "racy_threads.py")
    got = analyze_paths([fixture], root=REPO_ROOT, project=True)
    rules = sorted({f.rule for f in got})
    assert rules == ["BJX117", "BJX118", "BJX119"], [
        f.render() for f in got
    ]
    by_rule = {f.rule: f for f in got}
    assert by_rule["BJX117"].identity.endswith("Racy.counter")
    assert "<>" in by_rule["BJX118"].identity
    assert "queue get()" in by_rule["BJX119"].message


# -- CLI: --project / --no-project / exit codes --------------------------------


def run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "blendjax.analysis", *args],
        capture_output=True, text=True, cwd=cwd,
        env={**os.environ, "PYTHONPATH": REPO_ROOT},
    )


def test_cli_project_mode_default_on_and_opt_out(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "w.py").write_text(textwrap.dedent(RACY_WORKER))
    on = run_cli(["pkg"], cwd=str(tmp_path))
    assert on.returncode == 1 and "BJX117" in on.stdout
    off = run_cli(["pkg", "--no-project"], cwd=str(tmp_path))
    assert off.returncode == 0, off.stdout + off.stderr


def test_cli_project_mode_parse_failure_exits_3_with_hint(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "ok.py").write_text("x = 1\n")
    (pkg / "bad.py").write_text("def broken(:\n")
    r = run_cli(["pkg"], cwd=str(tmp_path))
    assert r.returncode == 3
    assert "--no-project" in r.stderr and "BJX000" in r.stderr
    # the quick path still reports the syntax error as a finding
    r2 = run_cli(["pkg", "--no-project"], cwd=str(tmp_path))
    assert r2.returncode == 1 and "BJX000" in r2.stdout


def test_cli_max_seconds_budget(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "ok.py").write_text("x = 1\n")
    ok = run_cli(["pkg", "--max-seconds", "120"], cwd=str(tmp_path))
    assert ok.returncode == 0
    over = run_cli(["pkg", "--max-seconds", "0"], cwd=str(tmp_path))
    assert over.returncode == 4
    assert "budget" in over.stderr


def test_full_repo_lint_fits_the_ci_wall_time_budget():
    """The CI lint job runs with --max-seconds 60; keep generous local
    headroom so slow CI runners still clear it (the shared-AST-cache
    pass runs the full repo in ~2 s on a dev box)."""
    t0 = time.perf_counter()
    analyze_paths(
        [os.path.join(REPO_ROOT, "blendjax")], root=REPO_ROOT, project=True
    )
    assert time.perf_counter() - t0 < 30.0


def test_list_rules_marks_scope():
    r = run_cli(["--list-rules"], cwd=REPO_ROOT)
    assert r.returncode == 0
    assert "BJX117 unlocked-shared-mutation [project]" in r.stdout
    assert "BJX101 jit-purity [file]" in r.stdout


def test_bjx117_lock_name_matching_is_word_boundary():
    """'host_blocks' is a counter, not a lock: a substring match
    silently dropped it from the race analysis (review finding)."""
    src = """
        import threading

        class Worker:
            def __init__(self):
                self.host_blocks = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                while True:
                    self.host_blocks += 1

            def snapshot(self):
                return self.host_blocks
    """
    got = project_findings(src, select=["BJX117"])
    assert [f.identity for f in got] == ["pkg.m0.Worker.host_blocks"]
    # real lock spellings still recognized as locks (exempt + with-able)
    lockish = """
        import threading

        class Worker:
            def __init__(self):
                self.lock_a = threading.Lock()
                self.state = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                with self.lock_a:
                    self.state += 1

            def snapshot(self):
                with self.lock_a:
                    return self.state
    """
    assert project_findings(lockish, select=["BJX117"]) == []


def test_bjx117_nested_public_named_closures_stay_thread_confined():
    """A closure with a public-looking name inside a spawn target runs
    only in its parent's context — it must not be seeded as a 'main'
    entry point (review finding: spurious second context)."""
    src = """
        import threading

        class Confined:
            def __init__(self):
                self.n = 0

            def start(self):
                threading.Thread(target=self._drain, daemon=True).start()

            def _drain(self):
                def flush():
                    self.n += 1
                while True:
                    flush()
    """
    assert project_findings(src, select=["BJX117"]) == []


# -- jit-boundary dataflow rules (BJX120/121/122) ------------------------------


STEP_AND_FEED = """
    import functools

    import jax

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, batch):
        return state

    def feed(state, batch):
        batch["_trace"] = {"t0": 0.0}
        return step(state, batch)
"""


def test_bjx120_flags_direct_stamp_into_jit():
    got = project_findings(STEP_AND_FEED, select=["BJX120"])
    assert [f.rule for f in got] == ["BJX120"]
    assert "'_trace'" in got[0].message and "feed" in got[0].message
    assert got[0].identity == "pkg.m0.feed:_trace->jax.jit(step)"


def test_bjx120_pop_and_filtered_rebuild_are_strips():
    clean = """
        import jax

        step = jax.jit(lambda b: b)

        def feed_pop(batch):
            batch["_trace"] = {}
            batch.pop("_trace", None)
            return step(batch)

        def feed_filter(batch):
            batch["_scenario"] = {}
            clean = {k: v for k, v in batch.items() if not k.startswith("_")}
            return step(clean)
    """
    assert project_findings(clean, select=["BJX120"]) == []


def test_bjx120_provenance_through_rebinding_and_dict_copies():
    """Re-binding aliases share taint (in-place pop strips every alias);
    dict(**batch) / dict(batch) / .copy() copies carry the keys."""
    src = """
        import jax

        step = jax.jit(lambda b: b)

        def leak_copy(batch):
            batch["_scenario_rows"] = [1]
            b2 = batch
            b3 = dict(**b2)
            return step(b3)

        def clean_alias_pop(batch):
            batch["_scenario_rows"] = [1]
            b2 = batch
            b2.pop("_scenario_rows", None)
            return step(batch)
    """
    got = project_findings(src, select=["BJX120"])
    assert [f.rule for f in got] == ["BJX120"]
    assert "leak_copy" in got[0].message


def test_bjx120_strip_via_helper_one_call_hop():
    """A helper whose summary strips the sidecars launders the dict —
    including across modules."""
    helper = """
        _STAMPS = ("_trace", "_scenario_rows")

        def scrub(msg):
            for k in _STAMPS:
                msg.pop(k, None)
            return msg
    """
    feeder = """
        import jax

        from pkg.m0 import scrub

        step = jax.jit(lambda b: b)

        def feed(batch):
            batch["_trace"] = {}
            return step(scrub(batch))
    """
    assert project_findings(helper, feeder, select=["BJX120"]) == []


def test_bjx120_leak_through_forwarding_helper_anchors_in_origin():
    """A helper that forwards its argument into a jit makes the CALLER
    the finding site (that is where the fix goes)."""
    src = """
        import jax

        step = jax.jit(lambda b: b)

        def collate(batch):
            return step(batch)

        def feed(batch):
            batch["_trace"] = {}
            return collate(batch)
    """
    got = project_findings(src, select=["BJX120"])
    assert [f.rule for f in got] == ["BJX120"]
    assert "feed" in got[0].message and "'collate'" in got[0].message


def test_bjx120_wrapped_callee_summaries_are_stable():
    """functools.wraps-decorated callees keep their dataflow summaries:
    a decorated scrubber still strips, a decorated stamper still
    taints."""
    src = """
        import functools

        import jax

        def audited(fn):
            @functools.wraps(fn)
            def inner(*a, **k):
                return fn(*a, **k)
            return inner

        step = jax.jit(lambda b: b)

        @audited
        def scrub(batch):
            batch.pop("_trace", None)
            return batch

        @audited
        def mark(batch):
            batch["_trace"] = {}
            return batch

        def clean(batch):
            batch["_trace"] = {}
            return step(scrub(batch))

        def leaky(batch):
            return step(mark(batch))
    """
    got = project_findings(src, select=["BJX120"])
    assert [f.rule for f in got] == ["BJX120"]
    assert "leaky" in got[0].message


def test_bjx120_wire_decode_is_a_taint_source():
    src = """
        import jax

        from blendjax.transport.wire import decode_message

        step = jax.jit(lambda b: b)

        def replay(frames):
            msg = decode_message(frames)
            return step(msg)
    """
    got = project_findings(src, select=["BJX120"])
    assert [f.rule for f in got] == ["BJX120"]
    assert "_seq" in got[0].message


def test_bjx120_inline_suppression():
    src = STEP_AND_FEED.replace(
        "return step(state, batch)",
        "return step(state, batch)  # sanctioned  # bjx: ignore[BJX120]",
    )
    assert project_findings(src, select=["BJX120"]) == []


def test_bjx121_loop_donation_without_rebind():
    src = """
        import jax

        def _step(state, batch):
            return state

        step = jax.jit(_step, donate_argnums=(0,))

        def run(state, batches):
            for b in batches:
                out = step(state, b)
            return out

        def run_clean(state, batches):
            for b in batches:
                state = step(state, b)
            return state
    """
    got = project_findings(src, select=["BJX121"])
    assert [f.rule for f in got] == ["BJX121"]
    assert "inside a loop" in got[0].message and "'run'" in got[0].message


def test_bjx121_tuple_rebind_and_if_merge_are_clean():
    src = """
        import jax

        def _step(state, prio, batch):
            return state, prio

        step = jax.jit(_step, donate_argnums=(0, 1))

        def update(state, prio, batch):
            state, prio = step(state, prio, batch)
            return state, prio

        def branched(state, prio, batch, flag):
            if flag:
                state, prio = step(state, prio, batch)
            else:
                state = state
            return state, prio
    """
    assert project_findings(src, select=["BJX121"]) == []


def test_bjx122_dynamic_keyset_and_bucket_launder():
    src = """
        import jax

        step = jax.jit(lambda b: b)

        def feed(batch, msg):
            batch[msg["name"]] = msg["value"]
            return step(batch)

        def feed_bucketed(batch, msg):
            n = pad_to_bucket(msg["count"])
            cfg = {}
            cfg[n] = 1
            return step(batch)
    """
    got = project_findings(src, select=["BJX122"])
    assert [f.rule for f in got] == ["BJX122"]
    assert "key set" in got[0].message or "gained a key" in got[0].message
    assert "feed" in got[0].message


def test_jit_boundary_fixtures_flag_end_to_end():
    """The acceptance gate: both historical stamp-leak regressions, the
    PR-12 policy-sync shape, and the unbounded-static-arg shape all
    flag through analyze_paths(project=True) — one finding each, with
    the sanctioned twins in the same files staying quiet."""
    expect = {
        "stamp_leak_trace.py": ("BJX120", "feed:_trace->jax.jit(train_step)"),
        "stamp_leak_scenario.py": (
            "BJX120", "EchoSampler.draw:_scenario_rows->"
        ),
        "use_after_donate_sync.py": ("BJX121", "Learner.update:state"),
        "retrace_unbounded.py": ("BJX122", "feed:jax.jit(_decode):n="),
    }
    for name, (rule, ident) in expect.items():
        fixture = os.path.join(REPO_ROOT, "tests", "fixtures", name)
        got = analyze_paths([fixture], root=REPO_ROOT, project=True)
        assert [f.rule for f in got] == [rule], (name, [
            f.render() for f in got
        ])
        assert ident in got[0].identity, (name, got[0].identity)


def test_cli_flags_jit_boundary_fixtures():
    """Same gate through the CLI (exit code 1 + rule id in the text
    output), as the issue's acceptance criterion demands."""
    for name, rule in (
        ("stamp_leak_trace.py", "BJX120"),
        ("stamp_leak_scenario.py", "BJX120"),
        ("use_after_donate_sync.py", "BJX121"),
        ("retrace_unbounded.py", "BJX122"),
    ):
        r = run_cli(
            [os.path.join("tests", "fixtures", name), "--no-baseline"],
            cwd=REPO_ROOT,
        )
        assert r.returncode == 1, (name, r.stdout, r.stderr)
        assert rule in r.stdout, (name, r.stdout)


def test_jit_boundary_fingerprints_survive_line_shifts(tmp_path):
    """Baseline-v2 identities for BJX120/121/122 are line-independent:
    grandfathered findings stay suppressed after the file shifts."""
    mod = tmp_path / "pkg"
    mod.mkdir()
    path = mod / "w.py"
    src = textwrap.dedent(STEP_AND_FEED)
    path.write_text(src)
    got = analyze_paths([str(mod)], root=str(tmp_path), project=True)
    got = [f for f in got if f.rule == "BJX120"]
    assert len(got) == 1
    baseline = tmp_path / "bl.json"
    write_baseline(str(baseline), got, str(tmp_path))
    data = json.load(open(baseline))
    assert data["version"] == 2
    assert data["entries"][0]["identity"] == "pkg.w.feed:_trace->jax.jit(step)"
    path.write_text("# leading comment\nX = 1\n\n" + src)
    again = analyze_paths([str(mod)], root=str(tmp_path), project=True)
    again = [f for f in again if f.rule == "BJX120"]
    left = apply_baseline(again, load_baseline(str(baseline)), str(tmp_path))
    assert left == []

# -- contract-drift gate (BJX123) --------------------------------------------


def _mods(*sources):
    from blendjax.analysis.core import ModuleContext

    return [
        ModuleContext(textwrap.dedent(src), rel)
        for rel, src in sources
    ]


def test_contracts_metric_extraction_variants():
    """Every emission idiom lands in the catalog: direct literal,
    local name-bind, f-string family prefix, ``self.registry``
    receiver, and the ALL-CAPS spec-table loop."""
    from blendjax.analysis.contracts import extract_metrics

    cat = extract_metrics(_mods(("pkg/m.py", """
        TRANSITIONS = ("trace.wire_ms", "trace.step_ms")

        def emit(metrics, idx):
            metrics.count("wire.frames")
            span_name = f"ingest.recv.shard{idx}"
            with metrics.span(span_name):
                pass
            metrics.observe(f"echo.lag{idx}", 1.0)

        class C:
            def tick(self, n):
                self.registry.gauge_max("train.inflight_hwm", n)
                for name in TRANSITIONS:
                    self.registry.observe(name, 0.0)
    """)))
    assert "wire.frames" in cat.names
    assert "train.inflight_hwm" in cat.names
    assert "trace.wire_ms" in cat.names and "trace.step_ms" in cat.names
    assert "ingest.recv.shard" in cat.prefixes
    assert "echo.lag" in cat.prefixes
    # helper calls on non-registry receivers are not metric emissions
    assert not any(n.startswith("self.") for n in cat.names)


def test_contracts_stamp_and_knob_extraction():
    from blendjax.analysis.contracts import (
        extract_env_knobs,
        extract_stamp_keys,
    )

    mods = _mods(("pkg/wire.py", """
        import os

        SEQ_KEY = "_seq"
        NOT_A_KEY = "plain"

        def read():
            os.environ.get("BLENDJAX_MY_KNOB", "0")
            return {"_batched": True}
    """))
    stamps = extract_stamp_keys(mods)
    assert "_seq" in stamps.names
    assert "_batched" in stamps.names  # wire-control literal
    assert "plain" not in stamps.names
    # the analysis layer's sidecar universe is part of the contract
    assert "_trace" in stamps.names and "_mask" in stamps.names
    knobs = extract_env_knobs(mods)
    assert set(knobs.names) == {"BLENDJAX_MY_KNOB"}


def test_contracts_doc_matching_grammar():
    """Doc-side parsing: wildcard families, trailing-N families,
    artifact filenames excluded, and the ``BLENDJAX_BENCH_*`` family
    reference not read as a knob named with a trailing underscore."""
    from blendjax.analysis.contracts import (
        _doc_metric_live,
        _metric_documented,
        documented_knobs,
        documented_metrics,
        extract_metrics,
    )

    lines = [
        "Counters: `wire.frames`, the `echo.*` family, and per-shard",
        "`ingest.recv.shardN` spans; traces export to `trace.json`.",
        "Every switch is a `BLENDJAX_BENCH_*` variable —",
        "`BLENDJAX_BENCH_CHUNK` (default 16).",
    ]
    docs = documented_metrics(lines)
    assert "wire.frames" in docs and "echo.*" in docs
    assert "ingest.recv.shardN" in docs
    assert "trace.json" not in docs  # artifact filename, not a metric
    assert _metric_documented("echo.fresh", docs)
    assert not _metric_documented("rl.fresh", docs)
    knobs = documented_knobs(lines)
    assert knobs == {"BLENDJAX_BENCH_CHUNK": 4}
    cat = extract_metrics(_mods(("pkg/m.py", """
        def f(metrics, i):
            metrics.span(f"ingest.recv.shard{i}")
    """)))
    assert _doc_metric_live("ingest.recv.shardN", cat)
    assert not _doc_metric_live("ingest.recv.extra", cat)


def test_contracts_end_to_end_drift_both_ways(tmp_path):
    """Undocumented code entries AND stale doc entries each produce a
    BJX123 finding; a complete doc set is clean."""
    from blendjax.analysis.contracts import check_contracts
    from blendjax.analysis.core import parse_paths
    from blendjax.analysis.project import (
        NON_SIDECAR_KEYS,
        SIDECAR_LITERAL_KEYS,
    )

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text(textwrap.dedent("""
        import os

        def emit(metrics):
            metrics.count("wire.frames")
            os.environ.get("BLENDJAX_MY_KNOB")
    """))
    docs = tmp_path / "docs"
    docs.mkdir()
    universe = "\n".join(
        f"- `{k}`" for k in sorted(SIDECAR_LITERAL_KEYS | NON_SIDECAR_KEYS)
    )
    (docs / "wire-protocol.md").write_text(universe + "\n")
    (docs / "observability.md").write_text("`wire.bytes` only.\n")
    modules, errors = parse_paths([str(pkg)], root=str(tmp_path))
    assert not errors
    got = check_contracts(modules, str(tmp_path))
    idents = {f.identity for f in got}
    assert "metric:wire.frames" in idents        # emitted, undocumented
    assert "stale-metric:wire.bytes" in idents   # documented, never emitted
    assert "knob:BLENDJAX_MY_KNOB" in idents
    assert all(f.rule == "BJX123" for f in got)

    (docs / "observability.md").write_text("`wire.frames` counted.\n")
    (docs / "knobs.md").write_text("`BLENDJAX_MY_KNOB` toggles it.\n")
    assert check_contracts(modules, str(tmp_path)) == []


def test_cli_contracts_gate_repo_is_clean():
    """The acceptance criterion: the real repo's catalogs and docs
    agree — `--contracts` exits 0 (and stays inside the CI budget)."""
    r = run_cli(["--contracts", "--max-seconds", "60"], cwd=REPO_ROOT)
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_contracts_exit_1_on_drift(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        "def f(metrics):\n    metrics.count('ghost.metric')\n"
    )
    r = run_cli(["--contracts", "pkg"], cwd=str(tmp_path))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "BJX123" in r.stdout and "ghost.metric" in r.stdout


# -- suppression hygiene (BJX124) --------------------------------------------


def test_strict_suppressions_justification_shapes():
    from blendjax.analysis.core import check_suppression_hygiene

    got = check_suppression_hygiene(_mods(("pkg/m.py", """
        x = 1  # bjx: ignore[BJX101]
        y = 2  # bjx: ignore[BJX101] — sanctioned: init-time only
        # the reservoir is thread-confined here
        z = 3  # bjx: ignore[BJX117]
        # bjx: ignore[BJX108]
        w = 4
        msg = "suppress with '# bjx: ignore[BJX107]' and say why"
    """)))
    assert [f.line for f in got] == [2, 6]  # bare inline + bare above-line
    assert all(f.rule == "BJX124" for f in got)
    # markers inside string literals are prose, not suppressions
    assert all("BJX107" not in str(f.line) or f.line != 8 for f in got)


def test_strict_suppressions_identity_survives_line_shift():
    from blendjax.analysis.core import check_suppression_hygiene

    src = "x = 1  # bjx: ignore[BJX101]\n"
    a = check_suppression_hygiene(_mods(("pkg/m.py", src)))
    b = check_suppression_hygiene(_mods(("pkg/m.py", "# pad\n\n" + src)))
    assert len(a) == len(b) == 1
    assert a[0].identity == b[0].identity


def test_cli_strict_suppressions_flag(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text("x = 1  # bjx: ignore[BJX101]\n")
    off = run_cli(["pkg", "--no-baseline"], cwd=str(tmp_path))
    assert off.returncode == 0, off.stdout + off.stderr
    on = run_cli(
        ["pkg", "--no-baseline", "--strict-suppressions"], cwd=str(tmp_path)
    )
    assert on.returncode == 1, on.stdout + on.stderr
    assert "BJX124" in on.stdout


def test_repo_suppressions_all_justified():
    """Self-gate for the hygiene pass: every '# bjx: ignore[...]' in
    the repo carries its reason (CI runs with --strict-suppressions)."""
    from blendjax.analysis.core import check_suppression_hygiene, parse_paths

    paths = [os.path.join(REPO_ROOT, p) for p in ("blendjax", "scripts")]
    paths.append(os.path.join(REPO_ROOT, "bench.py"))
    modules, errors = parse_paths(paths, root=REPO_ROOT)
    assert not errors
    got = check_suppression_hygiene(modules)
    assert got == [], [f.render() for f in got]


# -- SARIF output -------------------------------------------------------------


def test_cli_sarif_output_carries_identity_fingerprint():
    r = run_cli(
        [
            os.path.join("tests", "fixtures", "stamp_leak_trace.py"),
            "--no-baseline", "--format", "sarif",
        ],
        cwd=REPO_ROOT,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "bjx-lint"
    rule_ids = {rule["id"] for rule in run["tool"]["driver"]["rules"]}
    results = run["results"]
    assert any(res["ruleId"] == "BJX120" for res in results)
    assert all(res["ruleId"] in rule_ids for res in results)
    leak = next(res for res in results if res["ruleId"] == "BJX120")
    loc = leak["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("stamp_leak_trace.py")
    assert loc["region"]["startLine"] > 0
    assert (
        leak["partialFingerprints"]["bjxIdentity/v2"]
        == "tests.fixtures.stamp_leak_trace.feed:_trace"
        "->jax.jit(train_step)"
    )


def test_cli_full_repo_lint_within_budget():
    """The CI latency gate: the whole-program pass over the full repo
    (rules + dataflow + hygiene) completes inside --max-seconds 60."""
    r = run_cli(
        ["blendjax", "--strict-suppressions", "--max-seconds", "60"],
        cwd=REPO_ROOT,
    )
    assert r.returncode == 0, r.stdout + r.stderr


# -- BJX126 mesh-axis-literal -------------------------------------------------

AXIS_LITERAL = """
    from jax.sharding import NamedSharding, PartitionSpec as P

    def pin(mesh, x):
        import jax
        return jax.device_put(x, NamedSharding(mesh, P("data")))

    def fold(mesh):
        return P(("data", "fsdp"), None)
"""


def test_bjx126_flags_axis_literals_in_library_code():
    got = findings(
        AXIS_LITERAL, relpath="blendjax/train/foo.py", select=["BJX126"]
    )
    assert [f.rule for f in got] == ["BJX126"] * 2
    assert "fsdp" in got[1].message


def test_bjx126_layout_layer_and_tests_are_exempt():
    assert rule_ids(
        AXIS_LITERAL, relpath="blendjax/parallel/foo.py",
        select=["BJX126"],
    ) == []
    assert rule_ids(
        AXIS_LITERAL, relpath="tests/test_foo.py", select=["BJX126"]
    ) == []


def test_bjx126_negatives_threaded_axis_and_non_axis_strings():
    clean = """
        from jax.sharding import PartitionSpec as P

        def pin(mesh, data_axis):
            return P(data_axis)

        def not_an_axis():
            return P("batch")

        def not_a_spec():
            return dict(axis="data")
    """
    assert rule_ids(
        clean, relpath="blendjax/train/foo.py", select=["BJX126"]
    ) == []


def test_bjx126_inline_suppression():
    src = """
        from jax.sharding import PartitionSpec as P

        def fixture(mesh):
            return P("data")  # bjx: ignore[BJX126]
    """
    assert rule_ids(
        src, relpath="blendjax/train/foo.py", select=["BJX126"]
    ) == []
