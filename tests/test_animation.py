"""Animation lifecycle ordering — mirrors the reference's canonical
assertion (``tests/test_animation.py:7-26``): two episodes of frames 1..3
produce pre_play -> [pre_animation -> (pre_frame post_frame)x3 ->
post_animation]x2 -> post_play."""

from blendjax.producer.animation import AnimationController, Engine
from blendjax.producer.signal import Signal


class RecordingEngine(Engine):
    def __init__(self, log):
        self.log = log

    def frame_set(self, frame):
        self.log.append(("sim", frame))

    def reset(self):
        self.log.append(("reset",))


def _wire(ctrl, log):
    ctrl.pre_play.add(lambda: log.append(("pre_play",)))
    ctrl.pre_animation.add(lambda: log.append(("pre_anim",)))
    ctrl.pre_frame.add(lambda f: log.append(("pre", f)))
    ctrl.post_frame.add(lambda f: log.append(("post", f)))
    ctrl.post_animation.add(lambda: log.append(("post_anim",)))
    ctrl.post_play.add(lambda: log.append(("post_play",)))


def test_lifecycle_two_episodes():
    log = []
    ctrl = AnimationController(RecordingEngine(log))
    _wire(ctrl, log)
    ctrl.play(frame_range=(1, 3), num_episodes=2)

    episode = [("reset",), ("pre_anim",)]
    for f in (1, 2, 3):
        episode += [("pre", f), ("sim", f), ("post", f)]
    episode += [("post_anim",)]
    assert log == [("pre_play",)] + episode * 2 + [("post_play",)]
    assert ctrl.episode == 2 and not ctrl.playing


def test_rewind_restarts_episode_with_pre_animation():
    log = []
    ctrl = AnimationController(RecordingEngine(log))
    _wire(ctrl, log)
    fired = []

    def maybe_rewind(f):
        if f == 2 and not fired:
            fired.append(True)
            ctrl.rewind()

    ctrl.post_frame.add(maybe_rewind)
    ctrl.play(frame_range=(1, 3), num_episodes=1)

    frames = [e[1] for e in log if e[0] == "pre"]
    assert frames == [1, 2, 1, 2, 3]
    # rewind re-fires pre_animation (env reset hook) but keeps one episode
    assert sum(1 for e in log if e == ("pre_anim",)) == 2
    assert sum(1 for e in log if e == ("post_anim",)) == 1
    assert ctrl.episode == 1


def test_cancel_stops_midway():
    log = []
    ctrl = AnimationController(RecordingEngine(log))
    _wire(ctrl, log)
    ctrl.post_frame.add(lambda f: ctrl.cancel() if f == 2 else None)
    ctrl.play(frame_range=(1, 100), num_episodes=-1)
    frames = [e[1] for e in log if e[0] == "pre"]
    assert frames == [1, 2]
    assert log[-1] == ("post_play",)


def test_signal_partial_binding_and_remove():
    s = Signal()
    got = []
    h = s.add(lambda tag, x: got.append((tag, x)), "bound")
    s.invoke(42)
    assert got == [("bound", 42)]
    s.remove(h)
    s.invoke(43)
    assert got == [("bound", 42)]
